"""Disaggregated prefill/decode benchmark.

The reference lists disaggregated serving as roadmap item 8
(reference README.md:115); this framework implements it end to end
(role-labeled endpoints -> dual pick in one scheduling cycle ->
x-gateway-prefill-endpoint protocol surface). This bench quantifies WHEN
it pays, against the same hardware budget (8 pods) co-located.

Workload where disaggregation wins — long uncached prompts (RAG/document
QA: ~32 KB per-request context, no cross-request sharing) near capacity,
with prefill-priority interference on (while any prompt is prefilling, a
co-located pod's decodes run at 15% rate — the continuous-batching stall
that motivates P/D in the first place). The prefill fleet absorbs the
2-second prompt computes; the decode fleet streams tokens uninterrupted.

Honesty leg (stderr): the same comparison on the high-prefix-hit
interactive workload, where prefill is cheap and co-located wins — P/D is
a workload decision, not a default; the bench prints both.

Prints ONE JSON line: pd goodput, vs_baseline = pd/co-located ratio
(3-seed mean) on the win-regime workload.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys


def _force_platform() -> None:
    platform = os.environ.get("GIE_GOODPUT_PLATFORM", "cpu")
    import jax

    jax.config.update("jax_platforms", platform)


def run_compare(wl, n_prefill: int, seeds=(0, 1, 2), duration_s: float = 25.0):
    from gie_tpu.sched.config import tuned_profile
    from gie_tpu.sched.profile import Scheduler
    from gie_tpu.simulator import StubConfig
    from gie_tpu.simulator.cluster import SimCluster

    stub = StubConfig(max_running=8, prefill_tokens_per_s=4000.0,
                      decode_tokens_per_s=50.0, prefix_cache_chunks=2048,
                      decode_interference=0.85)
    cfg, weights = tuned_profile()
    pdcfg = dataclasses.replace(cfg, pd_disaggregation=True)
    out = []
    for seed in seeds:
        base = SimCluster(n_pods=8, stub_cfg=stub, seed=seed).run(
            "tpu", wl, duration_s=duration_s)
        fleet = (
            [dataclasses.replace(stub, role="prefill")] * n_prefill
            + [dataclasses.replace(stub, role="decode")] * (8 - n_prefill)
        )
        pd = SimCluster(n_pods=8, stub_cfg=fleet, seed=seed).run(
            "tpu", wl, duration_s=duration_s,
            scheduler=Scheduler(pdcfg, weights=weights))
        out.append((base, pd))
    return out


def main() -> None:
    _force_platform()
    from gie_tpu.simulator.cluster import WorkloadConfig

    # Win regime: long uncached prompts (RAG), 5P/3D split (the prompt
    # compute dominates, so the fleet leans prefill).
    rag = WorkloadConfig(arrival_qps=6.0, n_sessions=512,
                         system_prompt_bytes=256, user_suffix_bytes=32768,
                         decode_tokens_mean=64.0, ttft_slo_s=4.0)
    runs = run_compare(rag, n_prefill=5)
    ratios = [pd.goodput_tokens_per_s / max(base.goodput_tokens_per_s, 1e-9)
              for base, pd in runs]
    for seed, ((base, pd), r) in enumerate(zip(runs, ratios)):
        print(
            f"RAG seed {seed}: co-located goodput={base.goodput_tokens_per_s:6.1f} "
            f"slo={base.slo_attainment:.2f} | pd 5P/3D "
            f"goodput={pd.goodput_tokens_per_s:6.1f} "
            f"slo={pd.slo_attainment:.2f}  ratio={r:.2f}",
            file=sys.stderr,
        )
    mean_ratio = sum(ratios) / len(ratios)
    pd_goodput = sum(pd.goodput_tokens_per_s for _, pd in runs) / len(runs)

    # Honesty leg: interactive chat (high prefix hit -> cheap prefill) —
    # co-located wins; P/D is for prefill-heavy workloads.
    chat = WorkloadConfig(arrival_qps=24.0, n_sessions=32,
                          system_prompt_bytes=8192, user_suffix_bytes=128,
                          decode_tokens_mean=128.0, ttft_slo_s=2.0)
    (base, pd), = run_compare(chat, n_prefill=2, seeds=(0,))
    print(
        f"chat (hit~0.85): co-located goodput={base.goodput_tokens_per_s:6.1f} "
        f"| pd 2P/6D goodput={pd.goodput_tokens_per_s:6.1f} "
        f"(co-located wins here — P/D is a workload decision)",
        file=sys.stderr,
    )

    print(json.dumps({
        "metric": "pd_goodput_vs_colocated_rag",
        "value": round(pd_goodput, 1),
        "unit": "tokens/s",
        "vs_baseline": round(mean_ratio, 2),
    }))


if __name__ == "__main__":
    main()
