"""Headline benchmark: batched endpoint-pick latency on TPU.

Measures the full scheduling cycle (filters -> queue/kv/lora/prefix/
assumed-load scorer blend -> top-k pick -> prefix + load state update) for
the north-star shape: 1024 pending requests x 256 live endpoints
(BASELINE.md: target <= 50 us p50 per batch; reference comparison point is
the CPU EPP's O(10 ms)-per-request scheduler budget,
reference docs/proposals/006-scheduler/README.md:43).

Methodology (round 4). Three defenses, each earned by a prior round's
failure mode (docs/BENCH_NOTES.md):

1. DEVICE-SIDE CYCLE CHAINING over DISTINCT waves (round 3): each dispatch
   runs CHAIN cycles inside one XLA program (`lax.scan`), with the state
   pytree as the carry. Every cycle sees a different request wave — the
   wave is DERIVED ON DEVICE from one base wave by a per-cycle row
   rotation + chunk-hash salt, so (a) XLA cannot hoist request-dependent
   stages out of the loop (the r2 constant-wave fiction measured 0.4 us),
   (b) the relay cannot content-cache repeated computation, and (c) the
   dispatch payload is ONE wave regardless of chain length — a relay that
   re-ships arguments per dispatch (observed: ~1.4 ms for a 6 MB operand)
   cannot inflate the long chains more than the short ones.

2. SLOPE TIMING: per-cycle time = (T(CHAIN_LONG) - T(CHAIN_SHORT)) /
   (CHAIN_LONG - CHAIN_SHORT), medians over REPS repetitions, PIPELINE
   windows in flight per repetition. Fixed per-dispatch overhead (host,
   tunnel RTT, relay bookkeeping) cancels in the difference; only the
   marginal cost of one more scheduling cycle remains — which is the
   production-relevant quantity (the EPP streams waves back-to-back).
   Guard: if the slope collapses below a quarter of the bulk rate (a
   flat-time degraded relay window would make it ~0), the bulk per-cycle
   number is reported instead — never the optimistic one.

3. CALIBRATION (round 3 found tunnel timing untrustworthy in BOTH
   directions): a chained bf16 matmul of KNOWN cost (2*2048^3 FLOPs/iter)
   runs first through the identical scan+slope harness. The implied
   TFLOP/s must land in a physically plausible band for one TPU chip
   ([2, 1000]); outside it, the capture is stamped "calibration:
   implausible" on stderr so the number can be weighed accordingly.

Prints ONE JSON line:
  metric       pick_p50_us_1024x256 — slope-based p50 per-cycle latency
  vs_baseline  north-star target (50 us per 1024x256 batch, BASELINE.md)
               divided by our p50: >= 1.0 means the target is met. (The
               reference's own stated budget is O(10 ms) PER REQUEST on a
               CPU EPP — ~200,000x slower per decision; stderr reports it.)
Extra detail goes to stderr.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import time


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _apply_platform_override() -> None:
    """GIE_BENCH_PLATFORM=cpu runs the whole bench on the host backend —
    methodology smoke-testing only (the official capture is the default
    TPU backend; the sitecustomize pins JAX_PLATFORMS before env vars can
    take effect, hence the explicit config update)."""
    p = os.environ.get("GIE_BENCH_PLATFORM")
    if p:
        import jax

        jax.config.update("jax_platforms", p)


_PROBE_CODE = (
    "import os, jax\n"
    "p = os.environ.get('GIE_BENCH_PLATFORM')\n"
    "if p: jax.config.update('jax_platforms', p)\n"
    "d = jax.devices(); print(d[0].platform)\n"
)


def _wait_for_backend(
    total_s: float = 570.0,
    probe_timeout_s: float = 75.0,
    sleep_s: float = 20.0,
) -> str:
    """Survive a transient relay outage (VERDICT r3 #1: rounds 1 and 3
    both lost their capture to a down tunnel and a fixed 180 s bail).

    jax backend init holds a process-wide lock while it hangs, so retrying
    in-process is impossible — each probe is a SUBPROCESS that attempts
    `jax.devices()`; the parent only initializes jax after a probe
    succeeds. Probes retry with pauses for up to ~9.5 minutes.

    Returns the platform tag for the JSON record. When every probe fails
    (the BENCH_r01-r05 rc=3 "axon relay unreachable" aborts), the bench no
    longer exits nonzero with an empty capture: it falls back to the CPU
    backend ("cpu-fallback"), with reduced repetition counts so the run
    stays bounded. A CPU number is NOT comparable to the TPU target — the
    tag exists so the perf trajectory records the relay outage instead of
    a hole — but the methodology (scan + slope + calibration guard) is
    exercised end to end.
    """
    deadline = time.monotonic() + total_s
    attempt = 0
    while True:
        attempt += 1
        t0 = time.monotonic()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True, text=True, timeout=probe_timeout_s,
            )
            ok = proc.returncode == 0
            detail = (proc.stdout or proc.stderr).strip().splitlines()
            detail = detail[-1] if detail else ""
        except subprocess.TimeoutExpired:
            ok, detail = False, f"probe hung >{probe_timeout_s:.0f}s"
        dt = time.monotonic() - t0
        if ok:
            _log(f"backend probe {attempt}: up after {dt:.1f}s ({detail})")
            return detail or "tpu"
        remaining = deadline - time.monotonic()
        _log(
            f"backend probe {attempt}: DOWN after {dt:.1f}s ({detail}); "
            f"{remaining:.0f}s of retry budget left"
        )
        if remaining <= sleep_s:
            break
        time.sleep(sleep_s)
    _log(
        f"backend did not initialize within {total_s:.0f}s across "
        f"{attempt} probes (axon relay unreachable?) — falling back to "
        "JAX_PLATFORMS=cpu so the capture records a tagged number "
        "instead of aborting empty"
    )
    os.environ["GIE_BENCH_PLATFORM"] = "cpu"
    # One confirming probe on the CPU backend; if even that fails, the
    # environment is broken beyond any fallback.
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            capture_output=True, text=True, timeout=probe_timeout_s,
        )
        ok = proc.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        _log("FATAL: CPU fallback backend failed to initialize too")
        sys.exit(3)
    # Bound the fallback's wall time: CPU cycles are ~100-1000x the TPU's,
    # and the capture is a tagged trajectory marker, not a target check.
    global PIPELINE, REPS
    PIPELINE, REPS = 2, 5
    return "cpu-fallback"


def _in_process_watchdog(timeout_s: float = 180.0):
    """Last-ditch guard: the probe said the relay is up, but if THIS
    process's init still hangs, bail instead of wedging the driver."""
    import threading

    _apply_platform_override()
    import jax

    result: list = []

    def probe() -> None:
        try:
            result.append(jax.devices())
        except Exception as e:
            result.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if not result:
        _log(f"FATAL: in-process backend init hung >{timeout_s:.0f}s")
        os._exit(3)
    if isinstance(result[0], Exception):
        _log(f"FATAL: JAX backend init failed: {result[0]}")
        os._exit(3)


def _preflight(n_probe: int = 5) -> None:
    """Host conditions on the record, so a contended capture is
    diagnosable (round 2 lost 2x to a concurrent process)."""
    try:
        load1, load5, _ = os.getloadavg()
    except OSError:  # pragma: no cover - platform without getloadavg
        load1 = load5 = float("nan")
    samples = []
    for _ in range(n_probe):
        t0 = time.perf_counter()
        time.sleep(0.001)
        samples.append(time.perf_counter() - t0 - 0.001)
    jitter_us = max(samples) * 1e6
    ncpu = os.cpu_count() or 1
    _log(
        f"preflight: loadavg1={load1:.2f} loadavg5={load5:.2f} ncpu={ncpu} "
        f"sleep-jitter={jitter_us:.0f}us "
        f"{'(host contended)' if load1 > ncpu * 0.5 else '(host quiet)'}"
    )


# Chain lengths for the slope: long enough that the marginal cost
# dominates noise, short enough that a rep stays sub-second even at the
# ~4 ms/cycle degraded-relay worst case.
CHAIN_SHORT = 16
CHAIN_LONG = 64
PIPELINE = 4   # windows in flight per timed repetition
REPS = 20      # timed repetitions per chain length

# GIE_BENCH_SMOKE=1: tiny shapes for methodology/CI smoke runs on the CPU
# backend (the official capture always uses the constants above).
_SMOKE = os.environ.get("GIE_BENCH_SMOKE") == "1"
if _SMOKE:
    CHAIN_SHORT, CHAIN_LONG, PIPELINE, REPS = 4, 12, 2, 3


def _timed_reps(fn, n_reps: int, block):
    """Median wall time of `fn` (which enqueues PIPELINE windows) over
    n_reps, blocking once per rep."""
    import numpy as np

    times = []
    for _ in range(n_reps):
        t0 = time.perf_counter()
        out = fn()
        block(out)
        times.append(time.perf_counter() - t0)
    return float(np.percentile(np.asarray(times), 50)), times


def _calibrate(jax, jnp):
    """Chained bf16 matmul of known cost through the same scan+slope
    harness; returns (implied_tflops, plausible)."""
    import numpy as np

    D = 512 if _SMOKE else 2048
    flops_per_iter = 2 * D**3  # 17.18 GFLOP at D=2048
    rng = np.random.default_rng(7)
    w = jnp.asarray(rng.standard_normal((D, D)) * 0.02, jnp.bfloat16)
    x0 = jnp.asarray(rng.standard_normal((D, D)), jnp.bfloat16)

    def chain(x, salts):
        def step(carry, salt):
            y = jnp.dot(carry, w, preferred_element_type=jnp.float32)
            # Normalize + salt: keeps values bounded AND makes every
            # iteration's data distinct (no relay content-caching).
            y = y * jax.lax.rsqrt(jnp.mean(y * y) + 1e-6) + salt
            return y.astype(jnp.bfloat16), ()
        out, _ = jax.lax.scan(step, x, salts)
        return out

    fns = {}
    for L in (CHAIN_SHORT, CHAIN_LONG):
        salts = jnp.asarray(
            rng.standard_normal((L, 1, 1)) * 1e-3, jnp.bfloat16)
        fns[L] = (jax.jit(functools.partial(chain, salts=salts)), salts)

    x = jax.device_put(x0)
    for L, (f, _) in fns.items():
        jax.block_until_ready(f(x))  # compile

    med = {}
    for L, (f, _) in fns.items():
        def rep(f=f):
            y = x
            for _ in range(PIPELINE):
                y = f(y)
            return y
        med[L], _ = _timed_reps(rep, REPS, jax.block_until_ready)

    per_iter_s = max(
        (med[CHAIN_LONG] - med[CHAIN_SHORT])
        / (PIPELINE * (CHAIN_LONG - CHAIN_SHORT)),
        1e-9,
    )
    tflops = flops_per_iter / per_iter_s / 1e12
    bulk_us = med[CHAIN_LONG] / (PIPELINE * CHAIN_LONG) * 1e6
    plausible = 2.0 <= tflops <= 1000.0
    _log(
        f"calibration: matmul {D}x{D} bf16 slope={per_iter_s*1e6:.1f}us/iter "
        f"bulk={bulk_us:.1f}us/iter implied={tflops:.1f} TFLOP/s "
        f"-> {'plausible' if plausible else 'IMPLAUSIBLE'} "
        "(band [2, 1000] for one TPU chip)"
    )
    return tflops, plausible


def main() -> None:
    backend = _wait_for_backend()
    _in_process_watchdog()
    _preflight()

    _apply_platform_override()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
    from gie_tpu.sched.types import SchedState, Weights
    from gie_tpu.utils.testing import make_endpoints, make_requests

    dev = jax.devices()[0]
    _log(f"device: {dev}")

    calib_tflops, calib_ok = _calibrate(jax, jnp)

    n, m = (256, 64) if _SMOKE else (1024, 256)
    rng = np.random.default_rng(0)
    # M-axis bucket = 256 (VERDICT r3 #2): state, masks, and every scorer
    # column are laid out at the north-star width, not M_MAX=512.
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 50, m).tolist(),
        kv=rng.uniform(0, 0.95, m).tolist(),
        max_lora=8,
        m_slots=m,
    )
    # Realistic mixed traffic: shared system prompts (prefix hits), LoRA ids.
    base = b"SYSTEM: You are a helpful assistant specialised in task %d. "
    prompts = [(base % (i % 16)) * 6 + b"user question %d" % i for i in range(n)]
    reqs = make_requests(
        n,
        prompts=prompts,
        lora_id=(rng.integers(-1, 12, n)).tolist(),
        m_slots=m,
    )
    # Chunk-axis bucket, exactly as the live batching layer sizes it
    # (sched/batching.py): prefix lanes cover the longest prompt, not
    # MAX_CHUNKS.
    from gie_tpu.sched.types import chunk_bucket_for

    cb = chunk_bucket_for(int(np.asarray(reqs.n_chunks).max()))
    reqs = reqs.replace(chunk_hashes=reqs.chunk_hashes[:, :cb])
    _log(f"chunk bucket: {cb} lanes "
         f"(max prompt chunks {int(np.asarray(reqs.n_chunks).max())})")
    cfg = ProfileConfig()
    cycle = functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None)

    def make_window(cycle_fn, L, seed):
        """Jit CHAIN=L scheduling cycles as ONE device program.

        The production scheduler streams waves back-to-back without a host
        sync per cycle; the scan reproduces that steady state (the state
        pytree is the carry, so every cycle sees its predecessor's
        updates). Each cycle's wave is DERIVED ON DEVICE from the base
        wave: row rotation by a per-cycle shift + chunk-hash salt — no
        array is equal across iterations (hoisting/caching defense) and
        the dispatch payload stays one wave.
        """
        salts = jnp.asarray(rng.integers(
            1, 2**32, L, dtype=np.uint64).astype(np.uint32))
        shifts = jnp.asarray(
            ((17 * np.arange(1, L + 1) + seed) % n).astype(np.int32))

        def window(state, key, reqs, eps, weights):
            def step(carry, xs):
                st, k = carry
                salt, shift = xs
                wave = jax.tree.map(
                    lambda x: jnp.roll(x, shift, axis=0), reqs)
                wave = wave.replace(chunk_hashes=wave.chunk_hashes ^ salt)
                k, sub = jax.random.split(k)
                result, st = cycle_fn(st, wave, eps, weights, sub, None)
                return (st, k), result.indices[:, 0]

            (state, key), primaries = jax.lax.scan(
                step, (state, key), (salts, shifts))
            return state, key, primaries[-1]

        return jax.jit(window, donate_argnums=(0,))

    fns = {L: make_window(cycle, L, 0) for L in (CHAIN_SHORT, CHAIN_LONG)}

    weights = Weights.default()
    key = jax.random.PRNGKey(0)
    reqs = jax.device_put(reqs)
    eps = jax.device_put(eps)

    med = {}
    state = SchedState.init(m=m)
    for L in (CHAIN_SHORT, CHAIN_LONG):
        f = fns[L]
        t0 = time.perf_counter()
        state, key, last = f(state, key, reqs, eps, weights)
        jax.block_until_ready(last)
        _log(f"compile+first window (chain={L}): "
             f"{time.perf_counter()-t0:.2f}s")
        # Settle window (allocator steady state).
        state, key, last = f(state, key, reqs, eps, weights)
        jax.block_until_ready(last)

    def make_rep(f):
        def rep():
            nonlocal state, key
            out = None
            for _ in range(PIPELINE):
                state, key, out = f(state, key, reqs, eps, weights)
            return out
        return rep

    for L in (CHAIN_SHORT, CHAIN_LONG):
        med[L], _ = _timed_reps(make_rep(fns[L]), REPS, jax.block_until_ready)

    bulk_us = med[CHAIN_LONG] / (PIPELINE * CHAIN_LONG) * 1e6
    short_us = med[CHAIN_SHORT] / (PIPELINE * CHAIN_SHORT) * 1e6
    slope_us = (
        (med[CHAIN_LONG] - med[CHAIN_SHORT])
        / (PIPELINE * (CHAIN_LONG - CHAIN_SHORT))
        * 1e6
    )
    # Degraded-relay guard: a flat-time window makes the slope ~0; never
    # report the optimistic branch.
    if slope_us < 0.25 * bulk_us:
        _log(
            f"WARNING: slope {slope_us:.1f}us < 25% of bulk {bulk_us:.1f}us "
            "— relay timing looks flat/degraded; reporting the bulk "
            "per-cycle number (conservative)"
        )
        p50 = bulk_us
        method = "bulk"
    else:
        p50 = slope_us
        method = "slope"

    per_req_us = p50 / n
    target_us = 50.0                # north-star batch target (BASELINE.md)
    baseline_per_req_us = 10_000.0  # reference O(10 ms)/request goal
    vs = target_us / p50

    _log(
        f"p50={p50:.1f}us [{method}] slope={slope_us:.1f}us "
        f"bulk={bulk_us:.1f}us short-chain={short_us:.1f}us "
        f"(chains={CHAIN_SHORT}/{CHAIN_LONG} pipeline={PIPELINE} "
        f"reps={REPS} m_bucket={m}) "
        f"calibration={'ok' if calib_ok else 'IMPLAUSIBLE'} "
        f"({calib_tflops:.0f} TFLOP/s) "
        f"per-request={per_req_us:.3f}us target<=50us/batch "
        f"picks/s={n/(p50/1e6):.0f} "
        f"vs-reference-per-request={baseline_per_req_us/per_req_us:.0f}x"
    )
    # The headline is EMITTED before any optional diagnostics below: the
    # relay's documented failure mode is a hang (not an exception), and a
    # hang inside a post-headline diagnostic must not cost the capture.
    print(
        json.dumps(
            {
                "metric": "pick_p50_us_1024x256",
                "value": round(p50, 1),
                "unit": "us",
                "vs_baseline": round(vs, 1),
                # "cpu-fallback" = the TPU relay never came up and this
                # number ran on the host backend: a trajectory marker,
                # not comparable against the 50 us target.
                "backend": backend,
            }
        ),
        flush=True,
    )

    # Diagnostic stage split (stderr only; guarded — must never break the
    # headline): the same chained measurement with the prefix column off.
    # The delta attributes the prefix gather/scatter share of the cycle on
    # REAL hardware, the one stage whose TPU lowering cost the CPU-side
    # model can't predict (scatter serialization) — round-5 bisect data.
    try:
        np_cycle = functools.partial(
            scheduling_cycle, cfg=ProfileConfig(enable_prefix=False),
            predictor_fn=None)
        np_fn = make_window(np_cycle, CHAIN_LONG, seed=5)
        np_state = SchedState.init(m=m)
        np_key = jax.random.PRNGKey(2)
        np_state, np_key, last = np_fn(np_state, np_key, reqs, eps, weights)
        jax.block_until_ready(last)

        def np_rep():
            nonlocal np_state, np_key
            out = None
            for _ in range(PIPELINE):
                np_state, np_key, out = np_fn(
                    np_state, np_key, reqs, eps, weights)
            return out

        np_med, _ = _timed_reps(
            np_rep, max(REPS // 2, 2), jax.block_until_ready)
        np_us = np_med / (PIPELINE * CHAIN_LONG) * 1e6
        _log(
            f"stage split: no-prefix bulk={np_us:.1f}us/cycle vs full "
            f"{bulk_us:.1f}us -> prefix path ~{bulk_us - np_us:.1f}us"
        )
    except Exception as e:  # diagnostics only
        _log(f"stage split skipped: {type(e).__name__}: {e}")

    # Host-path pipeline detail (ISSUE 1; stderr only, guarded): the
    # vectorized wave-assembly cost and the implied dispatch/compute
    # overlap. With the two-stage collector, steady-state device
    # occupancy = cycle / max(assembly, cycle): occupancy 1.0 means the
    # host keeps the TPU fed; < 1.0 means assembly is the bottleneck.
    try:
        from types import SimpleNamespace

        from gie_tpu.extproc.server import PickRequest
        from gie_tpu.sched.batching import _Pending, assemble_wave
        from gie_tpu.utils.lora import LoraRegistry

        cands = [SimpleNamespace(slot=j) for j in range(m)]
        items = [
            _Pending(
                PickRequest(
                    headers={}, body=prompts[i],
                    model="adapter-%d" % (i % 12) if i % 3 else "",
                    decode_tokens=float(i % 200),
                ),
                cands,
            )
            for i in range(n)
        ]
        reg = LoraRegistry()
        assemble_wave(items, m, reg)  # warm numpy/jax dispatch paths
        asm = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            assemble_wave(items, m, reg)
            asm.append(time.perf_counter() - t0)
        host_assembly_us = float(np.percentile(np.asarray(asm) * 1e6, 50))
        pipeline_occupancy = min(1.0, p50 / max(host_assembly_us, 1e-9))
        _log(
            f"pipeline: host_assembly_us={host_assembly_us:.1f} "
            f"pipeline_occupancy={pipeline_occupancy:.2f} "
            f"(assembly of a {n}x{m} wave vs the {p50:.1f}us cycle; "
            "occupancy = device-busy fraction when the two-stage collector "
            "overlaps assembly with the cycle, docs/PIPELINE.md)"
        )
    except Exception as e:  # diagnostics only
        _log(f"pipeline detail skipped: {type(e).__name__}: {e}")

    # Synchronous single-cycle round trip (includes host<->device latency +
    # tunnel RTT) — context only.
    try:
        single = jax.jit(cycle, donate_argnums=(0,))
        s_state = SchedState.init(m=m)
        result, s_state = single(s_state, reqs, eps, weights, key, None)
        jax.block_until_ready(result.indices)
        sync = []
        for _ in range(30):
            t0 = time.perf_counter()
            result, s_state = single(s_state, reqs, eps, weights, key, None)
            jax.block_until_ready(result.indices)
            sync.append(time.perf_counter() - t0)
        sync_p50 = float(np.percentile(np.asarray(sync) * 1e6, 50))
        _log(f"sync_roundtrip_p50={sync_p50:.1f}us (host<->device per dispatch)")
    except Exception as e:  # diagnostics only
        _log(f"sync roundtrip skipped: {type(e).__name__}: {e}")


def _parse_args(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="gie-tpu pick-latency benchmark. With no flags, the "
        "classic single-device 1024x256 headline capture runs; "
        "--mesh-sizes switches to the gie-mesh sweep mode (docs/MESH.md).")
    p.add_argument(
        "--mesh-sizes", default="",
        help="comma list of mesh device counts (e.g. 1,2,4,8): run the "
        "dp x tp sharded-cycle sweep instead of the headline capture")
    p.add_argument(
        "--mesh-m", default="1024,4096,8192",
        help="comma list of endpoint-axis widths for the mesh sweep")
    p.add_argument(
        "--mesh-n", type=int, default=0,
        help="request-axis width for the mesh sweep (0 = 1024, or 256 "
        "on the CPU fallback)")
    p.add_argument(
        "--mesh-pickers", default="topk",
        help="comma list of pickers to sweep (topk and/or sinkhorn)")
    p.add_argument(
        "--fleet-m", default="",
        help="comma list of FLEET widths (e.g. 65536,262144): run the "
        "gie-fleet hierarchical two-level sweep (docs/FLEET.md) instead "
        "of the headline capture")
    p.add_argument(
        "--fleet-topk", type=int, default=4,
        help="coarse-stage candidate cells per wave (fleet sweep)")
    p.add_argument(
        "--fleet-cell-cap", type=int, default=256,
        help="endpoints per cell (fleet sweep; multiple of 32)")
    p.add_argument(
        "--fleet-n", type=int, default=0,
        help="request-axis width for the fleet sweep (0 = 256 on the "
        "CPU fallback, 1024 otherwise)")
    return p.parse_args(argv)


def fleet_sweep(args) -> None:
    """gie-fleet scaling sweep (docs/FLEET.md): pick latency of the
    hierarchical two-level cycle — coarse cell stage over the WHOLE
    fleet, dense chain over the gathered top-K candidate block — at
    fleet widths far past M_MAX (65k, 262k endpoints), per wave of N
    requests. Emits one JSON record per width with the compression
    ratio (dense-stage fraction of the fleet) and the same backend
    tagging as every capture; on the CPU fallback the number is a
    tagged trajectory marker (BENCH_r09), not a TPU target check —
    the scaling SHAPE (cost ~ cells + K*cell_cap, not M) is the
    claim, and the bitwise parity property is pinned separately by
    tests/test_fleet.py.
    """
    widths = [int(s) for s in args.fleet_m.split(",") if s]
    backend = _wait_for_backend()
    _in_process_watchdog()
    _preflight()
    _apply_platform_override()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gie_tpu.fleet import FleetPicker
    from gie_tpu.fleet.picker import fleet_cycle
    from gie_tpu.sched.profile import ProfileConfig
    from gie_tpu.sched.types import Weights, chunk_bucket_for
    from gie_tpu.utils.testing import make_endpoints, make_requests

    cpu = jax.devices()[0].platform == "cpu"
    tag = "cpu-fallback" if cpu else backend
    n = args.fleet_n or (256 if cpu else 1024)
    chain, pipeline, reps = (4, 1, 3) if cpu else (32, 4, 10)
    topk, cell_cap = args.fleet_topk, args.fleet_cell_cap
    _log(f"fleet sweep: m={widths} topk={topk} cell_cap={cell_cap} n={n} "
         f"chain={chain} reps={reps} backend={tag}")

    # The picker is the state factory + ratio oracle; the measured cycle
    # is its jitted fleet_cycle, chained exactly like the headline scan.
    picker = FleetPicker(
        ProfileConfig(), topk=topk, cell_cap=cell_cap)
    cfg = ProfileConfig()
    cycle = functools.partial(
        fleet_cycle, cfg=cfg, predictor_fn=None,
        cell_cap=cell_cap, topk=topk)

    rng = np.random.default_rng(0)
    weights = Weights.default()
    for m in widths:
        if m % cell_cap:
            _log(f"m={m}: not a multiple of cell_cap={cell_cap} — skipped")
            continue
        eps = make_endpoints(
            m,
            queue=rng.integers(0, 50, m).tolist(),
            kv=rng.uniform(0, 0.95, m).tolist(),
            max_lora=8,
            m_slots=m,
        )
        base = b"SYSTEM: You are a helpful assistant for task %d. "
        prompts = [(base % (i % 16)) * 6 + b"user question %d" % i
                   for i in range(n)]
        reqs = make_requests(
            n, prompts=prompts,
            lora_id=(rng.integers(-1, 12, n)).tolist(), m_slots=m)
        cb = chunk_bucket_for(int(np.asarray(reqs.n_chunks).max()))
        reqs = reqs.replace(chunk_hashes=reqs.chunk_hashes[:, :cb])
        salts = jnp.asarray(rng.integers(
            1, 2**32, chain, dtype=np.uint64).astype(np.uint32))
        shifts = jnp.asarray(
            ((17 * np.arange(1, chain + 1) + 3) % n).astype(np.int32))

        def window(state, key, reqs, eps, weights):
            def step(carry, xs):
                st, k = carry
                salt, shift = xs
                wave = jax.tree.map(
                    lambda x: jnp.roll(x, shift, axis=0), reqs)
                wave = wave.replace(chunk_hashes=wave.chunk_hashes ^ salt)
                k, sub = jax.random.split(k)
                result, st = cycle(st, wave, eps, weights, sub, None)
                return (st, k), result.indices[:, 0]

            (state, key), primaries = jax.lax.scan(
                step, (state, key), (salts, shifts))
            return state, key, primaries[-1]

        fn = jax.jit(window, donate_argnums=(0,))
        state = picker._init_state(m)
        key = jax.random.PRNGKey(0)
        t0 = time.perf_counter()
        state, key, last = fn(state, key, reqs, eps, weights)
        jax.block_until_ready(last)
        _log(f"m={m}: compile+first {time.perf_counter()-t0:.2f}s "
             f"(cells={m // cell_cap} dense block={topk * cell_cap})")
        state, key, last = fn(state, key, reqs, eps, weights)
        jax.block_until_ready(last)

        def rep():
            nonlocal state, key
            out = None
            for _ in range(pipeline):
                state, key, out = fn(state, key, reqs, eps, weights)
            return out

        med, _ = _timed_reps(rep, reps, jax.block_until_ready)
        p50 = med / (pipeline * chain) * 1e6
        rec = {
            "metric": f"fleet_pick_p50_us_{n}x{m}",
            "value": round(p50, 1),
            "unit": "us",
            "m": m,
            "n": n,
            "fleet_topk": topk,
            "fleet_cell_cap": cell_cap,
            "cells": m // cell_cap,
            # Dense-stage fraction of the fleet: the two-level cycle
            # scores topk*cell_cap endpoints where the flat cycle would
            # score (an impossible) M.
            "compression_ratio": round(picker.compression_ratio(m), 6),
            "mode": "sketch" if m > 1024 else "exact",
            "method": "bulk",
            "chain": chain,
            "reps": reps,
            "backend": tag,
        }
        print(json.dumps(rec), flush=True)
    _log("fleet sweep complete")


def mesh_sweep(args) -> None:
    """gie-mesh scaling sweep: pick latency of the dp x tp sharded cycle
    per (mesh size, M width, picker), each against the same-run
    single-device baseline — the "scheduler scales with chips" trajectory
    (ISSUE 15). Emits one JSON record line per combo with the same
    backend tagging as the headline capture; BENCH_r02's real-TPU
    single-device point (p50 76 us at 1024x256) is stamped into every
    record for cross-capture context.

    On the CPU fallback the "mesh" is XLA's virtual host-device grid —
    all shards share one physical CPU, so per-mesh numbers are a
    methodology/trajectory marker (tagged, like every cpu-fallback
    record), not a scaling measurement; the scaling PROPERTY is pinned
    separately by tests/test_distributed_equivalence.py.
    """
    sizes = [int(s) for s in args.mesh_sizes.split(",") if s]
    widths = [int(s) for s in args.mesh_m.split(",") if s]
    pickers = [s.strip() for s in args.mesh_pickers.split(",") if s.strip()]

    # The virtual CPU mesh needs the host-platform device count forced
    # BEFORE jax initializes (same lever as __graft_entry__): harmless on
    # a real TPU platform (the flag only affects the host backend).
    import re

    need = max(sizes)
    flags = os.environ.get("XLA_FLAGS", "")
    mobj = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if mobj is None:
        flags = (
            flags + f" --xla_force_host_platform_device_count={need}"
        ).strip()
    elif int(mobj.group(1)) < need:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            f"--xla_force_host_platform_device_count={need}", flags)
    os.environ["XLA_FLAGS"] = flags

    backend = _wait_for_backend()
    _in_process_watchdog()
    _preflight()
    _apply_platform_override()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gie_tpu.parallel.mesh import cycle_shardings, make_mesh
    from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
    from gie_tpu.sched.types import SchedState, Weights, chunk_bucket_for
    from gie_tpu.utils.testing import make_endpoints, make_requests
    from jax.sharding import NamedSharding, PartitionSpec as P

    cpu = jax.devices()[0].platform == "cpu"
    tag = "cpu-fallback" if cpu else backend
    n = args.mesh_n or (256 if cpu else 1024)
    chain, pipeline, reps = (4, 1, 3) if cpu else (32, 4, 10)
    have = len(jax.devices())
    _log(f"mesh sweep: sizes={sizes} m={widths} pickers={pickers} n={n} "
         f"chain={chain} reps={reps} backend={tag} devices={have}")

    rng = np.random.default_rng(0)
    records = []
    for m in widths:
        eps = make_endpoints(
            m,
            queue=rng.integers(0, 50, m).tolist(),
            kv=rng.uniform(0, 0.95, m).tolist(),
            max_lora=8,
            m_slots=m,
        )
        base = b"SYSTEM: You are a helpful assistant specialised in task %d. "
        prompts = [(base % (i % 16)) * 6 + b"user question %d" % i
                   for i in range(n)]
        reqs = make_requests(
            n, prompts=prompts,
            lora_id=(rng.integers(-1, 12, n)).tolist(), m_slots=m)
        cb = chunk_bucket_for(int(np.asarray(reqs.n_chunks).max()))
        reqs = reqs.replace(chunk_hashes=reqs.chunk_hashes[:, :cb])
        salts = jnp.asarray(rng.integers(
            1, 2**32, chain, dtype=np.uint64).astype(np.uint32))
        shifts = jnp.asarray(
            ((17 * np.arange(1, chain + 1) + 3) % n).astype(np.int32))
        weights = Weights.default()

        baseline_us: dict[str, float] = {}
        for s in sizes:
            if s > have:
                _log(f"mesh={s}: only {have} device(s) — skipped")
                continue
            mesh = make_mesh(s)
            st_sh, req_sh, eps_sh, w_sh, key_sh = cycle_shardings(mesh)
            for picker in pickers:
                cfg = (ProfileConfig() if picker == "topk"
                       else ProfileConfig(picker=picker))
                cycle = functools.partial(
                    scheduling_cycle, cfg=cfg, predictor_fn=None, mesh=mesh)

                def window(state, key, reqs, eps, weights):
                    def step(carry, xs):
                        st, k = carry
                        salt, shift = xs
                        wave = jax.tree.map(
                            lambda x: jnp.roll(x, shift, axis=0), reqs)
                        wave = wave.replace(
                            chunk_hashes=wave.chunk_hashes ^ salt)
                        k, sub = jax.random.split(k)
                        result, st = cycle(st, wave, eps, weights, sub, None)
                        return (st, k), result.indices[:, 0]

                    (state, key), primaries = jax.lax.scan(
                        step, (state, key), (salts, shifts))
                    return state, key, primaries[-1]

                fn = jax.jit(
                    window,
                    in_shardings=(st_sh, key_sh, req_sh, eps_sh, w_sh),
                    donate_argnums=(0,),
                )
                state = SchedState.init(m=m)
                key = jax.random.PRNGKey(0)
                t0 = time.perf_counter()
                state, key, last = fn(state, key, reqs, eps, weights)
                jax.block_until_ready(last)
                _log(f"m={m} mesh={s} picker={picker}: compile+first "
                     f"{time.perf_counter()-t0:.2f}s "
                     f"(dp={mesh.shape['dp']} tp={mesh.shape['tp']})")
                state, key, last = fn(state, key, reqs, eps, weights)
                jax.block_until_ready(last)

                def rep():
                    nonlocal state, key
                    out = None
                    for _ in range(pipeline):
                        state, key, out = fn(state, key, reqs, eps, weights)
                    return out

                med, _ = _timed_reps(rep, reps, jax.block_until_ready)
                p50 = med / (pipeline * chain) * 1e6
                # Only a true single-device run is the baseline: with
                # sizes like "8,4" (or a skipped first size) every other
                # choice would compare configs against themselves and
                # ship fabricated speedups into the trajectory.
                if s == 1:
                    baseline_us[picker] = p50
                base = baseline_us.get(picker)
                rec = {
                    "metric": f"mesh_pick_p50_us_{n}x{m}",
                    "value": round(p50, 1),
                    "unit": "us",
                    "mesh_devices": s,
                    "dp": int(mesh.shape["dp"]),
                    "tp": int(mesh.shape["tp"]),
                    "m": m,
                    "n": n,
                    "picker": picker,
                    "method": "bulk",
                    "chain": chain,
                    "reps": reps,
                    "backend": tag,
                    "virtual_devices": cpu,
                    # null when no single-device run is in this sweep.
                    "baseline_single_us": (
                        round(base, 1) if base is not None else None),
                    "speedup_vs_single": (
                        round(base / p50, 2) if base is not None else None),
                    # Cross-capture context: the one successful real-TPU
                    # single-device point (BENCH_r02, default profile).
                    "bench_r02_single_device_us_1024x256": 76.2,
                }
                records.append(rec)
                print(json.dumps(rec), flush=True)
    _log(f"mesh sweep complete: {len(records)} records")


if __name__ == "__main__":
    _ARGS = _parse_args()
    if _ARGS.fleet_m:
        fleet_sweep(_ARGS)
    elif _ARGS.mesh_sizes:
        mesh_sweep(_ARGS)
    else:
        main()
