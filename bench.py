"""Headline benchmark: batched endpoint-pick latency on TPU.

Measures the full scheduling cycle (filters -> queue/kv/lora/prefix/
assumed-load scorer blend -> top-k pick -> prefix + load state update) for
the north-star shape: 1024 pending requests x 256 live endpoints
(BASELINE.md: target <= 50 us p50 per batch; reference comparison point is
the CPU EPP's O(10 ms)-per-request scheduler budget,
reference docs/proposals/006-scheduler/README.md:43).

Methodology (round 3): the measured quantity is DEVICE time per cycle, made
robust to host contention. Each dispatch runs a chain of CHAIN_LEN cycles
inside one XLA program (`jax.lax.scan` over the scheduling cycle, state
donated and carried on device), so one host dispatch amortizes over
CHAIN_LEN cycles; windows are kept PIPELINE deep in flight so the
host<->device round trip (axon tunnel, ~ms under load) overlaps device
compute instead of appearing in the measurement. Earlier rounds dispatched
each cycle from the host and the driver capture inflated 38 us of device
work to 76 us under a concurrent process (BENCH_r02.json vs
docs/BENCH_NOTES.md); with the chain, a contended host delays only the
enqueue of the next window, which is hidden while the device still has
PIPELINE-1 windows of queued work.

Honesty guard: the scan iterates over CHAIN_LEN DISTINCT request waves
(stacked as the scan xs), not one wave reused — with a constant wave, XLA's
loop-invariant code motion hoists nearly the whole scoring pipeline out of
the loop and the "per-cycle" number collapses to the state-update tail
(~0.4 us — measured, and rejected, while building this). Endpoint metrics
stay constant across the chain, which matches production: waves arrive
every few ms while metrics refresh at scrape cadence.

Prints ONE JSON line:
  metric       pick_p50_us_1024x256 — p50 per-cycle latency across
               measurement repetitions (each rep = PIPELINE windows x
               CHAIN_LEN chained cycles, timed end-to-end and divided by
               the cycle count)
  vs_baseline  north-star target (50 us per 1024x256 batch, BASELINE.md)
               divided by our p50: >= 1.0 means the target is met. (The
               reference's own stated budget is O(10 ms) PER REQUEST on a
               CPU EPP — ~240,000x slower per decision; stderr reports it.)
Extra detail goes to stderr.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import numpy as np


def _device_watchdog(timeout_s: float = 180.0):
    """Fail fast when the TPU backend is unreachable.

    The axon tunnel dials a local relay; if the relay is down,
    jax.devices() blocks forever — far worse for the driver than a clean
    nonzero exit. Probe device init in a daemon thread and bail with
    diagnostics if it does not come up in time.
    """
    import threading

    result: list = []

    def probe() -> None:
        try:
            result.append(jax.devices())
        except Exception as e:  # surfaced below
            result.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if not result:
        print(
            f"FATAL: JAX backend failed to initialize within {timeout_s:.0f}s "
            "(axon relay unreachable?) — aborting instead of hanging",
            file=sys.stderr,
        )
        os._exit(3)
    if isinstance(result[0], Exception):
        print(f"FATAL: JAX backend init failed: {result[0]}", file=sys.stderr)
        os._exit(3)


def _preflight(n_probe: int = 5) -> None:
    """Report host conditions so a contended capture is diagnosable.

    The chained measurement is designed to survive contention, but the
    1-min loadavg and a quick host-timer jitter probe make the conditions
    of THIS capture part of the record.
    """
    try:
        load1, load5, _ = os.getloadavg()
    except OSError:  # pragma: no cover - platform without getloadavg
        load1 = load5 = float("nan")
    samples = []
    for _ in range(n_probe):
        t0 = time.perf_counter()
        time.sleep(0.001)
        samples.append(time.perf_counter() - t0 - 0.001)
    jitter_us = max(samples) * 1e6
    ncpu = os.cpu_count() or 1
    print(
        f"preflight: loadavg1={load1:.2f} loadavg5={load5:.2f} ncpu={ncpu} "
        f"sleep-jitter={jitter_us:.0f}us "
        f"{'(host contended)' if load1 > ncpu * 0.5 else '(host quiet)'}",
        file=sys.stderr,
    )


def main() -> None:
    import jax.numpy as jnp

    _device_watchdog()
    _preflight()

    from gie_tpu.sched import constants as C  # noqa: F401 (shape doc)
    from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
    from gie_tpu.sched.types import SchedState, Weights
    from gie_tpu.utils.testing import make_endpoints, make_requests

    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)

    n, m = 1024, 256
    rng = np.random.default_rng(0)
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 50, m).tolist(),
        kv=rng.uniform(0, 0.95, m).tolist(),
        max_lora=8,
    )
    # Realistic mixed traffic: shared system prompts (prefix hits), LoRA ids.
    base = b"SYSTEM: You are a helpful assistant specialised in task %d. "
    prompts = [(base % (i % 16)) * 6 + b"user question %d" % i for i in range(n)]
    reqs = make_requests(
        n,
        prompts=prompts,
        lora_id=(rng.integers(-1, 12, n)).tolist(),
    )
    cfg = ProfileConfig()
    cycle = functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None)

    CHAIN_LEN = 64    # distinct request waves fused into one dispatch
    PIPELINE = 4      # windows kept in flight per timed repetition
    REPS = 30         # timed repetitions (p50/p99 across these)

    # CHAIN_LEN distinct waves, stacked on a leading axis for lax.scan.
    # Derived from the base wave by a per-wave row rotation + a per-wave
    # hash salt: every wave keeps the realistic 16-system-prompt sharing
    # structure, but no array is equal across iterations, so XLA cannot
    # hoist any request-dependent stage out of the loop.
    salts = rng.integers(1, 2**32, CHAIN_LEN, dtype=np.uint64).astype(np.uint32)

    def stack_waves(x, *, hash_salt=False):
        x = np.asarray(x)
        rolled = np.stack(
            [np.roll(x, 17 * w, axis=0) for w in range(CHAIN_LEN)]
        )
        if hash_salt:
            rolled = rolled ^ salts.reshape(-1, *([1] * x.ndim))
        return rolled

    waves = jax.tree.map(stack_waves, reqs)
    waves = waves.replace(
        chunk_hashes=jnp.asarray(
            stack_waves(reqs.chunk_hashes, hash_salt=True)
        )
    )

    def window(state, key, waves, eps, weights):
        """CHAIN_LEN scheduling cycles as ONE device program.

        The production scheduler streams waves back-to-back without a host
        sync per cycle; the scan reproduces that steady state exactly (the
        state pytree — prefix index, assumed load, rr, tick — is the scan
        carry, so every cycle sees its predecessor's updates, same as the
        per-dispatch path), with a fresh request wave per cycle.
        """

        def step(carry, wave):
            st, k = carry
            k, sub = jax.random.split(k)
            result, st = cycle(st, wave, eps, weights, sub, None)
            return (st, k), result.indices[:, 0]

        (state, key), primaries = jax.lax.scan(step, (state, key), waves)
        return state, key, primaries[-1]

    win_fn = jax.jit(window, donate_argnums=(0,))

    state = SchedState.init()
    weights = Weights.default()
    key = jax.random.PRNGKey(0)
    waves = jax.device_put(waves)
    eps = jax.device_put(eps)

    # Warm-up / compile.
    t0 = time.perf_counter()
    state, key, last = win_fn(state, key, waves, eps, weights)
    jax.block_until_ready(last)
    print(f"compile+first window: {time.perf_counter()-t0:.2f}s", file=sys.stderr)

    # One more settle window (cache/allocator steady state).
    state, key, last = win_fn(state, key, waves, eps, weights)
    jax.block_until_ready(last)

    # Timed repetitions: each rep enqueues PIPELINE windows asynchronously
    # and blocks once at the end. Per-cycle time = rep wall time /
    # (PIPELINE*CHAIN_LEN). Host stalls during a rep only delay enqueues,
    # which the device rides out on its queued windows.
    rep_us = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(PIPELINE):
            state, key, last = win_fn(state, key, waves, eps, weights)
        jax.block_until_ready(last)
        rep_us.append(
            (time.perf_counter() - t0) / (PIPELINE * CHAIN_LEN) * 1e6
        )
    rep_us_arr = np.asarray(rep_us)
    p50 = float(np.percentile(rep_us_arr, 50))
    p99 = float(np.percentile(rep_us_arr, 99))
    best = float(rep_us_arr.min())

    # Synchronous single-cycle round trip (includes host<->device latency +
    # tunnel RTT) — context only, not the headline.
    single = jax.jit(cycle, donate_argnums=(0,))
    s_state = SchedState.init()
    result, s_state = single(s_state, reqs, eps, weights, key, None)
    jax.block_until_ready(result.indices)
    sync = []
    for _ in range(30):
        t0 = time.perf_counter()
        result, s_state = single(s_state, reqs, eps, weights, key, None)
        jax.block_until_ready(result.indices)
        sync.append(time.perf_counter() - t0)
    sync_p50 = float(np.percentile(np.asarray(sync) * 1e6, 50))

    per_req_us = p50 / n
    target_us = 50.0                # north-star batch target (BASELINE.md)
    baseline_per_req_us = 10_000.0  # reference O(10 ms)/request goal
    vs = target_us / p50

    print(
        f"p50={p50:.1f}us p99={p99:.1f}us best={best:.1f}us "
        f"sync_roundtrip_p50={sync_p50:.1f}us "
        f"(chain={CHAIN_LEN} pipeline={PIPELINE} reps={REPS}) "
        f"per-request={per_req_us:.3f}us target<=50us/batch "
        f"picks/s={n/(p50/1e6):.0f} "
        f"vs-reference-per-request={baseline_per_req_us/per_req_us:.0f}x",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "pick_p50_us_1024x256",
                "value": round(p50, 1),
                "unit": "us",
                "vs_baseline": round(vs, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
