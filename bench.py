"""Headline benchmark: batched endpoint-pick latency on TPU.

Measures the full scheduling cycle (filters -> queue/kv/lora/prefix/
assumed-load scorer blend -> top-k pick -> prefix + load state update) for
the north-star shape: 1024 pending requests x 256 live endpoints
(BASELINE.md: target <= 50 us p50 per batch; reference comparison point is
the CPU EPP's O(10 ms)-per-request scheduler budget,
reference docs/proposals/006-scheduler/README.md:43).

Prints ONE JSON line:
  metric       pick_p50_us_1024x256 — p50 per-batch latency in the
               pipelined steady state (state donated on device; the host
               does not sync each cycle, matching production operation)
  vs_baseline  north-star target (50 us per 1024x256 batch, BASELINE.md)
               divided by our p50: >= 1.0 means the target is met. (The
               reference's own stated budget is O(10 ms) PER REQUEST on a
               CPU EPP — ~240,000x slower per decision; stderr reports it.)
Extra detail goes to stderr.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import numpy as np


def _device_watchdog(timeout_s: float = 180.0):
    """Fail fast when the TPU backend is unreachable.

    The axon tunnel dials a local relay; if the relay is down,
    jax.devices() blocks forever — far worse for the driver than a clean
    nonzero exit. Probe device init in a daemon thread and bail with
    diagnostics if it does not come up in time.
    """
    import threading

    result: list = []

    def probe() -> None:
        try:
            result.append(jax.devices())
        except Exception as e:  # surfaced below
            result.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if not result:
        print(
            f"FATAL: JAX backend failed to initialize within {timeout_s:.0f}s "
            "(axon relay unreachable?) — aborting instead of hanging",
            file=sys.stderr,
        )
        os._exit(3)
    if isinstance(result[0], Exception):
        print(f"FATAL: JAX backend init failed: {result[0]}", file=sys.stderr)
        os._exit(3)


def main() -> None:
    import jax.numpy as jnp

    _device_watchdog()

    from gie_tpu.sched import constants as C
    from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
    from gie_tpu.sched.types import SchedState, Weights
    from gie_tpu.utils.testing import make_endpoints, make_requests

    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)

    n, m = 1024, 256
    rng = np.random.default_rng(0)
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 50, m).tolist(),
        kv=rng.uniform(0, 0.95, m).tolist(),
        max_lora=8,
    )
    # Realistic mixed traffic: shared system prompts (prefix hits), LoRA ids.
    base = b"SYSTEM: You are a helpful assistant specialised in task %d. "
    prompts = [(base % (i % 16)) * 6 + b"user question %d" % i for i in range(n)]
    reqs = make_requests(
        n,
        prompts=prompts,
        lora_id=(rng.integers(-1, 12, n)).tolist(),
    )
    cfg = ProfileConfig()
    fn = jax.jit(
        functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=None),
        donate_argnums=0,
    )

    state = SchedState.init()
    weights = Weights.default()
    key = jax.random.PRNGKey(0)
    reqs = jax.device_put(reqs)
    eps = jax.device_put(eps)

    # Warm-up / compile.
    t0 = time.perf_counter()
    result, state = fn(state, reqs, eps, weights, key, None)
    jax.block_until_ready(result.indices)
    print(f"compile+first: {time.perf_counter()-t0:.2f}s", file=sys.stderr)

    # Steady state, pipelined: the scheduler never host-syncs per cycle in
    # production (results stream back asynchronously while the next wave
    # dispatches), so the honest per-batch latency is the amortized cost of
    # a pipelined window. p50 over many windows suppresses tunnel jitter.
    windows, per_window = 20, 50
    window_us = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(per_window):
            result, state = fn(state, reqs, eps, weights, key, None)
        jax.block_until_ready(result.indices)
        window_us.append((time.perf_counter() - t0) / per_window * 1e6)
    p50 = float(np.percentile(window_us, 50))
    p99 = float(np.percentile(window_us, 99))

    # Synchronous single-cycle round trip (includes host<->device latency).
    sync = []
    for _ in range(50):
        t0 = time.perf_counter()
        result, state = fn(state, reqs, eps, weights, key, None)
        jax.block_until_ready(result.indices)
        sync.append(time.perf_counter() - t0)
    amortized_us = float(np.percentile(np.asarray(sync) * 1e6, 50))

    per_req_us = p50 / n
    target_us = 50.0                # north-star batch target (BASELINE.md)
    baseline_per_req_us = 10_000.0  # reference O(10 ms)/request goal
    vs = target_us / p50

    print(
        f"p50={p50:.1f}us p99={p99:.1f}us sync_p50={amortized_us:.1f}us "
        f"per-request={per_req_us:.3f}us target<=50us/batch "
        f"picks/s={n/(p50/1e6):.0f} "
        f"vs-reference-per-request={baseline_per_req_us/per_req_us:.0f}x",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "pick_p50_us_1024x256",
                "value": round(p50, 1),
                "unit": "us",
                "vs_baseline": round(vs, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
