import time, sys
t00 = time.time()
def log(msg):
    print(f"[{time.time()-t00:6.1f}s] {msg}", file=sys.stderr, flush=True)
import jax, jax.numpy as jnp, numpy as np
from gie_tpu.sched import constants as C
from gie_tpu.sched import filters, scorers
from gie_tpu.sched.types import Weights
from gie_tpu.utils.testing import make_endpoints, make_requests
log("imports done")
n, m = 1024, 256
rng = np.random.default_rng(0)
eps = make_endpoints(m, queue=rng.integers(0, 50, m).tolist(), kv=rng.uniform(0, 0.95, m).tolist(), max_lora=8)
base = b"SYSTEM: You are a helpful assistant specialised in task %d. "
prompts = [(base % (i % 16)) * 6 + b"user question %d" % i for i in range(n)]
reqs = make_requests(n, prompts=prompts, lora_id=(rng.integers(-1, 12, n)).tolist())
log("requests made")
K = 64
def stack_waves(x):
    x = np.asarray(x)
    return np.stack([np.roll(x, 17 * w, axis=0) for w in range(K)])
waves = jax.tree.map(stack_waves, reqs)
log("waves stacked (host)")
waves = jax.device_put(waves)
jax.block_until_ready(waves.valid)
log("waves on device")
eps = jax.device_put(eps)
weights = Weights.default()

def l1_win(load, rr, waves):
    def step(carry, wave):
        load, rr = carry
        mask = filters.base_mask(wave, eps)
        named = {
            "queue": jnp.broadcast_to(scorers.queue_score(eps, queue_norm=64.0)[None, :], mask.shape),
            "kv_cache": jnp.broadcast_to(scorers.kv_cache_score(eps)[None, :], mask.shape),
            "assumed_load": jnp.broadcast_to(scorers.assumed_load_score(load, load_norm=32.0)[None, :], mask.shape),
        }
        stacked = jnp.stack(list(named.values()))
        wvec = jnp.stack([getattr(weights, k) for k in named])
        total = jnp.einsum("s,snm->nm", wvec, stacked) / jnp.maximum(jnp.sum(wvec), jnp.float32(1e-6))
        masked = jnp.where(mask, total, C.NEG_SCORE)
        pick = jnp.argmax(masked, axis=-1)
        load = load * 0.95 + jnp.zeros((C.M_MAX,), jnp.float32).at[pick].add(1.0)
        return (load, rr + 1), pick
    (load, rr), outs = jax.lax.scan(step, (load, rr), waves)
    return load, rr, outs[-1]

win = jax.jit(l1_win, donate_argnums=(0,))
load = jnp.zeros((C.M_MAX,), jnp.float32); rr = jnp.uint32(0)
log("compiling...")
load, rr, o = win(load, rr, waves); jax.block_until_ready(o)
log("first window done")
for rep in range(5):
    t0 = time.perf_counter()
    load, rr, o = win(load, rr, waves)
    jax.block_until_ready(o)
    log(f"rep {rep}: {(time.perf_counter()-t0)/K*1e6:.1f}us/iter")
