"""Multi-chip scheduling-cycle benchmark: dp-sharded pick latency.

Measures the SAME north-star shape as bench.py (1024 requests x 256
endpoints) through the production multi-chip path — Scheduler(mesh=...) /
the --mesh-devices flag — at every dp width the available devices allow
(1, 2, 4, 8 chips). On a real TPU pod slice this is the scaling curve of
the scheduling cycle over ICI; on a host with one chip (or CPU) it falls
back to a virtual device mesh, which validates the sharded program
end-to-end but measures host threads, not ICI — the JSON line says which.

Beyond the wall-clock curve, the harness SEPARATES compute scaling from
collective overhead (VERDICT r02 #4): it parses the compiled sharded
program for its actual collective ops (all-reduce / all-gather /
reduce-scatter / collective-permute) and their tensor sizes, then emits an
analytic ICI projection — per-chip compute = t1/dp, collective time =
ring cost of the measured collective bytes at the stated ICI bandwidth —
with the crossover dp (if any) where sharding pays on real hardware. The
emulated-CPU wall numbers validate the program; the projection is the
deployment guidance (the CPU fabric's thread overheads say nothing about
ICI).

Prints ONE JSON line:
  metric       sharded_pick_p50_us_1024x256_dp<N> at the widest mesh
  vs_baseline  single-device p50 / widest-mesh p50 (speedup; >= 1.0 means
               sharding pays at this shape)

Reference seam: the reference's EPP is single-process CPU (SURVEY.md
section 2.10 — replica-parallel only); a dp-sharded cycle has no analogue
there. This harness exists so a multi-chip deployment can verify the
sharding pays before enabling --mesh-devices.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time


def _ensure_devices(min_devices: int) -> str:
    """Pick the fabric BEFORE the JAX backend initializes (a post-init
    platform switch cannot grow the device count — round-1 lesson).

    Default: a virtual CPU mesh of `min_devices` (functional validation;
    deterministic in any container). On a real TPU pod slice run with
    GIE_MESH_FABRIC=ici to measure the actual ICI scaling curve."""
    import jax

    if os.environ.get("GIE_MESH_FABRIC", "").lower() == "ici":
        return "ici"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={min_devices}"
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    return "virtual-cpu"


_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
    "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}


def collective_bytes(hlo_text: str) -> dict[str, int]:
    """Sum the output bytes of every cross-device collective in a compiled
    HLO module, by op kind. This is the program's ACTUAL communication
    volume — not a guess — read from the same executable the bench times."""
    import re

    out: dict[str, int] = {}
    op_re = re.compile(
        r"=\s*((?:\(|)[a-z0-9]+\[[^=]*?)\s*"
        r"(all-reduce|all-gather|reduce-scatter|collective-permute)"
        r"(?:-start)?\(", )
    shape_re = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    for m in op_re.finditer(hlo_text):
        shapes, kind = m.group(1), m.group(2)
        total = 0
        for dt, dims in shape_re.findall(shapes):
            if dt not in _DTYPE_BYTES:
                continue
            size = _DTYPE_BYTES[dt]
            for d in dims.split(","):
                if d:
                    size *= int(d)
            total += size
        out[kind] = out.get(kind, 0) + total
    return out


def project_ici(t1_us: float, coll: dict[str, int], dp: int,
                ici_gbps: float) -> tuple[float, float, float]:
    """Analytic per-batch time at width dp on real ICI:
      compute = t1/dp (the cycle is embarrassingly dp-parallel over N)
      collective = ring cost 2*(dp-1)/dp * all-reduce bytes / BW
                   + (dp-1)/dp * (all-gather + reduce-scatter) bytes / BW
    Returns (compute_us, collective_us, total_us)."""
    compute = t1_us / dp
    ar = coll.get("all-reduce", 0)
    agrs = coll.get("all-gather", 0) + coll.get("reduce-scatter", 0)
    cp = coll.get("collective-permute", 0)
    bw = ici_gbps * 1e9
    coll_s = (2 * (dp - 1) / dp * ar + (dp - 1) / dp * agrs + cp) / bw
    return compute, coll_s * 1e6, compute + coll_s * 1e6


def main() -> None:
    fabric = _ensure_devices(8)
    import jax
    import numpy as np

    from gie_tpu.parallel.mesh import make_mesh
    from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle
    from gie_tpu.sched.types import SchedState, Weights
    from gie_tpu.utils.testing import make_endpoints, make_requests

    n, m = 1024, 256
    rng = np.random.default_rng(0)
    eps = make_endpoints(
        m,
        queue=rng.integers(0, 50, m).tolist(),
        kv=rng.uniform(0, 0.95, m).tolist(),
        max_lora=8,
    )
    base = b"SYSTEM: You are a helpful assistant specialised in task %d. "
    prompts = [(base % (i % 16)) * 6 + b"user question %d" % i
               for i in range(n)]
    reqs = make_requests(n, prompts=prompts,
                         lora_id=(rng.integers(-1, 12, n)).tolist())
    cfg = ProfileConfig()
    weights = Weights.default()
    key = jax.random.PRNGKey(0)

    n_dev = len(jax.devices())
    widths = [w for w in (1, 2, 4, 8) if w <= n_dev]
    results = {}
    for width in widths:
        if width == 1:
            fn = jax.jit(
                functools.partial(scheduling_cycle, cfg=cfg,
                                  predictor_fn=None),
                donate_argnums=0,
            )
        else:
            # The exact production recipe the --mesh-devices flag runs
            # (same helper, same default dp x tp split, same donation) —
            # the bench must measure the program it claims to validate.
            from gie_tpu.parallel.mesh import sharded_cycle

            fn = sharded_cycle(make_mesh(width), cfg, None,
                               donate_state=True)
        state = SchedState.init()
        result, state = fn(state, reqs, eps, weights, key, None)
        jax.block_until_ready(result.indices)
        # Same statistic as bench.py: p50 over pipelined-window means.
        windows, per_window = 10, 10
        window_us = []
        for _ in range(windows):
            t0 = time.perf_counter()
            for _ in range(per_window):
                result, state = fn(state, reqs, eps, weights, key, None)
            jax.block_until_ready(result.indices)
            window_us.append((time.perf_counter() - t0) / per_window * 1e6)
        p50 = float(np.percentile(window_us, 50))
        results[width] = p50
        print(f"dp={width}: {p50:9.1f} us/batch  [{fabric}]",
              file=sys.stderr)

    widest = max(results)
    speedup = results[1] / results[widest]
    print(json.dumps({
        "metric": f"sharded_pick_p50_us_1024x256_dp{widest}_{fabric}",
        "value": round(results[widest], 1),
        "unit": "us",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
