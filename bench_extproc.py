"""Admission-path benchmark: zero-parse fast lane vs legacy ext-proc path.

Measures the per-request EPP admission overhead (ISSUE 5, docs/EXTPROC.md)
— everything between "request fully received" and "routing decision sent":
header ingestion, body scan/parse, BBR chain, pick, and ProcessingResponse
construction — for BOTH lanes of extproc.server.StreamingServer:

  fast    --extproc-fast-lane path: native JSON field scan (jsonscan.cc),
          needed-keys header copy, pooled pre-serialized response
          templates, shared pass-through body responses.
  legacy  the seed's path: full json.loads per request, full header copy,
          per-request nested-protobuf response build.

The picker is a RoundRobinPicker so the measurement isolates admission
CPU from the TPU scheduler (bench.py owns the pick cycle; the two-stage
collector's wait would swamp microsecond-level admission costs). Streams
are in-memory (the mockProcessServer pattern of tests/test_extproc.py);
request protos are pre-built and replayed, so proto construction of the
INPUT side is excluded and both lanes see identical bytes.

Per (impl, workload) configuration, one JSON line on stdout
(bench_scrape.py record format):

  cpu_us_per_req   process CPU microseconds per request — the headline
                   "per-request admission CPU" of the issue's >=3x target.
  wall_p50_us / wall_p99_us
                   per-request wall latency distribution.
  req_per_s_core   1e6 / cpu_us_per_req: admission throughput one core
                   sustains before the EPP itself is the bottleneck.

Workloads: headers-only pick, a ~1 KiB completion body, an ~8 KiB chat
body, and the gRPC-transcoding path (h2c pool), which exercises the
at-most-once parse contract (legacy paid json.loads twice there before
this PR).

Run: `make bench-extproc` (or python bench_extproc.py [--requests N]).
Exits non-zero when the fast lane fails to beat legacy by --min-speedup
(regression guard; generous vs the >=3x CI-box headline so slow shared
runners do not flap).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

from gie_tpu.bbr.chain import ModelExtractorPlugin, PluginChain
from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.extproc import pb
from gie_tpu.extproc.server import RoundRobinPicker, StreamingServer

N_ENDPOINTS = 16

# Bench-lane backend tag (ROADMAP item 8 / make bench-cpu): the CPU
# fallback lane exports "backend":"cpu-fallback" on every JSON record —
# the same tag bench.py uses — so artifact consumers can segregate
# CPU-lane numbers from real-hardware captures and the BENCH trajectory
# never goes dark when no TPU is reachable.
_BACKEND_TAG = os.environ.get("GIE_BENCH_BACKEND", "")


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class _ReplayStream:
    """Replays pre-built request protos; drops responses (the send side is
    what the lanes differ on, so building responses stays IN the measured
    path — only retention is skipped)."""

    __slots__ = ("messages", "i", "sent_count")

    def __init__(self, messages):
        self.messages = messages
        self.i = 0
        self.sent_count = 0

    def recv(self):
        i = self.i
        if i >= len(self.messages):
            return None
        self.i = i + 1
        return self.messages[i]

    def send(self, resp) -> None:
        self.sent_count += 1


def make_datastore(grpc_pool: bool = False) -> Datastore:
    from tests.test_datastore import make_pod

    ds = Datastore()
    pool = EndpointPool(
        selector={"app": "vllm"}, target_ports=[8000], namespace="default"
    )
    if grpc_pool:
        pool.app_protocol = "kubernetes.io/h2c"
    ds.pool_set(pool)
    for i in range(N_ENDPOINTS):
        ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.0.{i}"))
    return ds


def headers_msg(end_of_stream: bool) -> pb.ProcessingRequest:
    # A realistic Envoy-mesh header set (~24 keys): the handful the pick
    # reads plus the cookies / tracing baggage / peer metadata it never
    # does (the needed-keys scan's win). x-envoy-peer-metadata really is
    # a ~1 KB base64 blob on istio-style meshes.
    hm = pb.HeaderMap()
    for k, v in (
        (":method", "POST"),
        (":scheme", "https"),
        (":path", "/v1/completions"),
        (":authority", "pool.example.svc"),
        ("content-type", "application/json"),
        ("content-length", "1024"),
        ("accept", "application/json"),
        ("accept-encoding", "gzip, br"),
        ("user-agent", "openai-python/1.40.0"),
        ("authorization", "Bearer " + "t" * 64),
        ("cookie", "session=" + "c" * 96),
        ("x-request-id", "9f1d4c3a-77aa-43f2-a1b0-2f8e6f1d9c55"),
        ("x-forwarded-for", "10.1.2.3, 10.0.0.1"),
        ("x-forwarded-proto", "https"),
        ("x-envoy-attempt-count", "1"),
        ("x-envoy-expected-rq-timeout-ms", "600000"),
        ("x-envoy-peer-metadata-id", "sidecar~10.1.2.3~gw.ns~ns.svc"),
        ("x-envoy-peer-metadata", "Q" * 800),
        ("traceparent", "00-" + "a" * 32 + "-" + "b" * 16 + "-01"),
        ("tracestate", "vendor=opaque"),
        ("x-b3-traceid", "b" * 32),
        ("x-b3-spanid", "c" * 16),
        ("baggage", "tenant=42,plan=pro"),
        ("x-gateway-inference-objective", "standard"),
        ("x-gateway-inference-fairness-id", "tenant-42"),
    ):
        hm.headers.append(pb.HeaderValue(key=k, raw_value=v.encode()))
    return pb.ProcessingRequest(
        request_headers=pb.HttpHeaders(headers=hm, end_of_stream=end_of_stream)
    )


def body_msg(data: bytes) -> pb.ProcessingRequest:
    return pb.ProcessingRequest(
        request_body=pb.HttpBody(body=data, end_of_stream=True)
    )


def completion_body(prompt_chars: int) -> bytes:
    return json.dumps({
        "model": "llama-3.1-8b-instruct",
        "prompt": "x" * prompt_chars,
        "max_tokens": 256,
        "temperature": 0.7,
        "stream": False,
    }).encode()


def chat_body(content_chars: int) -> bytes:
    return json.dumps({
        "model": "llama-3.1-70b-instruct",
        "messages": [
            {"role": "system", "content": "You are a helpful assistant."},
            {"role": "user", "content": "y" * content_chars},
        ],
        "max_completion_tokens": 512,
    }).encode()


WORKLOADS = {
    "headers_only": [headers_msg(end_of_stream=True)],
    "completion_1k": [headers_msg(False), body_msg(completion_body(1024))],
    "chat_8k": [headers_msg(False), body_msg(chat_body(8192))],
    "completion_16k": [headers_msg(False), body_msg(completion_body(16384))],
    "transcode_1k": [headers_msg(False), body_msg(completion_body(1024))],
}


def _install_obs(impl: str):
    """Arm the gie-obs lanes (docs/OBSERVABILITY.md):

      fast_obs0  recorder installed, NO tracer — the --obs default
                 (--obs-sample-rate 0). The disabled-overhead guard:
                 admission must pay one module-attr load + branch, so
                 this lane must still clear the legacy guard factor.
      fast_obs1  recorder + tracer at rate 1.0 — every request carries
                 a TraceCtx and exports a trace; the measured ceiling
                 of tracing cost (reported, not gated: full sampling is
                 a debug posture, not a production one).
    """
    from gie_tpu import obs
    from gie_tpu.obs.recorder import FlightRecorder
    from gie_tpu.obs.trace import Tracer

    if impl == "fast_obs0":
        obs.install(recorder=FlightRecorder(512))
    elif impl == "fast_obs1":
        obs.install(tracer=Tracer(1.0, slow_s=10.0),
                    recorder=FlightRecorder(512))


def run_one(impl: str, workload: str, n_requests: int) -> dict:
    messages = WORKLOADS[workload]
    ds = make_datastore(grpc_pool=workload.startswith("transcode"))
    srv = StreamingServer(
        ds,
        RoundRobinPicker(),
        bbr_chain=PluginChain([ModelExtractorPlugin()]),
        fast_lane=impl.startswith("fast"),
    )
    from gie_tpu import obs

    _install_obs(impl)
    try:
        for _ in range(min(200, n_requests)):  # warm caches/templates
            srv.process(_ReplayStream(messages))
        wall = np.empty(n_requests, np.float64)
        cpu0 = time.process_time()
        for i in range(n_requests):
            stream = _ReplayStream(messages)
            t0 = time.perf_counter()
            srv.process(stream)
            wall[i] = time.perf_counter() - t0
        cpu = time.process_time() - cpu0
    finally:
        obs.uninstall()
    return {
        "impl": impl,
        "workload": workload,
        "requests": n_requests,
        **({"backend": _BACKEND_TAG} if _BACKEND_TAG else {}),
        "cpu_us_per_req": round(cpu / n_requests * 1e6, 2),
        "wall_p50_us": round(float(np.percentile(wall, 50)) * 1e6, 2),
        "wall_p99_us": round(float(np.percentile(wall, 99)) * 1e6, 2),
        "req_per_s_core": round(n_requests / cpu, 0) if cpu > 0 else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=3000,
                    help="measured requests per (impl, workload)")
    ap.add_argument("--min-speedup", type=float, default=1.25,
                    help="regression guard: fast-lane per-request CPU must "
                         "beat legacy by this factor on completion_1k "
                         "(generous vs the measured ~2-3x so noisy shared "
                         "runners do not flap)")
    args = ap.parse_args()

    from gie_tpu.extproc import fieldscan

    _log(f"native jsonscan available: {fieldscan.available()}")

    guard = "completion_1k"
    results = {}
    for workload in WORKLOADS:
        impls = ["fast", "legacy"]
        if workload == guard:
            # gie-obs lanes on the guard workload only (docs/
            # OBSERVABILITY.md): obs0 = recorder-only default (the
            # disabled-overhead guard), obs1 = full tracing ceiling.
            impls += ["fast_obs0", "fast_obs1"]
        for impl in impls:
            r = run_one(impl, workload, args.requests)
            results[(impl, workload)] = r
            print(json.dumps(r), flush=True)

    fast, legacy = results[("fast", guard)], results[("legacy", guard)]
    obs0 = results[("fast_obs0", guard)]
    obs1 = results[("fast_obs1", guard)]
    speedup = (legacy["cpu_us_per_req"] / fast["cpu_us_per_req"]
               if fast["cpu_us_per_req"] > 0 else float("inf"))
    obs0_speedup = (legacy["cpu_us_per_req"] / obs0["cpu_us_per_req"]
                    if obs0["cpu_us_per_req"] > 0 else float("inf"))
    obs1_overhead = (obs1["cpu_us_per_req"] / fast["cpu_us_per_req"]
                     if fast["cpu_us_per_req"] > 0 else float("inf"))
    p99_ok = fast["wall_p99_us"] <= legacy["wall_p99_us"]
    _log(
        f"summary @ {guard}: fast {fast['cpu_us_per_req']} us/req cpu "
        f"(p50 {fast['wall_p50_us']} us, p99 {fast['wall_p99_us']} us) | "
        f"legacy {legacy['cpu_us_per_req']} us/req cpu "
        f"(p50 {legacy['wall_p50_us']} us, p99 {legacy['wall_p99_us']} us) "
        f"| admission cpu speedup {speedup:.1f}x | obs-disabled "
        f"{obs0_speedup:.1f}x vs legacy | obs-on-full-sample "
        f"{obs1_overhead:.2f}x vs fast"
    )
    print(json.dumps({
        "metric": "extproc_admission_cpu_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        **({"backend": _BACKEND_TAG} if _BACKEND_TAG else {}),
        "fast_cpu_us_per_req": fast["cpu_us_per_req"],
        "fast_wall_p99_us": fast["wall_p99_us"],
        "legacy_cpu_us_per_req": legacy["cpu_us_per_req"],
        "legacy_wall_p99_us": legacy["wall_p99_us"],
        "obs_disabled_speedup": round(obs0_speedup, 2),
        "obs_full_sample_overhead": round(obs1_overhead, 2),
    }), flush=True)

    if speedup < args.min_speedup:
        _log(f"REGRESSION: fast-lane speedup {speedup:.2f}x < "
             f"required {args.min_speedup}x")
        sys.exit(1)
    if obs0_speedup < args.min_speedup:
        # The disabled-overhead guard (ISSUE 9 acceptance): with the
        # recorder installed but tracing off — the --obs default — the
        # fast lane must STILL clear the legacy guard factor, because
        # the admission path's obs cost is one module-attr load and a
        # falsy branch.
        _log(f"REGRESSION: obs-disabled fast lane speedup "
             f"{obs0_speedup:.2f}x < required {args.min_speedup}x")
        sys.exit(1)
    if not p99_ok:
        _log("REGRESSION: fast-lane wall p99 exceeds legacy")
        sys.exit(1)


if __name__ == "__main__":
    main()
