"""Admission-path benchmark: zero-parse fast lane vs legacy ext-proc path.

Measures the per-request EPP admission overhead (ISSUE 5, docs/EXTPROC.md)
— everything between "request fully received" and "routing decision sent":
header ingestion, body scan/parse, BBR chain, pick, and ProcessingResponse
construction — for BOTH lanes of extproc.server.StreamingServer:

  fast    --extproc-fast-lane path: native JSON field scan (jsonscan.cc),
          needed-keys header copy, pooled pre-serialized response
          templates, shared pass-through body responses.
  wire    --extproc-wire path (gie-wire): raw serialized frames replayed
          through WireSession.feed — the pbwalk classifier + fast-lane
          scan machinery with ZERO protobuf materialization on the
          classified path (materialized_per_req on the record pins it).
  legacy  the seed's path: full json.loads per request, full header copy,
          per-request nested-protobuf response build.

The picker is a RoundRobinPicker so the measurement isolates admission
CPU from the TPU scheduler (bench.py owns the pick cycle; the two-stage
collector's wait would swamp microsecond-level admission costs). Streams
are in-memory (the mockProcessServer pattern of tests/test_extproc.py);
request protos are pre-built and replayed, so proto construction of the
INPUT side is excluded and both lanes see identical bytes.

Per (impl, workload) configuration, one JSON line on stdout
(bench_scrape.py record format):

  cpu_us_per_req   process CPU microseconds per request — the headline
                   "per-request admission CPU" of the issue's >=3x target.
  wall_p50_us / wall_p99_us
                   per-request wall latency distribution.
  req_per_s_core   1e6 / cpu_us_per_req: admission throughput one core
                   sustains before the EPP itself is the bottleneck.

Workloads: headers-only pick, a ~1 KiB completion body, an ~8 KiB chat
body, and the gRPC-transcoding path (h2c pool), which exercises the
at-most-once parse contract (legacy paid json.loads twice there before
this PR).

After the in-memory lanes, a real-gRPC `--workers` sweep (default
1,2,4) serves the headers-only workload through ExtProcWorkerPool —
N SO_REUSEPORT acceptors over one shared StreamingServer — with one
JSON record per worker count: end-to-end streams/s, the per-worker
accept spread (gie_extproc_worker_accepted_streams_total deltas), and
`scaling_efficiency` (throughput vs the first sweep point, normalised
by worker count). On a 1-CPU container the efficiency number is a
methodology marker, not a scaling claim (every acceptor shares the one
core); the scaling PROPERTY is pinned in virtual time by storm-ci.

Run: `make bench-extproc` (or python bench_extproc.py [--requests N]).
Exits non-zero when the fast OR wire lane fails to beat legacy by
--min-speedup (regression guard; generous vs the >=3x CI-box headline
so slow shared runners do not flap), or when any sweep stream errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

from gie_tpu.bbr.chain import ModelExtractorPlugin, PluginChain
from gie_tpu.datastore import Datastore
from gie_tpu.datastore.objects import EndpointPool
from gie_tpu.extproc import pb
from gie_tpu.extproc.server import RoundRobinPicker, StreamingServer

N_ENDPOINTS = 16

# Bench-lane backend tag (ROADMAP item 8 / make bench-cpu): the CPU
# fallback lane exports "backend":"cpu-fallback" on every JSON record —
# the same tag bench.py uses — so artifact consumers can segregate
# CPU-lane numbers from real-hardware captures and the BENCH trajectory
# never goes dark when no TPU is reachable.
_BACKEND_TAG = os.environ.get("GIE_BENCH_BACKEND", "")


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class _ReplayStream:
    """Replays pre-built request protos; drops responses (the send side is
    what the lanes differ on, so building responses stays IN the measured
    path — only retention is skipped)."""

    __slots__ = ("messages", "i", "sent_count")

    def __init__(self, messages):
        self.messages = messages
        self.i = 0
        self.sent_count = 0

    def recv(self):
        i = self.i
        if i >= len(self.messages):
            return None
        self.i = i + 1
        return self.messages[i]

    def send(self, resp) -> None:
        self.sent_count += 1


def make_datastore(grpc_pool: bool = False) -> Datastore:
    from tests.test_datastore import make_pod

    ds = Datastore()
    pool = EndpointPool(
        selector={"app": "vllm"}, target_ports=[8000], namespace="default"
    )
    if grpc_pool:
        pool.app_protocol = "kubernetes.io/h2c"
    ds.pool_set(pool)
    for i in range(N_ENDPOINTS):
        ds.pod_update_or_add(make_pod(name=f"p{i}", ip=f"10.0.0.{i}"))
    return ds


def headers_msg(end_of_stream: bool) -> pb.ProcessingRequest:
    # A realistic Envoy-mesh header set (~24 keys): the handful the pick
    # reads plus the cookies / tracing baggage / peer metadata it never
    # does (the needed-keys scan's win). x-envoy-peer-metadata really is
    # a ~1 KB base64 blob on istio-style meshes.
    hm = pb.HeaderMap()
    for k, v in (
        (":method", "POST"),
        (":scheme", "https"),
        (":path", "/v1/completions"),
        (":authority", "pool.example.svc"),
        ("content-type", "application/json"),
        ("content-length", "1024"),
        ("accept", "application/json"),
        ("accept-encoding", "gzip, br"),
        ("user-agent", "openai-python/1.40.0"),
        ("authorization", "Bearer " + "t" * 64),
        ("cookie", "session=" + "c" * 96),
        ("x-request-id", "9f1d4c3a-77aa-43f2-a1b0-2f8e6f1d9c55"),
        ("x-forwarded-for", "10.1.2.3, 10.0.0.1"),
        ("x-forwarded-proto", "https"),
        ("x-envoy-attempt-count", "1"),
        ("x-envoy-expected-rq-timeout-ms", "600000"),
        ("x-envoy-peer-metadata-id", "sidecar~10.1.2.3~gw.ns~ns.svc"),
        ("x-envoy-peer-metadata", "Q" * 800),
        ("traceparent", "00-" + "a" * 32 + "-" + "b" * 16 + "-01"),
        ("tracestate", "vendor=opaque"),
        ("x-b3-traceid", "b" * 32),
        ("x-b3-spanid", "c" * 16),
        ("baggage", "tenant=42,plan=pro"),
        ("x-gateway-inference-objective", "standard"),
        ("x-gateway-inference-fairness-id", "tenant-42"),
    ):
        hm.headers.append(pb.HeaderValue(key=k, raw_value=v.encode()))
    return pb.ProcessingRequest(
        request_headers=pb.HttpHeaders(headers=hm, end_of_stream=end_of_stream)
    )


def body_msg(data: bytes) -> pb.ProcessingRequest:
    return pb.ProcessingRequest(
        request_body=pb.HttpBody(body=data, end_of_stream=True)
    )


def completion_body(prompt_chars: int) -> bytes:
    return json.dumps({
        "model": "llama-3.1-8b-instruct",
        "prompt": "x" * prompt_chars,
        "max_tokens": 256,
        "temperature": 0.7,
        "stream": False,
    }).encode()


def chat_body(content_chars: int) -> bytes:
    return json.dumps({
        "model": "llama-3.1-70b-instruct",
        "messages": [
            {"role": "system", "content": "You are a helpful assistant."},
            {"role": "user", "content": "y" * content_chars},
        ],
        "max_completion_tokens": 512,
    }).encode()


WORKLOADS = {
    "headers_only": [headers_msg(end_of_stream=True)],
    "completion_1k": [headers_msg(False), body_msg(completion_body(1024))],
    "chat_8k": [headers_msg(False), body_msg(chat_body(8192))],
    "completion_16k": [headers_msg(False), body_msg(completion_body(16384))],
    "transcode_1k": [headers_msg(False), body_msg(completion_body(1024))],
}


def _install_obs(impl: str):
    """Arm the gie-obs lanes (docs/OBSERVABILITY.md):

      fast_obs0  recorder installed, NO tracer — the --obs default
                 (--obs-sample-rate 0). The disabled-overhead guard:
                 admission must pay one module-attr load + branch, so
                 this lane must still clear the legacy guard factor.
      fast_obs1  recorder + tracer at rate 1.0 — every request carries
                 a TraceCtx and exports a trace; the measured ceiling
                 of tracing cost (reported, not gated: full sampling is
                 a debug posture, not a production one).
    """
    from gie_tpu import obs
    from gie_tpu.obs.recorder import FlightRecorder
    from gie_tpu.obs.trace import Tracer

    if impl == "fast_obs0":
        obs.install(recorder=FlightRecorder(512))
    elif impl == "fast_obs1":
        obs.install(tracer=Tracer(1.0, slow_s=10.0),
                    recorder=FlightRecorder(512))


def run_one_wire(workload: str, n_requests: int) -> dict:
    """The wire lane has no recv loop to replay protos through: feed the
    pre-serialized frame bytes straight into a WireSession per request —
    exactly what service.py's identity-deserializer handler does — and
    keep response-byte production in the measured path (the returned
    frames are what the handler would hand to gRPC)."""
    from gie_tpu.extproc import wire as wiremod

    frames = [m.SerializeToString() for m in WORKLOADS[workload]]
    ds = make_datastore(grpc_pool=workload.startswith("transcode"))
    srv = StreamingServer(
        ds,
        RoundRobinPicker(),
        bbr_chain=PluginChain([ModelExtractorPlugin()]),
        fast_lane=True,
    )
    for _ in range(min(200, n_requests)):  # warm caches/templates
        sess = srv.wire_session()
        for f in frames:
            sess.feed(f)
        sess.close(None)
    mat0 = wiremod.MATERIALIZED
    wall = np.empty(n_requests, np.float64)
    cpu0 = time.process_time()
    for i in range(n_requests):
        t0 = time.perf_counter()
        sess = srv.wire_session()
        for f in frames:
            sess.feed(f)
        sess.close(None)
        wall[i] = time.perf_counter() - t0
    cpu = time.process_time() - cpu0
    mat = wiremod.MATERIALIZED - mat0
    return {
        "impl": "wire",
        "workload": workload,
        "requests": n_requests,
        **({"backend": _BACKEND_TAG} if _BACKEND_TAG else {}),
        "cpu_us_per_req": round(cpu / n_requests * 1e6, 2),
        "wall_p50_us": round(float(np.percentile(wall, 50)) * 1e6, 2),
        "wall_p99_us": round(float(np.percentile(wall, 99)) * 1e6, 2),
        "req_per_s_core": round(n_requests / cpu, 0) if cpu > 0 else None,
        # FromString fallbacks per request on this workload: 0.0 is the
        # zero-materialization claim, in the artifact and not just the
        # test suite (tests/test_extproc_wirelane.py pins it hard).
        "materialized_per_req": round(mat / n_requests, 4),
    }


_PROCESS_METHOD = "/envoy.service.ext_proc.v3.ExternalProcessor/Process"


def run_workers_sweep(worker_counts: list[int], n_streams: int) -> list[dict]:
    """Real-gRPC throughput of the wire lane behind ExtProcWorkerPool at
    each worker count. One client channel is one TCP connection is one
    SO_REUSEPORT acceptor, so the driver opens several channels per
    worker (Envoy's connection pool shape) and splits the streams across
    them from client threads; per-worker accept deltas go on the record
    so a one-acceptor skew is visible in the artifact."""
    import threading

    import grpc

    from gie_tpu.extproc.workers import ExtProcWorkerPool
    from gie_tpu.runtime import metrics as own_metrics

    frames = [m.SerializeToString() for m in WORKLOADS["headers_only"]]
    accepts_name = "gie_extproc_worker_accepted_streams_total"

    def _accepts(w: int) -> list[float]:
        return [own_metrics.REGISTRY.get_sample_value(
            accepts_name, {"worker": str(i)}) or 0.0 for i in range(w)]

    def _drive(port: int, n: int, errors: list) -> None:
        try:
            # A local subchannel pool per channel: without it grpc
            # shares one TCP connection between same-target channels,
            # and SO_REUSEPORT would see ONE connection to spread.
            channel = grpc.insecure_channel(
                f"127.0.0.1:{port}",
                options=(("grpc.use_local_subchannel_pool", 1),))
            process = channel.stream_stream(
                _PROCESS_METHOD,
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            for _ in range(n):
                for _resp in process(iter(frames)):
                    pass
            channel.close()
        except Exception as exc:  # surfaced by the caller, fails the run
            errors.append(exc)

    records = []
    base = None  # (workers, req_per_s) of the first sweep point
    for w in worker_counts:
        ds = make_datastore()
        srv = StreamingServer(
            ds,
            RoundRobinPicker(),
            bbr_chain=PluginChain([ModelExtractorPlugin()]),
            fast_lane=True,
        )
        pool = ExtProcWorkerPool(srv, w, wire=True)
        port = pool.bind("127.0.0.1:0")
        pool.start()
        before = _accepts(w)
        n_channels = max(4, 4 * w)
        split = [n_streams // n_channels] * n_channels
        split[0] += n_streams - sum(split)
        errors: list = []
        threads = [threading.Thread(target=_drive, args=(port, n, errors))
                   for n in split if n > 0]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        after = _accepts(w)
        pool.stop(grace=5.0).wait(10.0)
        if errors:
            raise RuntimeError(f"workers={w} sweep stream failed: {errors[0]}")
        rps = n_streams / wall if wall > 0 else float("inf")
        if base is None:
            base = (w, rps)
        rec = {
            "impl": "wire_grpc",
            "workload": "headers_only",
            "workers": w,
            "streams": n_streams,
            **({"backend": _BACKEND_TAG} if _BACKEND_TAG else {}),
            "req_per_s": round(rps, 1),
            "wall_us_per_req": round(wall / n_streams * 1e6, 2),
            "per_worker_accepts": [int(a - b) for a, b in zip(after, before)],
            # Throughput vs the first sweep point, normalised by worker
            # count: 1.0 is perfect linear scaling. Reported, not gated —
            # on a 1-CPU box every acceptor shares the core and this
            # sits near 1/workers by construction.
            "scaling_efficiency": round(rps / base[1] * base[0] / w, 3),
        }
        records.append(rec)
        print(json.dumps(rec), flush=True)
    return records


def run_one(impl: str, workload: str, n_requests: int) -> dict:
    messages = WORKLOADS[workload]
    ds = make_datastore(grpc_pool=workload.startswith("transcode"))
    srv = StreamingServer(
        ds,
        RoundRobinPicker(),
        bbr_chain=PluginChain([ModelExtractorPlugin()]),
        fast_lane=impl.startswith("fast"),
    )
    from gie_tpu import obs

    _install_obs(impl)
    try:
        for _ in range(min(200, n_requests)):  # warm caches/templates
            srv.process(_ReplayStream(messages))
        wall = np.empty(n_requests, np.float64)
        cpu0 = time.process_time()
        for i in range(n_requests):
            stream = _ReplayStream(messages)
            t0 = time.perf_counter()
            srv.process(stream)
            wall[i] = time.perf_counter() - t0
        cpu = time.process_time() - cpu0
    finally:
        obs.uninstall()
    return {
        "impl": impl,
        "workload": workload,
        "requests": n_requests,
        **({"backend": _BACKEND_TAG} if _BACKEND_TAG else {}),
        "cpu_us_per_req": round(cpu / n_requests * 1e6, 2),
        "wall_p50_us": round(float(np.percentile(wall, 50)) * 1e6, 2),
        "wall_p99_us": round(float(np.percentile(wall, 99)) * 1e6, 2),
        "req_per_s_core": round(n_requests / cpu, 0) if cpu > 0 else None,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=3000,
                    help="measured requests per (impl, workload)")
    ap.add_argument("--min-speedup", type=float, default=1.25,
                    help="regression guard: fast- AND wire-lane per-request "
                         "CPU must beat legacy by this factor on "
                         "completion_1k (generous vs the measured ~2-3x so "
                         "noisy shared runners do not flap)")
    ap.add_argument("--workers", default="1,2,4",
                    help="comma-separated worker counts for the real-gRPC "
                         "ExtProcWorkerPool sweep (empty string skips it)")
    ap.add_argument("--grpc-streams", type=int, default=600,
                    help="ext-proc streams per sweep point")
    args = ap.parse_args()

    from gie_tpu.extproc import fieldscan

    _log(f"native jsonscan available: {fieldscan.available()}")

    guard = "completion_1k"
    results = {}
    for workload in WORKLOADS:
        impls = ["fast", "wire", "legacy"]
        if workload == guard:
            # gie-obs lanes on the guard workload only (docs/
            # OBSERVABILITY.md): obs0 = recorder-only default (the
            # disabled-overhead guard), obs1 = full tracing ceiling.
            impls += ["fast_obs0", "fast_obs1"]
        for impl in impls:
            r = (run_one_wire(workload, args.requests) if impl == "wire"
                 else run_one(impl, workload, args.requests))
            results[(impl, workload)] = r
            print(json.dumps(r), flush=True)

    worker_counts = [int(x) for x in args.workers.split(",") if x.strip()]
    if worker_counts:
        run_workers_sweep(worker_counts, args.grpc_streams)

    fast, legacy = results[("fast", guard)], results[("legacy", guard)]
    obs0 = results[("fast_obs0", guard)]
    obs1 = results[("fast_obs1", guard)]
    wire = results[("wire", guard)]
    wire_hdrs = results[("wire", "headers_only")]
    speedup = (legacy["cpu_us_per_req"] / fast["cpu_us_per_req"]
               if fast["cpu_us_per_req"] > 0 else float("inf"))
    wire_speedup = (legacy["cpu_us_per_req"] / wire["cpu_us_per_req"]
                    if wire["cpu_us_per_req"] > 0 else float("inf"))
    obs0_speedup = (legacy["cpu_us_per_req"] / obs0["cpu_us_per_req"]
                    if obs0["cpu_us_per_req"] > 0 else float("inf"))
    obs1_overhead = (obs1["cpu_us_per_req"] / fast["cpu_us_per_req"]
                     if fast["cpu_us_per_req"] > 0 else float("inf"))
    p99_ok = fast["wall_p99_us"] <= legacy["wall_p99_us"]
    _log(
        f"summary @ {guard}: fast {fast['cpu_us_per_req']} us/req cpu "
        f"(p50 {fast['wall_p50_us']} us, p99 {fast['wall_p99_us']} us) | "
        f"legacy {legacy['cpu_us_per_req']} us/req cpu "
        f"(p50 {legacy['wall_p50_us']} us, p99 {legacy['wall_p99_us']} us) "
        f"| admission cpu speedup {speedup:.1f}x | wire {wire_speedup:.1f}x "
        f"(headers_only {wire_hdrs['cpu_us_per_req']} us/req, "
        f"{wire_hdrs['materialized_per_req']} materializations/req) | "
        f"obs-disabled {obs0_speedup:.1f}x vs legacy | obs-on-full-sample "
        f"{obs1_overhead:.2f}x vs fast"
    )
    print(json.dumps({
        "metric": "extproc_admission_cpu_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        **({"backend": _BACKEND_TAG} if _BACKEND_TAG else {}),
        "fast_cpu_us_per_req": fast["cpu_us_per_req"],
        "fast_wall_p99_us": fast["wall_p99_us"],
        "wire_cpu_us_per_req": wire["cpu_us_per_req"],
        "wire_speedup": round(wire_speedup, 2),
        "wire_headers_only_cpu_us_per_req": wire_hdrs["cpu_us_per_req"],
        "wire_headers_only_materialized_per_req":
            wire_hdrs["materialized_per_req"],
        "legacy_cpu_us_per_req": legacy["cpu_us_per_req"],
        "legacy_wall_p99_us": legacy["wall_p99_us"],
        "obs_disabled_speedup": round(obs0_speedup, 2),
        "obs_full_sample_overhead": round(obs1_overhead, 2),
    }), flush=True)

    if speedup < args.min_speedup:
        _log(f"REGRESSION: fast-lane speedup {speedup:.2f}x < "
             f"required {args.min_speedup}x")
        sys.exit(1)
    if wire_speedup < args.min_speedup:
        # gie-wire guard extension: the protobuf-free lane must clear
        # the same factor — it strictly removes work vs the fast lane,
        # so falling under it means a materialization leak or a walker
        # regression, not runner noise.
        _log(f"REGRESSION: wire-lane speedup {wire_speedup:.2f}x < "
             f"required {args.min_speedup}x")
        sys.exit(1)
    if obs0_speedup < args.min_speedup:
        # The disabled-overhead guard (ISSUE 9 acceptance): with the
        # recorder installed but tracing off — the --obs default — the
        # fast lane must STILL clear the legacy guard factor, because
        # the admission path's obs cost is one module-attr load and a
        # falsy branch.
        _log(f"REGRESSION: obs-disabled fast lane speedup "
             f"{obs0_speedup:.2f}x < required {args.min_speedup}x")
        sys.exit(1)
    if not p99_ok:
        _log("REGRESSION: fast-lane wall p99 exceeds legacy")
        sys.exit(1)


if __name__ == "__main__":
    main()
