# gie-tpu EPP container image (reference lwepp.Dockerfile parity: the
# reference builds a distroless static Go binary; the TPU-native EPP is a
# Python/JAX process plus a small native library, so the image is a slim
# Python base with the native chunker built in a throwaway stage).
#
# Build:  docker build -t gie-tpu-epp .
# Run  :  docker run -p 9002:9002 -p 9003:9003 -p 9090:9090 gie-tpu-epp \
#             --pool-name my-pool --kube
#
# NOTE: requirements below name the runtime deps this tree was built
# against (jax/flax/optax/orbax/grpcio/protobuf/prometheus-client/pyyaml/
# cryptography + `kubernetes` for --kube). Pin versions to your fleet's
# JAX/TPU release; TPU images should derive from your libtpu base instead
# of python:slim.

FROM python:3.12-slim AS native-build
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY native/ native/
# Force a rebuild: the repo tracks a host-built .so whose mtime would
# otherwise make `make` no-op and ship a foreign-ABI binary. The asan +
# fuzz variants build alongside so the sanitizer smoke (docs/ANALYSIS.md)
# is reproducible in-container.
RUN make -C native clean all asan fuzz

FROM python:3.12-slim
RUN pip install --no-cache-dir \
        "jax[tpu]" flax optax orbax-checkpoint \
        grpcio protobuf prometheus-client pyyaml cryptography kubernetes
WORKDIR /app
COPY gie_tpu/ gie_tpu/
COPY config/ config/
COPY --from=native-build /src/native/libgiechunker.so native/libgiechunker.so
COPY --from=native-build /src/native/libgiepromparse.so native/libgiepromparse.so
COPY --from=native-build /src/native/libgiejsonscan.so native/libgiejsonscan.so
# Sanitizer smoke in-container (docs/ANALYSIS.md):
#   docker run --entrypoint sh gie-tpu-epp -c \
#     'python hack/fuzz_seeds.py /tmp/corpus && \
#      native/fuzz/bin/fuzz_jsonscan -max_total_time=30 /tmp/corpus/jsonscan'
COPY --from=native-build /src/native/fuzz/bin/ native/fuzz/bin/
COPY hack/fuzz_seeds.py hack/fuzz_seeds.py
COPY tests/test_fieldscan.py tests/test_fieldscan.py

# Ports: ext-proc gRPC / dedicated health / prometheus metrics.
EXPOSE 9002 9003 9090
ENTRYPOINT ["python", "-m", "gie_tpu.runtime.main"]
