import time, sys
import jax, jax.numpy as jnp, numpy as np
from gie_tpu.sched import constants as C
from gie_tpu.sched import filters, pickers, scorers
from gie_tpu.sched.types import SchedState, Weights
from gie_tpu.utils.testing import make_endpoints, make_requests

n, m = 1024, 256
rng = np.random.default_rng(0)
eps = make_endpoints(m, queue=rng.integers(0, 50, m).tolist(),
                     kv=rng.uniform(0, 0.95, m).tolist(), max_lora=8)
base = b"SYSTEM: You are a helpful assistant specialised in task %d. "
prompts = [(base % (i % 16)) * 6 + b"user question %d" % i for i in range(n)]
reqs = make_requests(n, prompts=prompts, lora_id=(rng.integers(-1, 12, n)).tolist())

K = 64
def stack_waves(x):
    x = np.asarray(x)
    return np.stack([np.roll(x, 17 * w, axis=0) for w in range(K)])
waves = jax.tree.map(stack_waves, reqs)
waves = jax.device_put(waves)
eps = jax.device_put(eps)
weights = Weights.default()
cfg_queue_norm, cfg_load_norm = 64.0, 32.0

def harness(name, step_fn, reps=5):
    def win(load, rr, waves):
        def step(carry, wave):
            load, rr = carry
            load, rr, out = step_fn(load, rr, wave)
            return (load, rr), out
        (load, rr), outs = jax.lax.scan(step, (load, rr), waves)
        return load, rr, outs[-1]
    win = jax.jit(win, donate_argnums=(0,))
    load = jnp.zeros((C.M_MAX,), jnp.float32); rr = jnp.uint32(0)
    load, rr, o = win(load, rr, waves); jax.block_until_ready(o)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        load, rr, o = win(load, rr, waves)
        jax.block_until_ready(o)
        ts.append((time.perf_counter()-t0)/K*1e6)
    print(f"{name}: per-iter min={min(ts):.1f}us", file=sys.stderr)

def columns(load, wave):
    mask = filters.base_mask(wave, eps)
    named = {
        "queue": jnp.broadcast_to(scorers.queue_score(eps, queue_norm=cfg_queue_norm)[None, :], mask.shape),
        "kv_cache": jnp.broadcast_to(scorers.kv_cache_score(eps)[None, :], mask.shape),
        "assumed_load": jnp.broadcast_to(scorers.assumed_load_score(load, load_norm=cfg_load_norm)[None, :], mask.shape),
    }
    stacked = jnp.stack(list(named.values()))
    wvec = jnp.stack([getattr(weights, k) for k in named])
    total = jnp.einsum("s,snm->nm", wvec, stacked) / jnp.maximum(jnp.sum(wvec), jnp.float32(1e-6))
    return mask, stacked, wvec, total

# L1: columns + blend + argmax, minimal update
def l1(load, rr, wave):
    mask, stacked, wvec, total = columns(load, wave)
    masked = jnp.where(mask, total, C.NEG_SCORE)
    pick = jnp.argmax(masked, axis=-1)
    load = load * 0.95 + jnp.zeros((C.M_MAX,), jnp.float32).at[pick].add(1.0)
    return load, rr + 1, pick
harness("L1 columns+blend+argmax", l1)

# L2: + quantize/rotate tie-break
def l2(load, rr, wave):
    mask, stacked, wvec, total = columns(load, wave)
    quantized = jnp.round(total / pickers._TIE_RESOLUTION) * pickers._TIE_RESOLUTION
    lane = jnp.arange(C.M_MAX, dtype=jnp.uint32)
    rot = ((lane + rr) % jnp.uint32(C.M_MAX)).astype(jnp.float32)
    masked = jnp.where(mask, quantized + rot * pickers._TIE_EPS, C.NEG_SCORE)
    pick = jnp.argmax(masked, axis=-1)
    load = load * 0.95 + jnp.zeros((C.M_MAX,), jnp.float32).at[pick].add(1.0)
    return load, rr + 1, pick
harness("L2 +tiebreak", l2)

# L3: + full _topk(4) + finalize
def l3(load, rr, wave):
    mask, stacked, wvec, total = columns(load, wave)
    quantized = jnp.round(total / pickers._TIE_RESOLUTION) * pickers._TIE_RESOLUTION
    lane = jnp.arange(C.M_MAX, dtype=jnp.uint32)
    rot = ((lane + rr) % jnp.uint32(C.M_MAX)).astype(jnp.float32)
    masked = jnp.where(mask, quantized + rot * pickers._TIE_EPS, C.NEG_SCORE)
    shed = jnp.zeros(wave.valid.shape, bool)
    res = pickers._finalize(masked, mask, shed, wave.valid)
    pick = res.indices[:, 0]
    safe = jnp.where(pick >= 0, pick, C.M_MAX - 1)
    load = load * 0.95 + jnp.zeros((C.M_MAX,), jnp.float32).at[safe].add(1.0)
    return load, rr + 1, pick
harness("L3 +topk4+finalize", l3)

# L4: + request_cost + where-gating like real cycle
def l4(load, rr, wave):
    mask, stacked, wvec, total = columns(load, wave)
    quantized = jnp.round(total / pickers._TIE_RESOLUTION) * pickers._TIE_RESOLUTION
    lane = jnp.arange(C.M_MAX, dtype=jnp.uint32)
    rot = ((lane + rr) % jnp.uint32(C.M_MAX)).astype(jnp.float32)
    masked = jnp.where(mask, quantized + rot * pickers._TIE_EPS, C.NEG_SCORE)
    shed = jnp.zeros(wave.valid.shape, bool)
    res = pickers._finalize(masked, mask, shed, wave.valid)
    primary = res.indices[:, 0]
    picked_ok = primary >= 0
    cost = jnp.where(picked_ok, jnp.clip((wave.prompt_len + wave.decode_len) / 2048.0, 0.25, 8.0), 0.0)
    slot = jnp.where(picked_ok, primary, C.M_MAX - 1)
    load = load * 0.95 + jnp.zeros((C.M_MAX,), jnp.float32).at[slot].add(cost)
    return load, rr + 1, primary
harness("L4 +cost-gating (≈queue_kv_only cycle)", l4)
EOF
