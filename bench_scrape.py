"""Scrape-path benchmark: multiplexed engine vs thread-per-endpoint.

Measures the metrics-ingestion path (ISSUE 4, docs/METRICSIO.md) at
16/64/256 endpoints on a 50 ms fast-poll cadence, for BOTH
implementations:

  engine   gie_tpu.metricsio.engine.ScrapeEngine — fixed worker-shard
           pool, deadline min-heap, batched MetricsStore writes.
  threads  gie_tpu.metricsio.scrape.ThreadPerEndpointScraper — the seed's
           one-thread-one-connection-per-endpoint loop.

Per (impl, n) configuration, one JSON line on stdout:

  sweep_cpu_ms   CPU seconds consumed per INTERVAL of polling the whole
                 pool (process CPU time x interval / wall) — the
                 "scrape-path wall-time per sweep". This charges
                 over-polling correctly: the legacy loop under GIL
                 contention spins some pollers faster than the interval
                 while starving others, burning MORE cpu for WORSE
                 freshness.
  staleness_p50_ms / staleness_p99_ms
                 distribution of per-endpoint row refresh gaps — the
                 quantity every picker decision actually depends on.
  threads        threading.active_count() during the run (the engine
                 stays at workers + constant regardless of pool size).
  sweeps_per_s   median per-endpoint refresh rate (target = 1/interval).

The fetcher is an in-process stub returning a fixed vLLM exposition
(incl. a LoRA-info line), so the comparison isolates scheduling, GIL,
parse, and store-write costs; network effects (keep-alive reuse vs
per-scrape TCP) additionally favor the engine in production and are
covered by the soak test's real-HTTP path.

Run: `make bench-scrape` (or python bench_scrape.py [--duration S]).
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from collections import defaultdict

import numpy as np

from gie_tpu.metricsio import MetricsStore
from gie_tpu.metricsio.engine import ScrapeEngine
from gie_tpu.metricsio.mappings import VLLM
from gie_tpu.metricsio.scrape import ThreadPerEndpointScraper

INTERVAL_S = 0.05
SIZES = (16, 64, 256)

STUB_TEXT = b"""# TYPE vllm:num_requests_waiting gauge
vllm:num_requests_waiting 7
# TYPE vllm:num_requests_running gauge
vllm:num_requests_running 3
# TYPE vllm:kv_cache_usage_perc gauge
vllm:kv_cache_usage_perc 0.42
# TYPE vllm:cache_config_info gauge
vllm:cache_config_info{block_size="16",num_gpu_blocks="2048"} 1
# TYPE vllm:lora_requests_info gauge
vllm:lora_requests_info{max_lora="4",running_lora_adapters="a1, a2",waiting_lora_adapters="a3"} 100.0
"""


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class _RecordingStore(MetricsStore):
    """MetricsStore that timestamps every row write (both the legacy
    per-row path and the engine's batched path) for staleness stats."""

    def __init__(self):
        super().__init__()
        self.times: dict[int, list] = defaultdict(list)
        self._tlock = threading.Lock()

    def update(self, slot, metrics, lora_active=(), lora_waiting=(),
               now=None):
        super().update(slot, metrics, lora_active, lora_waiting, now)
        with self._tlock:
            self.times[slot].append(time.monotonic())

    def update_rows(self, rows, now=None):
        super().update_rows(rows, now)
        t = time.monotonic()
        with self._tlock:
            for row in rows:
                self.times[row[0]].append(t)


def _stub_fetcher(url: str) -> bytes:
    return STUB_TEXT


def run_one(impl: str, n: int, duration_s: float) -> dict:
    store = _RecordingStore()
    if impl == "engine":
        scraper = ScrapeEngine(
            store, interval_s=INTERVAL_S, fetcher=_stub_fetcher)
    else:
        scraper = ThreadPerEndpointScraper(
            store, interval_s=INTERVAL_S, fetcher=_stub_fetcher)
    for slot in range(n):
        scraper.attach(
            slot, f"http://10.0.{slot // 250}.{slot % 250}:8000/metrics",
            VLLM)
    time.sleep(min(0.5, duration_s / 4))  # settle past attach staggering
    with store._tlock:
        store.times.clear()
    threads = threading.active_count()
    cpu0, wall0 = time.process_time(), time.monotonic()
    time.sleep(duration_s)
    cpu = time.process_time() - cpu0
    wall = time.monotonic() - wall0
    scraper.close()

    per_ep = [len(v) for v in store.times.values()] or [0]
    gaps = [np.diff(v) for v in store.times.values() if len(v) > 2]
    gaps = np.concatenate(gaps) if gaps else np.asarray([float("inf")])
    sweeps = float(np.median(per_ep)) / wall
    return {
        "impl": impl,
        "endpoints": n,
        "interval_ms": INTERVAL_S * 1e3,
        "sweep_cpu_ms": round(cpu / (wall / INTERVAL_S) * 1e3, 2),
        "staleness_p50_ms": round(float(np.percentile(gaps, 50)) * 1e3, 1),
        "staleness_p99_ms": round(float(np.percentile(gaps, 99)) * 1e3, 1),
        "sweeps_per_s": round(sweeps, 1),
        "threads": threads,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds per (impl, size) measurement window")
    args = ap.parse_args()

    results = {}
    for n in SIZES:
        for impl in ("engine", "threads"):
            r = run_one(impl, n, args.duration)
            results[(impl, n)] = r
            print(json.dumps(r), flush=True)

    n = SIZES[-1]
    eng, thr = results[("engine", n)], results[("threads", n)]
    speedup = (thr["sweep_cpu_ms"] / eng["sweep_cpu_ms"]
               if eng["sweep_cpu_ms"] > 0 else float("inf"))
    _log(
        f"summary @ {n} endpoints: engine {eng['sweep_cpu_ms']} ms/sweep "
        f"p99={eng['staleness_p99_ms']} ms threads={eng['threads']} | "
        f"legacy {thr['sweep_cpu_ms']} ms/sweep "
        f"p99={thr['staleness_p99_ms']} ms threads={thr['threads']} | "
        f"scrape-path speedup {speedup:.1f}x"
    )
    print(json.dumps({
        "metric": f"scrape_sweep_cpu_speedup_{n}ep",
        "value": round(speedup, 2),
        "unit": "x",
        "engine_p99_staleness_ms": eng["staleness_p99_ms"],
        "engine_threads": eng["threads"],
    }), flush=True)


if __name__ == "__main__":
    main()
