"""Secondary benchmark: cluster goodput vs the reference's default scorer.

The prefix-cache-aware benchmark of the BASELINE north star ("cluster
tokens/sec goodput >= 1.3x vs default least-kv-cache scorer"): a
cache-constrained, prefill-heavy workload (64 sessions x ~130 prefix chunks
against 2048-chunk per-pod caches) over 8 emulated vLLM pods at an arrival
rate (100 qps) where both policies are capacity-limited.

Runs the REAL pipeline end to end: stub prometheus text -> protocol parser ->
dense MetricsStore -> jitted scheduling cycle -> submit -> termination
feedback. Prints one JSON line; detail to stderr.

(The driver's official benchmark is bench.py; this script is the goodput
evidence. The sim is host-dominated, so it runs on the CPU platform by
default — forced IN-PROCESS before gie_tpu is imported, because the axon
TPU backend hangs forever at init when its relay is down, and environment
variables alone do not override the sitecustomize-registered platform.
Set GIE_GOODPUT_PLATFORM=tpu (or axon) to opt into chip runs.)
"""

from __future__ import annotations

import json
import os
import sys


def _force_platform() -> str:
    platform = os.environ.get("GIE_GOODPUT_PLATFORM", "cpu")
    import jax

    # config.update silently no-ops when a backend already initialized
    # (e.g. invoked from a process that already did TPU work), so verify
    # the platform actually took and say so when it did not.
    jax.config.update("jax_platforms", platform)
    active = jax.default_backend()
    if active != platform:
        print(
            f"WARNING: requested platform '{platform}' but backend is "
            f"'{active}' (JAX initialized before this script ran) — "
            "timings reflect that backend",
            file=sys.stderr,
        )
    return active


# The HEADLINE operating point, shared with bench_goodput_sweep.py and
# hack/exp_predictor_column.py so a retune here propagates to the
# robustness evidence instead of silently diverging from it.
#
# 100 qps (round 2, was 75): at 75 the tuned scheduler served the
# ENTIRE offered load (goodput == arrivals, ratio capped ~2.2x by the
# workload, not the scheduler); 100 qps keeps the baseline and the
# scheduler both capacity-limited so the ratio measures scheduling.
HEADLINE_WORKLOAD = dict(
    arrival_qps=100.0,
    n_sessions=64,
    system_prompt_bytes=8192,
    user_suffix_bytes=128,
    decode_tokens_mean=32.0,
    ttft_slo_s=2.5,
)
HEADLINE_STUB = dict(
    max_running=8,
    prefill_tokens_per_s=4000.0,
    decode_tokens_per_s=50.0,
    prefix_cache_chunks=2048,
)
HEADLINE_DURATION_S = 20.0


def main() -> None:
    backend = _force_platform()
    from gie_tpu.simulator import StubConfig
    from gie_tpu.simulator.cluster import SimCluster, WorkloadConfig, tuned_scheduler

    wl = WorkloadConfig(**HEADLINE_WORKLOAD)
    stub = StubConfig(**HEADLINE_STUB)
    duration = HEADLINE_DURATION_S
    results = {}
    # least-kv-assumed is the ADVERSARIAL baseline (VERDICT r3 #8): the
    # same reference-default greedy scorer, but with persistent in-flight
    # accounting between scrapes — the strongest floor the per-request
    # design supports. The official ratio stays vs plain least-kv (the
    # reference's actual default); stderr reports both.
    for policy in ("least-kv", "least-kv-assumed", "tpu",
                   "tpu+slo-admission"):
        cluster = SimCluster(n_pods=8, stub_cfg=stub, seed=0)
        trainer = None
        run_kwargs = {}
        if policy == "tpu+slo-admission":
            # Evidence leg (stderr only; the official metric stays the
            # shipped default): predictive SLO admission on top of the
            # tuned scheduler — sheds the few requests whose predicted
            # TTFT already misses the 2.5 s SLO, lifting goodput AND
            # attainment at this capacity-limited operating point.
            from gie_tpu.models.latency import LatencyPredictor, OnlineTrainer

            trainer = OnlineTrainer(LatencyPredictor(), batch_size=64)
            run_kwargs = dict(trainer=trainer, train_every_s=0.5,
                              slo_admission=True)
        sched = tuned_scheduler() if policy.startswith("tpu") else None
        stats = cluster.run(policy.split("+")[0], wl, duration_s=duration,
                            scheduler=sched, **run_kwargs)
        results[policy] = stats
        print(
            f"{policy:17s} goodput={stats.goodput_tokens_per_s:7.1f} tok/s "
            f"ttft_p50={stats.ttft_p50_s:5.2f}s p99={stats.ttft_p99_s:5.2f}s "
            f"slo={stats.slo_attainment:.2f} hit={stats.prefix_hit_rate:.2f} "
            f"completed={stats.completed} shed={stats.shed}",
            file=sys.stderr,
        )

    ratio = (
        results["tpu"].goodput_tokens_per_s
        / max(results["least-kv"].goodput_tokens_per_s, 1e-9)
    )
    ratio_adv = (
        results["tpu"].goodput_tokens_per_s
        / max(results["least-kv-assumed"].goodput_tokens_per_s, 1e-9)
    )
    print(
        f"ratios: vs least-kv={ratio:.2f}x  "
        f"vs least-kv-assumed (adversarial floor)={ratio_adv:.2f}x",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "goodput_tokens_per_s_vs_least_kv",
                "value": round(results["tpu"].goodput_tokens_per_s, 1),
                "unit": "tokens/s",
                "vs_baseline": round(ratio, 2),
                # bench.py's tag convention (make bench-cpu): CPU-lane
                # records are segregated from real-hardware captures.
                "backend": ("cpu-fallback" if backend == "cpu"
                            else backend),
            }
        )
    )


if __name__ == "__main__":
    main()
