"""`make learn-ci` driver: the gie-learn pipeline end to end, pinned.

Three assertions, in order (docs/LEARNED.md "CI gate"):

1. Determinism — training from the checked-in fixture dump at the
   committed hyperparameters reproduces the committed artifact's weight
   BITS (float32 hex, not decimal repr). Same dump + seed => same
   policy, byte for byte; a drift here means the trainer, the dataset
   builder, or the fixture changed without a regenerate.
2. Promotion — the twin judge races the freshly-trained policy against
   the tuned heuristic on the storm-learn-judge deep-overload gauntlet
   AND the fixture trace replayed as a literal arrival schedule, and
   must return PROMOTE (every gate on every scenario).
3. Verdict determinism — the judged schedule fingerprints match the
   committed LEARNJUDGE artifact row for row: the twin saw bit-identical
   traffic, so a future verdict flip is a scheduling change, not noise.

Run from the repo root:  JAX_PLATFORMS=cpu python hack/learn_ci.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(
    REPO, "tests", "fixtures", "learn", "storm-fixture-flightrec.json")
COMMITTED = os.path.join(REPO, "config", "policy", "storm-lora-v1.json")
JUDGMENT = os.path.join(REPO, "LEARNJUDGE_r01.json")

# The committed artifact's training hyperparameters (its provenance is
# the source of truth — read back below, not duplicated here).


def main() -> int:
    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("GIE_STORM_PLATFORM", "cpu"))

    from gie_tpu.learn import artifact as artifact_mod
    from gie_tpu.learn import dataset as dataset_mod
    from gie_tpu.learn import judge as judge_mod
    from gie_tpu.learn import train as train_mod

    committed = artifact_mod.load_artifact(COMMITTED)
    prov = committed["provenance"]
    art = train_mod.train(
        dataset_mod.load_dumps([FIXTURE]),
        seed=int(prov["seed"]),
        eval_fraction=float(prov["eval_fraction"]),
        l2=float(prov["l2"]))

    want = {k: v["hex"] for k, v in committed["weights"].items()}
    got = {k: v["hex"] for k, v in art["weights"].items()}
    if want != got:
        print(f"[learn-ci] FAIL: retrained weights {got} != committed "
              f"{want} — trainer/fixture drifted without a regenerate",
              file=sys.stderr)
        return 1
    print(f"[learn-ci] trained policy reproduces committed weight bits: "
          f"{got}", file=sys.stderr)

    judgment = judge_mod.judge(
        art, scenarios=("storm-learn-judge",), trace_dumps=(FIXTURE,))
    for row in judgment["scenarios"]:
        gates = ",".join(
            f"{k}={'ok' if v else 'FAIL'}" for k, v in row["gates"].items())
        print(f"[learn-ci] {row['name']}: learned "
              f"goodput={row['learned']['goodput_tokens_per_s']} vs "
              f"heuristic {row['heuristic']['goodput_tokens_per_s']} "
              f"({gates})", file=sys.stderr)
    if not judgment["promote"]:
        print("[learn-ci] FAIL: twin judge verdict is HOLD",
              file=sys.stderr)
        return 1

    with open(JUDGMENT) as fh:
        pinned = json.load(fh)
    pinned_fps = {r["name"]: r["schedule_fingerprint"]
                  for r in pinned["scenarios"]}
    live_fps = {r["name"]: r["schedule_fingerprint"]
                for r in judgment["scenarios"]}
    # Names embed the absolute trace path; compare on basenames.
    norm = lambda fps: {os.path.basename(k): v for k, v in fps.items()}
    if norm(pinned_fps) != norm(live_fps):
        print(f"[learn-ci] FAIL: judged schedule fingerprints "
              f"{live_fps} != committed {pinned_fps} — the twin did not "
              "see the committed traffic", file=sys.stderr)
        return 1
    print("[learn-ci] PROMOTE — verdict and schedule fingerprints match "
          "the committed judgment", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
