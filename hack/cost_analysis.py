"""XLA cost analysis of the compiled scheduling cycle (bytes accessed +
flops per configuration) — the HBM-traffic perf model behind the <=50 us
pick-latency budget (BASELINE.md). Run on any backend; bytes reflect the
compiled HLO's fusion structure:

    PYTHONPATH=. python hack/cost_analysis.py

History (1024x256, CPU-compiled HLO): the round-4 rewrite of
prefix.match_scores (fused cumulative-AND + bit-sliced vertical counters,
replacing lax.associative_scan + a [N,C,W,32] unpack) cut the full
default cycle from 51.4 MB (~63 us HBM-bound on one v5e) to 36.4 MB
(~44 us).
"""
import jax

jax.config.update("jax_platforms", "cpu")

import functools  # noqa: E402

import numpy as np  # noqa: E402

from gie_tpu.sched.profile import ProfileConfig, scheduling_cycle  # noqa: E402
from gie_tpu.sched.types import SchedState, Weights  # noqa: E402
from gie_tpu.utils.testing import make_endpoints, make_requests  # noqa: E402


def main() -> None:
    n, m = 1024, 256
    rng = np.random.default_rng(0)
    eps = make_endpoints(
        m, queue=rng.integers(0, 50, m).tolist(),
        kv=rng.uniform(0, 0.95, m).tolist(), max_lora=8, m_slots=m)
    base = b"SYSTEM: task %d. "
    prompts = [(base % (i % 16)) * 6 + b"u%d" % i for i in range(n)]
    reqs = make_requests(
        n, prompts=prompts, lora_id=rng.integers(-1, 12, n).tolist(),
        m_slots=m)
    # Chunk-axis bucket, as the batching layer sizes it.
    from gie_tpu.sched.types import chunk_bucket_for

    cb = chunk_bucket_for(int(np.asarray(reqs.n_chunks).max()))
    reqs = reqs.replace(chunk_hashes=reqs.chunk_hashes[:, :cb])
    print(f"shape: n={n} m={m} chunk_lanes={cb}")
    st = SchedState.init(m=m)
    w = Weights.default()
    key = jax.random.PRNGKey(0)

    for name, cfg in [
        ("full-default", ProfileConfig()),
        ("no-prefix", ProfileConfig(enable_prefix=False)),
        ("no-session", ProfileConfig(enable_session=False)),
        ("no-lora", ProfileConfig(enable_lora=False)),
        ("sinkhorn", ProfileConfig(picker="sinkhorn")),
        ("pd", ProfileConfig(pd_disaggregation=True)),
    ]:
        fn = jax.jit(functools.partial(
            scheduling_cycle, cfg=cfg, predictor_fn=None))
        ca = fn.lower(st, reqs, eps, w, key, None).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops = ca.get("flops", 0)
        ba = ca.get("bytes accessed", 0)
        print(f"{name:14s} flops={flops/1e6:8.1f}M bytes={ba/1e6:8.1f}MB "
              f"(hbm-bound est @819GB/s: {ba/819e9*1e6:6.1f}us)")


if __name__ == "__main__":
    main()
