"""XLA cost analysis of the compiled scheduling cycle (bytes accessed +
flops per configuration) — the HBM-traffic perf model behind the <=50 us
pick-latency budget (BASELINE.md). Run on any backend; bytes reflect the
compiled HLO's fusion structure:

    PYTHONPATH=. python hack/cost_analysis.py

The workload fixture is shared with tests/test_cost_budget.py (the CI
gate) via gie_tpu/utils/costmodel.py, so the printed numbers and the
gate's ceilings can never measure different programs.

History (1024x256, CPU-compiled HLO): the round-4 rewrite of
prefix.match_scores (fused cumulative-AND + bit-sliced vertical counters,
replacing lax.associative_scan + a [N,C,W,32] unpack) plus chunk-axis
bucketing cut the full default cycle from 51.4 MB (~63 us HBM-bound on
one v5e) to 30.5 MB (~37 us); the dual-form Sinkhorn iteration trimmed
that picker from 60.8 to 58.5 MB. Round 5's threshold-descent topk
(pickers._topk no longer rewrites the [N, M] operand between rounds)
took the default cycle to 29.6 MB (~36 us) and the pd dual pick from
48.6 to 44.5 MB; aligning the measurement with production donation
semantics (the live Scheduler donates the state, so scatters update in
place) puts the honest numbers at 27.5 MB default / 42.4 pd / 55.5
sinkhorn (~33.6 us default). A merged evict+OR insert scatter was
prototyped and REJECTED — row-level last-wins drops concurrent different-endpoint bits
on shared chunk rows, exactly the common shared-prefix wave.
"""
import jax

jax.config.update("jax_platforms", "cpu")

from gie_tpu.sched.profile import ProfileConfig  # noqa: E402
from gie_tpu.utils.costmodel import cycle_cost  # noqa: E402


def main() -> None:
    for name, cfg in [
        ("full-default", ProfileConfig()),
        ("no-prefix", ProfileConfig(enable_prefix=False)),
        ("no-session", ProfileConfig(enable_session=False)),
        ("no-lora", ProfileConfig(enable_lora=False)),
        ("sinkhorn", ProfileConfig(picker="sinkhorn")),
        ("pd", ProfileConfig(pd_disaggregation=True)),
    ]:
        c = cycle_cost(cfg)
        print(f"{name:14s} flops={c['flops']/1e6:8.1f}M "
              f"bytes={c['bytes']/1e6:8.1f}MB "
              f"(hbm-bound est @819GB/s: {c['bytes']/819e9*1e6:6.1f}us)")


if __name__ == "__main__":
    main()
