"""Export seed corpora for the native fuzz harnesses (native/fuzz/).

The jsonscan corpus is lifted verbatim from tests/test_fieldscan.py's
directed corpora (the same bodies the parity suite pins against
json.loads), the promparse corpus from production-shaped exposition
samples (including the 0xFE spec||text split the harness understands),
the chunker corpus from prompt-like byte blobs sized around the
header scheme fuzz_chunker.cc decodes, and the pbwalk corpus from
hand-serialized ProcessingRequest frames covering every walker verdict
class (classified / FALLBACK / INVALID). Run from the repo root:

    python hack/fuzz_seeds.py [out_dir]   # default native/fuzz/corpus

`make fuzz-smoke` runs this automatically before the harnesses.
"""

from __future__ import annotations

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Module-level directed corpora exported from the fieldscan parity suite.
_FIELDSCAN_LISTS = (
    "PLAIN_BODIES",
    "UNICODE_BODIES",
    "FALLBACK_BODIES",
    "DUPLICATE_KEY_BODIES",
    "NUMBER_BODIES",
    "INVALID_BODIES",
)

PROMPARSE_SEEDS = [
    # Production vLLM exposition under the default query spec.
    b"# HELP vllm:num_requests_waiting x\n"
    b"# TYPE vllm:num_requests_waiting gauge\n"
    b"vllm:num_requests_waiting 7\n"
    b"vllm:num_requests_running 3 1700000000000\n"
    b"vllm:kv_cache_usage_perc 0.42\n"
    b'unrelated_metric{a="b"} 9\n',
    b'vllm:cache_config_info{block_size="16",num_gpu_blocks="2048"} 1\n'
    b"vllm:num_requests_waiting 0\n"
    b"vllm:num_requests_running 0\n"
    b"vllm:kv_cache_usage_perc 0\n",
    b'vllm:num_requests_waiting{engine="a\\"b\\\\c",zone="x"} 5\n'
    b"vllm:num_requests_running 1\n"
    b"vllm:kv_cache_usage_perc 0.5\n",
    b"vllm:kv_cache_usage_perc +Inf\n"
    b"vllm:num_requests_waiting -Inf\n"
    b"vllm:num_requests_running NaN\n",
    b'vllm:lora_requests_info{running_lora_adapters="a,b",'
    b'max_lora="4",waiting_lora_adapters=""} 1.0 99\n'
    b'vllm:lora_requests_info{running_lora_adapters="c",'
    b'max_lora="4",waiting_lora_adapters="d"} 1.0 100\n'
    b"vllm:num_requests_running 2\n",
    # Custom spec segment before the 0xFE separator: both grammars fuzz.
    b"metric_a\nmetric_b|l=v|vl\xfemetric_a 1\nmetric_b{l=\"v\",vl=\"3\"} 1\n",
    b"\xfe",      # empty spec, empty text
    b"",          # default spec, empty text
    b"vllm:num_requests_waiting 1e309\n",  # overflow-to-inf value path
]

CHUNKER_SEEDS = [
    # 3-byte header (n_prompts/chunk_bytes/max_chunks) + weights + body.
    bytes([0, 15, 8]) + bytes([1]) + b"The quick brown fox " * 8,
    bytes([3, 63, 32]) + bytes([1, 2, 3, 4]) + bytes(range(256)) * 3,
    bytes([1, 0, 0]) + bytes([7, 9]) + b"\x00" * 129,  # max_chunks=0 legal
    bytes([2, 95, 16]) + bytes([255, 0, 128]) + b"abc" * 211,
    bytes([0, 1, 32]) + bytes([1]),  # empty body
]


def _varint(n: int) -> bytes:
    out = bytearray()
    while n > 0x7F:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def _ld(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _hv(key: bytes, raw: bytes) -> bytes:
    return _ld(1, key) + _ld(3, raw)


# The admission HeaderMap every classified request-headers frame
# carries (serialized HeaderMap: repeated HeaderValue in field 1), and
# its HttpHeaders.headers wrapping (field 1 again, one level up).
_HEADER_MAP = (_ld(1, _hv(b":path", b"/v1/completions"))
               + _ld(1, _hv(b"content-type", b"application/json"))
               + _ld(1, _hv(b"x-gateway-model-name", b"llama")))
_HDRS = _ld(1, _HEADER_MAP)

# Hand-built serialized ProcessingRequest frames spanning every pbwalk
# verdict class (gie-wire): classified headers/body arms, FALLBACK
# triggers (trailers, metadata_context, reserved field 1, duplicate
# arms), and INVALID shapes (truncation, bad UTF-8, over-length LEN) —
# the byte-mutation fuzzer then walks outward from valid structures.
PBWALK_SEEDS = [
    _ld(2, _HDRS + bytes([3 << 3, 1])),              # request_headers eos
    _ld(2, _HDRS),                                   # headers, no eos
    _ld(3, _ld(1, b'{"model":"llama","prompt":"hi"}')
        + bytes([2 << 3, 1])),                       # request_body eos
    _ld(3, _ld(1, b'{"stream":')),                   # body chunk, no eos
    _ld(5, _HDRS),                                   # response_headers
    _ld(6, _ld(1, b'data: {"ok":1}\n\n')),           # response_body
    _ld(4, _ld(1, _ld(1, _hv(b"grpc-status", b"0")))),  # trailers: FALLBACK
    _ld(8, _ld(1, b"")) + _ld(2, _HDRS),             # metadata_context
    _ld(1, b"\x01\x02") + _ld(2, _HDRS),             # reserved field 1
    _ld(2, _HDRS) + _ld(3, _ld(1, b"{}")),           # duplicate oneof arms
    # HeaderValue.value (field 2) is a proto3 string: bad UTF-8 is
    # INVALID (raw_value, field 3, is bytes and takes anything).
    _ld(2, _ld(1, _ld(1, _ld(1, b"k") + _ld(2, b"\xff\xfe")))),
    _ld(2, _HDRS)[:-4],                              # truncated
    b"",                                             # empty frame
]


def _load_fieldscan_bodies() -> list[bytes]:
    if REPO not in sys.path:
        sys.path.insert(0, REPO)  # test module imports gie_tpu
    try:
        import pytest  # noqa: F401
    except ImportError:
        # Runtime container: no pytest. The corpora are plain
        # module-level byte lists; a decorator-absorbing stub is enough
        # to import them.
        import types
        import unittest.mock as mock
        stub = types.ModuleType("pytest")
        stub.mark = mock.MagicMock()
        sys.modules["pytest"] = stub
    path = os.path.join(REPO, "tests", "test_fieldscan.py")
    spec = importlib.util.spec_from_file_location("_fieldscan_corpus", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bodies: list[bytes] = []
    for name in _FIELDSCAN_LISTS:
        bodies.extend(getattr(mod, name))
    return bodies


def _write(out_dir: str, name: str, seeds: list[bytes]) -> int:
    d = os.path.join(out_dir, name)
    os.makedirs(d, exist_ok=True)
    for i, blob in enumerate(seeds):
        with open(os.path.join(d, f"seed_{i:03d}"), "wb") as f:
            f.write(blob)
    return len(seeds)


def main(argv: list[str]) -> int:
    out_dir = argv[1] if len(argv) > 1 else os.path.join(
        REPO, "native", "fuzz", "corpus")
    json_seeds = _load_fieldscan_bodies()
    # jsonscan also doubles as the headers_scan input; add a serialized
    # HeaderMap-shaped blob so the varint walker starts from valid bytes.
    json_seeds = list(json_seeds) + [
        b"\n\x1a\n\x0ccontent-type\x12\x10application/json"
        b"\n\x14\n\x05:path\x12\x0b/v1/generate",
    ]
    counts = {
        "jsonscan": _write(out_dir, "jsonscan", json_seeds),
        "promparse": _write(out_dir, "promparse", PROMPARSE_SEEDS),
        "chunker": _write(out_dir, "chunker", CHUNKER_SEEDS),
        "pbwalk": _write(out_dir, "pbwalk", PBWALK_SEEDS),
    }
    for name, n in sorted(counts.items()):
        print(f"fuzz_seeds: {n:3d} seed(s) -> {out_dir}/{name}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
