#!/usr/bin/env bash
# Data-plane smoke: a real (unmodified) Envoy routes one HTTP request
# through ext_proc -> this EPP -> a demo pod, and the response proves the
# EPP's steering was honored (reference site-src/guides/
# implementers.md:125-135; config/envoy/bootstrap.yaml for the wiring).
#
#   envoy --config config/envoy/bootstrap.yaml
#        \__ ext_proc -> EPP :9002 (demo mode, --insecure-serving)
#        \__ original_dst on x-gateway-destination-endpoint -> demo pod
#
# Skips cleanly (exit 0, "SKIP") when no envoy binary is on PATH — the CI
# image has none; run it wherever Envoy is installed. Requires: bash,
# curl, python3, and the repo at its root.
set -u
cd "$(dirname "$0")/.."

ENVOY_BIN="${ENVOY_BIN:-$(command -v envoy || true)}"
if [ -z "${ENVOY_BIN}" ]; then
  echo "SKIP: no envoy binary on PATH (set ENVOY_BIN to override)"
  exit 0
fi

LOGDIR="$(mktemp -d)"
EPP_PID=""
ENVOY_PID=""
cleanup() {
  [ -n "${ENVOY_PID}" ] && kill "${ENVOY_PID}" 2>/dev/null
  [ -n "${EPP_PID}" ] && kill "${EPP_PID}" 2>/dev/null
  echo "logs: ${LOGDIR}"
}
trap cleanup EXIT

echo "== starting EPP (demo mode, CPU backend) =="
python3 -c "import jax; jax.config.update('jax_platforms','cpu');
import sys
from gie_tpu.runtime.main import main
sys.exit(main(['--demo','--demo-pods','3','--insecure-serving','--pool-name','demo-pool']))" \
  >"${LOGDIR}/epp.log" 2>&1 &
EPP_PID=$!

for _ in $(seq 1 60); do
  grep -q '"msg": "serving"' "${LOGDIR}/epp.log" 2>/dev/null && break
  sleep 1
done
if ! grep -q '"msg": "serving"' "${LOGDIR}/epp.log"; then
  echo "FAIL: EPP did not start"; tail -5 "${LOGDIR}/epp.log"; exit 1
fi

echo "== starting envoy =="
"${ENVOY_BIN}" --config-path config/envoy/bootstrap.yaml \
  --log-level warn >"${LOGDIR}/envoy.log" 2>&1 &
ENVOY_PID=$!
for _ in $(seq 1 30); do
  curl -sf -o /dev/null http://127.0.0.1:9901/ready && break
  sleep 1
done

echo "== driving one completion request through envoy =="
RESP_HEADERS="${LOGDIR}/resp_headers.txt"
BODY='{"model":"demo","prompt":"hello","max_tokens":16}'
HTTP_CODE=$(curl -s -o "${LOGDIR}/resp_body.txt" -D "${RESP_HEADERS}" \
  -w '%{http_code}' -X POST -H 'content-type: application/json' \
  -d "${BODY}" http://127.0.0.1:8081/v1/completions)

if [ "${HTTP_CODE}" != "200" ]; then
  echo "FAIL: expected 200 through the data plane, got ${HTTP_CODE}"
  tail -5 "${LOGDIR}/envoy.log"; exit 1
fi
SERVED=$(awk 'tolower($1)=="x-served-by:" {print $2}' "${RESP_HEADERS}" | tr -d '\r')
if [ -z "${SERVED}" ]; then
  echo "FAIL: response did not come from a demo pod (no X-Served-By)"
  exit 1
fi
echo "PASS: request served by demo pod ${SERVED} via EPP steering"
