"""Regenerate the gie-learn fixture dump (tests/fixtures/learn/).

The fixture is a REAL flight-recorder dump: a seeded virtual-clock storm
(LoRA churn over a small pool — enough contention that queue/kv/load
columns vary and serve latencies spread) with the recorder armed, dumped
through the same load_records format production harvests produce. The
learn tests and `make learn-ci` train from this file and replay it
through TraceReplay, so regenerate ONLY when the record schema or the
storm engine's decision sequence intentionally changes, and commit the
result:

    JAX_PLATFORMS=cpu python hack/learn_fixture.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update(
    "jax_platforms", os.environ.get("GIE_STORM_PLATFORM", "cpu"))

OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures", "learn", "storm-fixture-flightrec.json")
SEED = 2024


def main() -> int:
    from gie_tpu import obs
    from gie_tpu.obs.recorder import FlightRecorder, load_records
    from gie_tpu.storm import shapes as S
    from gie_tpu.storm.engine import PoolSpec, StormEngine

    prog = S.Program(
        S.TrafficConfig(base_qps=24.0, duration_s=8.0, n_sessions=12,
                        sheddable_fraction=0.2),
        [S.LoraChurn(adapters=3, hot=1, rotate_every_s=2.0, p=0.4),
         S.FlashCrowd(at_s=2.0, ramp_s=0.5, hold_s=3.0, magnitude=4.0,
                      decay_s=0.5)],
        seed=SEED)
    eng = StormEngine(prog, pool=PoolSpec(n_pods=3), virtual_time=True,
                      name="learn-fixture")
    try:
        sched = prog.compile()
        # Warm BEFORE arming the recorder: warmup picks are harness
        # traffic, not workload — the fixture must carry arrivals only.
        eng.warmup(sched)
        obs.install(recorder=FlightRecorder(8192))
        try:
            eng.run(schedule=sched, warmup=False)
            records = obs.RECORDER.snapshot()
        finally:
            obs.uninstall()
        fingerprint = sched.fingerprint()
    finally:
        eng.close()

    payload = {
        "name": "learn-fixture",
        "schedule_fingerprint": fingerprint,
        "records": records,
    }
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, default=str, sort_keys=True)
    loaded = load_records(json.dumps(payload, default=str))
    served = sum(1 for r in loaded if r.get("outcome") == "2xx"
                 and "serve_latency_ms" in r)
    print(f"wrote {OUT}: {len(loaded)} records, {served} scored serves, "
          f"fingerprint {fingerprint[:16]}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
