"""Experiment (VERDICT r4 #8): where does the latency COLUMN earn its keep?

The flagship goodput bench is a HOMOGENEOUS fleet, where the predictor's
per-endpoint embedding has nothing persistent to learn — queue/kv metrics
already rank pods, so the trained column was goodput-neutral there
(BENCH_NOTES round 4: 2320 vs 2328 tok/s). BASELINE configs[3] sells the
column as a scorer signal, so this experiment builds the regime that
signal was designed for: a heterogeneous fleet (half the pods degraded —
slower prefill AND decode, as with mixed accelerator generations or
noisy neighbors) under mixed decode lengths. Metric-only scoring sees a
degraded pod only through its lagging queue; the per-endpoint embedding
learns the pod IS slow and steers proportionally.

Runs tpu (tuned scheduler, metric-only) vs tpu+column (same scheduler +
online trainer feeding the confidence-gated latency column; SLO admission
OFF so the column is the only delta). Also reports the homogeneous fleet
for contrast. One JSON line; detail to stderr.
"""

from __future__ import annotations

import json
import sys


def _force_platform() -> None:
    import os

    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("GIE_GOODPUT_PLATFORM", "cpu"))


def run_fleet(fleet_name, cfgs, with_column, seed=0, duration=20.0):
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from bench_goodput import HEADLINE_WORKLOAD
    from gie_tpu.simulator.cluster import (
        SimCluster,
        WorkloadConfig,
        tuned_scheduler,
    )

    # The headline workload already mixes decode lengths (exponential-ish
    # draws around decode_tokens_mean) — the fleet, not the workload, is
    # what this experiment perturbs.
    wl = WorkloadConfig(**HEADLINE_WORKLOAD)
    cluster = SimCluster(n_pods=len(cfgs), stub_cfg=cfgs, seed=seed)
    kwargs = {}
    if with_column:
        from gie_tpu.models.latency import LatencyPredictor, OnlineTrainer

        kwargs = dict(
            trainer=OnlineTrainer(LatencyPredictor(), batch_size=64),
            train_every_s=0.5,
        )
    stats = cluster.run("tpu", wl, duration_s=duration,
                        scheduler=tuned_scheduler(), **kwargs)
    tag = "column" if with_column else "metric-only"
    print(
        f"{fleet_name:12s} {tag:11s} goodput={stats.goodput_tokens_per_s:7.1f}"
        f" tok/s slo={stats.slo_attainment:.2f}"
        f" hit={stats.prefix_hit_rate:.2f} ttft_p50={stats.ttft_p50_s:.2f}s",
        file=sys.stderr, flush=True,
    )
    return stats


def main() -> None:
    _force_platform()
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from bench_goodput import HEADLINE_STUB
    from gie_tpu.simulator import StubConfig

    base = HEADLINE_STUB
    fast = StubConfig(**base)
    degraded = StubConfig(**{
        **base,
        "prefill_tokens_per_s": 1500.0,
        "decode_tokens_per_s": 20.0,
    })

    hetero = [fast] * 4 + [degraded] * 4
    homog = [fast] * 8

    results = {}
    for fleet_name, cfgs in (("hetero", hetero), ("homogeneous", homog)):
        for with_column in (False, True):
            key = (fleet_name, "column" if with_column else "metric-only")
            results[key] = run_fleet(fleet_name, cfgs, with_column)

    het_ratio = (
        results[("hetero", "column")].goodput_tokens_per_s
        / max(results[("hetero", "metric-only")].goodput_tokens_per_s, 1e-9))
    hom_ratio = (
        results[("homogeneous", "column")].goodput_tokens_per_s
        / max(results[("homogeneous", "metric-only")].goodput_tokens_per_s,
              1e-9))
    print(f"column lift: hetero={het_ratio:.3f}x homogeneous={hom_ratio:.3f}x",
          file=sys.stderr)
    print(json.dumps({
        "metric": "latency_column_goodput_lift_hetero_fleet",
        "value": round(het_ratio, 3),
        "unit": "ratio",
        "vs_baseline": round(het_ratio, 3),
    }))


if __name__ == "__main__":
    main()
