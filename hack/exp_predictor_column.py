"""Experiment (VERDICT r4 #8): where does the latency COLUMN earn its keep?

The flagship goodput bench is a HOMOGENEOUS fleet, where the predictor's
per-endpoint embedding has nothing persistent to learn — queue/kv metrics
already rank pods, so the trained column was goodput-neutral there
(BENCH_NOTES round 4: 2320 vs 2328 tok/s). BASELINE configs[3] sells the
column as a scorer signal, so this experiment builds the regime that
signal was designed for: a heterogeneous fleet (half the pods degraded —
slower prefill AND decode, as with mixed accelerator generations or
noisy neighbors) under mixed decode lengths. Metric-only scoring sees a
degraded pod only through its lagging queue; the per-endpoint embedding
learns the pod IS slow and steers proportionally.

Runs tpu (tuned scheduler, metric-only) vs tpu+column (same scheduler +
online trainer feeding the confidence-gated latency column; SLO admission
OFF so the column is the only delta). Also reports the homogeneous fleet
for contrast. One JSON line; detail to stderr.
"""

from __future__ import annotations

import json
import sys


def run_fleet(fleet_name, cfgs, with_column, seed=0, duration=None,
              wl_over=None):
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from bench_goodput import HEADLINE_DURATION_S, HEADLINE_WORKLOAD
    from gie_tpu.simulator.cluster import (
        SimCluster,
        WorkloadConfig,
        tuned_scheduler,
    )

    # The headline workload already mixes decode lengths (exponential-ish
    # draws around decode_tokens_mean) — the fleet, not the workload, is
    # what this experiment perturbs (wl_over builds the cache-affinity-free
    # variant).
    wl = WorkloadConfig(**{**HEADLINE_WORKLOAD, **(wl_over or {})})
    duration = HEADLINE_DURATION_S if duration is None else duration
    cluster = SimCluster(n_pods=len(cfgs), stub_cfg=cfgs, seed=seed)
    kwargs = {}
    sched = tuned_scheduler()
    if with_column:
        from gie_tpu.models.latency import (
            LatencyPredictor,
            OnlineTrainer,
            predictor_score_fn,
        )
        import jax.numpy as jnp

        from gie_tpu.sched import Scheduler

        # tuned_profile ships latency=0.0 (the column is off in the
        # flagship profile); the column arm raises the CEILING to 1.5 and
        # wires the predictor into the compiled cycle — the confidence
        # gate still phases the live weight in from 0 as training
        # converges, exactly the production path.
        p = LatencyPredictor()
        trainer = OnlineTrainer(p, batch_size=64)
        sched = Scheduler(
            sched.cfg,
            weights=sched.weights.replace(latency=jnp.float32(1.5)),
            predictor_fn=predictor_score_fn(p),
            predictor_params=trainer.params,
        )
        kwargs = dict(trainer=trainer, train_every_s=0.5)
    stats = cluster.run("tpu", wl, duration_s=duration,
                        scheduler=sched, **kwargs)
    tag = "column" if with_column else "metric-only"
    print(
        f"{fleet_name:12s} {tag:11s} goodput={stats.goodput_tokens_per_s:7.1f}"
        f" tok/s slo={stats.slo_attainment:.2f}"
        f" hit={stats.prefix_hit_rate:.2f} ttft_p50={stats.ttft_p50_s:.2f}s",
        file=sys.stderr, flush=True,
    )
    return stats


def main() -> None:
    import os
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from bench_goodput import HEADLINE_STUB, _force_platform

    _force_platform()
    from gie_tpu.simulator import StubConfig

    base = HEADLINE_STUB
    fast = StubConfig(**base)
    degraded = StubConfig(**{
        **base,
        "prefill_tokens_per_s": 1500.0,
        "decode_tokens_per_s": 20.0,
    })

    hetero = [fast] * 4 + [degraded] * 4
    homog = [fast] * 8
    # Cache-affinity-free traffic over the hetero fleet: ~every prompt
    # unique (4096 sessions, tiny shared prefix), so prefix/session
    # scoring has nothing to protect and the column's learned slow-pod
    # signal is the only persistent speed information (queue depth lags).
    unique_wl = dict(n_sessions=4096, system_prompt_bytes=256,
                     user_suffix_bytes=1024)

    results = {}
    cases = (
        ("hetero", hetero, None),
        ("hetero+unique", hetero, unique_wl),
        ("homogeneous", homog, None),
    )
    for fleet_name, cfgs, wl_over in cases:
        for with_column in (False, True):
            key = (fleet_name, "column" if with_column else "metric-only")
            results[key] = run_fleet(fleet_name, cfgs, with_column,
                                     wl_over=wl_over)

    ratios = {}
    for fleet_name, _, _ in cases:
        ratios[fleet_name] = (
            results[(fleet_name, "column")].goodput_tokens_per_s
            / max(results[(fleet_name, "metric-only")].goodput_tokens_per_s,
                  1e-9))
    print("column lift: " + "  ".join(
        f"{k}={v:.3f}x" for k, v in ratios.items()), file=sys.stderr)
    best = max(ratios.values())
    print(json.dumps({
        "metric": "latency_column_goodput_lift_best_regime",
        "value": round(best, 3),
        "unit": "ratio",
        "vs_baseline": round(best, 3),
    }))


if __name__ == "__main__":
    main()
