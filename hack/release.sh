#!/usr/bin/env bash
# Release packaging (reference hack/release-*.sh parity): stamp the bundle
# version, regenerate CRDs, run the suite + conformance, and emit a
# versioned artifact directory with manifests + conformance report.
set -euo pipefail

cd "$(dirname "$0")/.."
if [[ -n "$(git status --porcelain)" ]]; then
    echo "ERROR: working tree is dirty; commit or stash before releasing" >&2
    exit 1
fi
VERSION=$(python -c "from gie_tpu.version import BUNDLE_VERSION; print(BUNDLE_VERSION)")
OUT="dist/${VERSION}"
rm -rf "${OUT}"

echo "==> release ${VERSION}"
make native generate
python -m pytest tests/ -q
python -m conformance.run --report "conformance-report-${VERSION}.yaml"

mkdir -p "${OUT}"
cp -r config/crd/bases "${OUT}/crds"
cp config/scheduler/sinkhorn-tuned.yaml "${OUT}/"
mv "conformance-report-${VERSION}.yaml" "${OUT}/"
git rev-parse HEAD > "${OUT}/COMMIT"
echo "==> artifacts in ${OUT}"
ls -l "${OUT}"
