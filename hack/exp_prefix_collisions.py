"""Experiment (round 5, ROADMAP item 5): is the direct-mapped prefix
table's collision rate the binding hit-rate loss?

Answer: no. Quadrupling PREFIX_SLOTS (2^15 -> 2^17) lifts hit rate only
+0.01 (0.924 -> 0.930 seed 0; 0.908 -> 0.923 seed 2) and goodput moves
WITHIN seed noise — up on seed 0 (+2.9%), down on seeds 1/2 (-0.9%,
-6.9%), mean slightly negative (2535 vs 2579 tok/s). Collisions are a
~1pp hit tail, not the goodput-binding loss, and the bigger table also
retains stale presence longer; 2-way set association stays retired. The
remaining hit tail is same-wave session splits under the OT capacity
constraint (the round-5 session-failover ladder ships the cheap lever
for that). See BENCH_NOTES round 5.

History: the first version of this experiment assigned C.PREFIX_SLOTS
and concluded from bit-identical output — a NO-OP (SchedState.init's
default froze at import), caught in review. The state swap below is the
real plumbing.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ.get(
    "GIE_GOODPUT_PLATFORM", "cpu"))

from bench_goodput import (  # noqa: E402
    HEADLINE_DURATION_S,
    HEADLINE_STUB,
    HEADLINE_WORKLOAD,
)
from gie_tpu.simulator import StubConfig  # noqa: E402
from gie_tpu.simulator.cluster import (  # noqa: E402
    SimCluster,
    WorkloadConfig,
    tuned_scheduler,
)


def main() -> None:
    from gie_tpu.sched.types import SchedState

    # Seeds 0-2: the docstring's "mean slightly negative" verdict is the
    # cross-seed mean, so the script must reproduce all three pairs.
    means = {15: 0.0, 17: 0.0}
    for slots_shift in (15, 17):  # 32768 (default) vs 131072 rows
        for seed in (0, 1, 2):
            wl = WorkloadConfig(**HEADLINE_WORKLOAD)
            cluster = SimCluster(
                n_pods=8, stub_cfg=StubConfig(**HEADLINE_STUB), seed=seed)
            sched = tuned_scheduler()
            # Rebuild the device state with the requested table size:
            # assigning C.PREFIX_SLOTS is a NO-OP (SchedState.init's
            # default froze at import) — the round-5 review caught the
            # first version of this experiment comparing 2^15 against
            # itself. All runtime indexing derives from
            # table.keys.shape[0], so swapping the state is the plumbing.
            sched.state = SchedState.init(
                slots=1 << slots_shift,
                m=int(sched.state.assumed_load.shape[0]))
            stats = cluster.run("tpu", wl, duration_s=HEADLINE_DURATION_S,
                                scheduler=sched)
            means[slots_shift] += stats.goodput_tokens_per_s / 3.0
            print(
                f"PREFIX_SLOTS=2^{slots_shift} seed={seed} "
                f"(table rows: {int(sched.state.prefix.keys.shape[0])}): "
                f"goodput={stats.goodput_tokens_per_s:.1f} "
                f"hit={stats.prefix_hit_rate:.3f} "
                f"slo={stats.slo_attainment:.2f}",
                flush=True,
            )
    print(f"means: 2^15={means[15]:.1f} 2^17={means[17]:.1f} tok/s",
          flush=True)


if __name__ == "__main__":
    main()
