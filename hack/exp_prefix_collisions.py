"""Experiment (round 5, ROADMAP item 5): is the direct-mapped prefix
table's collision rate the binding hit-rate loss?

Answer: no. Quadrupling PREFIX_SLOTS (2^15 -> 2^17) at the headline
operating point leaves goodput and hit rate bit-identical (2389.0 tok/s,
hit 0.914), so 2-way set association would buy nothing — the remaining
0.91-vs-0.97 hit tail is same-wave session splits under the OT capacity
constraint, not index collisions. See BENCH_NOTES round 5.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ.get(
    "GIE_GOODPUT_PLATFORM", "cpu"))

from bench_goodput import (  # noqa: E402
    HEADLINE_DURATION_S,
    HEADLINE_STUB,
    HEADLINE_WORKLOAD,
)
from gie_tpu.sched import constants as C  # noqa: E402
from gie_tpu.simulator import StubConfig  # noqa: E402
from gie_tpu.simulator.cluster import (  # noqa: E402
    SimCluster,
    WorkloadConfig,
    tuned_scheduler,
)


def main() -> None:
    for slots_shift in (15, 17):  # 32768 (default) vs 131072 rows
        C.PREFIX_SLOTS = 1 << slots_shift
        wl = WorkloadConfig(**HEADLINE_WORKLOAD)
        cluster = SimCluster(
            n_pods=8, stub_cfg=StubConfig(**HEADLINE_STUB), seed=0)
        stats = cluster.run("tpu", wl, duration_s=HEADLINE_DURATION_S,
                            scheduler=tuned_scheduler())
        print(
            f"PREFIX_SLOTS=2^{slots_shift}: "
            f"goodput={stats.goodput_tokens_per_s:.1f} "
            f"hit={stats.prefix_hit_rate:.3f} "
            f"slo={stats.slo_attainment:.2f}",
            flush=True,
        )


if __name__ == "__main__":
    main()
