"""Degraded-rung calibration sweeps (docs/RESILIENCE.md "ladder
calibration"; ISSUE 10/11 satellites).

Two sweeps, one harness: pin the ladder on a rung
(DegradationLadder.force_level + prohibitive recovery thresholds), run
the same seeded flash-crowd storm through the REAL stack per candidate
value, score goodput / SLO attainment / TTFT percentiles — the rung's
OWN policy performance, isolated from transition dynamics — and record
the winning default.

  cached-kv   the CACHED rung's ``queue + w*kv`` weight
              (--ladder-cached-kv-weight; ISSUE 10, table recorded).
  wrr-alpha   the ROUND_ROBIN rung's smooth-WRR queue-shape exponent
              ``weight = (1+queue)^-alpha`` (--ladder-wrr-alpha;
              ISSUE 11 — alpha 0 is uniform rotation, ignoring the
              last-known-good rows the blackout froze; larger alphas
              trust the stale queue column harder).

    JAX_PLATFORMS=cpu python hack/storm_sweep.py --sweep wrr-alpha
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _run_rung_storm(*, seed: int, duration_s: float, ladder_kw: dict,
                    rung: int, name: str) -> dict:
    from gie_tpu.resilience.ladder import LadderConfig
    from gie_tpu.storm import shapes as S
    from gie_tpu.storm.engine import EngineConfig, PoolSpec, StormEngine

    tc = S.TrafficConfig(base_qps=36.0, duration_s=duration_s,
                         n_sessions=16, decode_tokens_mean=20.0)
    prog = S.Program(tc, [
        S.FlashCrowd(at_s=1.5, ramp_s=0.8, hold_s=3.0, magnitude=3.0),
    ], seed=seed)
    # Prohibitive recovery thresholds + force_level pin the rung so the
    # sweep measures the rung's policy, not the ladder dynamics.
    ladder = LadderConfig(
        dispatch_error_streak=10_000, recover_streak=10_000,
        min_dwell_s=1e9, probe_interval_s=1e9,
        serve_min_samples=10_000, **ladder_kw)
    eng = StormEngine(
        prog, pool=PoolSpec(n_pods=6),
        cfg=EngineConfig(ttft_slo_s=2.5, ladder=ladder, force_rung=rung),
        name=name)
    try:
        return eng.run().scorecard
    finally:
        eng.close()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", default="cached-kv",
                        choices=["cached-kv", "wrr-alpha"])
    parser.add_argument("--values", default=None,
                        help="comma-separated candidate values "
                             "(defaults per sweep)")
    parser.add_argument("--seed", type=int, default=626262)
    parser.add_argument("--duration-s", type=float, default=8.0)
    parser.add_argument("--out", default=None,
                        help="optional JSON artifact path")
    args = parser.parse_args()

    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("GIE_STORM_PLATFORM", "cpu"))

    from gie_tpu.resilience.ladder import Rung

    if args.sweep == "cached-kv":
        values = args.values or "0,2,4,8,16,32"
        knob, rung = "cached_kv_weight", int(Rung.CACHED)
        scenario = "flash-crowd x3 @36qps, 6 pods, forced CACHED"
    else:
        values = args.values or "0,0.5,1,2,4"
        knob, rung = "wrr_queue_alpha", int(Rung.ROUND_ROBIN)
        scenario = "flash-crowd x3 @36qps, 6 pods, forced ROUND_ROBIN"

    rows = []
    for v in [float(x) for x in values.split(",")]:
        card = _run_rung_storm(
            seed=args.seed, duration_s=args.duration_s,
            ladder_kw={knob: v}, rung=rung,
            name=f"{args.sweep}-{v:g}")
        row = {
            knob: v,
            "goodput_tokens_per_s": round(card["goodput_tokens_per_s"], 1),
            "slo_attainment": round(card["slo_attainment"], 3),
            "ttft_p50_s": round(card["ttft_p50_s"], 3),
            "ttft_p99_s": round(card["ttft_p99_s"], 3),
            "completed": card["completed"],
            "shed": card["shed"],
            "client_5xx": card["client_5xx"],
        }
        rows.append(row)
        print(f"{knob}={v:5g}  goodput={row['goodput_tokens_per_s']:8.1f}"
              f" tok/s  slo={row['slo_attainment']:.3f}"
              f"  p99={row['ttft_p99_s']:.3f}s"
              f"  completed={row['completed']}", file=sys.stderr)
    artifact = {"sweep": f"ladder-{args.sweep}", "seed": args.seed,
                "scenario": scenario, "rows": rows}
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(artifact, fh, indent=1)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
