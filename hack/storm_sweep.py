"""CACHED-rung kv-weight calibration sweep (docs/RESILIENCE.md "ladder
calibration"; ISSUE 10 satellite).

The degraded CACHED pick ranks endpoints by ``queue + w * kv_util``.
This sweep pins the ladder at CACHED (DegradationLadder.force_level)
and runs the same seeded flash-crowd storm through the REAL stack for
each candidate weight, scoring goodput / SLO attainment / TTFT p99 —
the rung's OWN performance, isolated from transition dynamics. The
resulting table is recorded in docs/RESILIENCE.md and sets the
``--ladder-cached-kv-weight`` default.

    JAX_PLATFORMS=cpu python hack/storm_sweep.py [--weights 0,2,8,32]
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--weights", default="0,2,4,8,16,32",
                        help="comma-separated cached_kv_weight candidates")
    parser.add_argument("--seed", type=int, default=626262)
    parser.add_argument("--duration-s", type=float, default=8.0)
    parser.add_argument("--out", default=None,
                        help="optional JSON artifact path")
    args = parser.parse_args()

    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("GIE_STORM_PLATFORM", "cpu"))

    from gie_tpu.resilience.ladder import LadderConfig, Rung
    from gie_tpu.storm import shapes as S
    from gie_tpu.storm.engine import EngineConfig, PoolSpec, StormEngine

    rows = []
    for w in [float(x) for x in args.weights.split(",")]:
        tc = S.TrafficConfig(base_qps=36.0, duration_s=args.duration_s,
                             n_sessions=16, decode_tokens_mean=20.0)
        prog = S.Program(tc, [
            S.FlashCrowd(at_s=1.5, ramp_s=0.8, hold_s=3.0, magnitude=3.0),
        ], seed=args.seed)
        # Prohibitive recovery thresholds + force_level pin the rung so
        # the sweep measures the CACHED policy, not the ladder dynamics.
        ladder = LadderConfig(
            dispatch_error_streak=10_000, recover_streak=10_000,
            min_dwell_s=1e9, probe_interval_s=1e9,
            serve_min_samples=10_000, cached_kv_weight=w)
        eng = StormEngine(
            prog, pool=PoolSpec(n_pods=6),
            cfg=EngineConfig(ttft_slo_s=2.5, ladder=ladder,
                             force_rung=int(Rung.CACHED)),
            name=f"cached-w{w:g}")
        try:
            card = eng.run().scorecard
        finally:
            eng.close()
        row = {
            "cached_kv_weight": w,
            "goodput_tokens_per_s": round(card["goodput_tokens_per_s"], 1),
            "slo_attainment": round(card["slo_attainment"], 3),
            "ttft_p50_s": round(card["ttft_p50_s"], 3),
            "ttft_p99_s": round(card["ttft_p99_s"], 3),
            "completed": card["completed"],
            "shed": card["shed"],
            "client_5xx": card["client_5xx"],
        }
        rows.append(row)
        print(f"w={w:5g}  goodput={row['goodput_tokens_per_s']:8.1f} tok/s"
              f"  slo={row['slo_attainment']:.3f}"
              f"  p99={row['ttft_p99_s']:.3f}s"
              f"  completed={row['completed']}", file=sys.stderr)
    artifact = {"sweep": "ladder-cached-kv-weight", "seed": args.seed,
                "scenario": "flash-crowd x3 @36qps, 6 pods, forced CACHED",
                "rows": rows}
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(artifact, fh, indent=1)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
