"""Degraded-rung calibration sweeps (docs/RESILIENCE.md "ladder
calibration"; ISSUE 10/11 satellites) — now a thin wrapper over the
generalized policy-search harness (gie_tpu/storm/search.py, ISSUE 14).

Two sweeps, one harness: pin the ladder on a rung
(DegradationLadder.force_level + prohibitive recovery thresholds), run
the same seeded flash-crowd storm per candidate value, score goodput /
SLO attainment / TTFT percentiles — the rung's OWN policy performance,
isolated from transition dynamics — and record the winning default.

  cached-kv   the CACHED rung's ``queue + w*kv`` weight
              (--ladder-cached-kv-weight; ISSUE 10, table recorded).
  wrr-alpha   the ROUND_ROBIN rung's smooth-WRR queue-shape exponent
              ``weight = (1+queue)^-alpha`` (--ladder-wrr-alpha;
              ISSUE 11).

Sweeps run under the gie-twin virtual clock by default (seconds of wall
clock per candidate; --real-time restores the historical mode — the
recorded PR 10/11 tables were measured in real time).

    JAX_PLATFORMS=cpu python hack/storm_sweep.py --sweep wrr-alpha
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sweep", default="cached-kv",
                        choices=["cached-kv", "wrr-alpha"])
    parser.add_argument("--values", default=None,
                        help="comma-separated candidate values "
                             "(defaults per sweep)")
    parser.add_argument("--seed", type=int, default=626262)
    parser.add_argument("--duration-s", type=float, default=8.0)
    parser.add_argument("--real-time", action="store_true",
                        help="run on the real clock (the historical "
                             "sweep mode) instead of the virtual clock")
    parser.add_argument("--out", default=None,
                        help="optional JSON artifact path")
    args = parser.parse_args()

    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("GIE_STORM_PLATFORM", "cpu"))

    from gie_tpu.resilience.ladder import LadderConfig, Rung
    from gie_tpu.resilience.scenarios import Scenario
    from gie_tpu.storm import search
    from gie_tpu.storm.engine import EngineConfig

    if args.sweep == "cached-kv":
        values = args.values or "0,2,4,8,16,32"
        knob, rung = "cached_kv_weight", int(Rung.CACHED)
        scenario = "flash-crowd x3 @36qps, 6 pods, forced CACHED"
    else:
        values = args.values or "0,0.5,1,2,4"
        knob, rung = "wrr_queue_alpha", int(Rung.ROUND_ROBIN)
        scenario = "flash-crowd x3 @36qps, 6 pods, forced ROUND_ROBIN"
    candidates = [float(x) for x in values.split(",")]

    # The historical sweep storm as an in-memory scenario drive.
    scn = Scenario(
        name=f"ladder-{args.sweep}-sweep",
        description=scenario,
        seed=args.seed,
        rules={},
        drive={"storm": {
            "base_qps": 36.0,
            "duration_s": args.duration_s,
            "ttft_slo_s": 2.5,
            "traffic": {"n_sessions": 16, "decode_tokens_mean": 20.0},
            "pool": {"n_pods": 6},
            "shapes": [
                {"kind": "flash_crowd", "at_s": 1.5, "ramp_s": 0.8,
                 "hold_s": 3.0, "magnitude": 3.0},
            ],
        }})
    # Prohibitive recovery thresholds + force_rung pin the rung so the
    # sweep measures the rung's policy, not the ladder dynamics.
    base_cfg = EngineConfig(
        ttft_slo_s=2.5,
        ladder=LadderConfig(
            dispatch_error_streak=10_000, recover_streak=10_000,
            min_dwell_s=1e9, probe_interval_s=1e9,
            serve_min_samples=10_000),
        force_rung=rung)

    artifact_board = search.search(
        scn,
        configs=[{f"ladder.{knob}": v} for v in candidates],
        seed=args.seed, rounds=1, base_duration_s=args.duration_s,
        virtual=not args.real_time, cfg=base_cfg)

    by_value = {row["config"][f"ladder.{knob}"]: row
                for row in artifact_board["leaderboard"]}
    rows = []
    for v in candidates:
        row_src = by_value[v]
        row = {
            knob: v,
            "goodput_tokens_per_s": round(
                row_src["goodput_tokens_per_s"], 1),
            "slo_attainment": round(row_src["slo_attainment"], 3),
            "ttft_p50_s": round(row_src["ttft_p50_s"], 3),
            "ttft_p99_s": round(row_src["ttft_p99_s"], 3),
            "completed": row_src["completed"],
            "shed": row_src["shed"],
            "client_5xx": row_src["client_5xx"],
            "rank": row_src["rank"],
        }
        rows.append(row)
        print(f"{knob}={v:5g}  goodput={row['goodput_tokens_per_s']:8.1f}"
              f" tok/s  slo={row['slo_attainment']:.3f}"
              f"  p99={row['ttft_p99_s']:.3f}s"
              f"  completed={row['completed']}  rank#{row['rank']}",
              file=sys.stderr)
    artifact = {"sweep": f"ladder-{args.sweep}", "seed": args.seed,
                "scenario": scenario,
                "virtual_time": not args.real_time, "rows": rows}
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(artifact, fh, indent=1)
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
