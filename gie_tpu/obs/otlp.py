"""OTLP/HTTP JSON span export (docs/OBSERVABILITY.md "OTLP export").

Closes the ROADMAP item-8 obs residual: the traces the Tracer already
exports to the /debugz feeds additionally POST to an OpenTelemetry
collector as OTLP/HTTP **JSON** (`/v1/traces`) — the encoding the OTLP
spec defines alongside protobuf, so no new dependency rides in.

Hot-path contract: ``export`` (the Tracer.on_export sink) only appends
to a bounded deque and sets an event — serialization and the HTTP POST
happen on this module's background thread, batched (``batch_max`` spans
or ``flush_interval_s``, whichever first). A slow or dead collector
costs dropped spans (the deque bound), never a slow request teardown.

Span mapping: one root span per exported trace (name "gie.request",
the trace's own W3C trace ID, a span ID derived deterministically from
it) whose span EVENTS are the trace's stage events. A federation hop
(the ``federation:<peer>`` stage the batching completer stamps on a
cross-cluster pick, docs/FEDERATION.md) additionally becomes a CHILD
span "gie.federation" carrying the peer cluster attribute — so a pick
that spilled to a peer reads as one joined trace in the collector, the
gateway leg parented over the cross-cluster leg.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import zlib
from collections import deque
from typing import Optional

from gie_tpu.runtime.logging import get_logger

_NS = 1_000_000_000


def _span_id(trace_id: str, salt: str = "") -> str:
    """Deterministic 16-hex span ID from the trace ID (no RNG: replays
    and multi-replica exports agree)."""
    a = zlib.crc32((trace_id + salt).encode()) & 0xFFFFFFFF
    b = zlib.crc32((salt + trace_id[::-1]).encode()) & 0xFFFFFFFF
    sid = f"{a:08x}{b:08x}"
    return sid if sid != "0" * 16 else "1" + sid[1:]


def _attr(key: str, value) -> dict:
    if isinstance(value, bool):
        return {"key": key, "value": {"boolValue": value}}
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if isinstance(value, int):
            return {"key": key, "value": {"intValue": str(value)}}
        return {"key": key, "value": {"doubleValue": value}}
    return {"key": key, "value": {"stringValue": str(value)}}


def trace_to_spans(trace: dict) -> list:
    """One exported Tracer dict -> OTLP span list (root + optional
    federation child). Pure function — unit-testable without a wire."""
    trace_id = str(trace.get("trace_id", "")).ljust(32, "0")[:32]
    end_s = float(trace.get("finished_at", time.time()))
    latency_s = float(trace.get("latency_ms", 0.0)) / 1e3
    start_s = end_s - max(latency_s, 0.0)
    from gie_tpu.obs.trace import Tracer

    root_sid = _span_id(trace_id)
    outcome = str(trace.get("outcome", ""))
    # ONE error classification for both surfaces: the /debugz feeds and
    # the exported span status must agree on what counts as an error
    # (shed and deadline included — overload failures are exactly what
    # collector-side alerts watch).
    err = outcome in Tracer.ERROR_OUTCOMES
    root = {
        "traceId": trace_id,
        "spanId": root_sid,
        "name": "gie.request",
        "kind": 2,  # SPAN_KIND_SERVER
        "startTimeUnixNano": str(int(start_s * _NS)),
        "endTimeUnixNano": str(int(end_s * _NS)),
        "attributes": [
            _attr("gie.outcome", outcome),
            _attr("gie.sampled", bool(trace.get("sampled", False))),
            _attr("gie.request_id", str(trace.get("request_id", ""))),
        ],
        "status": {"code": 2 if err else 1},
        "events": [],
    }
    pick = trace.get("pick")
    if isinstance(pick, dict):
        root["attributes"].append(_attr("gie.chosen", pick.get("chosen", "")))
        root["attributes"].append(_attr("gie.rung", pick.get("rung", "")))
    spans = [root]
    for ev in trace.get("events", ()):
        stage = str(ev.get("stage", ""))
        at_s = start_s + float(ev.get("at_ms", 0.0)) / 1e3
        root["events"].append({
            "timeUnixNano": str(int(at_s * _NS)),
            "name": stage,
        })
        if stage.startswith("federation:"):
            # The cross-cluster hop as its own child span: from the
            # spill decision to the end of the request (the remote
            # serve), parented under the gateway leg.
            peer = stage.partition(":")[2]
            spans.append({
                "traceId": trace_id,
                "spanId": _span_id(trace_id, salt=stage),
                "parentSpanId": root_sid,
                "name": "gie.federation",
                "kind": 3,  # SPAN_KIND_CLIENT
                "startTimeUnixNano": str(int(at_s * _NS)),
                "endTimeUnixNano": str(int(end_s * _NS)),
                "attributes": [_attr("gie.peer_cluster", peer)],
                "status": {"code": root["status"]["code"]},
            })
    return spans


class OtlpSpanExporter:
    """Batched background OTLP/HTTP JSON exporter. ``export`` is the
    Tracer.on_export sink (enqueue-only); ``close`` flushes."""

    def __init__(self, endpoint: str, *, service_name: str = "gie-tpu-epp",
                 batch_max: int = 64, flush_interval_s: float = 2.0,
                 queue_max: int = 2048, timeout_s: float = 5.0,
                 post=None):
        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.batch_max = max(int(batch_max), 1)
        self.flush_interval_s = flush_interval_s
        self.timeout_s = timeout_s
        self._post = post if post is not None else self._http_post
        self.log = get_logger("obs.otlp")
        # deque appends/popleft are GIL-atomic; the bound makes a dead
        # collector cost dropped spans, never memory.
        self._queue: deque = deque(maxlen=max(int(queue_max), 1))
        self._kick = threading.Event()
        self._stop = threading.Event()
        self.exported = 0
        self.dropped = 0
        self.post_errors = 0
        self._thread = threading.Thread(
            target=self._loop, name="otlp-export", daemon=True)
        self._thread.start()

    # -- sink (request-teardown side; never blocks) ------------------------

    def export(self, trace: dict) -> None:
        if len(self._queue) == self._queue.maxlen:
            self.dropped += 1  # the append below evicts the oldest
        self._queue.append(trace)
        if len(self._queue) >= self.batch_max:
            self._kick.set()

    # -- background side ---------------------------------------------------

    def _http_post(self, body: bytes) -> None:
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            r.read()

    def _drain(self) -> list:
        out = []
        while len(out) < self.batch_max:
            try:
                out.append(self._queue.popleft())
            except IndexError:
                break
        return out

    def _flush(self) -> None:
        while True:
            batch = self._drain()
            if not batch:
                return
            spans = []
            for trace in batch:
                try:
                    spans.extend(trace_to_spans(trace))
                except Exception:
                    self.dropped += 1
            if not spans:
                continue
            payload = {
                "resourceSpans": [{
                    "resource": {"attributes": [
                        _attr("service.name", self.service_name)]},
                    "scopeSpans": [{
                        "scope": {"name": "gie_tpu.obs"},
                        "spans": spans,
                    }],
                }],
            }
            try:
                self._post(json.dumps(payload).encode())
                self.exported += len(spans)
            except Exception as e:
                self.post_errors += 1
                self.dropped += len(batch)
                self.log.v(3).info("otlp post failed", err=str(e))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(self.flush_interval_s)
            self._kick.clear()
            try:
                self._flush()
            except Exception as e:  # the exporter must never die
                self.log.error("otlp flush failed", err=e)

    def close(self) -> None:
        self._stop.set()
        self._kick.set()
        self._thread.join(timeout=5)
        try:
            self._flush()  # final drain on the caller's thread
        except Exception:
            pass

    def report(self) -> dict:
        return {
            "url": self.url,
            "queued": len(self._queue),
            "exported": self.exported,
            "dropped": self.dropped,
            "post_errors": self.post_errors,
        }
