"""/debugz introspection plane on the metrics HTTP surface.

One stdlib ThreadingHTTPServer replaces ``prometheus_client``'s
``start_http_server`` so the EPP's single operator port serves BOTH:

  /metrics            Prometheus exposition. Content-negotiated: an
                      ``Accept: application/openmetrics-text`` scrape
                      gets the OpenMetrics form, which is what carries
                      the trace-ID EXEMPLARS attached to
                      gie_extproc_admission_seconds /
                      gie_pick_latency_seconds buckets — the bucket ->
                      trace join (docs/OBSERVABILITY.md).
  /debugz             JSON catalog of the registered zpages.
  /debugz/<page>      one zpage, JSON. The runner registers: traces /
                      trace / picks / pick / breakers / ladder / drain /
                      queue / datastore / scheduler / buildinfo.

Providers are callables ``(query: dict[str, str]) -> object`` so the
plane stays dependency-inverted: obs knows nothing about the runner's
subsystems, the runner hands in closures. Handlers run on the HTTP
server's worker threads; every provider reads snapshots/reports that
take at most a leaf lock briefly — never the pick lock, and all JSON
serialization happens here, outside every gie_tpu lock.
"""

from __future__ import annotations

import gzip
import hmac
import ipaddress
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Mapping
from urllib.parse import parse_qsl, urlparse

import prometheus_client as prom
from prometheus_client.openmetrics import exposition as openmetrics

Provider = Callable[[dict], object]


def _jsonable(obj):
    """json.dumps default: numpy scalars -> python, everything else str."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


# --debugz-bind NAMES that keep /debugz loopback-only (the default);
# numeric values are classified with the same is_loopback predicate the
# peer gate applies, so 127.0.0.2 (a loopback alias) stays gated and a
# typo cannot silently disable the hardening.
_LOOPBACK_BIND_NAMES = frozenset({"", "localhost", "loopback"})


def _is_loopback_bind(value: str) -> bool:
    value = (value or "").strip().lower()
    if value in _LOOPBACK_BIND_NAMES:
        return True
    try:
        return ipaddress.ip_address(value.split("%")[0]).is_loopback
    except ValueError:
        # Unrecognized value: keep the GATE CLOSED — "loopback-only
        # unless a non-loopback ADDRESS is named" means an unparsable
        # name must not become an accidental opt-out.
        return True


class DebugzServer:
    """The combined /metrics + /debugz listener.

    The SOCKET stays on ``bind`` (0.0.0.0 by default — Prometheus must
    scrape /metrics from off-pod), but the /debugz zpages are a
    different trust story: pick explanations, breaker boards, and
    datastore dumps are operator introspection, plaintext JSON with no
    auth. ``debugz_bind`` therefore gates the /debugz PATHS by peer
    address: with a loopback value (the default) requests from any
    non-loopback peer get 403 and a pointer at the flag; an explicit
    non-loopback ``--debugz-bind`` (e.g. the pod IP, or 0.0.0.0) is the
    operator's opt-out (docs/OBSERVABILITY.md "bind hardening").
    """

    def __init__(self, port: int, registry, providers: Mapping[str, Provider],
                 bind: str = "0.0.0.0", debugz_bind: str = "127.0.0.1",
                 debugz_token: str | None = None):
        self.registry = registry
        self.providers = dict(providers)
        self.debugz_bind = debugz_bind
        self._debugz_loopback_only = _is_loopback_bind(debugz_bind)
        # Bearer-token auth for off-loopback zpage access
        # (--debugz-token, docs/OBSERVABILITY.md "bind hardening"): with
        # a token configured, a NON-loopback peer must present
        # ``Authorization: Bearer <token>`` (constant-time compare) on
        # every /debugz path — 401 otherwise — regardless of the
        # debugz_bind opt-out (the token is the stronger gate and always
        # wins for remote peers). Loopback peers never need it, and
        # /metrics is untouched either way.
        self._debugz_token = debugz_token or None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    outer._handle(self)
                except BrokenPipeError:
                    pass  # scraper went away mid-write
                except Exception as e:  # debug plane must never crash
                    try:
                        self.send_error(500, f"{type(e).__name__}: {e}")
                    except Exception:
                        pass

            def log_message(self, *args):
                pass  # operator plane: no per-scrape stderr chatter

        try:
            self._httpd = ThreadingHTTPServer((bind, port), Handler)
        except OSError as e:
            raise OSError(f"failed to bind metrics/debugz port {port}: {e}")
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="gie-debugz", daemon=True)
        self._thread.start()

    # -- request handling --------------------------------------------------

    def _debugz_allowed(self, peer_host: str) -> bool:
        """May this peer read /debugz pages? Loopback peers always may;
        anyone else only when the operator opted out of the loopback
        default with an explicit --debugz-bind."""
        return (not self._debugz_loopback_only
                or self._peer_is_loopback(peer_host))

    def _peer_is_loopback(self, peer_host: str) -> bool:
        """THE peer-classification predicate — both gates (bind opt-out
        and token) route through it, so they can never disagree about
        the same peer. Unparsable peers are treated as remote."""
        try:
            return ipaddress.ip_address(peer_host.split("%")[0]).is_loopback
        except ValueError:
            return False

    def _token_ok(self, req: BaseHTTPRequestHandler) -> bool:
        """Constant-time bearer-token check (hmac.compare_digest — the
        zpage gate must not become a timing oracle for its own secret).
        Compared as BYTES: compare_digest rejects non-ASCII strings with
        a TypeError, which would turn a hostile non-ASCII Authorization
        header into a 500 instead of the documented 401."""
        auth = req.headers.get("Authorization", "") or ""
        if not auth.startswith("Bearer "):
            return False
        return hmac.compare_digest(
            auth[7:].strip().encode("utf-8", "surrogateescape"),
            self._debugz_token.encode("utf-8", "surrogateescape"))

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        if path == "/debugz" or path.startswith("/debugz/"):
            peer = req.client_address[0]
            if self._debugz_token and not self._peer_is_loopback(peer):
                # Token configured: it is the remote-peer gate, stronger
                # than (and overriding) the bind opt-out.
                if not self._token_ok(req):
                    req.send_error(
                        401, "debugz requires Authorization: Bearer "
                             "<--debugz-token> from non-loopback peers")
                    return
            elif not self._debugz_allowed(peer):
                req.send_error(
                    403, "debugz is loopback-only by default; start with "
                         "an explicit --debugz-bind (or --debugz-token) "
                         "to expose it")
                return
        if path == "/debugz":
            self._send_json(req, {
                "pages": sorted(f"/debugz/{name}" for name in self.providers),
                "metrics": "/metrics (Accept: application/openmetrics-text "
                           "for exemplars)",
            })
            return
        if path.startswith("/debugz/"):
            name = path[len("/debugz/"):]
            provider = self.providers.get(name)
            if provider is None:
                req.send_error(404, f"no such zpage: {name}")
                return
            query = dict(parse_qsl(parsed.query))
            self._send_json(req, provider(query))
            return
        # Everything else is the exposition — prometheus_client's
        # start_http_server serves metrics on ANY path, and existing
        # scrape configs may point at non-/metrics paths.
        self._serve_metrics(req, parse_qsl(parsed.query))

    def _serve_metrics(self, req: BaseHTTPRequestHandler,
                       query_pairs: list) -> None:
        """Exposition with prometheus_client-handler parity: ``name[]``
        metric filtering, gzip under Accept-Encoding (Prometheus sends
        it by default — the ~50-metric exemplar-bearing exposition
        should not ship uncompressed every 15 s), and OpenMetrics under
        content negotiation (the exemplar transport)."""
        names = [v for k, v in query_pairs if k == "name[]"]
        registry = self.registry
        if names:
            registry = registry.restricted_registry(names)
        accept = req.headers.get("Accept", "")
        if "application/openmetrics-text" in accept:
            body = openmetrics.generate_latest(registry)
            ctype = openmetrics.CONTENT_TYPE_LATEST
        else:
            body = prom.generate_latest(registry)
            ctype = prom.CONTENT_TYPE_LATEST
        req.send_response(200)
        req.send_header("Content-Type", ctype)
        if "gzip" in req.headers.get("Accept-Encoding", ""):
            body = gzip.compress(body, 5)
            req.send_header("Content-Encoding", "gzip")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _send_json(self, req: BaseHTTPRequestHandler, obj) -> None:
        body = json.dumps(obj, indent=1, default=_jsonable).encode()
        req.send_response(200)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    # ----------------------------------------------------------------------

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def start_debugz_server(
    port: int, registry, providers: Mapping[str, Provider] | None = None,
    bind: str = "0.0.0.0", debugz_bind: str = "127.0.0.1",
    debugz_token: str | None = None,
) -> DebugzServer:
    """Start the combined listener (the runner's metrics-port server)."""
    return DebugzServer(port, registry, providers or {}, bind=bind,
                        debugz_bind=debugz_bind, debugz_token=debugz_token)
