"""/debugz introspection plane on the metrics HTTP surface.

One stdlib ThreadingHTTPServer replaces ``prometheus_client``'s
``start_http_server`` so the EPP's single operator port serves BOTH:

  /metrics            Prometheus exposition. Content-negotiated: an
                      ``Accept: application/openmetrics-text`` scrape
                      gets the OpenMetrics form, which is what carries
                      the trace-ID EXEMPLARS attached to
                      gie_extproc_admission_seconds /
                      gie_pick_latency_seconds buckets — the bucket ->
                      trace join (docs/OBSERVABILITY.md).
  /debugz             JSON catalog of the registered zpages.
  /debugz/<page>      one zpage, JSON. The runner registers: traces /
                      trace / picks / pick / breakers / ladder / drain /
                      queue / datastore / scheduler / buildinfo.

Providers are callables ``(query: dict[str, str]) -> object`` so the
plane stays dependency-inverted: obs knows nothing about the runner's
subsystems, the runner hands in closures. Handlers run on the HTTP
server's worker threads; every provider reads snapshots/reports that
take at most a leaf lock briefly — never the pick lock, and all JSON
serialization happens here, outside every gie_tpu lock.
"""

from __future__ import annotations

import gzip
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Mapping
from urllib.parse import parse_qsl, urlparse

import prometheus_client as prom
from prometheus_client.openmetrics import exposition as openmetrics

Provider = Callable[[dict], object]


def _jsonable(obj):
    """json.dumps default: numpy scalars -> python, everything else str."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


class DebugzServer:
    """The combined /metrics + /debugz listener."""

    def __init__(self, port: int, registry, providers: Mapping[str, Provider],
                 bind: str = "0.0.0.0"):
        self.registry = registry
        self.providers = dict(providers)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    outer._handle(self)
                except BrokenPipeError:
                    pass  # scraper went away mid-write
                except Exception as e:  # debug plane must never crash
                    try:
                        self.send_error(500, f"{type(e).__name__}: {e}")
                    except Exception:
                        pass

            def log_message(self, *args):
                pass  # operator plane: no per-scrape stderr chatter

        try:
            self._httpd = ThreadingHTTPServer((bind, port), Handler)
        except OSError as e:
            raise OSError(f"failed to bind metrics/debugz port {port}: {e}")
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="gie-debugz", daemon=True)
        self._thread.start()

    # -- request handling --------------------------------------------------

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        if path == "/debugz":
            self._send_json(req, {
                "pages": sorted(f"/debugz/{name}" for name in self.providers),
                "metrics": "/metrics (Accept: application/openmetrics-text "
                           "for exemplars)",
            })
            return
        if path.startswith("/debugz/"):
            name = path[len("/debugz/"):]
            provider = self.providers.get(name)
            if provider is None:
                req.send_error(404, f"no such zpage: {name}")
                return
            query = dict(parse_qsl(parsed.query))
            self._send_json(req, provider(query))
            return
        # Everything else is the exposition — prometheus_client's
        # start_http_server serves metrics on ANY path, and existing
        # scrape configs may point at non-/metrics paths.
        self._serve_metrics(req, parse_qsl(parsed.query))

    def _serve_metrics(self, req: BaseHTTPRequestHandler,
                       query_pairs: list) -> None:
        """Exposition with prometheus_client-handler parity: ``name[]``
        metric filtering, gzip under Accept-Encoding (Prometheus sends
        it by default — the ~50-metric exemplar-bearing exposition
        should not ship uncompressed every 15 s), and OpenMetrics under
        content negotiation (the exemplar transport)."""
        names = [v for k, v in query_pairs if k == "name[]"]
        registry = self.registry
        if names:
            registry = registry.restricted_registry(names)
        accept = req.headers.get("Accept", "")
        if "application/openmetrics-text" in accept:
            body = openmetrics.generate_latest(registry)
            ctype = openmetrics.CONTENT_TYPE_LATEST
        else:
            body = prom.generate_latest(registry)
            ctype = prom.CONTENT_TYPE_LATEST
        req.send_response(200)
        req.send_header("Content-Type", ctype)
        if "gzip" in req.headers.get("Accept-Encoding", ""):
            body = gzip.compress(body, 5)
            req.send_header("Content-Encoding", "gzip")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _send_json(self, req: BaseHTTPRequestHandler, obj) -> None:
        body = json.dumps(obj, indent=1, default=_jsonable).encode()
        req.send_response(200)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    # ----------------------------------------------------------------------

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def start_debugz_server(
    port: int, registry, providers: Mapping[str, Provider] | None = None,
    bind: str = "0.0.0.0",
) -> DebugzServer:
    """Start the combined listener (the runner's metrics-port server)."""
    return DebugzServer(port, registry, providers or {}, bind=bind)
