"""End-to-end request tracing: context extraction, deterministic head
sampling, and the bounded export feeds the /debugz zpages read.

The reference ships no in-tree tracing (SURVEY.md 5.1 — OTLP appears
only as an indirect dependency). This is the minimal in-process form
that answers "why did request X land on pod Y / 503 / take 900 ms":

  * the trace ID comes from the W3C ``traceparent`` header when Envoy
    (or the client's own tracer) supplies one, else from Envoy's
    ``x-request-id``, else it is generated — so one ID correlates the
    gateway's view with the mesh's, and exemplars on the admission/pick
    histograms link Prometheus buckets back to exactly these traces;
  * sampling is a pure function of (seed, trace ID): every replica of
    an EPP fleet keeps or drops the SAME requests, and a replayed
    request samples identically (tests pin bit-identical keep/drop);
  * errors, sheds, deadline breaches, and latency tail outliers export
    regardless of the head decision — the traces worth having are
    exactly the ones head sampling would lose at low rates.

Hot-path budget: with sampling off (rate 0) the runner installs no
Tracer at all, so admission pays one module-attribute load and a falsy
branch (the bench-extproc guard pins it). With tracing on, every
request carries a slotted TraceCtx whose events are (name, monotonic)
tuple appends; the export dict is built only for kept traces, inside
``finish``.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from collections import deque
from typing import Optional

# Context headers read at the ext-proc headers hop (joined into
# extproc.server.NEEDED_REQUEST_HEADERS so the fast lane's needed-keys
# scan copies them).
TRACEPARENT_HEADER = "traceparent"
REQUEST_ID_HEADER = "x-request-id"

_HEX = set("0123456789abcdef")


def trace_id_from_headers(headers: dict) -> tuple[str, str]:
    """-> (trace_id, request_id). ``traceparent`` wins (the 32-hex trace
    field of ``00-<32 hex>-<16 hex>-<2 hex>``), else ``x-request-id``
    (Envoy's UUID, dashes stripped so the ID is exemplar/URL-clean),
    else empty — the caller generates. Malformed values fall through
    rather than erroring: tracing must never fail a request."""
    rid = ""
    vals = headers.get(REQUEST_ID_HEADER)
    if vals:
        rid = vals[0]
    vals = headers.get(TRACEPARENT_HEADER)
    if vals:
        tp = vals[0]
        # version-format per W3C: fixed offsets, lowercase hex.
        if len(tp) >= 55 and tp[2] == "-" and tp[35] == "-":
            tid = tp[3:35]
            if all(c in _HEX for c in tid) and tid != "0" * 32:
                return tid, rid
    if rid:
        stripped = rid.replace("-", "").lower()
        if stripped and all(c in _HEX for c in stripped):
            return stripped[:32], rid
        # Non-hex request IDs still correlate: hash to a stable 32-hex.
        return f"{zlib.crc32(rid.encode()):08x}" + "0" * 24, rid
    return "", rid


class TraceCtx:
    """Per-request trace context: slotted, allocated once at the ext-proc
    headers hop, threaded by reference through admission -> flow queue ->
    wave assembly -> pick -> serve outcome. Events are (stage, monotonic)
    tuples; holders append directly (list.append is GIL-atomic and the
    context belongs to one request)."""

    __slots__ = ("trace_id", "request_id", "sampled", "started", "events")

    def __init__(self, trace_id: str, request_id: str, sampled: bool,
                 started: float):
        self.trace_id = trace_id
        self.request_id = request_id
        self.sampled = sampled
        self.started = started
        self.events: list = [("admission", started)]

    def event(self, name: str) -> None:
        self.events.append((name, time.monotonic()))


class Sampler:
    """Deterministic head sampler: keep/drop is a pure function of
    (seed, trace_id) via a seeded CRC32 — bit-identical across calls,
    instances, and replicas (tests/test_obs.py pins this). No RNG state,
    so concurrent admission threads never contend."""

    __slots__ = ("rate", "seed", "_threshold")

    def __init__(self, rate: float, seed: int = 0):
        if not (0.0 <= rate <= 1.0):
            raise ValueError(f"sample rate must be in [0, 1], got {rate}")
        self.rate = rate
        self.seed = seed & 0xFFFFFFFF
        self._threshold = int(rate * 0x1_0000_0000)

    def keep(self, trace_id: str) -> bool:
        if self._threshold >= 0x1_0000_0000:
            return True
        if self._threshold <= 0:
            return False
        return zlib.crc32(trace_id.encode(), self.seed) < self._threshold


class Tracer:
    """Begin/finish surface + the bounded export feeds.

    ``begin`` runs on the admission path for every request while tracing
    is on: extract/generate the ID, decide sampling, hand back a
    TraceCtx. ``finish`` runs at stream teardown on EVERY exit path
    (extproc.server._process's finally — ok, shed, deadline 503,
    unavailable, stream abort, internal error) and exports the trace
    when it was head-sampled OR its outcome/latency makes it one of the
    always-sample classes. Export feeds are deques (appends GIL-atomic)
    behind one leaf lock (lockorder.toml rank 91) held only for the
    append + counter bump — no I/O, no serialization under it.
    """

    # Outcomes that export regardless of the head-sampling decision.
    ERROR_OUTCOMES = frozenset({
        "shed", "deadline", "unavailable", "error", "aborted", "serve_5xx",
    })

    def __init__(self, sample_rate: float, seed: int = 0,
                 slow_s: float = 0.25, keep: int = 256,
                 tenant_rates: Optional[dict] = None):
        self.sampler = Sampler(sample_rate, seed)
        # Per-tenant head-sampling overrides (--obs-tenant-sample,
        # docs/FAIRNESS.md): one noisy tenant traced at 1.0 while the
        # fleet stays at the fleet rate. Keyed by the request's fairness
        # ID (x-gateway-inference-fairness-id); same deterministic
        # seeded-CRC32 keep/drop as the fleet sampler, so replicas agree
        # per trace ID within a tenant too. Empty map = zero extra work
        # in begin() beyond one falsy check.
        self.tenant_rates = dict(tenant_rates or {})
        self._tenant_thresholds: dict[str, int] = {}
        self._tenant_header = ""
        if self.tenant_rates:
            # Deferred import: extproc.metadata is constant-only, but the
            # package import edge must not run at obs-module import time.
            from gie_tpu.extproc import metadata as _md

            self._tenant_header = _md.FLOW_FAIRNESS_ID_KEY
            for tenant, rate in self.tenant_rates.items():
                if not (0.0 <= rate <= 1.0):
                    raise ValueError(
                        f"tenant sample rate must be in [0, 1]: "
                        f"{tenant}={rate}")
                self._tenant_thresholds[tenant] = int(rate * 0x1_0000_0000)
        # Latency tail threshold: a request slower than this exports even
        # unsampled (the "why did request X take 900 ms" class).
        self.slow_s = slow_s
        self._gen = itertools.count(1)
        self._gen_prefix = f"{os.getpid() & 0xFFFF:04x}"
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=keep)
        self._errors: deque = deque(maxlen=keep)
        self._slow: deque = deque(maxlen=keep)
        self.started_total = 0
        self.exported_total = 0
        # Optional export sink (obs/otlp.py OtlpSpanExporter.export):
        # called OUTSIDE the feed lock with the finished trace dict. The
        # contract is enqueue-only — the sink must never block (the OTLP
        # exporter batches on its own thread).
        self.on_export = None

    # -- request path ------------------------------------------------------

    def begin(self, headers: dict) -> TraceCtx:
        tid, rid = trace_id_from_headers(headers)
        if not tid:
            # No upstream context: generate a local, collision-safe ID
            # (pid-prefixed counter — deterministic, no RNG).
            tid = f"{self._gen_prefix}{next(self._gen):012x}" + "0" * 16
        self.started_total += 1  # GIL-atomic; approximate under races
        sampled = self.sampler.keep(tid)
        if self._tenant_thresholds:
            vals = headers.get(self._tenant_header)
            if vals:
                threshold = self._tenant_thresholds.get(vals[0])
                if threshold is not None:
                    # Tenant override REPLACES the fleet decision both
                    # ways: a noisy tenant at 1.0 always keeps, a spammy
                    # one at 0.0 always drops (errors still always
                    # export via finish()).
                    sampled = (
                        threshold >= 0x1_0000_0000
                        or (threshold > 0 and zlib.crc32(
                            tid.encode(), self.sampler.seed) < threshold))
        return TraceCtx(tid, rid, sampled, time.monotonic())

    def finish(self, ctx: TraceCtx, outcome: str,
               record: Optional[dict] = None, detail: str = "") -> None:
        """Close one trace. Builds and stores the export dict only when
        the trace is kept; the drop path is two float compares."""
        now = time.monotonic()
        latency = now - ctx.started
        is_error = outcome in self.ERROR_OUTCOMES
        is_slow = latency >= self.slow_s
        if not (ctx.sampled or is_error or is_slow):
            return
        # Deferred import: runtime.metrics is import-light, but keeping
        # the module edge lazy lets unit tests drive the tracer bare.
        from gie_tpu.runtime import metrics as own_metrics

        own_metrics.TRACES_EXPORTED.labels(
            reason="error" if is_error else
            ("slow" if is_slow else "sampled")).inc()
        started = ctx.started
        trace = {
            "trace_id": ctx.trace_id,
            "request_id": ctx.request_id,
            "sampled": ctx.sampled,
            "outcome": outcome,
            "detail": detail,
            "latency_ms": round(latency * 1e3, 3),
            "finished_at": time.time(),
            "events": [
                {"stage": name, "at_ms": round((t - started) * 1e3, 3)}
                for name, t in ctx.events
            ],
        }
        if record is not None:
            # Summary only — the full decision record lives in the
            # flight recorder and /debugz/pick joins on trace_id.
            trace["pick"] = {
                "chosen": record.get("chosen", ""),
                "rung": record.get("rung", ""),
                "outcome": record.get("outcome", ""),
            }
        with self._lock:
            self._recent.append(trace)
            if is_error:
                self._errors.append(trace)
            if is_slow:
                self._slow.append(trace)
            self.exported_total += 1
        sink = self.on_export
        if sink is not None:
            try:
                sink(trace)
            except Exception:
                pass  # span export must never fail a request teardown

    # -- zpage reads -------------------------------------------------------

    def traces(self, kind: str = "recent", n: int = 50) -> list[dict]:
        feed = {"recent": self._recent, "errors": self._errors,
                "slow": self._slow}.get(kind)
        if feed is None:
            return []
        with self._lock:
            items = list(feed)
        return items[-max(n, 0):][::-1]  # newest first

    def get(self, trace_id: str) -> Optional[dict]:
        # All three feeds: a tail-latency trace evicted from _recent
        # (but retained in _slow) must stay findable by ID — "why did
        # request X take 900 ms" is the lookup this method exists for.
        with self._lock:
            items = (list(self._recent) + list(self._errors)
                     + list(self._slow))
        for t in reversed(items):
            if t["trace_id"] == trace_id:
                return t
        return None

    def report(self) -> dict:
        with self._lock:
            return {
                "sample_rate": self.sampler.rate,
                "slow_ms": self.slow_s * 1e3,
                "started_total": self.started_total,
                "exported_total": self.exported_total,
                "recent": len(self._recent),
                "errors": len(self._errors),
                "slow": len(self._slow),
            }
