"""gie-obs: the causality layer (ISSUE 9, docs/OBSERVABILITY.md).

After eight interacting subsystems (fast-lane admission, flow queue,
wave batching, TPU pick cycle, breakers, ladder, drain, deadline
budgets), aggregate histograms can say THAT p99 moved but never WHY
request X landed on pod Y, got a 503, or took 900 ms. This package is
the missing per-request record:

  trace.py     TraceCtx propagation (W3C ``traceparent`` / Envoy
               ``x-request-id``) through admission -> flow-queue hold ->
               wave -> pick -> serve outcome, with deterministic head
               sampling plus always-sample for errors/sheds/deadline
               breaches/latency tail outliers.
  recorder.py  the pick flight recorder: a fixed-size lock-free ring of
               per-request decision records (candidates, exclusions,
               scorer breakdown, rung, outcome) with JSON export.
  debugz.py    the /debugz introspection plane on the metrics HTTP
               surface (zpages for traces, pick explanations, breaker
               board, ladder, drain set, flow queue, datastore) plus
               OpenMetrics exemplar exposition.
  metricscheck.py  the ``make obs-check`` metrics-catalog lint.

Install pattern mirrors resilience/faults.py: module globals guarded by
one attribute load so every woven site costs a falsy branch while obs
is uninstalled (the bench-extproc regression guard pins the admission
path; the pick path's recorder writes happen at wave-completion
cadence, off the admission hot path entirely).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

# THE hot-path flags. extproc/batching read these per request/wave and
# branch away immediately while nothing is installed.
ENABLED = False
TRACER = None     # Optional[trace.Tracer]  — None also when sample rate 0
RECORDER = None   # Optional[recorder.FlightRecorder]


def install(tracer=None, recorder=None) -> None:
    """Install the process-global tracer and/or flight recorder (the
    runner does this at startup; tests install their own). Passing None
    for either leaves that surface disabled."""
    global ENABLED, TRACER, RECORDER
    TRACER = tracer
    RECORDER = recorder
    ENABLED = tracer is not None or recorder is not None


def uninstall() -> None:
    global ENABLED, TRACER, RECORDER
    ENABLED = False
    TRACER = None
    RECORDER = None


def dump_artifact(directory: str, name: str) -> Optional[str]:
    """Write the installed flight recorder (and, when tracing, the
    recent/error trace feeds) to ``directory/<name>-flightrec.json`` so
    a failed chaos scenario explains itself. Returns the path, or None
    when nothing is installed. Never raises — artifact capture rides on
    shutdown/test-failure paths that must complete regardless."""
    if RECORDER is None and TRACER is None:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        safe = "".join(
            c if (c.isalnum() or c in "-_.") else "-" for c in name)
        path = os.path.join(directory, f"{safe}-flightrec.json")
        payload = {
            "name": name,
            "written_at": time.time(),
            "records": RECORDER.snapshot() if RECORDER is not None else [],
        }
        if TRACER is not None:
            payload["traces"] = TRACER.traces("recent", n=64)
            payload["error_traces"] = TRACER.traces("errors", n=64)
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)
        return path
    except Exception:
        return None
