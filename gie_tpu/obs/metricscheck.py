"""Metrics-catalog lint (``make obs-check``).

The catalog IS an API: dashboards, alerts, and the autoscale/HPA story
all key on metric names and label shapes, and a metric that lands with
the wrong prefix, no help text, or a per-endpoint-ID label (unbounded
cardinality — one series per pod IP will eventually kill the Prometheus
that scrapes a large fleet) is a production incident deferred. This
check walks the process-global registry after importing every module
that registers instruments and enforces:

  OC001  every metric name is ``gie_``-prefixed (one namespace; the
         default python_/process_ collectors are not registered on the
         EPP's own registry).
  OC002  help text present and not just the name echoed back.
  OC003  label-set width bounded (<= MAX_LABELS): labels multiply
         series; anything wider than a few enum-ish dimensions belongs
         in the flight recorder, not the exposition. ``_info`` gauges
         get a wider bound (MAX_INFO_LABELS): the constant-1 info idiom
         is one series total no matter how many identity labels it
         carries, and build/feature-flag mixes legitimately stack up.
  OC004  no per-endpoint/per-request identity labels (endpoint, pod,
         ip, slot, trace/request IDs, url...): identity lives in
         exemplars and /debugz records, never in series labels.

Run: ``python -m gie_tpu.obs.metricscheck`` (exit 1 on findings), wired
as ``make obs-check`` gating ``make test`` next to lint/chaos-ci.
"""

from __future__ import annotations

import sys

MAX_LABELS = 4
MAX_INFO_LABELS = 8  # _info gauges: one constant-1 series by idiom

# Identity-shaped label names whose value sets scale with the pool or
# the request stream — per-series cardinality bombs.
FORBIDDEN_LABELS = frozenset({
    "endpoint", "hostport", "host", "pod", "pod_name", "ip", "address",
    "slot", "trace_id", "request_id", "url", "path", "id", "name",
})

# Label names histograms/summaries synthesize; never the catalog's.
_SYNTHETIC = frozenset({"le", "quantile"})


def check_registry(registry) -> list[str]:
    """-> list of human-readable findings (empty = catalog clean)."""
    findings: list[str] = []
    seen: set[str] = set()
    # The instrument objects carry the declared shape (collect() samples
    # only show labels that have been observed); fall back to collected
    # Metric objects for custom collectors.
    collectors = []
    try:
        with registry._lock:
            collectors = list(set(registry._names_to_collectors.values()))
    except AttributeError:
        pass
    for c in collectors:
        name = getattr(c, "_name", None)
        if name is None:
            continue
        seen.add(name)
        doc = getattr(c, "_documentation", "") or ""
        labels = [ln for ln in getattr(c, "_labelnames", ())
                  if ln not in _SYNTHETIC]
        findings.extend(_check_one(name, doc, labels))
    for metric in registry.collect():
        if metric.name in seen:
            continue
        labels = sorted({
            ln for s in metric.samples for ln in s.labels
            if ln not in _SYNTHETIC})
        findings.extend(
            _check_one(metric.name, metric.documentation or "", labels))
    return findings


def _check_one(name: str, doc: str, labels: list) -> list[str]:
    out = []
    if not name.startswith("gie_"):
        out.append(f"OC001 {name}: metric name must be gie_-prefixed")
    if not doc.strip() or doc.strip() == name:
        out.append(f"OC002 {name}: help text missing")
    bound = MAX_INFO_LABELS if name.endswith("_info") else MAX_LABELS
    if len(labels) > bound:
        out.append(
            f"OC003 {name}: {len(labels)} labels {sorted(labels)} exceeds "
            f"the {bound}-label cardinality bound")
    bad = sorted(set(labels) & FORBIDDEN_LABELS)
    if bad:
        out.append(
            f"OC004 {name}: per-identity label(s) {bad} — identity belongs "
            "in exemplars/flight-recorder records, not series labels")
    return out


def main(argv=None) -> int:
    # Import FOR REGISTRATION: every module that defines instruments on
    # the shared registry. runtime.metrics carries the whole catalog
    # (the pool-aggregate gauges register lazily — force them with a
    # stub snapshot so their names are checked too); runtime.tracing
    # adds gie_span_seconds.
    from gie_tpu.runtime import metrics as own_metrics
    from gie_tpu.runtime import tracing  # noqa: F401 — registers SPANS

    own_metrics.register_pool_aggregates(lambda: {})
    findings = check_registry(own_metrics.REGISTRY)
    for f in findings:
        print(f)
    n = len(list(own_metrics.REGISTRY.collect()))
    if findings:
        print(f"obs-check: {len(findings)} finding(s) over {n} metrics",
              file=sys.stderr)
        return 1
    print(f"obs-check: catalog clean ({n} metrics)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
