"""Pick flight recorder: a fixed-size lock-free ring of per-request
scheduling decision records.

Every answered pick — full TPU cycle or degraded rung — appends one
record: the candidate subset the request arrived with, who was excluded
and why (breaker quarantine, graceful drain), the ranked choice with
its blended score vector, a host-side scorer breakdown for the chosen
endpoint, the ladder rung, the remaining deadline budget, and (filled
in later by the serve-outcome path) what the data plane actually did
with the decision. This is the record "Simple is Better" (PAPERS.md)
assumes exists: rich enough to replay and score scheduling policies
offline, and the raw material for ROADMAP items 3/8/9 (learned-policy
training traces, p99 outlier ejection, real-hardware calibration).

Concurrency: writers are the batching completer, the dispatcher's
degraded path, and the ext-proc response threads (outcome updates) —
all append/mutate without a lock. The ring is a preallocated slot list;
each writer takes a ticket from an ``itertools.count`` (its C-level
``next`` is atomic under the GIL) and stores a FULLY-BUILT dict with
one list-item assignment. Readers reconstruct order from the ``seq``
embedded in each record, so a torn read can only miss the newest
in-flight slot, never see a half-written record. Outcome updates mutate
fields of an already-published dict (GIL-atomic item assignment).

Records are written at wave-completion cadence on the completer thread
— NEVER under the scheduler's pick lock, and with no device pulls of
their own (the scorer breakdown reads the wave's already-materialized
host-side arrays; gie-lint's GL002 blocking set covers the JSON export
so it can never creep under a declared lock).
"""

from __future__ import annotations

import itertools
import json
from typing import Optional

# Record-schema version, stamped as ``v`` on every published record.
# Offline consumers (the ROADMAP item-3 trainers, replay tooling) key
# compatibility off it: bump it when a field CHANGES MEANING, never for
# additive fields — loaders tolerate unknown fields by contract
# (:func:`load_records`). Version history lives in docs/OBSERVABILITY.md
# ("record schema").
SCHEMA_VERSION = 1


def load_records(text: str) -> list[dict]:
    """Tolerant loader for flight-recorder dumps (export_json /
    obs.dump_artifact artifacts): accepts a bare record list or a
    ``{"records": [...]}`` envelope, keeps unknown fields verbatim, and
    treats records from ANY schema version as loadable — pre-version
    dumps (no ``v``) are stamped ``v: 0``, future-version records are
    kept as-is rather than dropped (the consumer decides what of a newer
    record it understands; a trainer that crashed on a new field would
    rot every archived dump the day the schema grew one)."""
    raw = json.loads(text)
    if isinstance(raw, dict):
        raw = raw.get("records", [])
    if not isinstance(raw, list):
        raise ValueError(
            "flight-recorder dump must be a record list or a "
            "{'records': [...]} envelope")
    out: list[dict] = []
    for rec in raw:
        if not isinstance(rec, dict):
            continue  # tolerate-unknown: skip non-record junk entries
        if not isinstance(rec.get("v"), int):
            rec = {**rec, "v": 0}
        out.append(rec)
    return out


class FlightRecorder:
    """Fixed-size lock-free decision-record ring."""

    def __init__(self, size: int = 512):
        if size < 1:
            raise ValueError("flight recorder size must be >= 1")
        self.size = size
        self._slots: list = [None] * size
        self._tickets = itertools.count()

    def append(self, record: dict) -> dict:
        """Publish one fully-built record (stamps ``seq`` + the schema
        version ``v``); returns it so callers can keep the reference for
        later outcome updates."""
        i = next(self._tickets)          # atomic ticket
        record["seq"] = i
        record["v"] = SCHEMA_VERSION
        self._slots[i % self.size] = record  # atomic publish
        return record

    def __len__(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def snapshot(self, n: int = 0) -> list[dict]:
        """Shallow copies of the live records, oldest first (newest-first
        when trimmed to the last ``n``). Copying detaches the zpage/JSON
        view from in-flight outcome mutations; record field values are
        scalars/small lists, so a shallow copy is a consistent-enough
        read without any writer coordination."""
        live = [dict(s) for s in list(self._slots) if s is not None]
        live.sort(key=lambda r: r.get("seq", 0))
        if n > 0:
            live = live[-n:][::-1]
        return live

    def find(self, trace_id: str = "", seq: Optional[int] = None
             ) -> Optional[dict]:
        """Newest record matching a trace ID (or exact seq) — the
        /debugz/pick join."""
        best = None
        for s in list(self._slots):
            if s is None:
                continue
            if seq is not None:
                if s.get("seq") == seq:
                    return dict(s)
                continue
            if trace_id and s.get("trace_id") == trace_id:
                if best is None or s.get("seq", 0) > best.get("seq", 0):
                    best = s
        return dict(best) if best is not None else None

    def export_json(self, n: int = 0) -> str:
        """Serialize the ring for artifacts/zpages. Listed in gie-lint's
        GL002 blocking set: serialization is I/O-scale work and must
        never run under a declared lock (the pick lock above all)."""
        return json.dumps(self.snapshot(n), default=str)
