"""Pick flight recorder: a fixed-size lock-free ring of per-request
scheduling decision records.

Every answered pick — full TPU cycle or degraded rung — appends one
record: the candidate subset the request arrived with, who was excluded
and why (breaker quarantine, graceful drain), the ranked choice with
its blended score vector, a host-side scorer breakdown for the chosen
endpoint, the ladder rung, the remaining deadline budget, and (filled
in later by the serve-outcome path) what the data plane actually did
with the decision. This is the record "Simple is Better" (PAPERS.md)
assumes exists: rich enough to replay and score scheduling policies
offline, and the raw material for ROADMAP items 3/8/9 (learned-policy
training traces, p99 outlier ejection, real-hardware calibration).

Concurrency: writers are the batching completer, the dispatcher's
degraded path, and the ext-proc response threads (outcome updates) —
all append/mutate without a lock. The ring is a preallocated slot list;
each writer takes a ticket from an ``itertools.count`` (its C-level
``next`` is atomic under the GIL) and stores a FULLY-BUILT dict with
one list-item assignment. Readers reconstruct order from the ``seq``
embedded in each record, so a torn read can only miss the newest
in-flight slot, never see a half-written record. Outcome updates mutate
fields of an already-published dict (GIL-atomic item assignment).

Records are written at wave-completion cadence on the completer thread
— NEVER under the scheduler's pick lock, and with no device pulls of
their own (the scorer breakdown reads the wave's already-materialized
host-side arrays; gie-lint's GL002 blocking set covers the JSON export
so it can never creep under a declared lock).
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Optional

# Record-schema version, stamped as ``v`` on every published record.
# Offline consumers (the ROADMAP item-3 trainers, replay tooling) key
# compatibility off it: bump it when a field CHANGES MEANING, never for
# additive fields — loaders tolerate unknown fields by contract
# (:func:`load_records`). Version history lives in docs/OBSERVABILITY.md
# ("record schema").
#
# v2: ``scorers`` grew device-gathered ``prefix``/``session`` affinity
# columns (PickResult.affinity — the v1 breakdown only carried the three
# host-reconstructible columns, so a v2 trainer reading a v1 dump sees
# them defaulted-and-counted by gie_tpu/learn/dataset.py, same as any
# absent column). A meaning bump, not additive: ``scorers`` changed from
# "everything host-derivable" to "the device blend's locality columns
# included". Hierarchical picks may also carry a ``fleet`` provenance
# object (candidate cells / coarse scores / compression) — additive.
SCHEMA_VERSION = 2


def load_records(text: str, stats: Optional[dict] = None) -> list[dict]:
    """Tolerant loader for flight-recorder dumps (export_json /
    obs.dump_artifact artifacts): accepts a bare record list or a
    ``{"records": [...]}`` envelope, keeps unknown fields verbatim, and
    treats records from ANY schema version as loadable — pre-version
    dumps (no ``v``) are stamped ``v: 0``, future-version records are
    kept as-is rather than dropped (the consumer decides what of a newer
    record it understands; a trainer that crashed on a new field would
    rot every archived dump the day the schema grew one).

    Tolerance is COUNTED, never silent: pass ``stats`` (any dict) and
    the loader increments a reason key per tolerated entry —
    ``junk_entry`` for non-dict list items, ``unversioned`` for records
    missing a schema version. Records a serve outcome never closed
    (abort/5xx cleared or never wrote ``served``) load fine here; it is
    the CONSUMER's job to skip them with its own counted reason
    (gie_tpu/learn/dataset.py does exactly that) rather than KeyError on
    the missing field."""

    def _count(reason: str) -> None:
        if stats is not None:
            stats[reason] = stats.get(reason, 0) + 1

    raw = json.loads(text)
    if isinstance(raw, dict):
        raw = raw.get("records", [])
    if not isinstance(raw, list):
        raise ValueError(
            "flight-recorder dump must be a record list or a "
            "{'records': [...]} envelope")
    out: list[dict] = []
    for rec in raw:
        if not isinstance(rec, dict):
            _count("junk_entry")
            continue  # tolerate-unknown: skip non-record junk entries
        if not isinstance(rec.get("v"), int):
            _count("unversioned")
            rec = {**rec, "v": 0}
        out.append(rec)
    return out


class FlightRecorder:
    """Fixed-size lock-free decision-record ring."""

    def __init__(self, size: int = 512):
        if size < 1:
            raise ValueError("flight recorder size must be >= 1")
        self.size = size
        self._slots: list = [None] * size
        self._tickets = itertools.count()

    def append(self, record: dict) -> dict:
        """Publish one fully-built record (stamps ``seq`` + the schema
        version ``v``); returns it so callers can keep the reference for
        later outcome updates."""
        i = next(self._tickets)          # atomic ticket
        record["seq"] = i
        record["v"] = SCHEMA_VERSION
        self._slots[i % self.size] = record  # atomic publish
        return record

    def __len__(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def snapshot(self, n: int = 0) -> list[dict]:
        """Shallow copies of the live records, oldest first (newest-first
        when trimmed to the last ``n``). Copying detaches the zpage/JSON
        view from in-flight outcome mutations; record field values are
        scalars/small lists, so a shallow copy is a consistent-enough
        read without any writer coordination."""
        live = [dict(s) for s in list(self._slots) if s is not None]
        live.sort(key=lambda r: r.get("seq", 0))
        if n > 0:
            live = live[-n:][::-1]
        return live

    def find(self, trace_id: str = "", seq: Optional[int] = None
             ) -> Optional[dict]:
        """Newest record matching a trace ID (or exact seq) — the
        /debugz/pick join."""
        best = None
        for s in list(self._slots):
            if s is None:
                continue
            if seq is not None:
                if s.get("seq") == seq:
                    return dict(s)
                continue
            if trace_id and s.get("trace_id") == trace_id:
                if best is None or s.get("seq", 0) > best.get("seq", 0):
                    best = s
        return dict(best) if best is not None else None

    def export_json(self, n: int = 0) -> str:
        """Serialize the ring for artifacts/zpages. Listed in gie-lint's
        GL002 blocking set: serialization is I/O-scale work and must
        never run under a declared lock (the pick lock above all)."""
        return json.dumps(self.snapshot(n), default=str)


class DumpRotator:
    """Periodic flight-recorder harvesting with a bounded file budget —
    gie-learn's training feed (--obs-dump-interval-s, docs/LEARNED.md).

    Each :meth:`rotate_once` snapshots the installed recorder into
    ``directory/<name>-<seq>.json`` (the same envelope shape
    obs.dump_artifact writes, so gie_tpu.learn.dataset loads both), then
    prunes the oldest rotation files beyond ``keep``. The lock guards
    ONLY the sequence counter — callers race from the runner's rotation
    thread and ad-hoc harvests (tests, a future zpage action) — while
    every snapshot/serialize/unlink happens OUTSIDE it, per the GL002
    rule that recorder export I/O never runs under a declared lock.
    """

    def __init__(self, directory: str, *, keep: int = 8,
                 name: str = "rotation", clock=None):
        if keep < 1:
            raise ValueError("dump rotation keep must be >= 1")
        self.directory = directory
        self.keep = keep
        self.name = name
        self._clock = clock
        self._seq = 0
        self._lock = threading.Lock()

    def _next_seq(self) -> int:
        with self._lock:
            seq = self._seq
            self._seq += 1
        return seq

    def rotation_files(self) -> list[str]:
        """This rotator's dump files, oldest first (zero-padded sequence
        numbers make name order == age order). Other artifacts in the
        directory — chaos-scenario dumps, foreign rotators — are never
        listed, so they can never be pruned by this one."""
        prefix = f"{self.name}-"
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(
            os.path.join(self.directory, n) for n in names
            if n.startswith(prefix) and n.endswith(".json"))

    def rotate_once(self, recorder=None) -> Optional[str]:
        """Dump one snapshot and prune; returns the written path, or
        None when no recorder is installed or the write failed (the
        rotation thread rides shutdown-adjacent paths — it logs through
        its caller, never raises)."""
        from gie_tpu import obs

        rec = recorder if recorder is not None else obs.RECORDER
        if rec is None:
            return None
        seq = self._next_seq()
        try:
            os.makedirs(self.directory, exist_ok=True)
            path = os.path.join(
                self.directory, f"{self.name}-{seq:08d}.json")
            payload = {
                "name": f"{self.name}-{seq:08d}",
                "written_at": (self._clock() if self._clock is not None
                               else time.time()),
                "records": rec.snapshot(),
            }
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=1, default=str)
            for stale in self.rotation_files()[:-self.keep]:
                try:
                    os.unlink(stale)
                except OSError:
                    pass  # pruned by a racing rotate, or perms — skip
            return path
        except (OSError, ValueError):
            return None
