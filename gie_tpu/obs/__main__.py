"""``python -m gie_tpu.obs`` — operator CLI for the observability plane.

Subcommands:

  dump --out DIR    Harvest the flight-recorder ring of a RUNNING
                    gateway into a dump file gie_tpu.learn can train
                    from. The ring lives in the serving process, so the
                    harvest goes through the /debugz/picks zpage on the
                    metrics port (loopback by default — same trust model
                    as every other zpage; --token forwards the
                    --debugz-token bearer for off-pod harvests).

The written file is the standard dump envelope ({"name", "written_at",
"records"}), byte-compatible with obs.dump_artifact artifacts and the
--obs-dump-interval-s rotation files, so every consumer
(gie_tpu.learn.dataset, replay tooling) loads all three identically.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request


def _fetch_picks(host: str, port: int, n: int, token: str,
                 timeout_s: float) -> list:
    url = f"http://{host}:{port}/debugz/picks?n={int(n)}"
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        body = resp.read()
    records = json.loads(body)
    if not isinstance(records, list):
        raise ValueError(
            f"/debugz/picks returned {type(records).__name__}, not a "
            "record list — is something else listening on that port?")
    return records


def _cmd_dump(args: argparse.Namespace) -> int:
    try:
        records = _fetch_picks(args.host, args.port, args.n, args.token,
                               args.timeout_s)
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"harvest failed: {e}", file=sys.stderr)
        return 1
    os.makedirs(args.out, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    path = os.path.join(args.out, f"harvest-{stamp}-flightrec.json")
    payload = {
        "name": f"harvest-{stamp}",
        "written_at": time.time(),
        "records": records,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, default=str)
    print(f"wrote {path}: {len(records)} records")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m gie_tpu.obs",
        description="Observability-plane operator CLI (docs/"
                    "OBSERVABILITY.md, docs/LEARNED.md).")
    sub = parser.add_subparsers(dest="cmd", required=True)
    dump = sub.add_parser(
        "dump", help="harvest a running gateway's flight-recorder ring "
                     "into a training dump")
    dump.add_argument("--out", required=True, metavar="DIR",
                      help="output directory (file name is timestamped)")
    dump.add_argument("--host", default="127.0.0.1",
                      help="gateway metrics host (default loopback)")
    dump.add_argument("--port", type=int, default=9090,
                      help="gateway metrics port (--metrics-port)")
    dump.add_argument("-n", type=int, default=0,
                      help="newest N records only (0 = whole ring)")
    dump.add_argument("--token", default="",
                      help="bearer token for off-loopback /debugz "
                           "(--debugz-token)")
    dump.add_argument("--timeout-s", type=float, default=10.0)
    dump.set_defaults(fn=_cmd_dump)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
