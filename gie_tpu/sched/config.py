"""Declarative scheduler configuration.

The reference's scheduler architecture calls for a declarative plugin /
profile configuration API (reference docs/proposals/0845-scheduler-
architecture-proposal/README.md:92, and the text plugin config referenced by
003:33). Here one YAML document configures the whole batched profile:

    picker: sinkhorn
    queue_limit: 128
    load_decay: 0.95
    plugins:            # enable/disable scorer stages
      prefix: true
      lora: true
      saturation: true
    weights:            # profile-level blend weights
      queue: 2.0
      prefix: 4.0
      assumed_load: 1.5

Unknown keys fail loudly (a typo'd knob must not silently no-op).
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import yaml

from gie_tpu.sched.profile import ProfileConfig
from gie_tpu.sched.types import Weights

_PLUGIN_FLAGS = {
    "prefix": "enable_prefix",
    "lora": "enable_lora",
    "saturation": "enable_saturation",
    "session": "enable_session",
}

_WEIGHT_FIELDS = {f.name for f in dataclasses.fields(Weights)}
_CONFIG_FIELDS = {f.name for f in dataclasses.fields(ProfileConfig)}


def load_scheduler_config(text: str) -> tuple[ProfileConfig, Weights]:
    """YAML -> (ProfileConfig, Weights); raises ValueError on unknown keys."""
    doc = yaml.safe_load(text) or {}
    if not isinstance(doc, dict):
        raise ValueError("scheduler config must be a YAML mapping")

    cfg_kwargs: dict = {}
    weights = Weights.default()

    for key, value in doc.items():
        if key == "plugins":
            if not isinstance(value, dict):
                raise ValueError("plugins must be a mapping of name: bool")
            for name, enabled in value.items():
                if name not in _PLUGIN_FLAGS:
                    raise ValueError(
                        f"unknown plugin {name!r}; known: {sorted(_PLUGIN_FLAGS)}"
                    )
                cfg_kwargs[_PLUGIN_FLAGS[name]] = bool(enabled)
        elif key == "weights":
            if not isinstance(value, dict):
                raise ValueError("weights must be a mapping of name: number")
            for name, w in value.items():
                if name not in _WEIGHT_FIELDS:
                    raise ValueError(
                        f"unknown weight {name!r}; known: {sorted(_WEIGHT_FIELDS)}"
                    )
                weights = weights.replace(**{name: jnp.float32(float(w))})
        elif key == "picker":
            if value not in ("topk", "random", "sinkhorn"):
                raise ValueError(
                    f"unknown picker {value!r}; known: topk, random, sinkhorn"
                )
            cfg_kwargs[key] = value
        elif key in _CONFIG_FIELDS:
            cfg_kwargs[key] = value
        else:
            raise ValueError(
                f"unknown scheduler config key {key!r}; known: "
                f"{sorted(_CONFIG_FIELDS | {'plugins', 'weights'})}"
            )
    return ProfileConfig(**cfg_kwargs), weights


def load_scheduler_config_file(path: str) -> tuple[ProfileConfig, Weights]:
    with open(path) as f:
        return load_scheduler_config(f.read())


def tuned_profile() -> tuple[ProfileConfig, Weights]:
    """The swept profile (see config/scheduler/sinkhorn-tuned.yaml and
    docs/BENCH_NOTES.md): Sinkhorn OT picker whose capacity constraint lets
    prefix affinity run high without herding, plus the round-2
    consistent-hash session-stickiness column (weight 8.0) that lifts the
    sim prefix hit rate from 0.72 to ~0.91 — 4.3x mean / 3.8x min goodput
    vs the least-kv baseline over 5 seeds at 100 qps. The production
    default when no --scheduler-config overrides it."""
    cfg = ProfileConfig(
        picker="sinkhorn", load_decay=0.95, load_norm=8.0, queue_norm=16.0,
        sinkhorn_rounding_temp=0.05,
    )
    weights = Weights(
        queue=jnp.float32(2.0),
        kv_cache=jnp.float32(1.0),
        prefix=jnp.float32(4.0),
        lora=jnp.float32(1.0),
        assumed_load=jnp.float32(1.5),
        latency=jnp.float32(0.0),
        session=jnp.float32(8.0),
    )
    return cfg, weights
