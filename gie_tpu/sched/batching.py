"""BatchingTPUPicker: micro-batching bridge from per-stream picks to the
batched TPU scheduling cycle.

The reference's alternate-scheduler seam (docs/proposals/006-scheduler/
README.md:160-162) describes exactly this component: an out-of-process
scheduler "accepting batches of requests + endpoints and returning
selections". Ext-proc opens one stream per HTTP request (server.go:105), so
concurrent Process threads enqueue here; a collector thread drains the queue
every `max_wait_s` (or at `max_batch`) and runs ONE jitted scheduling cycle
for the whole wave — decoupling stream cadence from batch cadence
(SURVEY.md section 7.4 "latency discipline across the Go<->TPU boundary").

The collector is a TWO-STAGE pipeline (docs/PIPELINE.md): the dispatcher
drains the queue, assembles the wave with vectorized numpy column ops, and
dispatches the cycle asynchronously (Scheduler.pick_async); a completer
thread materializes results and fans them out. The device runs cycle k
while the host assembles cycle k+1 — neither side idles waiting for the
other, and a bounded in-flight depth caps the tail latency a dispatched
wave can accumulate behind its predecessors.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import grpc
import numpy as np

from gie_tpu import obs
from gie_tpu.runtime import metrics as own_metrics

from gie_tpu.extproc.server import (
    ExtProcError,
    PickRequest,
    PickResult,
    ShedError,
)
from gie_tpu.extproc import metadata as mdkeys
from gie_tpu.resilience import deadline as deadline_mod
from gie_tpu.resilience import faults
from gie_tpu.runtime.clock import MONOTONIC, Clock
from gie_tpu.resilience.ladder import ResilienceState, Rung
from gie_tpu.fairness import FairnessState
from gie_tpu.sched import constants as C
from gie_tpu.sched.filters import drain_filter
from gie_tpu.sched.hashing import batch_chunk_hashes
from gie_tpu.models.latency import host_features
from gie_tpu.sched.profile import Scheduler, pd_costs_host, request_cost_host
from gie_tpu.sched.types import RequestBatch, chunk_bucket_for, m_bucket_for
from gie_tpu.utils.lora import LoraRegistry

import jax.numpy as jnp

_BAND_NAMES = {
    int(C.Criticality.CRITICAL): "critical",
    int(C.Criticality.STANDARD): "standard",
    int(C.Criticality.SHEDDABLE): "sheddable",
}


def _band_for(headers: dict, registry=None) -> int:
    """Scheduler band from the objective header: a registered
    InferenceObjective name (proposal 1199) or a literal band name."""
    from gie_tpu.api.objectives import LITERAL_BANDS

    value = headers.get(mdkeys.OBJECTIVE_KEY, [""])[0]
    if registry is not None:
        band = registry.resolve_band(value)
        if band is not None:
            return band
    return LITERAL_BANDS.get(value.lower().strip(),
                             int(C.Criticality.STANDARD))


def _ctx_tenant(ctx) -> str:
    """Fairness ID from a stream's captured headers (the response hops
    have the RequestContext, not the _Pending): same defensive shape as
    the enqueue-time extraction."""
    vals = getattr(ctx, "headers", None)
    vals = vals.get(mdkeys.FLOW_FAIRNESS_ID_KEY) if vals else None
    return (vals[0] if isinstance(vals, list) and vals
            and isinstance(vals[0], str) else "")


def _fair_order(items: list["_Pending"]) -> list["_Pending"]:
    """Criticality bands first, weighted deficit-round-robin by fairness
    ID within a band (gie_tpu/fairness, docs/FAIRNESS.md).

    Proposal 1199 scopes fairness within a priority band: CRITICAL drains
    before STANDARD before SHEDDABLE, and inside each band tenants
    (x-gateway-inference-fairness-id) share drained COST — each drain
    charges the item's request cost against the tenant's deficit, so a
    tenant of 8k-prompt requests no longer wins 10x the capacity of a
    chat neighbor per interleave slot. Bands and tenants come from values
    CACHED on each item at enqueue time — never a header re-parse per
    drain. This module-level form is STATELESS (uniform weights, fresh
    deficits) for tests and direct callers; the picker itself orders
    through its persistent FairnessState."""
    from gie_tpu.fairness.drr import DeficitRoundRobin

    return DeficitRoundRobin().order(items)


class _Pending:
    __slots__ = ("req", "candidates", "event", "result", "error",
                 "enqueued_at", "abandoned", "band", "cand_slots",
                 "excl_breaker", "excl_drain", "tenant", "cost",
                 "fed_remote", "fed_base")

    def __init__(self, req: PickRequest, candidates: list,
                 band: Optional[int] = None,
                 now: Optional[float] = None):
        self.req = req
        self.candidates = candidates
        self.event = threading.Event()
        self.result: Optional[PickResult] = None
        self.error: Optional[Exception] = None
        # Clock-seam timestamp (runtime/clock.py): age sheds and queue-
        # wait metrics compare this against the picker's clock, so both
        # must come from the same source (virtual in a time-compressed
        # storm).
        self.enqueued_at = MONOTONIC.now() if now is None else now
        # Set when the caller's pick() wait expired: the collector must DROP
        # the item rather than schedule it — a scheduled pick charges assumed
        # load that no served feedback will ever release.
        self.abandoned = False
        # Criticality band resolved ONCE, at enqueue (it was re-derived
        # with a header parse up to 4x per request: fair ordering, the
        # queue-age shed, the hold check, and wave assembly). pick()
        # resolves through the objective registry; direct constructions
        # (tests, benchmarks) fall back to literal band names.
        self.band = _band_for(req.headers) if band is None else band
        # Candidate slot ids as a dense vector: wave assembly and the hold
        # check index numpy arrays instead of iterating endpoint objects.
        self.cand_slots = np.fromiter(
            (getattr(ep, "slot", -1) for ep in candidates),
            np.int64, len(candidates))
        # Slots the wave-level filters excluded for THIS item (flight-
        # recorder provenance, gie_tpu/obs): breaker quarantine and
        # graceful drain. Empty tuples until a filter actually fires.
        self.excl_breaker: tuple = ()
        self.excl_drain: tuple = ()
        # Imported peer-cluster slots the federation spill policy ADDED
        # to this item's candidate set (docs/FEDERATION.md) — recorded
        # for the same provenance reasons — and the pre-spill candidate
        # list, kept so a drain CANCELLED while this item is held can
        # restore its local set (None until federation first mutates).
        self.fed_remote: tuple = ()
        self.fed_base = None
        # Tenant identity + request cost, resolved ONCE at enqueue for
        # the fairness layer (gie_tpu/fairness): DRR ordering, budget
        # accounting, and the preemptive shed all read these per drain.
        # Cost shares request_cost_host's units so fairness charges the
        # same quantity the scheduler's assumed-load does. The isinstance
        # guard keeps a malformed header value (None, not a list) from
        # poisoning the collector's pre-batch section.
        vals = req.headers.get(mdkeys.FLOW_FAIRNESS_ID_KEY)
        self.tenant = (vals[0] if isinstance(vals, list) and vals
                       and isinstance(vals[0], str) else "")
        self.cost = request_cost_host(
            float(len(req.body) if req.body else 0.0),
            float(req.decode_tokens or 0.0) * C.CHARS_PER_TOKEN)


def assemble_wave(
    batch: list["_Pending"], mb: int, lora_registry: LoraRegistry
) -> tuple[RequestBatch, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized host assembly of one wave: numpy COLUMN ops over the
    pending items, not a per-request Python loop (the old path iterated
    the batch once per column and the candidate list once per request —
    ~N*M Python-level operations on the hottest host path in the repo).

    Returns (RequestBatch, plen, dlen, lora): the device-ready wave plus
    the host columns the completer's fan-out re-reads (costs, feedback).
    """
    n = len(batch)
    prompts = [it.req.body or b"" for it in batch]
    hashes, counts = batch_chunk_hashes(prompts)
    # Chunk-axis bucket: short-prompt waves run 8/16 prefix lanes per
    # request instead of MAX_CHUNKS (the cycle is shape-polymorphic
    # in C; lanes beyond a request's n_chunks were masked anyway).
    cb = chunk_bucket_for(int(counts.max()) if n else 1)
    hashes = hashes[:, :cb]
    # LoRA ids: one registry lookup (lock acquisition) per DISTINCT model.
    # Dict insertion order = first occurrence, so new-adapter id assignment
    # matches the old per-item loop exactly.
    ids = {it.req.model: -1 for it in batch}
    for name in ids:
        ids[name] = lora_registry.id_for(name)
    lora = np.fromiter((ids[it.req.model] for it in batch), np.int32, n)
    crit = np.fromiter((it.band for it in batch), np.int32, n)
    plen = np.fromiter((len(p) for p in prompts), np.float32, n)
    # Decode-length hint per request (types.py RequestBatch.decode_len,
    # in prompt-char-equivalents): the transport's token hint (decode-
    # tokens header or the body's max_tokens cap, extproc/server.py
    # _decode_tokens) scaled by CHARS_PER_TOKEN. Charge and release
    # share this one array: the device cycle charges from the
    # RequestBatch value and every host-side release derives from the
    # same dlen, so the hint cannot desync accounting.
    dlen = np.float32(C.CHARS_PER_TOKEN) * np.fromiter(
        (it.req.decode_tokens or 0.0 for it in batch), np.float32, n)
    # Subset mask via one flat scatter: rows repeated by candidate count,
    # columns from the cached per-item slot vectors.
    n_cands = np.fromiter((it.cand_slots.size for it in batch), np.intp, n)
    rows = np.repeat(np.arange(n), n_cands)
    cols = (np.concatenate([it.cand_slots for it in batch])
            if n else np.zeros((0,), np.int64))
    ok = (cols >= 0) & (cols < mb)
    mask = np.zeros((n, mb), bool)
    mask[rows[ok], cols[ok]] = True

    reqs = RequestBatch(
        valid=jnp.ones((n,), bool),
        lora_id=jnp.asarray(lora),
        criticality=jnp.asarray(crit),
        prompt_len=jnp.asarray(plen),
        decode_len=jnp.asarray(dlen),
        chunk_hashes=jnp.asarray(hashes),
        n_chunks=jnp.asarray(counts),
        subset_mask=jnp.asarray(mask),
    )
    return reqs, plen, dlen, lora


class _Wave:
    """One dispatched wave in flight between dispatcher and completer."""

    __slots__ = ("batch", "pending", "endpoints", "eps_metrics",
                 "plen", "dlen", "lora")

    def __init__(self, batch, pending, endpoints, eps_metrics,
                 plen, dlen, lora):
        self.batch = batch            # list[_Pending], waiters to wake
        self.pending = pending        # profile.PendingWave (device arrays)
        self.endpoints = endpoints    # datastore endpoints at dispatch time
        self.eps_metrics = eps_metrics  # wave's metrics tensor (trainer rows)
        self.plen = plen
        self.dlen = dlen
        self.lora = lora


# Sentinel the dispatcher pushes on close(): the completer drains every
# wave queued BEFORE it, then exits — in-flight picks complete, never hang.
_CLOSE = object()


class _WaveQueue:
    """Unbounded FIFO between dispatcher and completer, built on a
    Condition threaded through the Clock seam (runtime/clock.py):
    ``queue.Queue``'s internal waits are invisible to a virtual clock,
    so a time-compressed storm could never park/wake the completer on
    the simulated timeline. API mirrors the ``queue.Queue`` subset the
    picker used (``get`` raises ``queue.Empty``; unbounded ``put`` never
    blocks, matching the maxsize-0 queue this replaces)."""

    def __init__(self, clock: Clock):
        self._clock = clock
        self._items: list = []
        self._cond = threading.Condition()

    def put(self, item, timeout: Optional[float] = None) -> None:
        del timeout  # unbounded: put never blocks (queue.Queue parity)
        with self._cond:
            self._items.append(item)
            self._clock.notify(self._cond)

    def get(self, timeout: Optional[float] = None):
        """One bounded receive: an empty queue waits at most ``timeout``
        (a wake with nothing to take raises ``queue.Empty`` early — the
        completer loop re-checks shutdown state and retries, so the
        short wait is indistinguishable from the full one)."""
        with self._cond:
            if not self._items:
                self._clock.wait(self._cond, timeout)
                if not self._items:
                    raise queue.Empty
            return self._items.pop(0)

    def get_nowait(self):
        with self._cond:
            if not self._items:
                raise queue.Empty
            return self._items.pop(0)

    def empty(self) -> bool:
        return not self._items


class BatchingTPUPicker:
    """EndpointPicker backed by the batched Scheduler."""

    def __init__(
        self,
        scheduler: Scheduler,
        datastore,
        metrics_store,
        *,
        max_wait_s: float = 0.002,
        max_batch: int = C.N_BUCKETS[-1],
        lora_registry: Optional[LoraRegistry] = None,
        trainer=None,
        hold_max_s: float = 0.0,
        hold_queue_limit: float = 128.0,
        hold_retry_s: float = 0.01,
        pick_timeout_s: float = 60.0,
        pd_budget_floor_s: float = 0.0,
        queue_bound: int = 0,
        queue_max_age_s: float = 0.0,
        pipeline_depth=2,
        background_warm: bool = False,
        resilience: Optional[ResilienceState] = None,
        fairness: Optional["FairnessState"] = None,
        federation=None,
        clock: Clock = MONOTONIC,
    ):
        # Clock seam (runtime/clock.py): every BEHAVIORAL read of time in
        # the pick path — enqueue ages, deadline checks, hold pacing, the
        # batching window, pick() waits, wave handoff — goes through this
        # clock, so StormEngine(virtual_time=True) drives the whole flow
        # queue on the simulated timeline. Pipeline stage EWMAs and
        # flight-record ``ts`` fields deliberately stay on the real clock
        # (they are observability, not behavior).
        self._clock = clock
        self.scheduler = scheduler
        self.datastore = datastore
        self.metrics_store = metrics_store
        self.max_wait_s = max_wait_s
        self.max_batch = max_batch
        # MUST be the same registry the metrics scraper interns adapter
        # names through, or affinity compares ids from two unrelated spaces.
        self.lora_registry = lora_registry if lora_registry is not None else LoraRegistry()
        # Optional models.latency.OnlineTrainer: pick-time feature rows are
        # recorded and completed by served feedback (measured latency).
        self.trainer = trainer
        # Optional api.objectives.ObjectiveRegistry resolving named
        # InferenceObjectives to criticality bands (proposal 1199).
        self.objective_registry = None
        # Flow-control wait queueing (the reference flow-control layer's
        # queue-until-capacity semantics): when > 0, non-critical requests
        # whose pick landed on a saturated endpoint are HELD and re-scheduled
        # until capacity frees or the hold deadline passes (then best-effort).
        # Ext-proc permits this: the headers response is simply not sent yet.
        self.hold_max_s = hold_max_s
        self.hold_queue_limit = hold_queue_limit
        self.hold_retry_s = hold_retry_s
        self.pick_timeout_s = pick_timeout_s
        # Budget-aware pd split (docs/RESILIENCE.md): a disaggregated
        # pick whose remaining deadline budget is under this floor
        # collapses to the decode worker only — the cross-worker prefill
        # hop (KV transfer + an extra network leg) would eat the budget.
        # 0 disables (seed behavior); the runner wires
        # --pd-budget-floor-ms.
        self.pd_budget_floor_s = pd_budget_floor_s
        # Flow-control queue BOUNDS (the reference flow-controller implies
        # bounded queues + overload policy, proposal 0683 README:64-66).
        # queue_bound > 0 caps pending depth: an arrival into a full queue
        # either evicts a strictly-lower-criticality waiter (which sheds
        # with 429) or is itself shed with 429 — CRITICAL is only ever
        # rejected when the whole queue is CRITICAL. queue_max_age_s > 0
        # sheds non-critical items that waited longer than the bound
        # (configure it ABOVE hold_max_s: holding is intentional queueing
        # within the same clock, and the age bound backstops it).
        if queue_bound < 0 or queue_max_age_s < 0:
            raise ValueError("queue bounds must be non-negative")
        if 0 < queue_max_age_s <= hold_max_s:
            # An age bound inside the hold window would shed every held
            # pick on its first retry — the hold feature would silently
            # become a 429 generator.
            raise ValueError(
                f"queue_max_age_s ({queue_max_age_s}) must exceed "
                f"hold_max_s ({hold_max_s}) when both are enabled")
        self.queue_bound = queue_bound
        self.queue_max_age_s = queue_max_age_s
        # Endpoint-axis (M) bucket: sized to the datastore's high-water
        # slot, grown immediately, shrunk only after _M_SHRINK_PATIENCE
        # consecutive waves fit the smaller bucket (a pod flap must not
        # thrash state migrations). Collector-thread-only state.
        self._m_bucket = C.M_BUCKETS[0]
        self._m_shrink_streak = 0
        self._pending: list[_Pending] = []
        self._cond = threading.Condition()
        self._closed = False
        # Two-stage pipeline (docs/PIPELINE.md): the dispatcher assembles
        # and async-dispatches waves; the completer materializes and fans
        # out. The in-flight bound is the backpressure seam — depth ~2
        # keeps the device fed (one wave running, one queued behind it)
        # without letting a slow consumer stack unbounded tail latency
        # onto every wave dispatched behind it.
        #
        # pipeline_depth="auto" (ROADMAP PR 1 follow-up) derives the
        # bound 1-3 from the measured host-assembly / device-cycle ratio
        # the pipeline histograms already capture, retuned every
        # _DEPTH_RETUNE_WAVES waves:
        #   host-bound (assembly >= 2x the device wait): the bound never
        #     binds in steady state — depth 1, the shallowest bound,
        #     merely caps the tail a transient burst can queue.
        #   balanced (0.5x..2x): depth 3 — one slow assembly (GC pause,
        #     queue-drain spike) must not starve the device, so one
        #     extra slot absorbs the jitter.
        #   device-bound (assembly < 0.5x): depth 2 — the classic double
        #     buffer; any deeper slot adds a full device cycle of queue
        #     latency to every wave while the device is already 100%
        #     busy.
        # The fixed default (2) is preserved: pass an int to pin it.
        self._depth_auto = pipeline_depth == "auto"
        if self._depth_auto:
            pipeline_depth = 2
        if not isinstance(pipeline_depth, int) or pipeline_depth < 1:
            raise ValueError('pipeline_depth must be >= 1 or "auto"')
        self._depth_limit = pipeline_depth
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        # Stage-time EWMAs feeding the auto policy. Written GIL-atomically
        # from their own stage's thread (assembly: dispatcher; device
        # wait: completer); read racily by the retune — an off-by-one-
        # sample read only shifts a threshold crossing by one window.
        self._asm_ewma = 0.0
        self._cycle_ewma = 0.0
        self._depth_waves = 0
        self._depth_want_prev = pipeline_depth
        self._waves = _WaveQueue(clock)
        # Background N-bucket lattice warming (ROADMAP follow-up): with
        # background_warm=True the dispatcher's first contact with a new
        # (m, chunk_lanes) lattice kicks Scheduler.warm_lattice_async for
        # the REST of that lattice's request-count buckets, so a later
        # load spike never stalls a wave on first-use jit. Opt-in (the
        # runner enables it): the compile threads contend for CPU, which
        # deterministic latency tests building this picker directly must
        # not absorb. Collector-thread-only state.
        self.background_warm = background_warm
        self._warmed_lattices: set[tuple[int, int]] = set()
        self._warm_threads: list[threading.Thread] = []
        # Unified resilience layer (gie_tpu/resilience, docs/RESILIENCE.md):
        # breaker board filtering candidates, degradation ladder deciding
        # per WAVE whether this wave takes the full device path, a probe
        # wave, or a host-side degraded pick. None = seed behavior.
        self.resilience = resilience
        # Multi-tenant fairness layer (gie_tpu/fairness, docs/FAIRNESS.md):
        # weighted-DRR flow ordering, per-tenant budget ledgers, and the
        # over-fair-share preemptive shed. Always on (uniform weights by
        # default = the proposal-1199 fair interleave, now cost-weighted);
        # the runner passes a weighted instance from --fairness-weights.
        self.fairness = (fairness if fairness is not None
                         else FairnessState(clock=clock.now))
        # Multi-cluster federation (gie_tpu/federation,
        # docs/FEDERATION.md): imported peer endpoints join candidate
        # sets through the spill policy at wave cadence. None = single
        # cluster (seed behavior).
        self.federation = federation
        # Smooth-weighted-round-robin credit per slot and the static-
        # subset rotation cursor (degraded rungs; collector/completer
        # threads only — the two never pick the same wave).
        self._wrr_credit: dict[int, float] = {}
        self._static_rr = 0
        self._degraded_lock = threading.Lock()
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()
        self._completer = threading.Thread(
            target=self._completer_loop, daemon=True)
        self._completer.start()

    # -- EndpointPicker interface -----------------------------------------

    def pick(self, req: PickRequest, candidates: list) -> PickResult:
        if not candidates:
            # Scale-from-zero wake signal (ROADMAP): an arrival against an
            # EMPTY pool is the only traffic evidence a scaled-to-zero
            # pool produces — record it before 503ing so the autoscale
            # recommender can wake the pool 0->1. Strict-subset misses
            # against a NON-empty pool are routing failures, not demand
            # for more replicas. getattr: latency tests stub the store.
            note = getattr(self.metrics_store, "note_empty_pool_arrival", None)
            eps = getattr(self.datastore, "endpoints", lambda: ())
            if note is not None and not eps():
                note()
            # Strict subsetting / no ready endpoints (004 README:77-79).
            raise ExtProcError(grpc.StatusCode.UNAVAILABLE, "no endpoints available")
        try:
            band = _band_for(req.headers, self.objective_registry)
        except Exception as e:
            # Band resolution happens ONCE, here at enqueue (the cached
            # value feeds fair ordering, the age shed, the hold check, and
            # assembly). A malformed objective header therefore fails THIS
            # request at its own call site — it can no longer poison the
            # collector's pre-batch section and take the whole queue down
            # with it.
            raise ExtProcError(
                grpc.StatusCode.INVALID_ARGUMENT,
                f"malformed objective header: {type(e).__name__}: {e}")
        item = _Pending(req, candidates, band=band,
                        now=self._clock.now())
        # Fairness ledger (gie_tpu/fairness): offered-cost accounting +
        # gie_tenant_requests_total — one leaf-lock note per enqueue.
        self.fairness.note_arrival(item.tenant, item.cost)
        tr = req.trace
        if tr is not None:
            tr.event("queued")
        with self._cond:
            if self._closed:
                raise ExtProcError(grpc.StatusCode.UNAVAILABLE, "picker shut down")
            if self.queue_bound > 0 and len(self._pending) >= self.queue_bound:
                self._admit_into_full_queue(band, tenant=item.tenant)
            self._pending.append(item)
            own_metrics.QUEUE_DEPTH.set(len(self._pending))
            self._clock.notify(self._cond)
        # Bounded wait: if the collector ever wedges (device hang, bug), fail
        # the stream instead of hanging the ext-proc thread forever. Budget =
        # flow-control hold window + a generous scheduling allowance (first
        # jit compile of a new batch bucket can take tens of seconds).
        if not self._clock.wait_event(
                item.event, self.hold_max_s + self.pick_timeout_s):
            item.abandoned = True
            raise ExtProcError(
                grpc.StatusCode.UNAVAILABLE, "scheduler did not respond in time"
            )
        if item.error is not None:
            raise item.error
        assert item.result is not None
        return item.result

    def _admit_into_full_queue(self, band: int, tenant: str = "") -> None:
        """Overload policy for a full flow-control queue (caller holds the
        lock): free a slot by dropping an abandoned waiter if one exists,
        else evict the newest waiter in the lowest-criticality band present
        (which must be strictly lower than the arrival's; it sheds with 429
        — within-band FIFO is preserved, and a band never evicts itself),
        else shed the arrival. Raises ShedError when the arrival loses.
        Within the victim band, an over-fair-share tenant's waiter is
        evicted FIRST (gie_tpu/fairness): under queue pressure the
        flooding tenant absorbs the eviction, not an in-budget neighbor.
        `band`/`tenant` are the arrival's already-resolved identity."""
        for i in range(len(self._pending) - 1, -1, -1):
            if self._pending[i].abandoned:
                del self._pending[i]
                return
        worst_i, worst_band = -1, band
        for i in range(len(self._pending) - 1, -1, -1):
            b = self._pending[i].band
            if b > worst_band:
                worst_i, worst_band = i, b
                if b == int(C.Criticality.SHEDDABLE):
                    break  # no worse band exists
        if worst_i < 0:
            own_metrics.QUEUE_SHED.labels(
                reason="depth", band=_BAND_NAMES.get(band, "standard")).inc()
            self.fairness.note_shed(
                tenant, _BAND_NAMES.get(band, "standard"))
            raise ShedError("flow-control queue full",
                            band=band, tenant=tenant)
        # Tenant-aware victim selection: the newest same-band waiter of
        # an over-share tenant beats plain newest-in-band. _cond (rank
        # 30) -> budgets leaf lock (rank 83) is hierarchy-clean.
        over = self.fairness.over_share_set()
        if over:
            for i in range(len(self._pending) - 1, -1, -1):
                it = self._pending[i]
                if it.band == worst_band and it.tenant in over:
                    worst_i = i
                    break
        victim = self._pending.pop(worst_i)
        victim.error = ShedError("evicted by higher-criticality arrival",
                                 band=victim.band, tenant=victim.tenant)
        victim.event.set()
        own_metrics.QUEUE_SHED.labels(
            reason="evicted",
            band=_BAND_NAMES.get(worst_band, "standard")).inc()
        self.fairness.note_shed(
            victim.tenant, _BAND_NAMES.get(worst_band, "standard"))

    def _preemptive_shed(self, batch: list["_Pending"],
                         over: frozenset) -> list["_Pending"]:
        """SLO-tier enforcement under saturation (docs/FAIRNESS.md):
        SHEDDABLE items of over-fair-share tenants shed 429 when every
        candidate endpoint is past the scheduler's queue saturation
        bound — the same pressure the cycle's sheddable-429 machinery
        detects, applied tenant-first so the flooding tenant absorbs the
        overload. CRITICAL and STANDARD are never touched here, and an
        unsaturated pool sheds nobody (over-share alone is not a crime
        while capacity is free). getattr guards: latency tests stub the
        store/scheduler."""
        host_q = getattr(self.metrics_store, "host_queue_depths", None)
        cfg = getattr(self.scheduler, "cfg", None)
        limit = float(getattr(cfg, "queue_limit", 0.0) or 0.0)
        if host_q is None or limit <= 0.0:
            return batch
        queues = host_q()
        kept: list[_Pending] = []
        for it in batch:
            if (it.band != int(C.Criticality.SHEDDABLE)
                    or it.tenant not in over):
                kept.append(it)
                continue
            slots = it.cand_slots
            slots = slots[(slots >= 0) & (slots < queues.shape[0])]
            if slots.size and bool(np.all(queues[slots] >= limit)):
                it.error = ShedError(
                    "tenant over fair share under saturation",
                    band=it.band, tenant=it.tenant)
                self._clock.set_event(it.event)
                own_metrics.QUEUE_SHED.labels(
                    reason="tenant", band="sheddable").inc()
                self.fairness.note_shed(it.tenant, "sheddable")
            else:
                kept.append(it)
        return kept

    def observe_served(self, served_hostport: str, ctx) -> None:
        """Served-endpoint feedback -> assumed-load release
        (004 README:84-101) + data-plane serve outcome (breaker/ladder,
        docs/RESILIENCE.md) + latency-predictor training signal."""
        pick_result = getattr(ctx, "pick_result", None)
        self._release_charge(pick_result, served_hostport)
        # Serve outcome: the Envoy :status harvested at the response-
        # headers hop (0 = the transport never surfaced one — nothing to
        # learn) and the pick-to-response-headers latency. Charged to
        # the endpoint that actually SERVED, which is what the outcome
        # describes (on data-plane failover the fallback's health is
        # what was observed, not the primary's).
        status = int(getattr(ctx, "resp_status", 0) or 0)
        primary = getattr(pick_result, "endpoint", "")
        rec = getattr(pick_result, "record", None)
        if rec is not None:
            # Close the flight-recorder record with what the data plane
            # actually did: who served, which fallback rank Envoy walked
            # to, the observed verdict and serve latency. Field writes
            # on a published dict are GIL-atomic; zpage reads snapshot.
            rec["served"] = served_hostport
            ranked = [primary] + list(
                getattr(pick_result, "fallbacks", None) or [])
            rec["fallback_rank"] = (
                ranked.index(served_hostport)
                if served_hostport in ranked else -1)
            if status > 0:
                rec["outcome"] = f"{status // 100}xx"
                picked_at = float(getattr(ctx, "picked_at", 0.0) or 0.0)
                if picked_at:
                    rec["serve_latency_ms"] = round(max(
                        self._clock.now() - picked_at, 0.0) * 1e3, 1)
        if (primary and served_hostport
                and served_hostport != primary):
            # Envoy walked the fallback list: an earlier entry — the
            # primary — refused the connection or reset before the
            # fallback served. Without this, a connect-refusing pod
            # whose requests always retry onto a fallback would never
            # feed its own breaker (it scrapes healthy, and the served
            # endpoint's 2xx is credited to the fallback) while adding
            # a failed hop to every request it wins.
            self._note_serve_outcome(primary, ok=False, cls="reset")
        if status > 0:
            picked_at = float(getattr(ctx, "picked_at", 0.0) or 0.0)
            latency_s = (
                max(self._clock.now() - picked_at, 0.0) if picked_at else 0.0)
            self._note_serve_outcome(
                served_hostport, ok=status < 500,
                cls=f"{status // 100}xx", latency_s=latency_s,
                trace=getattr(ctx, "trace", None),
                tenant=_ctx_tenant(ctx))
            if status >= 500:
                # An errored serve trains nothing: an Envoy local-reply
                # 503 (connect refused) arrives FAST, and a low-latency
                # TTFT sample would teach the predictor that the sick
                # endpoint is the most attractive one in the pool.
                return
        feedback = getattr(pick_result, "feedback", None)
        if self.trainer is not None and feedback is not None:
            features, slot, picked_at, picked_hostport = feedback
            if served_hostport != picked_hostport:
                # The data plane failed over to a fallback: the recorded
                # features describe the PRIMARY endpoint, so training on
                # this latency would mislabel the pair. Skip.
                return
            elapsed = max(self._clock.now() - picked_at, 1e-4)
            # Response headers arrive ~ first token: elapsed approximates
            # TTFT; TPOT is unobservable at this hop (no token counts), so
            # the sample trains the TTFT head only (tpot masked). The TPOT
            # half arrives later via observe_response_complete.
            self.trainer.observe(features, ttft_s=elapsed, tpot_s=None,
                                 slot=slot)

    def _release_charge(self, pick_result, served_hostport: str = "") -> None:
        """Release the assumed-load the cycle CHARGED (the primary pick,
        or both pd workers), not the slot of whichever endpoint actually
        served: on data-plane failover the primary's charge would leak
        and the fallback would get a spurious release. Guard against
        slot reuse — if the charged endpoint was evicted, its eviction
        already cleared the slot's load, so skip the release."""
        cost = getattr(pick_result, "assumed_cost", 1.0)
        charged = getattr(pick_result, "charged", None)
        if charged:
            # Disaggregated mode: release every charged worker whose slot
            # still belongs to the charged hostport (slot-reuse guard).
            slots, costs = [], []
            for slot, slot_cost, hostport in charged:
                ep = self.datastore.endpoint_by_hostport(hostport)
                if ep is not None and ep.slot == slot:
                    slots.append(slot)
                    costs.append(slot_cost)
            if slots:
                self.scheduler.complete(
                    np.asarray(slots, np.int32),
                    np.asarray(costs, np.float32),
                )
            return
        release_slot = None
        charged_slot = getattr(pick_result, "charged_slot", None)
        primary = getattr(pick_result, "endpoint", None)
        if charged_slot is not None and primary is not None:
            ep = self.datastore.endpoint_by_hostport(primary)
            if ep is not None and ep.slot == charged_slot:
                release_slot = charged_slot
        elif served_hostport:  # legacy pick results without bookkeeping
            ep = self.datastore.endpoint_by_hostport(served_hostport)
            if ep is not None:
                release_slot = ep.slot
        if release_slot is not None:
            self.scheduler.complete(
                np.asarray([release_slot], np.int32),
                np.asarray([cost], np.float32),
            )

    def observe_stream_aborted(self, ctx) -> None:
        """Stream-teardown feedback (extproc on_stream_aborted): the
        Envoy stream ended after a pick but BEFORE response headers.
        on_served will never fire for this stream, so the release it
        would have performed happens here (the stream must not leak
        assumed load until pod eviction) — every such exit. The
        breaker/ladder additionally see a reset outcome against the
        primary endpoint only when the end was ABNORMAL (ctx.aborted:
        cancellation, transport/protocol error, or the injected reset) —
        a clean half-close just means the route has no response
        processing, and charging those as resets would quarantine every
        healthy pod behind such a listener."""
        pick_result = getattr(ctx, "pick_result", None)
        if pick_result is None:
            return
        self._release_charge(pick_result)
        primary = getattr(pick_result, "endpoint", "")
        aborted = getattr(ctx, "aborted", True)
        rec = getattr(pick_result, "record", None)
        if rec is not None:
            rec["outcome"] = "reset" if aborted else "closed"
        if primary and aborted:
            self._note_serve_outcome(primary, ok=False, cls="reset",
                                     tenant=_ctx_tenant(ctx))

    def _note_serve_outcome(self, hostport: str, ok: bool, cls: str,
                            latency_s: float = 0.0, trace=None,
                            tenant: str = "") -> None:
        """Fan one data-plane serve outcome into the resilience layer:
        gie_serve_outcome_total, the serving endpoint's breaker (windowed
        error-rate + streak), the ladder's pool-wide serve floor, and the
        per-tenant budget ledger. A head-sampled request's serve-latency
        observation carries a trace-ID exemplar — the same bucket->trace
        join the admission/pick histograms already expose
        (docs/OBSERVABILITY.md)."""
        own_metrics.SERVE_OUTCOME.labels(cls).inc()
        if latency_s > 0.0:
            if trace is not None and getattr(trace, "sampled", False):
                own_metrics.SERVE_LATENCY.observe(
                    latency_s, {"trace_id": trace.trace_id})
            else:
                own_metrics.SERVE_LATENCY.observe(latency_s)
        self.fairness.note_serve(tenant, ok=ok, cls=cls)
        rs = self.resilience
        if rs is None:
            return
        ep = self.datastore.endpoint_by_hostport(hostport)
        if ep is not None and rs.board.record_serve_outcome(
                ep.slot, ok, latency_s=latency_s):
            # State transition: refresh the gauge here rather than
            # paying open_count()'s lock per request.
            own_metrics.BREAKER_OPEN.set(rs.board.open_count())
        if (ep is not None and ok and latency_s > 0.0
                and rs.ejector is not None):
            # p99 outlier ejection input (resilience/outlier.py): only
            # SUCCESSFUL serves' latencies — a fast local-reply 503
            # would drag a sick endpoint's quantile down exactly while
            # the error plane is what should be judging it. The eval
            # itself runs at wave cadence (ResilienceState.observe).
            rs.ejector.note(ep.slot, latency_s)
        rs.ladder.note_serve_outcome(ok)

    def observe_response_complete(self, ctx) -> None:
        """Response-stream-complete feedback -> TPOT training signal
        (VERDICT r3 #7): the ext-proc response-body hop harvests the
        output token count (transcoded Generate frames' completion_tokens,
        SSE data-frame count, or the usage block) and the first/last
        body-chunk times; their quotient is the measured per-token
        latency. Trains the TPOT head only — the TTFT half was observed
        at the response-headers hop."""
        if self.trainer is None:
            return
        if (getattr(ctx, "aborted", False)
                or int(getattr(ctx, "resp_status", 0) or 0) >= 500):
            # A reset/errored stream trains nothing (same rule as the
            # TTFT hop): its chunk timing describes the failure, not
            # token generation.
            return
        pick_result = getattr(ctx, "pick_result", None)
        feedback = getattr(pick_result, "feedback", None)
        if feedback is None:
            return
        features, slot, _picked_at, picked_hostport = feedback
        served = getattr(ctx, "served_hostport", "")
        if served and served != picked_hostport:
            # Data-plane failover: the features describe the primary, the
            # stream timing describes the fallback. Skip (same rule as
            # the TTFT hop).
            return
        if not getattr(ctx, "timing_is_generation", False):
            # Buffered JSON split across network flushes: chunk spacing
            # measures the proxy's write cadence, not token generation —
            # a 500-token body flushed in 2 ms would teach the TPOT head
            # ~4 us/token and poison every later prediction.
            return
        tokens = int(getattr(ctx, "resp_tokens", 0))
        t0 = getattr(ctx, "resp_first_at", 0.0)
        t1 = getattr(ctx, "resp_last_at", 0.0)
        if tokens < 2 or t1 <= t0:
            return  # single-chunk response: no inter-token interval exists
        tpot = (t1 - t0) / (tokens - 1)
        self.trainer.observe(features, ttft_s=None, tpot_s=tpot, slot=slot)

    def queue_report(self) -> dict:
        """Flow-queue zpage (/debugz/queue, gie_tpu/obs): live depth,
        per-band composition, and the oldest waiter's age. The lock is
        held only for the list copy; aggregation runs outside it."""
        now = self._clock.now()
        with self._cond:
            items = list(self._pending)
        bands: dict[str, int] = {}
        oldest = 0.0
        for it in items:
            name = _BAND_NAMES.get(it.band, str(it.band))
            bands[name] = bands.get(name, 0) + 1
            oldest = max(oldest, now - it.enqueued_at)
        return {
            "depth": len(items),
            "bands": bands,
            "oldest_wait_ms": round(oldest * 1e3, 1),
            "queue_bound": self.queue_bound,
            "queue_max_age_s": self.queue_max_age_s,
            "pipeline_depth_limit": self._depth_limit,
            "waves_in_flight": self._inflight,
        }

    def tenants_report(self) -> dict:
        """Per-tenant zpage (/debugz/tenants, gie_tpu/obs): live
        per-tenant queue composition joined with the fairness layer's
        budgets, weights, over-share verdicts, and DRR deficits — the
        end-to-end explanation of one tenant's deficit/shed state. The
        queue lock is held only for the identity copy."""
        with self._cond:
            pending = [(it.tenant, it.band) for it in self._pending]
        queue: dict[str, dict[str, int]] = {}
        for tenant, band in pending:
            per = queue.setdefault(tenant or "default", {})
            name = _BAND_NAMES.get(band, str(band))
            per[name] = per.get(name, 0) + 1
        rep = self.fairness.report()
        rep["queue"] = queue
        rep["queue_depth"] = len(pending)
        return rep

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._clock.notify(self._cond)
        self._worker.join(timeout=5)
        # DRAIN, don't abandon: every wave the dispatcher already pushed
        # still materializes and wakes its waiters before the completer
        # exits — the sentinel is FIFO-ordered behind the in-flight work.
        try:
            self._waves.put(_CLOSE, timeout=5)
        except queue.Full:
            pass  # completer wedged; it is a daemon thread
        self._completer.join(timeout=5)
        if not self._completer.is_alive():
            # A dispatcher that outlived its join (wedged in a first-use
            # jit compile) can push a wave AFTER the sentinel — the
            # completer has already exited, so nobody would ever
            # materialize it. Fail those waiters now rather than letting
            # them hang to the pick timeout. (A merely-slow completer is
            # still alive and keeps draining; only a dead one leaves
            # orphans.)
            while True:
                try:
                    wave = self._waves.get_nowait()
                except queue.Empty:
                    break
                if wave is _CLOSE:
                    continue
                for item in wave.batch:
                    if item.result is None and item.error is None:
                        item.error = ExtProcError(
                            grpc.StatusCode.UNAVAILABLE, "picker shut down")
                    self._clock.set_event(item.event)
                with self._inflight_cv:
                    self._inflight -= 1
                    self._clock.notify_all(self._inflight_cv)

    # -- collector ---------------------------------------------------------

    def _loop(self) -> None:
        # Virtual-time actor registration (runtime/clock.py; no-op on
        # the real clock): the collector is one of the simulation's
        # parked/active participants.
        tok = self._clock.actor_begin("picker-collector")
        try:
            self._loop_inner()
        finally:
            self._clock.actor_end(tok)

    def _loop_inner(self) -> None:
        # The collector must NEVER die: every code path that can raise is
        # inside a try whose handler fails the affected waiters and keeps
        # looping. A dead collector would hang every in-flight and future
        # pick() (bounded only by the pick() wait timeout).
        while True:
            batch: list[_Pending] = []
            try:
                with self._cond:
                    while not self._pending and not self._closed:
                        self._clock.wait(self._cond)
                    if self._closed and not self._pending:
                        return
                    # Micro-batch window: collect stragglers before draining.
                    if len(self._pending) < self.max_batch:
                        self._clock.wait(self._cond, self.max_wait_s)
                    if len(self._pending) > self.max_batch:
                        # Flow-control fairness: when demand exceeds one
                        # cycle, weighted deficit-round-robin across
                        # fairness IDs (x-gateway-inference-fairness-id,
                        # proposal 1199 + gie_tpu/fairness) so one tenant
                        # cannot monopolize a wave by count OR by cost.
                        # Only the drained prefix (the next wave) charges
                        # the persistent deficit state.
                        self._pending = self.fairness.order(
                            self._pending, take=self.max_batch)
                    batch = self._pending[: self.max_batch]
                    self._pending = self._pending[self.max_batch :]
                    own_metrics.QUEUE_DEPTH.set(len(self._pending))
                held = self._run_batch(batch)
            except Exception as e:  # propagate to all waiters
                if not batch:
                    # Failure in the pre-batch section (fair ordering /
                    # registry resolution): the poisoned item is still in
                    # self._pending and would wedge the loop permanently —
                    # fail the whole queue rather than hang it.
                    with self._cond:
                        batch, self._pending = self._pending, []
                        own_metrics.QUEUE_DEPTH.set(0)
                for item in batch:
                    # A fresh exception per waiter: handler threads raise
                    # these concurrently, and a shared instance would race
                    # on __traceback__/__context__ across threads.
                    item.error = ExtProcError(
                        grpc.StatusCode.INTERNAL, f"scheduler failure: {e}"
                    )
                    self._clock.set_event(item.event)
                continue
            if held:
                with self._cond:
                    # Held items rejoin at the HEAD (they arrived first);
                    # pace retries only when nothing NEW is waiting, so a
                    # fully-saturated pool doesn't busy-spin the collector
                    # and fresh arrivals are never delayed by the pacing.
                    new_arrivals = len(self._pending) > 0
                    self._pending = held + self._pending
                    own_metrics.QUEUE_DEPTH.set(len(self._pending))
                    if not new_arrivals:
                        self._clock.wait(self._cond, self.hold_retry_s)

    _M_SHRINK_PATIENCE = 64  # consecutive smaller-bucket waves before shrink
    _DEPTH_RETUNE_WAVES = 32  # auto pipeline-depth retune cadence

    def _retune_depth(self) -> None:
        """pipeline_depth="auto": pick the in-flight bound 1-3 from the
        measured stage-time ratio (rationale at the __init__ comment).
        Hysteresis: a change applies only when two consecutive retunes
        agree, so a ratio sitting on a threshold cannot flap the bound
        every window. Dispatcher-thread only (apart from the racy-read
        _cycle_ewma, which the completer owns)."""
        cycle = self._cycle_ewma
        if cycle <= 0.0 or self._asm_ewma <= 0.0:
            return  # no completed wave measured yet
        ratio = self._asm_ewma / cycle
        if ratio >= 2.0:
            want = 1
        elif ratio >= 0.5:
            want = 3
        else:
            want = 2
        agreed, self._depth_want_prev = want == self._depth_want_prev, want
        if not agreed or want == self._depth_limit:
            return
        with self._inflight_cv:
            self._depth_limit = want
            # Raising the limit may unblock a dispatcher waiting on the
            # old one; lowering just lets in-flight waves drain past it.
            self._clock.notify_all(self._inflight_cv)

    def _pick_m_bucket(self, endpoints) -> int:
        """Endpoint-axis bucket for this wave: smallest M bucket covering
        the high-water live slot. Grows immediately (a new pod must be
        addressable now); shrinks only after _M_SHRINK_PATIENCE consecutive
        waves fit the smaller bucket, so churn at a boundary doesn't thrash
        compiled shapes and state migrations. Collector-thread only."""
        high = 1 + max((ep.slot for ep in endpoints), default=-1)
        needed = m_bucket_for(max(high, 1))
        if needed > self._m_bucket:
            self._m_bucket = needed
            self._m_shrink_streak = 0
        elif needed < self._m_bucket:
            self._m_shrink_streak += 1
            if self._m_shrink_streak >= self._M_SHRINK_PATIENCE:
                self._m_bucket = needed
                self._m_shrink_streak = 0
        else:
            self._m_shrink_streak = 0
        return self._m_bucket

    def _run_batch(self, batch: list[_Pending]) -> list["_Pending"]:
        """Pipeline stage 1 (dispatcher): shed/hold decisions, vectorized
        wave assembly, async cycle dispatch, handoff to the completer.
        Returns the held items the collector should requeue. Blocks only
        when `pipeline_depth` waves are already in flight — the bounded
        queue is the backpressure seam that caps tail latency."""
        # Timed-out callers are gone: scheduling their items would charge
        # assumed load with no served feedback to ever release it.
        batch = [it for it in batch if not it.abandoned]
        if batch:
            # Deadline propagation (resilience/deadline.py): a pick whose
            # request budget expired while queued sheds with 503 BEFORE
            # the wave charges any device work — nobody is waiting for
            # the answer. Requests without a deadline header carry 0.0
            # and cost one float compare here.
            now = self._clock.now()
            kept: list[_Pending] = []
            for it in batch:
                d = it.req.deadline_at
                if d and now >= d:
                    it.error = deadline_mod.DeadlineExceeded("queue")
                    self._clock.set_event(it.event)
                    own_metrics.DEADLINE_SHED.labels(stage="queue").inc()
                else:
                    kept.append(it)
            batch = kept
        if self.queue_max_age_s > 0 and batch:
            # Age bound: a non-critical pick that has waited beyond the
            # bound sheds with 429 instead of occupying a wave slot —
            # bounded queue AGE, the second half of the flow-controller's
            # overload policy. CRITICAL is exempt (its latency bound comes
            # from draining first in _fair_order).
            now = self._clock.now()
            kept: list[_Pending] = []
            for it in batch:
                if (
                    it.band != int(C.Criticality.CRITICAL)
                    and now - it.enqueued_at > self.queue_max_age_s
                ):
                    it.error = ShedError("queued beyond flow-control age bound",
                                         band=it.band, tenant=it.tenant)
                    self._clock.set_event(it.event)
                    own_metrics.QUEUE_SHED.labels(
                        reason="age",
                        band=_BAND_NAMES.get(it.band, "standard")).inc()
                    self.fairness.note_shed(
                        it.tenant, _BAND_NAMES.get(it.band, "standard"))
                else:
                    kept.append(it)
            batch = kept
        if batch:
            # Preemptive per-tenant shed (gie_tpu/fairness, the SLO-tier
            # contract): under saturation, SHEDDABLE work of tenants over
            # their weighted fair share sheds 429 BEFORE the wave — the
            # abuser absorbs the overload, an in-budget neighbor's p99
            # does not. The over-share set is a cached frozenset; with
            # nobody over budget this is one read and a falsy branch.
            over = self.fairness.over_share_set()
            if over:
                batch = self._preemptive_shed(batch, over)
        if not batch:
            return []
        # Graceful-drain housekeeping at wave cadence (docs/RESILIENCE.md):
        # reap endpoints whose bounded drain deadline passed without the
        # pod's deletion event, export the gauge, and drop DRAINING
        # endpoints from each item's candidate set — the cycle's subset
        # mask, and therefore the primary pick AND the in-mask fallback
        # ranks, never land on a terminating pod (drain_filter keeps the
        # set when filtering would empty it: availability beats drain).
        # While nothing drains this costs two attribute loads and one
        # falsy check. getattr: latency tests stub the datastore.
        draining_count = getattr(self.datastore, "draining_count", None)
        if draining_count is not None:
            self.datastore.reap_expired_drains()
            n_draining = draining_count()
            own_metrics.DRAINING_ENDPOINTS.set(n_draining)
            if n_draining:
                for it in batch:
                    allowed = drain_filter(it.candidates)
                    if allowed is not it.candidates:
                        # Flight-recorder provenance: which slots drain
                        # excluded for this request (gie_tpu/obs).
                        it.excl_drain = tuple(
                            int(getattr(ep, "slot", -1))
                            for ep in it.candidates
                            if getattr(ep, "draining", False))
                        it.candidates = allowed
                        it.cand_slots = np.fromiter(
                            (getattr(ep, "slot", -1) for ep in allowed),
                            np.int64, len(allowed))
        # Federation spillover (gie_tpu/federation, docs/FEDERATION.md),
        # decided per wave BEFORE the hold check: a pick whose local
        # candidates are all saturated gains the imported peer
        # endpoints (penalized in the cost model) instead of being held
        # to die — and under whole-cluster drain the preference inverts
        # (new picks bleed to healthy peers). Strict subsetting is
        # honored: an upstream-pinned candidate set never spills.
        # CRITICAL never crosses while local candidates exist
        # (FederationState.spill_candidates owns the band rules).
        fed = self.federation
        if fed is not None and (fed.has_peers() or fed.draining):
            fed.observe()
            queues_f = self.metrics_store.host_queue_depths()
            for it in batch:
                if getattr(it.req, "subset", False):
                    continue
                if it.fed_remote:
                    # Already spilled on a prior cycle (a HELD item
                    # re-enters at ~10 ms cadence): re-appending would
                    # duplicate remotes unboundedly, so the set is kept
                    # — EXCEPT when the drain flag flipped since the
                    # spill, which invalidates the decision both ways:
                    # a drain-REPLACED item whose drain was cancelled
                    # must come home (restore the pre-spill locals and
                    # re-evaluate), and a spill-APPENDED item caught by
                    # a newly-raised drain must drop its locals (fall
                    # through to the replace branch).
                    was_replaced = all(
                        getattr(ep, "cluster", "") for ep in it.candidates)
                    if was_replaced == bool(fed.draining):
                        continue  # decision still matches the flag
                    if it.fed_base is not None:
                        it.candidates = it.fed_base
                    it.fed_remote = ()
                    it.cand_slots = np.fromiter(
                        (getattr(ep, "slot", -1) for ep in it.candidates),
                        np.int64, len(it.candidates))
                # cand_slots mirrors candidates here on every path, so
                # the common no-spill case costs zero array rebuilds.
                remote = fed.spill_candidates(
                    it.band, it.cand_slots, queues_f)
                if not remote:
                    continue
                it.fed_base = list(it.candidates)
                it.fed_remote = tuple(
                    int(getattr(ep, "slot", -1)) for ep in remote)
                if fed.draining:
                    # Drain bleed: local endpoints leave NEW-pick
                    # candidacy entirely (in-flight completes locally;
                    # spill_candidates returned None if no healthy peer
                    # exists — availability beats drain).
                    it.candidates = list(remote)
                else:
                    it.candidates = list(it.candidates) + list(remote)
                it.cand_slots = np.fromiter(
                    (getattr(ep, "slot", -1) for ep in it.candidates),
                    np.int64, len(it.candidates))
        # Flow-control hold decision happens BEFORE any scheduling, so a
        # held request never touches device state (assumed load, prefix
        # inserts, tick) — it simply waits for capacity or its deadline.
        # Criterion: non-critical, within deadline, and EVERY candidate is
        # saturated (if any candidate has capacity, schedule now — the
        # cycle will steer there anyway).
        held: list[_Pending] = []
        if self.hold_max_s > 0:
            queues = self.metrics_store.host_queue_depths()
            now = self._clock.now()
            runnable: list[_Pending] = []
            for it in batch:
                slots = it.cand_slots
                slots = slots[(slots >= 0) & (slots < C.M_MAX)]
                if (
                    it.band != C.Criticality.CRITICAL
                    and now - it.enqueued_at < self.hold_max_s
                    and bool(np.all(queues[slots] >= self.hold_queue_limit))
                ):
                    d = it.req.deadline_at
                    if d and d - now < 2.0 * self.hold_retry_s:
                        # Budget-aware hold (docs/RESILIENCE.md): the
                        # remaining deadline budget cannot survive even
                        # one more retry-pacing window plus the pick
                        # itself — holding would hold it to die at the
                        # queue-shed check. Pick NOW, best-effort, onto
                        # the saturated pool; long holds are reserved
                        # for requests that still have budget (or carry
                        # no deadline at all).
                        own_metrics.HOLD_BUDGET_BYPASS.inc()
                        runnable.append(it)
                    else:
                        tr_h = it.req.trace
                        if tr_h is not None and (
                                not tr_h.events
                                or tr_h.events[-1][0] != "held"):
                            # One event per hold SPELL, not per retry
                            # cycle (10 ms cadence): a request held for
                            # seconds must not grow its event list by
                            # hundreds of duplicate rows.
                            tr_h.event("held")
                        held.append(it)
                else:
                    runnable.append(it)
            batch = runnable
            if not batch:
                return held
        # Drained-cost ledger (gie_tpu/fairness): this batch IS the wave
        # — full device path or degraded rung alike — so charge each
        # tenant's windowed drained cost + gie_tenant_cost_total here,
        # once, at wave cadence.
        self.fairness.note_wave(batch)
        rs = self.resilience
        if rs is not None:
            # Per-WAVE resilience decision (never per request): fold the
            # staleness clock into the ladder, then either serve this
            # wave host-side on the current degraded rung or let it
            # through the full device path (always when FULL; as a probe
            # at probe cadence while level-degraded).
            rs.observe()
            rung = rs.ladder.rung()
            if rung != Rung.FULL and not rs.ladder.should_probe():
                self._degraded_pick(batch, rung)
                return held
            if rs.board.has_open:
                # Breaker candidate filter: quarantined endpoints drop
                # out of each item's candidate set — unless that would
                # empty it (availability beats quarantine; the breaker's
                # own half-open probes need traffic to heal).
                for it in batch:
                    allowed, dropped = [], []
                    for ep in it.candidates:
                        if rs.board.quarantined(getattr(ep, "slot", -1)):
                            dropped.append(ep)
                        else:
                            allowed.append(ep)
                    if allowed and dropped:
                        it.excl_breaker = tuple(
                            int(getattr(ep, "slot", -1)) for ep in dropped)
                        it.candidates = allowed
                        it.cand_slots = np.fromiter(
                            (getattr(ep, "slot", -1) for ep in allowed),
                            np.int64, len(allowed))
        t0 = time.perf_counter()
        n = len(batch)
        endpoints = self.datastore.endpoints()
        mb = self._pick_m_bucket(endpoints)
        own_metrics.BATCH_SIZE.observe(n)
        reqs, plen, dlen, lora = assemble_wave(batch, mb, self.lora_registry)
        eps = self.metrics_store.endpoint_batch(endpoints, m_slots=mb)
        # Async dispatch: the cycle is enqueued on the device stream and
        # the host returns immediately — the snapshot_load copy replaces
        # the old post-pick snapshot_assumed_load() (same post-schedule
        # state; the copy is ordered after this cycle and before the next
        # under the scheduler lock, and survives the next cycle's buffer
        # donation).
        try:
            if faults.ENABLED:
                faults.check("device.dispatch")
            pending = self.scheduler.pick_async(
                reqs, eps, snapshot_load=self.trainer is not None)
        except Exception:
            if rs is None:
                raise  # seed behavior: the collector fails the waiters
            # Device dispatch failed: feed the ladder and serve THIS wave
            # host-side at CACHED or worse — a sick device must cost a
            # slower pick, never an UNAVAILABLE storm.
            rs.ladder.note_dispatch_error()
            self._degraded_pick(
                batch, Rung(max(rs.ladder.rung(), Rung.CACHED)))
            return held
        lattice = (mb, int(reqs.chunk_hashes.shape[1]))
        if self.background_warm and lattice not in self._warmed_lattices:
            self._warmed_lattices.add(lattice)
            self._warm_threads.append(
                self.scheduler.warm_lattice_async(*lattice))
        asm_s = time.perf_counter() - t0
        own_metrics.HOST_ASSEMBLY.observe(asm_s)
        if self._depth_auto:
            self._asm_ewma = (asm_s if self._asm_ewma == 0.0
                              else 0.9 * self._asm_ewma + 0.1 * asm_s)
            self._depth_waves += 1
            if self._depth_waves >= self._DEPTH_RETUNE_WAVES:
                self._depth_waves = 0
                self._retune_depth()
        # Backpressure: block while `_depth_limit` waves are in flight —
        # the same semantics the bounded queue.put had, but against a
        # limit the auto policy may move at runtime.
        with self._inflight_cv:
            while self._inflight >= self._depth_limit:
                self._clock.wait(self._inflight_cv)
            self._inflight += 1
        own_metrics.PIPELINE_DEPTH.inc()
        own_metrics.PIPELINE_WAVES.inc()
        self._waves.put(
            _Wave(batch, pending, endpoints, eps.metrics, plen, dlen, lora))
        return held

    # -- completer (pipeline stage 2) --------------------------------------

    def _completer_loop(self) -> None:
        tok = self._clock.actor_begin("picker-completer")
        try:
            self._completer_loop_inner()
        finally:
            self._clock.actor_end(tok)

    def _completer_loop_inner(self) -> None:
        # Strictly dispatch-ordered (one thread, FIFO queue) and, like the
        # dispatcher, it must NEVER die: a failure touches only its own
        # wave's waiters, then the next wave is served regardless — device
        # fault isolation at wave granularity.
        while True:
            # Bounded receive (GR001): the sentinel is the normal exit,
            # but if close()'s put times out (queue full, wedged pipeline)
            # the loop must still observe shutdown rather than park
            # forever. _closed alone is NOT an exit condition — close()
            # flips it before the dispatcher drains, and a dispatcher
            # wedged in a first-use jit compile still pushes its
            # already-collected waves afterward (the drain-don't-abandon
            # contract). Exit only once the dispatcher is gone AND the
            # queue is verifiably empty: with the producer dead, the
            # queue can only shrink, so the snapshot is sound (close()
            # fails any residual orphans after we exit).
            try:
                wave = self._waves.get(timeout=1.0)
            except queue.Empty:
                if (self._closed and not self._worker.is_alive()
                        and self._waves.empty()):
                    return
                continue
            if wave is _CLOSE:
                return
            # Release the in-flight slot at PICKUP, not completion: the
            # bounded queue this replaced held `depth` waves while the
            # completer materialized one more, and that +1 of overlap
            # (next wave's assembly running during a slow fan-out) is
            # part of the pipeline's throughput.
            with self._inflight_cv:
                self._inflight -= 1
                self._clock.notify_all(self._inflight_cv)
            try:
                self._complete_wave(wave)
            except Exception as e:
                for item in wave.batch:
                    if item.result is None and item.error is None:
                        # A fresh exception per waiter: handler threads
                        # raise these concurrently, and a shared instance
                        # would race on __traceback__/__context__.
                        item.error = ExtProcError(
                            grpc.StatusCode.INTERNAL,
                            f"scheduler failure: {e}")
                    self._clock.set_event(item.event)
            finally:
                own_metrics.PIPELINE_DEPTH.dec()

    def _complete_wave(self, wave: _Wave) -> None:
        """Materialize one wave's device results and fan them out."""
        batch, plen, dlen, lora = wave.batch, wave.plen, wave.dlen, wave.lora
        t0 = time.perf_counter()
        try:
            result = wave.pending.materialize()
        except Exception:
            if self.resilience is None:
                raise  # seed behavior: _completer_loop fails the waiters
            # The dispatched cycle died on device: descend the ladder and
            # serve this wave's waiters host-side instead of failing them
            # — wave fault isolation upgraded from "contained" to
            # "answered".
            self.resilience.ladder.note_dispatch_error()
            self._degraded_pick(
                batch,
                Rung(max(self.resilience.ladder.rung(), Rung.CACHED)))
            return
        wait_s = time.perf_counter() - t0
        own_metrics.DEVICE_WAIT.observe(wait_s)
        if self.resilience is not None:
            # Full-path success (steady state or a probe wave while
            # degraded): the ladder's ascent signal, with the device wait
            # as the pick-latency-breach clock.
            self.resilience.ladder.note_dispatch_ok(latency_s=wait_s)
        if self._depth_auto:
            self._cycle_ewma = (wait_s if self._cycle_ewma == 0.0
                                else 0.9 * self._cycle_ewma + 0.1 * wait_s)
        # One bulk device->host transfer per wave, not one per request.
        # The load snapshot was captured on device right AFTER this wave's
        # cycle: the state had been migrated to the wave's M bucket, so
        # every picked slot is indexable (a pre-pick snapshot at the old
        # width crashed on the first pick past a grow boundary) — and the
        # simulator's feature twin snapshots post-schedule too, keeping
        # the trained feature space identical. Guarded on the snapshot,
        # not self.trainer: a trainer attached between dispatch and
        # completion must not make the completer index a snapshot the
        # dispatcher never requested.
        load_snapshot = (
            wave.pending.materialize_load()
            if self.trainer is not None else None)
        if load_snapshot is not None:
            metrics_np = np.asarray(wave.eps_metrics)

        by_slot = {ep.slot: ep for ep in wave.endpoints}
        indices = np.asarray(result.indices)
        status = np.asarray(result.status)
        # Disaggregated prefill/decode: the cycle's prefill picks (None in
        # classic mode — the pytree field is absent from the result).
        prefill_np = (
            np.asarray(result.prefill) if result.prefill is not None else None
        )
        # Device-gathered affinity provenance (flight-record schema v2,
        # ProfileConfig.record_affinity): (prefix, session) scorer values
        # at the chosen endpoint, already host-side with the result —
        # the completer never re-derives them.
        affinity_np = (
            np.asarray(result.affinity)
            if getattr(result, "affinity", None) is not None else None
        )
        # Hierarchical fleet provenance (gie_tpu/fleet): per-request
        # candidate cells feed both the flight record and the picker's
        # /debugz/fleet tallies, so they materialize even with obs off.
        fleet_aux = getattr(result, "fleet", None)
        fleet_cells = fleet_scores = fleet_ratio = None
        if fleet_aux is not None:
            fleet_cells = np.asarray(fleet_aux.cells)
            fleet_scores = np.asarray(fleet_aux.scores)
            ratio_fn = getattr(self.scheduler, "compression_ratio", None)
            if ratio_fn is not None:
                fleet_ratio = round(
                    ratio_fn(int(np.asarray(wave.eps_metrics).shape[0])), 6)
        # Ranked-fallback-tail hygiene flags, read once per wave: the
        # subset mask constrained the PRIMARY at dispatch, but the ranked
        # tail spans the whole pool — quarantined or DRAINING endpoints
        # must not ride along as data-plane failover targets. Draining is
        # read at COMPLETION time (endpoints are shared mutable objects),
        # so a drain marked between dispatch and fan-out still excludes.
        rs = self.resilience
        board_open = rs is not None and rs.board.has_open
        any_draining = any(
            getattr(ep, "draining", False) for ep in wave.endpoints)
        now_mono = self._clock.now()
        # Flight recorder (gie_tpu/obs, docs/OBSERVABILITY.md): one
        # decision record per request, built HERE on the completer from
        # the wave results that are already host-side — result.scores
        # materialized with the pick, the wave's metrics tensor, the
        # optional post-cycle load snapshot. No device pull happens under
        # any lock (GL002), and nothing is built while obs is off.
        recorder = obs.RECORDER
        rec_scores = rec_metrics = None
        rec_draining: list = []
        if recorder is not None:
            rec_scores = np.asarray(result.scores)
            rec_metrics = (metrics_np if load_snapshot is not None
                           else np.asarray(wave.eps_metrics))
            rec_draining = sorted(
                int(s) for s, ep in by_slot.items()
                if getattr(ep, "draining", False))

        def _rec_base(item: _Pending) -> dict:
            req = item.req
            tr = req.trace
            return {
                "ts": time.time(),
                "trace_id": tr.trace_id if tr is not None else "",
                "model": req.model,
                "band": _BAND_NAMES.get(item.band, str(item.band)),
                # Workload identity (additive fields, schema v1 loaders
                # keep them verbatim): what the request LOOKED like —
                # prompt size, decode hint, tenant — so a recorder dump
                # can be replayed as a storm trace (shapes.TraceReplay,
                # docs/STORM.md) and the item-3 trainers see the
                # request mix, not just the decision.
                "prompt_bytes": int(len(req.body) if req.body else 0),
                "decode_tokens": float(req.decode_tokens or 0.0),
                "tenant": item.tenant,
                "rung": "full",
                "candidates": [int(s) for s in item.cand_slots],
                "excluded_breaker": list(item.excl_breaker),
                "excluded_drain": list(item.excl_drain),
                "fed_remote": list(item.fed_remote),
                "draining": rec_draining,
                "deadline_remaining_ms": (
                    round((req.deadline_at - now_mono) * 1e3, 1)
                    if req.deadline_at else None),
            }

        for i, item in enumerate(batch):
            lat = self._clock.now() - item.enqueued_at
            tr = item.req.trace
            if tr is not None:
                tr.event("picked")
                if tr.sampled:
                    # OpenMetrics exemplar: the pick-latency bucket ->
                    # trace join (docs/OBSERVABILITY.md).
                    own_metrics.PICK_LATENCY.observe(
                        lat, {"trace_id": tr.trace_id})
                else:
                    own_metrics.PICK_LATENCY.observe(lat)
            else:
                own_metrics.PICK_LATENCY.observe(lat)
            if status[i] == C.Status.SHED:
                own_metrics.PICKS.labels(outcome="shed").inc()
                item.error = ShedError(band=item.band, tenant=item.tenant)
                self.fairness.note_shed(
                    item.tenant, _BAND_NAMES.get(item.band, "standard"))
                if recorder is not None:
                    rec = _rec_base(item)
                    rec["outcome"] = "shed"
                    recorder.append(rec)
            elif status[i] != C.Status.OK:
                own_metrics.PICKS.labels(outcome="unavailable").inc()
                item.error = ExtProcError(
                    grpc.StatusCode.UNAVAILABLE, "no endpoints available"
                )
                if recorder is not None:
                    rec = _rec_base(item)
                    rec["outcome"] = "unavailable"
                    recorder.append(rec)
            else:
                picked_slots = [
                    int(s) for s in indices[i] if s >= 0 and s in by_slot
                ]
                if picked_slots and (board_open or any_draining):
                    # Keep the raw list only if filtering would empty it
                    # (availability beats quarantine AND drain, the same
                    # rule as the dispatch-side filters) — exclusion
                    # parity between wave candidates and this tail is
                    # pinned by tests/test_dataplane.py.
                    healthy = [
                        s for s in picked_slots
                        if not ((board_open and rs.board.quarantined(s))
                                or (any_draining and getattr(
                                    by_slot[s], "draining", False)))]
                    if healthy:
                        picked_slots = healthy
                picked = [by_slot[s].hostport for s in picked_slots]
                if not picked:
                    own_metrics.PICKS.labels(outcome="unavailable").inc()
                    item.error = ExtProcError(
                        grpc.StatusCode.UNAVAILABLE, "no endpoints available"
                    )
                    if recorder is not None:
                        rec = _rec_base(item)
                        rec["outcome"] = "unavailable"
                        recorder.append(rec)
                else:
                    res = PickResult(endpoint=picked[0], fallbacks=picked[1:])
                    res.assumed_cost = request_cost_host(
                        float(plen[i]), float(dlen[i]))
                    peer = getattr(by_slot[picked_slots[0]], "cluster", "")
                    if peer and self.federation is not None:
                        # Cross-cluster pick: tally the spill (gie_
                        # federation_spill_total) and stamp the trace —
                        # the federation hop every joined OTLP trace
                        # shows (docs/FEDERATION.md).
                        self.federation.note_remote_pick(
                            peer, _BAND_NAMES.get(item.band, "standard"))
                        tr_f = item.req.trace
                        if tr_f is not None:
                            tr_f.event(f"federation:{peer}")
                    # The cycle charges the RAW primary (profile.py:214-218);
                    # if that slot wasn't routable, picked[0] differs and the
                    # observe_served guard will skip the release.
                    res.charged_slot = int(indices[i][0])
                    if prefill_np is not None:
                        p_slot = int(prefill_np[i])
                        p_ep = by_slot.get(p_slot)
                        p_cost, d_cost = pd_costs_host(
                            float(plen[i]), float(dlen[i]))
                        # pd charge bookkeeping is ALWAYS a charged list:
                        # falling back to the legacy single-slot path would
                        # release the full request cost from a slot the
                        # cycle only charged d_cost.
                        res.charged = [(res.charged_slot, d_cost, picked[0])]
                        d = item.req.deadline_at
                        if (p_ep is not None and self.pd_budget_floor_s > 0
                                and d
                                and d - now_mono < self.pd_budget_floor_s):
                            # Budget-aware pd split (docs/RESILIENCE.md):
                            # the remaining deadline budget cannot afford
                            # the cross-worker prefill hop (KV transfer +
                            # an extra network leg) — collapse to the
                            # decode worker only, which prefills locally.
                            # The cycle charged p_cost to the prefill
                            # slot; release it now so the skipped hop
                            # does not phantom-load a worker that will
                            # never see the request. (The decode worker's
                            # local prefill rides uncharged for this one
                            # request — a bounded under-count, versus an
                            # unbounded phantom charge.)
                            self.scheduler.complete(
                                np.asarray([p_slot], np.int32),
                                np.asarray([p_cost], np.float32))
                            own_metrics.PD_BUDGET_SINGLEHOP.inc()
                            p_ep = None
                        if p_ep is not None:
                            res.extra_headers = {
                                **res.extra_headers,
                                mdkeys.PREFILL_ENDPOINT_KEY: p_ep.hostport,
                            }
                            res.charged.append(
                                (p_slot, p_cost, p_ep.hostport))
                        # else: the prefill pod vanished between the cycle
                        # and this wave — its eviction already cleared the
                        # slot's load, so there is nothing to release.
                    if load_snapshot is not None:
                        slot = int(indices[i][0])
                        res.feedback = (
                            host_features(
                                metrics_np[slot],
                                float(load_snapshot[slot]),
                                float(plen[i]),
                                float(dlen[i]),
                                bool(lora[i] >= 0),
                            ),
                            slot,  # feeds the per-endpoint embedding
                            self._clock.now(),
                            picked[0],  # primary hostport the features describe
                        )
                    if recorder is not None:
                        rec = _rec_base(item)
                        rec["outcome"] = "picked"
                        rec["chosen"] = picked[0]
                        rec["chosen_slot"] = picked_slots[0]
                        rec["fallbacks"] = picked[1:]
                        peer_rec = getattr(
                            by_slot[picked_slots[0]], "cluster", "")
                        if peer_rec:
                            rec["peer_cluster"] = peer_rec
                        # Ranked blend scores straight from the cycle's
                        # materialized result — the chosen endpoint's
                        # entry may not be rank 0 when the tail filter
                        # dropped a quarantined/draining primary.
                        rec["ranked"] = [
                            {"slot": int(s), "score": round(float(v), 5)}
                            for s, v in zip(indices[i], rec_scores[i])
                            if s >= 0]
                        # Host-side scorer breakdown for the CHOSEN slot,
                        # mirroring scorers.py's normalization formulas
                        # over the wave's own metrics rows (no new D2H).
                        cfg = self.scheduler.cfg
                        row = rec_metrics[picked_slots[0]]
                        q = float(row[C.Metric.QUEUE_DEPTH])
                        kvu = float(row[C.Metric.KV_CACHE_UTIL])
                        breakdown = {
                            "queue": round(
                                min(max(1.0 - q / cfg.queue_norm, 0.0),
                                    1.0), 5),
                            "kv_cache": round(
                                min(max(1.0 - kvu, 0.0), 1.0), 5),
                        }
                        if load_snapshot is not None:
                            al = float(load_snapshot[picked_slots[0]])
                            breakdown["assumed_load"] = round(
                                min(max(1.0 - al / cfg.load_norm, 0.0),
                                    1.0), 5)
                        if affinity_np is not None:
                            # Device-side columns, not host approximations:
                            # the prefix fraction depends on the live table
                            # and session on the rendezvous hash — neither
                            # is reconstructible from the metrics rows.
                            breakdown["prefix"] = round(
                                float(affinity_np[i][0]), 5)
                            breakdown["session"] = round(
                                float(affinity_np[i][1]), 5)
                        rec["scorers"] = breakdown
                        rec["queue_depth"] = q
                        rec["kv_util"] = kvu
                        if fleet_cells is not None:
                            rec["fleet"] = {
                                "cells": [int(c) for c in fleet_cells[i]],
                                "cell_scores": [
                                    round(float(v), 5)
                                    for v in fleet_scores[i]],
                                "compression": fleet_ratio,
                            }
                        if prefill_np is not None:
                            rec["prefill_slot"] = int(prefill_np[i])
                        res.record = recorder.append(rec)
                    item.result = res
        if fleet_cells is not None:
            note = getattr(self.scheduler, "note_fleet_wave", None)
            if note is not None:
                # One host-side tally per wave for /debugz/fleet's top-K
                # hit histogram; arrays are already materialized above.
                note(fleet_cells, indices[:, 0])
        # Admission runs BEFORE waiters wake: a shed decision must replace
        # the result, never race the caller reading it. The "ok" outcome is
        # counted here — after admission — so a shed pick increments only
        # "shed", never both.
        self._slo_admission(batch)
        for item in batch:
            if item.result is not None:
                own_metrics.PICKS.labels(outcome="ok").inc()
            self._clock.set_event(item.event)

    # -- degraded pick path (resilience ladder rungs 1-3) ------------------

    _RUNG_LABELS = {
        Rung.CACHED: "cached",
        Rung.ROUND_ROBIN: "round_robin",
        Rung.STATIC: "static",
    }

    def _degraded_pick(self, batch: list[_Pending], rung: Rung) -> None:
        """Serve one wave entirely host-side on a degraded ladder rung
        (docs/RESILIENCE.md):

          CACHED       least (queue-depth + scaled KV) over the bounded-
                       staleness metrics rows, with an in-wave spread so
                       a burst does not pile onto one endpoint.
          ROUND_ROBIN  smooth weighted round-robin on the last-known-good
                       rows (weights from queue depth; stale data is only
                       trusted as a static weight, not a live signal).
          STATIC       plain rotation over a fixed subset of live
                       endpoints — the "never 503 the whole pool" floor.

        No device state is touched: nothing is charged (charged_slot = -1
        makes observe_served's slot-match guard skip the release), no
        prefix inserts, no tick. Called from the dispatcher (rung gate,
        dispatch failure) or the completer (materialize failure), never
        both for one wave; the shared WRR/rotation cursors are behind
        _degraded_lock."""
        endpoints = self.datastore.endpoints()
        by_slot = {ep.slot: ep for ep in endpoints}
        # Degraded rungs stay LOCAL: the spill policy's saturation /
        # drain reasoning reads live rows, and a degraded ladder means
        # exactly that data is suspect — cross-cluster hops on stale
        # verdicts would export the outage. Imported endpoints remain
        # only as the availability floor (no local endpoint at all).
        local_only = {s: ep for s, ep in by_slot.items()
                      if not getattr(ep, "cluster", "")}
        if local_only:
            by_slot = local_only
        # Degraded rungs honor graceful drain exactly like the full path:
        # a terminating pod leaves new-pick candidacy even while the
        # ladder is down (a rolling upgrade DURING a degradation must
        # still be zero-error), with the same availability floor.
        ready = {s: ep for s, ep in by_slot.items()
                 if not getattr(ep, "draining", False)}
        drain_set = {s for s in by_slot if s not in ready} if ready else set()
        if ready:
            by_slot = ready
        rs = self.resilience
        breaker_set: set = set()
        if rs is not None and rs.board.has_open and len(by_slot) > 1:
            allowed = {s for s in by_slot if not rs.board.quarantined(s)}
            if allowed:  # quarantine never empties the pool
                breaker_set = set(by_slot) - allowed
                by_slot = {s: ep for s, ep in by_slot.items()
                           if s in allowed}
        live = sorted(by_slot)
        if not live:
            for item in batch:
                item.error = ExtProcError(
                    grpc.StatusCode.UNAVAILABLE, "no endpoints available")
                self._clock.set_event(item.event)
                own_metrics.PICKS.labels(outcome="unavailable").inc()
            return
        label = self._RUNG_LABELS.get(rung, "static")
        # CACHED-rung KV weight from the ladder config (--ladder-cached-
        # kv-weight; default calibrated by the storm sweep recorded in
        # docs/RESILIENCE.md).
        kvw = (rs.ladder.cfg.cached_kv_weight if rs is not None else 8.0)
        # Last-known-good rows: queue depth + KV utilization, read once
        # per wave. On the RR/STATIC rungs these may be arbitrarily stale
        # — they only shape static weights there.
        rows, _ages = self.metrics_store.pool_rows(live)
        queue = rows[:, C.Metric.QUEUE_DEPTH].astype(np.float64)
        kv = rows[:, C.Metric.KV_CACHE_UTIL].astype(np.float64)
        col_of = {s: i for i, s in enumerate(live)}
        with self._degraded_lock:
            # Slot hygiene: WRR credit/debt must not outlive the endpoint
            # that earned it — a reclaimed slot's NEW pod starts at zero
            # instead of inheriting the old pod's debt, and the dict
            # stays bounded by the pool (prune against the unfiltered
            # endpoint set so a merely-quarantined slot keeps its credit).
            if self._wrr_credit:
                pool_slots = {ep.slot for ep in endpoints}
                for s in [s for s in self._wrr_credit
                          if s not in pool_slots]:
                    del self._wrr_credit[s]
            if rung == Rung.STATIC:
                subset = live[: max(
                    rs.static_subset if rs is not None else 4, 1)]
            for item in batch:
                cands = [int(s) for s in item.cand_slots if s in by_slot]
                if not cands:
                    cands = live
                if rung == Rung.CACHED:
                    # Fresh-enough data: least queue+KV now, plus an
                    # in-wave +1 spread per assignment.
                    scores = [queue[col_of[s]] + kvw * kv[col_of[s]]
                              for s in cands]
                    order = sorted(range(len(cands)),
                                   key=lambda j: (scores[j], cands[j]))
                    picked = [cands[j] for j in order]
                    queue[col_of[picked[0]]] += 1.0
                elif rung == Rung.ROUND_ROBIN:
                    # Smooth WRR: weight ~ (1+queue)^-alpha from the last
                    # good rows; every candidate gains its weight, the
                    # winner pays the pot back — long-run shares track
                    # weights with no starvation. The queue-shape
                    # exponent (--ladder-wrr-alpha) is storm-swept
                    # (docs/RESILIENCE.md "ladder calibration"): alpha 0
                    # is uniform RR (stale-data-blind), 1 the calibrated
                    # default.
                    alpha = (rs.ladder.cfg.wrr_queue_alpha
                             if rs is not None else 1.0)
                    weights = {
                        s: (1.0 + max(queue[col_of[s]], 0.0)) ** -alpha
                        for s in cands}
                    for s, w in weights.items():
                        self._wrr_credit[s] = (
                            self._wrr_credit.get(s, 0.0) + w)
                    picked = sorted(
                        cands,
                        key=lambda s: (-self._wrr_credit[s], s))
                    self._wrr_credit[picked[0]] -= sum(weights.values())
                else:  # STATIC
                    pool = [s for s in cands if s in subset] or cands
                    self._static_rr += 1
                    first = pool[self._static_rr % len(pool)]
                    picked = [first] + [s for s in pool if s != first]
                res = PickResult(
                    endpoint=by_slot[picked[0]].hostport,
                    fallbacks=[by_slot[s].hostport for s in picked[1:4]],
                )
                res.assumed_cost = 0.0
                res.charged_slot = -1  # nothing charged: skip the release
                recorder = obs.RECORDER
                if recorder is not None:
                    # Degraded picks record too (same schema as the full
                    # path): rung + exclusions explain exactly why this
                    # request skipped the device cycle, raw row signals
                    # stand in for the scorer breakdown the rung used.
                    tr = item.req.trace
                    j = col_of[picked[0]]
                    d = item.req.deadline_at
                    res.record = recorder.append({
                        "ts": time.time(),
                        "trace_id": tr.trace_id if tr is not None else "",
                        "model": item.req.model,
                        "band": _BAND_NAMES.get(item.band, str(item.band)),
                        "rung": label,
                        "candidates": [int(s) for s in item.cand_slots],
                        "excluded_breaker": sorted(
                            int(s) for s in item.cand_slots
                            if s in breaker_set),
                        "excluded_drain": sorted(
                            int(s) for s in item.cand_slots
                            if s in drain_set),
                        "draining": sorted(int(s) for s in drain_set),
                        "deadline_remaining_ms": (
                            round((d - self._clock.now()) * 1e3, 1)
                            if d else None),
                        "outcome": "picked",
                        "chosen": res.endpoint,
                        "chosen_slot": int(picked[0]),
                        "fallbacks": list(res.fallbacks),
                        "scorers": {"degraded_" + label: round(
                            float(queue[j] + kvw * kv[j]), 5)},
                        "queue_depth": float(queue[j]),
                        "kv_util": float(kv[j]),
                    })
                item.result = res
                own_metrics.DEGRADED_PICKS.labels(rung=label).inc()
                own_metrics.PICKS.labels(outcome="ok").inc()
                # Same trace lifecycle as the full path: the "picked"
                # stage and the bucket->trace exemplar must not vanish
                # exactly while the pool is degraded — that is when the
                # traces are read.
                lat = self._clock.now() - item.enqueued_at
                tr = item.req.trace
                if tr is not None:
                    tr.event("picked")
                    if tr.sampled:
                        own_metrics.PICK_LATENCY.observe(
                            lat, {"trace_id": tr.trace_id})
                    else:
                        own_metrics.PICK_LATENCY.observe(lat)
                else:
                    own_metrics.PICK_LATENCY.observe(lat)
                self._clock.set_event(item.event)

    def _slo_admission(self, batch: list[_Pending]) -> None:
        """Predictive SLO shedding (006 README:27-36 SLO dimension): after
        the cycle picked, non-critical requests carrying an
        x-gateway-inference-ttft-slo-ms header whose PREDICTED TTFT on the
        picked endpoint already misses the bound are shed with 429 — they
        would only burn prefill capacity to produce a late answer. The
        charge the cycle added for them is released immediately."""
        if self.trainer is None:
            return
        if getattr(self.trainer, "last_loss", None) is None:
            # Cold start: the predictor is still at random init (no train
            # step has run). Shedding on noise would 429 valid traffic —
            # and shed requests never serve, so an all-SLO workload would
            # starve the trainer and never leave this state. Admit until
            # the model has actually fit something.
            return
        rows, slots, slos, items = [], [], [], []
        for i, item in enumerate(batch):
            if item.result is None or item.result.feedback is None:
                continue
            raw = item.req.headers.get(mdkeys.TTFT_SLO_MS_KEY, [""])[0]
            try:
                slo_s = float(raw) / 1000.0
            except (TypeError, ValueError):
                continue
            if slo_s <= 0:
                continue
            if item.band == C.Criticality.CRITICAL:
                continue
            features, slot, _, _ = item.result.feedback
            rows.append(features)
            slots.append(slot)
            slos.append(slo_s)
            items.append(item)
        if not items:
            return
        pred = self.trainer.predict_ttft(np.stack(rows), np.asarray(slots))
        for j, item in enumerate(items):
            if pred[j] > slos[j]:
                res = item.result
                item.result = None
                item.error = ShedError(band=item.band, tenant=item.tenant)
                self.fairness.note_shed(
                    item.tenant, _BAND_NAMES.get(item.band, "standard"))
                if res.record is not None:
                    # The decision record outlives the reversal: the
                    # request was picked, then SLO-shed post-pick.
                    res.record["outcome"] = "shed_slo"
                # The cycle charged the pick; the request will not run.
                if res.charged:
                    self.scheduler.complete(
                        np.asarray([s for s, _, _ in res.charged], np.int32),
                        np.asarray([c for _, c, _ in res.charged], np.float32),
                    )
                elif res.charged_slot is not None and res.charged_slot >= 0:
                    self.scheduler.complete(
                        np.asarray([res.charged_slot], np.int32),
                        np.asarray([res.assumed_cost], np.float32),
                    )
                own_metrics.PICKS.labels(outcome="shed").inc()
