"""Vectorized filter stage.

The reference runs Filter plugins per request to prune candidate endpoints
(reference docs/proposals/0845-scheduler-architecture-proposal/README.md:62-66;
candidate subsetting pkg/lwepp/handlers/request.go:99-137). Here every filter
is a boolean mask over the full [N, M_MAX] request x endpoint grid, AND-ed
together — no control flow, one fused XLA kernel.
"""

from __future__ import annotations

import jax.numpy as jnp

from gie_tpu.sched import constants as C
from gie_tpu.sched.types import EndpointBatch, RequestBatch


def drain_filter(candidates: list) -> list:
    """Graceful-drain candidate prefilter (docs/RESILIENCE.md).

    Host-side sibling of the mask filters below: DRAINING endpoints
    (terminating pods completing their in-flight streams) are dropped
    from a pick's candidate set BEFORE wave assembly, so the device
    cycle never scores them — the [N, M] grid sees them only through
    the subset mask, exactly like a breaker-quarantined slot. Kept
    host-side rather than as an EndpointBatch column because drain is a
    membership property, not a metric: it changes at pod-churn cadence
    and must never cost the jitted cycle a recompile or an extra input.

    Availability beats drain: when every candidate is draining the set
    is returned unchanged — a pool mid-rolling-upgrade must keep
    answering (same floor rule as the breaker filter).
    """
    kept = [ep for ep in candidates if not getattr(ep, "draining", False)]
    if not kept or len(kept) == len(candidates):
        return candidates  # identity-preserving: callers compare `is`
    return kept


def base_mask(reqs: RequestBatch, eps: EndpointBatch) -> jnp.ndarray:
    """Validity + subset-hint mask.

    Strict subsetting semantics (reference
    docs/proposals/004-endpoint-picker-protocol/README.md:28-44,
    pkg/lwepp/handlers/request.go:130-133): the subset mask is honored even
    when it leaves zero candidates; the empty case surfaces as a 503 in the
    picker, never as a fallback to the full pool.
    """
    return reqs.valid[:, None] & eps.valid[None, :] & reqs.subset_mask


def saturation_mask(
    reqs: RequestBatch,
    eps: EndpointBatch,
    *,
    queue_limit: float,
    kv_limit: float,
) -> jnp.ndarray:
    """Drop saturated endpoints for non-critical traffic.

    Mirrors the saturation/has-capacity predicate of the scheduler proposal
    (reference docs/proposals/006-scheduler/README.md:150-156): endpoints with
    queue depth or KV-cache utilization beyond the limits are ineligible for
    STANDARD/SHEDDABLE requests; CRITICAL requests bypass the filter so they
    degrade to best-effort instead of shedding.
    """
    queue = eps.metrics[:, C.Metric.QUEUE_DEPTH]
    kv = eps.metrics[:, C.Metric.KV_CACHE_UTIL]
    has_capacity = (queue < queue_limit) & (kv < kv_limit)
    critical = reqs.criticality[:, None] == C.Criticality.CRITICAL
    return critical | has_capacity[None, :]


def lora_membership(
    reqs: RequestBatch, eps: EndpointBatch
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(request, endpoint) adapter residency: (active[N,M], waiting[N,M]).

    Shared by the LoRA capacity filter and the LoRA affinity scorer so the
    [N, M, LORA_SLOTS] comparison is computed once per cycle.
    """
    req_lora = reqs.lora_id[:, None, None]                     # [N, 1, 1]
    active = jnp.any(req_lora == eps.lora_active[None, :, :], axis=-1)
    waiting = jnp.any(req_lora == eps.lora_waiting[None, :, :], axis=-1)
    return active, waiting


def lora_capacity_mask(
    reqs: RequestBatch,
    eps: EndpointBatch,
    membership: tuple[jnp.ndarray, jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """LoRA-affinity eligibility.

    Re-design of the reference LoRA-affinity filter (BASELINE north star;
    adapter residency from vllm:lora_requests_info, reference
    docs/proposals/003-model-server-protocol/README.md:43-57). An endpoint is
    eligible for an adapter request if the adapter is already active/waiting
    there, or the endpoint still has free adapter slots (max_lora not yet
    reached). Base-model requests (-1) match everything.
    """
    active, waiting = membership if membership is not None else lora_membership(reqs, eps)
    resident = active | waiting                                # [N, M]

    n_active = jnp.sum(eps.lora_active >= 0, axis=-1)          # [M]
    n_waiting = jnp.sum(eps.lora_waiting >= 0, axis=-1)
    max_lora = eps.metrics[:, C.Metric.MAX_LORA]
    # max_lora == 0 means the server did not report LoRA metrics: no limit.
    has_slot = (max_lora <= 0) | ((n_active + n_waiting) < max_lora)

    is_base = reqs.lora_id[:, None] < 0
    return is_base | resident | has_slot[None, :]
