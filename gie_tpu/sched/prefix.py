"""Device-resident prefix-cache index: batched match + insert.

TPU re-design of the prefix-cache-aware scorer of reference
docs/proposals/0602-prefix-cache/README.md:95-129. The reference keeps an
LRU-indexed hash -> servers table per EPP replica and walks it per request;
here the table is dense device arrays (PrefixTable) and matching for the
whole batch is one gather + cumprod:

  slot(h)    = h & (S - 1)                       direct-mapped
  hit(n,c)   = keys[slot(h_nc)] == h_nc          chunk known at all
  on(n,c,m)  = present[slot(h_nc), m]            chunk plausibly cached on m
  match(n,m) = sum_c prod_{c'<=c} on(n,c',m)     longest-prefix property
  score      = match / n_chunks                  normalized [0, 1]

Staleness: every touched slot is stamped with the cycle tick; match ignores
slots older than `max_age` ticks (the LRU-decay analogue of the reference's
index eviction, 0602 README:113-122). Endpoint churn is handled by
`clear_endpoint`, which zeroes one endpoint's presence column when the
datastore evicts a pod, so a reused slot never inherits a dead pod's cache.

Inserts happen at pick time (assumed cache: the picked endpoint will hold
these chunks after serving — the same optimistic update the reference does
per pick), via dense scatters. Slot collisions overwrite the older key
(LRU-ish by construction); within one batch, colliding lanes resolve by
scatter order. The index is explicitly approximate — exactly as in the
reference design (0602 README:101 "approximate index").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gie_tpu.sched import constants as C
from gie_tpu.sched.types import PrefixTable, RequestBatch


def _slots(hashes: jax.Array, table_slots: int) -> jax.Array:
    return (hashes & jnp.uint32(table_slots - 1)).astype(jnp.int32)


def match_scores(
    table: PrefixTable,
    reqs: RequestBatch,
    tick: jax.Array,
    *,
    max_age: int,
) -> jax.Array:
    """Longest-prefix match fraction per (request, endpoint) -> f32[N, M_MAX]."""
    slots = _slots(reqs.chunk_hashes, table.keys.shape[0])     # i32[N, C]
    keys = table.keys[slots]                                   # u32[N, C]
    chunk_valid = (
        jnp.arange(C.MAX_CHUNKS, dtype=jnp.int32)[None, :] < reqs.n_chunks[:, None]
    )
    fresh = (tick - table.ages[slots]) <= jnp.uint32(max_age)  # [N, C]
    hit = (keys == reqs.chunk_hashes) & (reqs.chunk_hashes != 0) & chunk_valid & fresh

    on = table.present[slots] & hit[..., None]                 # bool[N, C, M]

    # Longest-prefix property: a chunk only counts if every earlier chunk
    # also matched on that endpoint (reference 0602 README:107-112).
    prefix_run = jnp.cumprod(on.astype(jnp.int32), axis=1)     # [N, C, M]
    matched = jnp.sum(prefix_run, axis=1).astype(jnp.float32)  # [N, M]
    denom = jnp.maximum(reqs.n_chunks.astype(jnp.float32), 1.0)
    return matched / denom[:, None]


def insert(
    table: PrefixTable,
    reqs: RequestBatch,
    picked: jax.Array,  # i32[N] primary endpoint slot per request (-1 = none)
    tick: jax.Array,    # u32 scalar
) -> PrefixTable:
    """Optimistically record the batch's chunks as cached on their picked
    endpoints (assumed-cache update, reference 0602 README:113-122).

    Per (request, chunk) lane: if the slot already holds this hash, OR the
    picked endpoint into its presence row; otherwise evict (clear the row,
    write the new key) and set the bit. Evictions are applied first, then
    presence bits scatter-OR (max) in. Invalid lanes scatter to index S,
    which is out of bounds and therefore dropped (JAX scatter drop
    semantics), so they never alias a real row.
    """
    n, cmax = reqs.chunk_hashes.shape
    nslots = table.keys.shape[0]
    flat_hash = reqs.chunk_hashes.reshape(-1)                       # [N*C]
    flat_slot = _slots(flat_hash, nslots)
    chunk_valid = (
        jnp.arange(cmax, dtype=jnp.int32)[None, :] < reqs.n_chunks[:, None]
    )
    valid = (
        chunk_valid & (reqs.chunk_hashes != 0) & (picked[:, None] >= 0)
    ).reshape(-1)

    ep = jnp.clip(picked, 0, C.M_MAX - 1)                           # [N]
    ep = jnp.broadcast_to(ep[:, None], (n, cmax)).reshape(-1)       # [N*C]

    # Out-of-bounds sentinel: dropped by scatter, aliases nothing.
    drop = nslots
    safe_slot = jnp.where(valid, flat_slot, drop)
    evict = valid & (table.keys[flat_slot] != flat_hash)
    evict_slot = jnp.where(evict, flat_slot, drop)

    # 1) Evictions: clear presence row, stamp new key.
    present = table.present.at[evict_slot].set(False, mode="drop")
    keys = table.keys.at[safe_slot].set(flat_hash, mode="drop")

    # 2) OR the picked-endpoint bit in (max == OR for bool).
    onehot = (
        jnp.arange(C.M_MAX, dtype=jnp.int32)[None, :] == ep[:, None]
    ) & valid[:, None]
    present = present.at[safe_slot].max(onehot, mode="drop")

    ages = table.ages.at[safe_slot].set(
        jnp.broadcast_to(tick, valid.shape), mode="drop"
    )
    return PrefixTable(keys=keys, present=present, ages=ages)


def ingest_keys(
    table: PrefixTable,
    hashes: jax.Array,   # u32[B], 0 = padding (ignored)
    ep_slot: jax.Array,  # i32 scalar endpoint slot
    tick: jax.Array,     # u32 scalar
    *,
    remove: bool,
) -> PrefixTable:
    """Event-driven index update (reference roadmap item 1, README.md:108:
    'prefix-cache aware load balancing with interfaces for REMOTE caches'):
    a model server (or cache sidecar) reports chunk-chain hashes it stored
    or evicted, and the device table reflects ground truth instead of the
    pick-time optimistic guess.

    Stored: same evict-then-OR scatter as `insert`, for one endpoint.
    Removed: clear ONLY this endpoint's presence bit on matching rows —
    other endpoints may still hold the chunk, and a non-matching row means
    the table already recycled the slot (nothing to do)."""
    nslots = table.keys.shape[0]
    valid = hashes != 0
    slot = _slots(hashes, nslots)
    drop = nslots
    if remove:
        match = valid & (table.keys[slot] == hashes)
        row = jnp.where(match, slot, drop)
        # Advanced indexing with a matching-shape column vector scatters
        # per-lane (row[b], ep_slot).
        col = jnp.broadcast_to(ep_slot, row.shape)
        present = table.present.at[row, col].set(False, mode="drop")
        return table.replace(present=present)
    safe = jnp.where(valid, slot, drop)
    evict = valid & (table.keys[slot] != hashes)
    evict_slot = jnp.where(evict, slot, drop)
    present = table.present.at[evict_slot].set(False, mode="drop")
    keys = table.keys.at[safe].set(hashes, mode="drop")
    col = jnp.broadcast_to(ep_slot, safe.shape)
    present = present.at[safe, col].max(valid, mode="drop")
    ages = table.ages.at[safe].set(
        jnp.broadcast_to(tick, safe.shape), mode="drop")
    return PrefixTable(keys=keys, present=present, ages=ages)


def clear_endpoint(table: PrefixTable, slot: jax.Array) -> PrefixTable:
    """Invalidate one endpoint's presence column (pod evicted/replaced —
    reference analogue: per-pod index removal on datastore PodDelete,
    pkg/lwepp/datastore/datastore.go:257-265)."""
    return table.replace(present=table.present.at[:, slot].set(False))
