"""Device-resident prefix-cache index: batched match + insert.

TPU re-design of the prefix-cache-aware scorer of reference
docs/proposals/0602-prefix-cache/README.md:95-129. The reference keeps an
LRU-indexed hash -> servers table per EPP replica and walks it per request;
here the table is dense device arrays (PrefixTable) with the endpoint set
BITPACKED into u32 words, and matching for the whole batch is one packed
gather + cumulative-AND + popcount:

  slot(h)     = h & (S - 1)                        direct-mapped
  hit(n,c)    = keys[slot(h_nc)] == h_nc           chunk known at all
  words(n,c,w)= present[slot(h_nc), w] * hit       packed endpoint bits
  run(n,c,w)  = AND_{c'<=c} words(n,c',w)          longest-prefix property
                (cumulative bitwise AND — all M_MAX endpoints advance per row op)
  match(n,m)  = sum_c bit_m(run(n,c))              popcount-style unpack
  score       = match / n_chunks                   normalized [0, 1]

The packed layout is the load-bearing TPU choice: the table is 4 MiB
(u32[S, M_WORDS] at 32768 x 1024) instead of 32 MiB (bool[S, M_MAX]), so
the per-cycle gather of [N, C] rows moves 8x fewer bytes and the
cumulative AND runs on 32 words instead of 1024 lanes.

Staleness: every touched slot is stamped with the cycle tick; match ignores
slots older than `max_age` ticks (the LRU-decay analogue of the reference's
index eviction, 0602 README:113-122). Endpoint churn is handled by
`clear_endpoint`, which zeroes one endpoint's presence BIT across the table
when the datastore evicts a pod, so a reused slot never inherits a dead
pod's cache.

Inserts happen at pick time (assumed cache: the picked endpoint will hold
these chunks after serving — the same optimistic update the reference does
per pick) via gather-OR-scatter on single (row, word) cells. Slot
collisions overwrite the older key (LRU-ish by construction). Within one
batch, lanes colliding on the same (row, word) cell resolve last-wins — a
concurrently-inserted OTHER endpoint's bit from the same wave can be lost
for that chunk (re-asserted the next time that endpoint is picked for it);
bits from earlier cycles are preserved by the OR. The index is explicitly
approximate — exactly as in the reference design (0602 README:101
"approximate index").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from gie_tpu.sched import constants as C
from gie_tpu.sched.types import PrefixTable, RequestBatch


def _slots(hashes: jax.Array, table_slots: int) -> jax.Array:
    return (hashes & jnp.uint32(table_slots - 1)).astype(jnp.int32)


def match_scores(
    table: PrefixTable,
    reqs: RequestBatch,
    tick: jax.Array,
    *,
    max_age: int,
) -> jax.Array:
    """Longest-prefix match fraction per (request, endpoint) -> f32[N, m]
    (m = the table's packed endpoint width, an M bucket)."""
    slots = _slots(reqs.chunk_hashes, table.keys.shape[0])     # i32[N, C]
    keys = table.keys[slots]                                   # u32[N, C]
    cmax = reqs.chunk_hashes.shape[1]  # a C bucket, <= MAX_CHUNKS
    chunk_valid = (
        jnp.arange(cmax, dtype=jnp.int32)[None, :] < reqs.n_chunks[:, None]
    )
    fresh = (tick - table.ages[slots]) <= jnp.uint32(max_age)  # [N, C]
    hit = (keys == reqs.chunk_hashes) & (reqs.chunk_hashes != 0) & chunk_valid & fresh

    words = table.present[slots]                               # u32[N, C, W]
    words = words * hit[..., None].astype(jnp.uint32)

    # Longest-prefix property (a chunk only counts if every earlier chunk
    # also matched, reference 0602 README:107-112) + per-endpoint depth
    # count, in ONE sequential sweep over the chunk axis:
    #
    #   acc    [N, W] u32  running cumulative-AND of the packed words
    #   planes [N, W] u32  x PLANES bit-sliced vertical counters — plane k
    #                      holds bit k of every endpoint's running depth
    #                      (max C=32 fits in 6 bits); adding acc is a
    #                      ripple-carry of XOR/AND on whole words.
    #
    # Everything is elementwise on ~32 KiB operands, so XLA fuses the
    # entire sweep into one pass that reads `words` (1 MiB) once. The
    # alternatives both blow HBM: lax.associative_scan materializes
    # log2(C) full [N, C, W] passes (~10+ MiB), and a naive
    # unpack-then-reduce materializes the [N, C, W, 32] bit tensor
    # (32 MiB at the 1024x32x256 north-star shape — ~60% of the whole
    # cycle's traffic).
    n_planes = max(cmax.bit_length(), 1)  # depth <= cmax fits these bits
    acc = jnp.full_like(words[:, 0, :], jnp.uint32(0xFFFFFFFF))
    planes = [jnp.zeros_like(acc) for _ in range(n_planes)]
    for c in range(words.shape[1]):
        acc = acc & words[:, c, :]
        carry = acc
        for k in range(n_planes):
            planes[k], carry = planes[k] ^ carry, planes[k] & carry
    # Unpack the PLANES small planes (never the [N, C, W] words).
    shifts = jnp.arange(32, dtype=jnp.uint32)
    matched = sum(
        ((p[..., None] >> shifts) & jnp.uint32(1)).astype(jnp.float32)
        * np.float32(1 << k)
        for k, p in enumerate(planes)
    ).reshape(words.shape[0], -1)                              # [N, M]
    denom = jnp.maximum(reqs.n_chunks.astype(jnp.float32), 1.0)
    return matched / denom[:, None]


def _cell(ep: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Endpoint slot -> (word column, bit value) in the packed row."""
    word = (ep // 32).astype(jnp.int32)
    bit = jnp.uint32(1) << (ep % 32).astype(jnp.uint32)
    return word, bit


def insert(
    table: PrefixTable,
    reqs: RequestBatch,
    picked: jax.Array,  # i32[N] primary endpoint slot per request (-1 = none)
    tick: jax.Array,    # u32 scalar
) -> PrefixTable:
    """Optimistically record the batch's chunks as cached on their picked
    endpoints (assumed-cache update, reference 0602 README:113-122).

    Per (request, chunk) lane: if the slot already holds this hash, OR the
    picked endpoint's bit into its presence row; otherwise evict (clear the
    row, write the new key) and set the bit. Evictions are applied first
    (full W-word row clear), then each lane ORs its single (row, word)
    cell via gather-modify-scatter. Invalid lanes scatter to index S, which
    is out of bounds and therefore dropped (JAX scatter drop semantics), so
    they never alias a real row.
    """
    n, cmax = reqs.chunk_hashes.shape
    nslots = table.keys.shape[0]
    flat_hash = reqs.chunk_hashes.reshape(-1)                       # [N*C]
    flat_slot = _slots(flat_hash, nslots)
    chunk_valid = (
        jnp.arange(cmax, dtype=jnp.int32)[None, :] < reqs.n_chunks[:, None]
    )
    valid = (
        chunk_valid & (reqs.chunk_hashes != 0) & (picked[:, None] >= 0)
    ).reshape(-1)

    m = table.present.shape[1] * 32
    ep = jnp.clip(picked, 0, m - 1)                                 # [N]
    ep = jnp.broadcast_to(ep[:, None], (n, cmax)).reshape(-1)       # [N*C]

    # Out-of-bounds sentinel: dropped by scatter, aliases nothing.
    drop = nslots
    safe_slot = jnp.where(valid, flat_slot, drop)
    evict = valid & (table.keys[flat_slot] != flat_hash)
    evict_slot = jnp.where(evict, flat_slot, drop)

    # 1) Evictions: clear the packed presence row, stamp the new key.
    present = table.present.at[evict_slot].set(
        jnp.uint32(0), mode="drop")
    keys = table.keys.at[safe_slot].set(flat_hash, mode="drop")

    # 2) OR the picked endpoint's bit into its (row, word) cell.
    word, bit = _cell(ep)
    old = present[jnp.where(valid, flat_slot, 0), word]             # [N*C]
    present = present.at[safe_slot, word].set(old | bit, mode="drop")

    ages = table.ages.at[safe_slot].set(
        jnp.broadcast_to(tick, valid.shape), mode="drop"
    )
    return PrefixTable(keys=keys, present=present, ages=ages)


def ingest_keys(
    table: PrefixTable,
    hashes: jax.Array,   # u32[B], 0 = padding (ignored)
    ep_slot: jax.Array,  # i32 scalar endpoint slot
    tick: jax.Array,     # u32 scalar
    *,
    remove: bool,
) -> PrefixTable:
    """Event-driven index update (reference roadmap item 1, README.md:108:
    'prefix-cache aware load balancing with interfaces for REMOTE caches'):
    a model server (or cache sidecar) reports chunk-chain hashes it stored
    or evicted, and the device table reflects ground truth instead of the
    pick-time optimistic guess.

    Stored: same evict-then-OR as `insert`, for one endpoint.
    Removed: clear ONLY this endpoint's presence bit on matching rows —
    other endpoints may still hold the chunk, and a non-matching row means
    the table already recycled the slot (nothing to do)."""
    nslots = table.keys.shape[0]
    valid = hashes != 0
    slot = _slots(hashes, nslots)
    drop = nslots
    word, bit = _cell(jnp.broadcast_to(ep_slot, slot.shape))
    if remove:
        match = valid & (table.keys[slot] == hashes)
        row = jnp.where(match, slot, drop)
        old = table.present[jnp.where(match, slot, 0), word]
        present = table.present.at[row, word].set(
            old & ~bit, mode="drop")
        return table.replace(present=present)
    safe = jnp.where(valid, slot, drop)
    evict = valid & (table.keys[slot] != hashes)
    evict_slot = jnp.where(evict, slot, drop)
    present = table.present.at[evict_slot].set(jnp.uint32(0), mode="drop")
    keys = table.keys.at[safe].set(hashes, mode="drop")
    old = present[jnp.where(valid, slot, 0), word]
    present = present.at[safe, word].set(old | bit, mode="drop")
    ages = table.ages.at[safe].set(
        jnp.broadcast_to(tick, safe.shape), mode="drop")
    return PrefixTable(keys=keys, present=present, ages=ages)


def unpack_presence(present) -> "np.ndarray":
    """u32[S, W] packed presence -> bool[S, W*32] (host-side test/debug
    helper; the device path never materializes this)."""
    import numpy as np

    p = np.asarray(present)
    bits = (p[..., None] >> np.arange(32, dtype=np.uint32)) & 1
    return bits.reshape(p.shape[0], -1).astype(bool)


def snapshot_table(table: PrefixTable) -> dict:
    """Host-side arrays of the packed table (replication digest export:
    key/presence/age columns exactly as laid out on device, so a follower
    install is a bit-exact transplant, not a rebuild)."""
    return {
        "keys": np.asarray(table.keys),
        "present": np.asarray(table.present),
        "ages": np.asarray(table.ages),
    }


def table_from_arrays(arrays: dict) -> "PrefixTable | None":
    """Validated inverse of snapshot_table -> PrefixTable, or None when the
    arrays are not a coherent packed table (wrong rank, mismatched row
    counts, or a presence width that is not whole 32-endpoint words). The
    cross-field checks mirror profile.Scheduler.restore_state's: corrupt
    input must fail HERE with None, not later inside the jitted cycle."""
    try:
        keys = np.asarray(arrays["keys"], np.uint32)
        present = np.asarray(arrays["present"], np.uint32)
        ages = np.asarray(arrays["ages"], np.uint32)
    except (KeyError, TypeError, ValueError):
        return None
    if keys.ndim != 1 or present.ndim != 2 or ages.shape != keys.shape:
        return None
    if present.shape[0] != keys.shape[0] or present.shape[1] < 1:
        return None
    return PrefixTable(
        keys=jnp.asarray(keys),
        present=jnp.asarray(present),
        ages=jnp.asarray(ages),
    )


def clear_endpoint(table: PrefixTable, slot: jax.Array) -> PrefixTable:
    """Invalidate one endpoint's presence bit across the table (pod
    evicted/replaced — reference analogue: per-pod index removal on
    datastore PodDelete, pkg/lwepp/datastore/datastore.go:257-265)."""
    word, bit = _cell(slot)
    column = table.present[:, word]
    return table.replace(
        present=table.present.at[:, word].set(column & ~bit)
    )
