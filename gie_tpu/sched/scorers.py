"""Vectorized score stage.

Each scorer is the batched re-design of one reference Score plugin
(reference docs/proposals/0845-scheduler-architecture-proposal/README.md:66-72:
scores normalized to [0, 1], blended by profile-level weights). Instead of a
per-request plugin loop, every scorer emits a full f32[N, M_MAX] column and
the blend is one weighted sum — the exact seam the scheduler proposal leaves
for an out-of-process batch scheduler (reference
docs/proposals/006-scheduler/README.md:160-162).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gie_tpu.sched import constants as C
from gie_tpu.sched.types import EndpointBatch, RequestBatch


def queue_score(eps: EndpointBatch, *, queue_norm: float) -> jax.Array:
    """Least-queue-depth scorer (reference default queue scorer; BASELINE
    configs[0] 'least-kv-cache/queue' CPU baseline). 1 at empty queue,
    0 at/after `queue_norm` outstanding requests. -> f32[M_MAX]."""
    q = eps.metrics[:, C.Metric.QUEUE_DEPTH]
    return jnp.clip(1.0 - q / queue_norm, 0.0, 1.0)


def kv_cache_score(eps: EndpointBatch) -> jax.Array:
    """Least-KV-cache-utilization scorer (KVCacheUtilization gauge, reference
    docs/proposals/003-model-server-protocol/README.md:28-34). -> f32[M_MAX]."""
    return jnp.clip(1.0 - eps.metrics[:, C.Metric.KV_CACHE_UTIL], 0.0, 1.0)


def lora_affinity_score(
    reqs: RequestBatch,
    eps: EndpointBatch,
    membership: tuple[jax.Array, jax.Array] | None = None,
) -> jax.Array:
    """LoRA-affinity scorer -> f32[N, M_MAX].

    1.0 where the requested adapter is already running on the endpoint,
    0.75 where it is queued to load (waiting), 0.25 where it would need a
    fresh load, 1.0 everywhere for base-model requests. Mirrors the
    affinity/cost trade-off of the reference LoRA scorer driven by
    vllm:lora_requests_info (reference
    docs/proposals/003-model-server-protocol/README.md:43-57).

    `membership` is the precomputed filters.lora_membership result, reused
    from the filter stage to avoid recomputing the slot comparison.
    """
    from gie_tpu.sched.filters import lora_membership

    active, waiting = membership if membership is not None else lora_membership(reqs, eps)
    is_base = reqs.lora_id[:, None] < 0
    return jnp.where(
        is_base,
        1.0,
        jnp.where(active, 1.0, jnp.where(waiting, 0.75, 0.25)),
    )


def session_affinity_score(
    reqs: RequestBatch,
    eps: EndpointBatch,
    *,
    key_chunks: int = 1,
) -> jax.Array:
    """Consistent-hash session stickiness -> f32[N, M_MAX].

    The prefix column (an approximate device-resident index) loses affinity
    to slot collisions, staleness, and same-batch splits; this column is
    index-FREE stickiness: a rendezvous (highest-random-weight) hash of the
    session key over the valid endpoints. Requests sharing a prompt prefix
    always agree on the same preference chain, before any cache is warm and
    regardless of index state — the deterministic half of the reference's
    load-blended prefix matching (reference
    docs/proposals/0602-prefix-cache/README.md:119-122, "session
    stickiness" via consistent prefix->server mapping).

    Key = the chunk-hash chain at depth `key_chunks` (chained CRC: chunk j
    incorporates chunks 0..j), i.e. the identity of the first
    key_chunks*CHUNK_BYTES bytes of the prompt — the session/system-prompt
    fingerprint. Scores form an explicit failover LADDER: 1.0 for the
    rendezvous winner, 0.55 for the runner-up, and a uniform
    pseudo-random value in [0, 0.25) for the rest. The distinct runner-up
    tier matters under the OT picker: when a session burst exceeds its
    home endpoint's wave capacity, the spill lands on ONE deterministic
    backup (which then warms for that session) instead of scattering
    among near-tied third choices. Round-5 tuning (seeds 0-2, both
    operating points): 0.55 lifts headline goodput +1.2% mean (never
    worse per-seed) while keeping the low-load hit rate at 0.866;
    stronger tiers (0.625-0.70) gain ~+3% headline but cause UNFORCED
    splits at low load (hit drops under 0.85) because the blended
    home-vs-backup gap shrinks below other columns' noise. Invalid
    endpoints score 0.
    """
    depth = jnp.clip(
        jnp.minimum(jnp.int32(key_chunks), reqs.n_chunks) - 1,
        0, reqs.chunk_hashes.shape[1] - 1,
    )                                                       # i32[N]
    key = jnp.take_along_axis(
        reqs.chunk_hashes, depth[:, None], axis=1
    )[:, 0].astype(jnp.uint32)                              # u32[N]

    slots = jnp.arange(eps.valid.shape[0], dtype=jnp.uint32)
    h = key[:, None] ^ (slots[None, :] * jnp.uint32(0x9E3779B1))
    # splitmix32-style avalanche so slot order carries no structure.
    h = (h ^ (h >> 16)) * jnp.uint32(0x7FEB352D)
    h = (h ^ (h >> 15)) * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    h = jnp.where(eps.valid[None, :], h, jnp.uint32(0))
    frac = h.astype(jnp.float32) / jnp.float32(2**32)       # [0, 1)
    winner = h == jnp.max(h, axis=-1, keepdims=True)
    h2 = jnp.where(winner, jnp.uint32(0), h)
    runner = (h2 == jnp.max(h2, axis=-1, keepdims=True)) & (h2 > 0)
    score = jnp.where(winner, 1.0, jnp.where(runner, 0.55, 0.25 * frac))
    no_session = (reqs.n_chunks <= 0) | (key == 0)
    score = jnp.where(no_session[:, None], 1.0, score)
    return jnp.where(eps.valid[None, :], score, 0.0)


def assumed_load_score(assumed_load: jax.Array, *, load_norm: float) -> jax.Array:
    """Penalty column for in-flight assumed load (reference
    docs/proposals/006-scheduler/README.md:156 assumed-load accounting):
    1 at zero assumed load, decaying to 0 at `load_norm` cost units.
    -> f32[M_MAX]."""
    return jnp.clip(1.0 - assumed_load / load_norm, 0.0, 1.0)
