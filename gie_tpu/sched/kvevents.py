"""KV-cache event interface: remote caches feed the prefix index.

Reference roadmap item 1 (reference README.md:108): "Prefix-cache aware
load balancing with interfaces for remote caches". The pick-time index
(prefix.insert) is an optimistic guess — it never observes server-side
evictions, and decays only by age. Model servers that publish KV-cache
events (vLLM's KVEvents — BlockStored/BlockRemoved/AllBlocksCleared — or
a cache sidecar) can drive the same device table with ground truth
instead: stored chunks OR their endpoint bit in, removed chunks clear it,
a cleared cache drops the endpoint's whole presence column.

Event hashes are the EPP's own chunk-chain hashes (gie_tpu.sched.hashing:
CRC32-chained 64-byte chunks) — the published contract for servers or
sidecars joining a pool with events enabled. Transport is pluggable: the
aggregator is a plain thread-safe sink; `KVEventHTTPServer` accepts
JSON-lines POSTs (one event per line) for deployments where pods push,
and the simulator publishes in-process.

Wire format (one JSON object per line, POST /events):

    {"type": "BlockStored",  "endpoint": "10.0.0.1:8000", "hashes": [..]}
    {"type": "BlockRemoved", "endpoint": "10.0.0.1:8000", "hashes": [..]}
    {"type": "AllBlocksCleared", "endpoint": "10.0.0.1:8000"}
"""

from __future__ import annotations

import hmac
import json
import threading
from typing import Callable, Optional

import numpy as np

from gie_tpu.sched import constants as C

BLOCK_STORED = "BlockStored"
BLOCK_REMOVED = "BlockRemoved"
ALL_CLEARED = "AllBlocksCleared"


class KVEventAggregator:
    """Thread-safe sink batching events per endpoint slot, flushed into
    the scheduler's device index.

    `resolve_slot` maps an endpoint "ip:port" to its scheduler slot (the
    datastore's hostport index); unknown endpoints are dropped — events
    from pods not (yet) in the pool carry no routable meaning.
    """

    def __init__(
        self,
        scheduler,
        resolve_slot: Callable[[str], Optional[int]],
        flush_every: int = 256,
    ):
        self._scheduler = scheduler
        self._resolve = resolve_slot
        self._flush_every = flush_every
        self._lock = threading.Lock()
        # slot -> (stored list, removed list)
        self._pending: dict[int, tuple[list, list]] = {}
        self._pending_n = 0
        self.dropped = 0       # events for unknown endpoints
        self.ingested = 0

    def publish(self, event: dict) -> None:
        """Accept one event dict (see module docstring for the shape)."""
        etype = event.get("type")
        slot = self._resolve(str(event.get("endpoint", "")))
        if slot is None or not (0 <= slot < C.M_MAX):
            self.dropped += 1
            return
        if etype == ALL_CLEARED:
            self.flush()
            # Cache reset on a LIVE pod (vLLM emits AllBlocksCleared on
            # cache reset, not pod death): forget its chunks, keep its
            # assumed load — the pod still carries its in-flight queue.
            # Full eviction (prefix + load) belongs to PodDelete.
            self._scheduler.clear_prefix_endpoint(slot)
            self.ingested += 1
            return
        hashes = [int(h) & 0xFFFFFFFF for h in event.get("hashes", [])]
        hashes = [h for h in hashes if h != 0]
        if etype not in (BLOCK_STORED, BLOCK_REMOVED) or not hashes:
            return
        with self._lock:
            stored, removed = self._pending.setdefault(slot, ([], []))
            (stored if etype == BLOCK_STORED else removed).extend(hashes)
            self._pending_n += len(hashes)
            do_flush = self._pending_n >= self._flush_every
        self.ingested += 1
        if do_flush:
            self.flush()

    def publish_lines(self, payload: bytes) -> int:
        """JSON-lines ingestion (the HTTP transport); returns events read."""
        n = 0
        for line in payload.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
                if not isinstance(event, dict):
                    continue  # a bare scalar/list parses but is no event
                self.publish(event)
                n += 1
            except (ValueError, TypeError):
                continue
        return n

    def flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, {}
            self._pending_n = 0
        for slot, (stored, removed) in pending.items():
            self._scheduler.apply_prefix_events(
                slot,
                np.asarray(stored, np.uint32),
                np.asarray(removed, np.uint32),
            )


class KVEventHTTPServer:
    """Minimal push transport: POST /events with JSON lines.

    This is a CONTROL-PLANE input — forged events steer routing — so it
    ships with the same posture as the ext-proc surface: loopback bind by
    default (set `bind` to the pod-network interface explicitly), an
    optional shared bearer token (401 on mismatch when configured), and a
    bounded request body (413 above `max_body` — the Content-Length is
    never trusted to size a read)."""

    MAX_BODY_DEFAULT = 4 * 1024 * 1024  # 4 MiB of JSON lines per POST

    def __init__(
        self,
        aggregator: KVEventAggregator,
        port: int = 0,
        *,
        bind: str = "127.0.0.1",
        token: Optional[str] = None,
        max_body: int = MAX_BODY_DEFAULT,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        agg = aggregator

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (stdlib naming)
                if self.path != "/events":
                    self.send_error(404)
                    return
                if token is not None:
                    got = self.headers.get("Authorization", "")
                    if not hmac.compare_digest(got, f"Bearer {token}"):
                        self.send_error(401)
                        return
                try:
                    length = int(self.headers.get("Content-Length", ""))
                except ValueError:
                    self.send_error(411)  # length required
                    return
                if length < 0 or length > max_body:
                    self.send_error(413)
                    return
                body = self.rfile.read(length)
                n = agg.publish_lines(body)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(json.dumps({"accepted": n}).encode())

            def log_message(self, *a):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((bind, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
