"""Scheduler pytree types.

The reference scheduler passes per-request CycleState and per-endpoint structs
through a plugin chain (reference
docs/proposals/0845-scheduler-architecture-proposal/README.md:17-23,49-91).
Here the equivalent state is a set of fixed-shape pytrees so the whole
scheduling cycle is one traced XLA program:

  EndpointBatch  — dense view of every endpoint's live metrics   [M_MAX, ...]
  RequestBatch   — dense view of N pending requests              [N, ...]
  SchedState     — device-resident cross-request state (assumed load,
                   prefix-cache index, RR counter) threaded functionally
  PickResult     — per-request ordered endpoint list + status
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from gie_tpu.sched import constants as C


@flax.struct.dataclass
class EndpointBatch:
    """Dense endpoint-side inputs for one scheduling cycle.

    Built by the datastore/metrics layer (reference equivalent:
    pkg/lwepp/datastore/datastore.go:40-52 Endpoint/EndpointPool plus the
    scraped PodMetrics of proposal 003). Row i is endpoint slot i; `valid`
    masks unused slots so pod churn never changes the compiled shape.
    """

    metrics: jax.Array       # f32[M_MAX, NUM_METRICS]
    valid: jax.Array         # bool[M_MAX]
    lora_active: jax.Array   # i32[M_MAX, LORA_SLOTS], adapter ids, -1 = empty
    lora_waiting: jax.Array  # i32[M_MAX, LORA_SLOTS]
    # Serving role per slot (constants.Role; BOTH=0 default) for
    # disaggregated prefill/decode. Defaulted so pre-existing explicit
    # EndpointBatch(...) constructions keep their meaning (co-located
    # serving). numpy, not jnp: import-time device constants are banned.
    role: jax.Array = flax.struct.field(
        default_factory=lambda: np.zeros((C.M_MAX,), np.int32)
    )

    @staticmethod
    def empty(m: int = C.M_MAX) -> "EndpointBatch":
        return EndpointBatch(
            metrics=jnp.zeros((m, C.NUM_METRICS), jnp.float32),
            valid=jnp.zeros((m,), bool),
            lora_active=jnp.full((m, C.LORA_SLOTS), -1, jnp.int32),
            lora_waiting=jnp.full((m, C.LORA_SLOTS), -1, jnp.int32),
            role=jnp.zeros((m,), jnp.int32),
        )


@flax.struct.dataclass
class RequestBatch:
    """Dense request-side inputs for one scheduling cycle.

    One row per pending request. `subset_mask` carries the data plane's
    candidate-subset hint (`envoy.lb.subset_hint` filter metadata, reference
    docs/proposals/004-endpoint-picker-protocol/README.md:28-44,
    pkg/lwepp/handlers/request.go:51-77): all-True when no hint was present,
    and a strict mask otherwise — an all-False row must yield a 503, never a
    silent fallback.
    """

    valid: jax.Array         # bool[N]
    lora_id: jax.Array       # i32[N], -1 = base model
    criticality: jax.Array   # i32[N], constants.Criticality
    prompt_len: jax.Array    # f32[N], prompt length (chars)
    decode_len: jax.Array    # f32[N], expected/actual output length hint
    chunk_hashes: jax.Array  # u32[N, MAX_CHUNKS] rolling prefix-chunk hashes
    n_chunks: jax.Array      # i32[N] number of valid chunk hashes
    subset_mask: jax.Array   # bool[N, M_MAX]

    @staticmethod
    def empty(n: int, m: int = C.M_MAX) -> "RequestBatch":
        return RequestBatch(
            valid=jnp.zeros((n,), bool),
            lora_id=jnp.full((n,), -1, jnp.int32),
            criticality=jnp.full((n,), C.Criticality.STANDARD, jnp.int32),
            prompt_len=jnp.zeros((n,), jnp.float32),
            decode_len=jnp.zeros((n,), jnp.float32),
            chunk_hashes=jnp.zeros((n, C.MAX_CHUNKS), jnp.uint32),
            n_chunks=jnp.zeros((n,), jnp.int32),
            subset_mask=jnp.ones((n, m), bool),
        )


@flax.struct.dataclass
class PrefixTable:
    """Fixed-capacity, direct-mapped chunk-hash -> endpoint-set index.

    TPU-native re-design of the approximate prefix-cache index of reference
    docs/proposals/0602-prefix-cache/README.md:95-129 (chunk-hash -> servers
    map with LRU): a direct-mapped table of PREFIX_SLOTS rows, each holding a
    32-bit chunk-hash key, a BITPACKED per-endpoint presence row (who
    plausibly has this chunk cached — bit m of word m//32), and an age tick
    for staleness decay. Packing the presence matrix into u32 words keeps
    the whole table at S x M_WORDS x 4 B (4 MiB at 32768 x 1024) instead of
    S x M_MAX bytes (32 MiB as bools) — 8x less HBM traffic on every
    match gather and insert scatter, the ops that dominate the cycle.
    Collisions overwrite (the index is explicitly approximate in the
    reference design too); XLA sees only dense scatter/gather.
    """

    keys: jax.Array     # u32[PREFIX_SLOTS], 0 = empty
    present: jax.Array  # u32[PREFIX_SLOTS, m//32] packed endpoint bits
    ages: jax.Array     # u32[PREFIX_SLOTS] last-touch tick

    @staticmethod
    def empty(slots: int = C.PREFIX_SLOTS, m: int = C.M_MAX) -> "PrefixTable":
        return PrefixTable(
            keys=jnp.zeros((slots,), jnp.uint32),
            present=jnp.zeros((slots, m // 32), jnp.uint32),
            ages=jnp.zeros((slots,), jnp.uint32),
        )


@flax.struct.dataclass
class SchedState:
    """Cross-cycle device-resident scheduler state, threaded functionally.

    `assumed_load` implements the assumed-load accounting the scheduler
    proposal mandates (reference docs/proposals/006-scheduler/README.md:156:
    loads are assumed at pick time and reconciled when the request is observed
    to terminate / metrics catch up). `rr` seeds deterministic tie-breaking
    (reference round-robin picker pkg/lwepp/handlers/server.go:85-101).
    """

    prefix: PrefixTable
    assumed_load: jax.Array  # f32[m] in normalized request-cost units
    rr: jax.Array            # u32 scalar round-robin / tie-break counter
    tick: jax.Array          # u32 scalar cycle counter
    # Sinkhorn column duals from the last wave (per-endpoint capacity
    # pressure), carried as a warm start: traffic patterns are wave-stable,
    # so re-solving from sqrt(v_prev) yields a better plan than from ones
    # (round 5: +2.3% goodput at the same iteration count; it does NOT buy
    # fewer iterations — docs/BENCH_NOTES.md). Ones = cold start; ignored
    # by non-sinkhorn pickers.
    ot_v: jax.Array          # f32[m]

    @staticmethod
    def init(slots: int = C.PREFIX_SLOTS, m: int = C.M_MAX) -> "SchedState":
        return SchedState(
            prefix=PrefixTable.empty(slots, m),
            assumed_load=jnp.zeros((m,), jnp.float32),
            rr=jnp.zeros((), jnp.uint32),
            tick=jnp.zeros((), jnp.uint32),
            ot_v=jnp.ones((m,), jnp.float32),
        )

    @property
    def m(self) -> int:
        """Endpoint-axis width this state is laid out for (an M bucket)."""
        return int(self.assumed_load.shape[0])


@flax.struct.dataclass
class PickResult:
    """Per-request scheduling outcome.

    `indices[n]` is the ordered endpoint slot list (primary + fallbacks,
    -1 padded) matching the comma-separated ordered fallback list of the
    endpoint-picker protocol (reference
    docs/proposals/004-endpoint-picker-protocol/README.md:50-82). `status`
    uses constants.Status (OK / NO_CAPACITY->503 / SHED->429).
    """

    indices: jax.Array  # i32[N, FALLBACKS]
    status: jax.Array   # i32[N]
    scores: jax.Array   # f32[N, FALLBACKS] total score of each chosen endpoint
    # Disaggregated prefill/decode (ProfileConfig.pd_disaggregation): the
    # prefill endpoint slot per request (-1 when not applicable). In pd
    # mode `indices` holds the DECODE pick (the destination that owns the
    # response stream) and `prefill` names the worker the data plane should
    # run prefill on (x-gateway-prefill-endpoint). None in classic mode so
    # the pytree structure — and every compiled cycle — is unchanged.
    prefill: object = None  # i32[N] | None
    # Device-side affinity provenance (flight-record schema v2,
    # ProfileConfig.record_affinity): the chosen endpoint's prefix-match
    # and session columns, gathered at the primary pick inside the cycle
    # so the recorder never recomputes (or worse, approximates) them
    # host-side. None when disabled — same pytree-stability rule as
    # `prefill`.
    affinity: object = None  # f32[N, 2] (prefix, session) | None
    # Hierarchical two-level picks only (gie_tpu/fleet): per-request
    # coarse-stage candidate cells + scores (fleet.FleetAux). None on the
    # dense cycle, so the default-off path's compiled pytree is unchanged.
    fleet: object = None  # FleetAux | None


@flax.struct.dataclass
class Weights:
    """Scorer blend weights — the profile-level weighted sum of reference
    docs/proposals/0845-scheduler-architecture-proposal/README.md:68-72
    (normalized scores, weighted at profile level), as a dynamic argument so
    retuning never recompiles."""

    queue: jax.Array         # f32 scalar
    kv_cache: jax.Array
    prefix: jax.Array
    lora: jax.Array
    assumed_load: jax.Array  # penalty weight on in-flight assumed load
    latency: jax.Array       # learned TTFT/TPOT predictor column
    # Consistent-hash session stickiness (index-free prefix affinity);
    # defaulted so pre-existing explicit Weights(...) constructions keep
    # their meaning (column off unless weighted in). numpy scalar, not jnp:
    # import-time device constants are banned (they capture into dispatch).
    session: jax.Array = flax.struct.field(
        default_factory=lambda: np.float32(0.0)
    )

    @staticmethod
    def default() -> "Weights":
        return Weights(
            queue=jnp.float32(1.0),
            kv_cache=jnp.float32(1.0),
            prefix=jnp.float32(2.0),
            lora=jnp.float32(1.0),
            assumed_load=jnp.float32(1.0),
            latency=jnp.float32(0.0),
            session=jnp.float32(0.0),
        )


def pad_requests(reqs: RequestBatch, n_bucket: int) -> RequestBatch:
    """Pad a RequestBatch up to `n_bucket` rows (host-side helper)."""
    n = int(reqs.valid.shape[0])
    if n == n_bucket:
        return reqs
    if n > n_bucket:
        raise ValueError(f"batch of {n} does not fit bucket {n_bucket}")
    pad = n_bucket - n

    def _pad(x):
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return np.pad(np.asarray(x), widths)

    return jax.tree.map(_pad, reqs)


def bucket_for(n: int) -> int:
    for b in C.N_BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds max bucket {C.N_BUCKETS[-1]}")


def chunk_bucket_for(count: int) -> int:
    """Smallest chunk-axis bucket covering `count` chunk lanes."""
    for b in C.C_BUCKETS:
        if count <= b:
            return b
    return C.MAX_CHUNKS


def m_bucket_for(count: int) -> int:
    """Smallest endpoint-axis bucket covering `count` slots (the HIGH-WATER
    slot index + 1, not the live count — slot ids must stay addressable)."""
    for b in C.M_BUCKETS:
        if count <= b:
            return b
    raise ValueError(
        f"{count} endpoint slots exceed max bucket {C.M_BUCKETS[-1]}")


def resize_state(state: SchedState, m: int) -> SchedState:
    """Migrate scheduler state across an M-bucket boundary.

    Grow: new slots start with zero assumed load and no prefix presence
    bits — exactly the state a fresh endpoint would have. Shrink: slots
    beyond the new bucket are dropped; the caller (Scheduler) only shrinks
    when the high-water live slot fits the smaller bucket, so anything
    truncated belongs to endpoints the datastore already evicted. Table
    keys/ages are m-independent and carried untouched, so surviving
    endpoints keep their cache affinity across the migration.
    """
    m_old = int(state.assumed_load.shape[0])
    if m == m_old:
        return state
    w = m // 32
    if m > m_old:
        load = jnp.pad(state.assumed_load, (0, m - m_old))
        # New slots start as cold sinkhorn duals (ones = no capacity
        # pressure learned), exactly a fresh endpoint's state.
        ot_v = jnp.pad(state.ot_v, (0, m - m_old), constant_values=1.0)
        present = jnp.pad(
            state.prefix.present, ((0, 0), (0, w - m_old // 32)))
    else:
        load = state.assumed_load[:m]
        ot_v = state.ot_v[:m]
        present = state.prefix.present[:, :w]
    return state.replace(
        assumed_load=load, ot_v=ot_v,
        prefix=state.prefix.replace(present=present))
