"""Sinkhorn optimal-transport picker: batched bin-packing of N requests
onto M endpoints (BASELINE configs[4] "learned bin-packing Picker").

The deterministic argmax picker routes every request of a wave to its
individually-best endpoint, herding onto the argmax until assumed-load
feedback catches up. The OT formulation assigns the whole wave at once:

  maximize   sum_{n,m} P[n,m] * score[n,m]
  subject to sum_m P[n,m] = 1           (each request placed once)
             sum_n P[n,m] <= cap[m]     (endpoint capacity this wave)

solved approximately by Sinkhorn iterations on K = exp(score / tau) with
alternating row normalization (exact) and column capping (projection), all
dense tensor algebra under jit — no data-dependent control flow. The final
per-request ordering comes from the transport plan, so two requests with the
same favorite endpoint split across it and the runner-up instead of
colliding.

Capacity model: each endpoint can absorb headroom proportional to its free
queue + KV space this wave; capacities are scaled so sum(cap) >= N, keeping
the problem feasible (best-effort overflow still lands somewhere).

Mesh sharding (docs/MESH.md). The solve couples every request through the
column duals (fleet-wide endpoint capacity pressure) and every endpoint
through the row sums, so a dp(requests) x tp(endpoints) layout needs a
cross-shard reduction per normalize sweep — and "sharding is a layout
choice, never a semantics change" (tests/test_distributed_equivalence)
demands those reductions be BIT-IDENTICAL to the single-device solve.
Floating-point sums are not associative, so identical values require an
identical reduction TREE, not just identical math: every coupled sum runs
as fixed contiguous GROUP partials (8 groups — the max mesh axis, so each
shard always owns whole groups) followed by an ordered left-to-right fold.
Under `shard_map` the group partials are all-gathered across the mesh axis
(the "global column-dual all-reduce per sweep" — psum would sum in
unspecified ring order); on a single device the gather is the identity and
the very same fold runs over the very same partials. Per-chip memory stays
O(N*M / (dp*tp)): the kernel, plan, and duals never materialize unsharded.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from gie_tpu.sched import constants as C
from gie_tpu.sched.pickers import NEG, _finalize
from gie_tpu.sched.types import EndpointBatch, PickResult

# Canonical reduction-group count: the fixed tree shape shared by every
# layout. 8 = the largest mesh axis this repo builds (make_mesh caps at
# the device count; equivalence is pinned for dp, tp <= 8), and every
# N/M bucket is a power of two, so min(8, axis) always divides the axis
# and each shard of a <=8-way axis owns whole contiguous groups.
GROUPS = 8


def _group_count(axis_len: int) -> int:
    for g in (GROUPS, 4, 2, 1):
        if axis_len % g == 0:
            return g
    return 1


def _fold_first(parts: jax.Array) -> jax.Array:
    """Ordered left-to-right sum over the LEADING (group) axis. A python
    loop on purpose: jnp.sum may tree-reduce in a shape-dependent order,
    and this fold IS the cross-layout contract."""
    acc = parts[0]
    for i in range(1, parts.shape[0]):
        acc = acc + parts[i]
    return acc


def _fold_last(parts: jax.Array) -> jax.Array:
    acc = parts[..., 0]
    for i in range(1, parts.shape[-1]):
        acc = acc + parts[..., i]
    return acc


def _sum_m(x: jax.Array) -> jax.Array:
    """Layout-invariant scalar sum of an endpoint-axis vector: fixed
    group partials + ordered fold, so a tp-sharded [M] input reduces
    bit-identically to a replicated one (each tp shard owns whole
    groups; GSPMD computes the in-group sums locally and the fold order
    is pinned by the unrolled adds)."""
    g = _group_count(int(x.shape[0]))
    return _fold_first(jnp.sum(x.reshape(g, -1), axis=1))


def _headroom(eps: EndpointBatch, queue_limit: float) -> jax.Array:
    """Raw per-endpoint free capacity (queue room x kv room, zero on
    invalid slots) -> f32[M]. Single source for BOTH the wave caps and
    the warm-start utilization gate — the gate must measure exactly the
    quantity the caps are built from, or a tuning change to one silently
    desynchronizes the other."""
    queue = eps.metrics[:, C.Metric.QUEUE_DEPTH]
    kv = eps.metrics[:, C.Metric.KV_CACHE_UTIL]
    headroom = jnp.clip(queue_limit - queue, 0.0, queue_limit) * jnp.clip(
        1.0 - kv, 0.05, 1.0
    )
    return jnp.where(eps.valid, headroom, 0.0)


def capacities(
    eps: EndpointBatch, n_requests: jax.Array, *, queue_limit: float
) -> jax.Array:
    """Per-endpoint wave capacity -> f32[M_MAX], scaled to sum >= the
    EFFECTIVE request mass (valid, candidate-bearing rows — padded bucket
    rows carry no transport mass and must not inflate the caps, or small
    waves never bind them and the picker degenerates to argmax)."""
    headroom = jnp.where(
        eps.valid, _headroom(eps, queue_limit) + 1e-3, 0.0)
    total = jnp.maximum(_sum_m(headroom), 1e-6)
    return headroom * (n_requests / total) * 1.25  # 25% slack for feasibility


def _dual_solve(
    k: jax.Array,        # f32[n_loc, m_loc] kernel block (full on 1 device)
    cap: jax.Array,      # f32[m_loc]
    v_init: jax.Array,   # f32[m_loc] warm-started column duals
    *,
    iters: int,
    gn: int,             # LOCAL request-axis group count (total // dp)
    gm: int,             # LOCAL endpoint-axis group count (total // tp)
    gather_n: Callable[[jax.Array], jax.Array],
    gather_m: Callable[[jax.Array], jax.Array],
) -> tuple[jax.Array, jax.Array]:
    """DUAL-FORM iterations: the iterates of row-normalize-then-column-cap
    compose into p_t = diag(u_t) K diag(v_t), so the loop only needs two
    matvecs per iteration (K @ v and u @ K) and carries two VECTORS — the
    full [N, M] plan is materialized exactly once at the end (the
    matrix-form scan carried the 1 MiB plan every iteration: ~2.5x the
    HBM traffic at 8 iterations, hack/cost_analysis.py).

    Both coupled reductions run grouped (see module docstring): the
    column load's request-axis sum is the capacity-pressure all-reduce —
    gather_n hands every shard ALL group partials so each sweep caps
    against fleet-wide load, not the shard's own slice — and the row
    sum's endpoint-axis fold keeps tp shards on the single-device
    ordering. gather_n/gather_m are the identity on one device.
    """
    n_loc, m_loc = k.shape
    kg = k.reshape(gn, n_loc // gn, gm, m_loc // gm)

    def row_sums(mat_g: jax.Array, v: jax.Array) -> jax.Array:
        # sum_m mat[n, m] * v[m] -> [n_loc]; per-(row, m-group) partials,
        # gathered over tp, folded in group order.
        parts = jnp.einsum(
            "anbm,bm->anb", mat_g, v.reshape(gm, m_loc // gm))
        return _fold_last(gather_m(parts)).reshape(n_loc)

    def col_sums(u: jax.Array) -> jax.Array:
        # sum_n u[n] * k[n, m] -> [m_loc]; per-(n-group, col) partials,
        # gathered over dp (the global column-dual all-reduce), folded.
        parts = jnp.einsum("an,anbm->abm", u.reshape(gn, n_loc // gn), kg)
        return _fold_first(gather_n(parts)).reshape(m_loc)

    def body(carry, _):
        u, v = carry
        # Row normalize: each request's mass is u_n * (K @ v)_n = 1.
        r = row_sums(kg, v)
        u = jnp.where(r > 0, 1.0 / r, u)
        # Column cap: load on endpoint m is v_m * (u @ K)_m.
        col = v * col_sums(u)
        v = v * jnp.where(
            col > cap, cap / jnp.maximum(col, 1e-9), 1.0)
        return (u, v), None

    (u, v), _ = jax.lax.scan(
        body,
        (jnp.ones((n_loc,), jnp.float32), v_init),
        None, length=iters,
    )
    plan = k * u[:, None] * v[None, :]
    # Final row normalization so the plan is a proper per-request
    # distribution even where capacity clipped it (grouped like every
    # other M-axis sum — it feeds the rounded scores directly).
    plan_g = plan.reshape(gn, n_loc // gn, gm, m_loc // gm)
    row = _fold_last(gather_m(jnp.sum(plan_g, axis=3))).reshape(n_loc)
    plan = jnp.where(row[:, None] > 0, plan / row[:, None], plan)
    return plan, v


def _identity(x: jax.Array) -> jax.Array:
    return x


def _solve_plan(
    k: jax.Array,
    cap: jax.Array,
    v_init: jax.Array,
    *,
    iters: int,
    mesh: Optional[Mesh],
) -> tuple[jax.Array, jax.Array]:
    """Dispatch the dual solve: single-device grouped form, or the same
    grouped form under shard_map with explicit all-gather collectives
    when a mesh is present (GSPMD's own partitioning of the scan inserts
    correct-but-unordered reductions whose float results drift from the
    single-device solve — here the collective placement is load-bearing,
    so it is explicit)."""
    n, m = int(k.shape[0]), int(k.shape[1])
    gn_total = _group_count(n)
    gm_total = _group_count(m)
    if mesh is None:
        return _dual_solve(
            k, cap, v_init, iters=iters, gn=gn_total, gm=gm_total,
            gather_n=_identity, gather_m=_identity)

    from jax.experimental.shard_map import shard_map

    dp, tp = int(mesh.shape["dp"]), int(mesh.shape["tp"])
    if gn_total % dp or gm_total % tp:
        raise ValueError(
            f"sinkhorn mesh axes (dp={dp}, tp={tp}) must divide the "
            f"canonical reduction groups (gn={gn_total}, gm={gm_total} "
            f"for a {n}x{m} wave) — mesh axes are capped at {GROUPS}")

    def _local(k_loc, cap_loc, v_loc):
        return _dual_solve(
            k_loc, cap_loc, v_loc, iters=iters,
            gn=gn_total // dp, gm=gm_total // tp,
            gather_n=lambda p: jax.lax.all_gather(
                p, "dp", axis=0, tiled=True),
            gather_m=lambda p: jax.lax.all_gather(
                p, "tp", axis=2, tiled=True),
        )

    solve = shard_map(
        _local, mesh=mesh,
        in_specs=(P("dp", "tp"), P("tp"), P("tp")),
        out_specs=(P("dp", "tp"), P("tp")),
        check_rep=False,
    )
    return solve(k, cap, v_init)


def sinkhorn_picker(
    scores: jax.Array,   # f32[N, M_MAX]
    mask: jax.Array,     # bool[N, M_MAX]
    shed: jax.Array,
    valid: jax.Array,
    eps: EndpointBatch,
    key: jax.Array,
    *,
    queue_limit: float,
    tau: float,
    iters: int,
    rounding_temp: float,
    use_pallas: bool = False,
    v0: Optional[jax.Array] = None,  # f32[M] last wave's column duals
    mesh: Optional[Mesh] = None,
) -> tuple[PickResult, jax.Array]:
    # Effective transport mass: valid rows that still have candidates
    # (padded rows and empty-subset rows contribute nothing). Integer-
    # valued f32 partial sums are exact under ANY reduction order (all
    # magnitudes < 2^24), so this one needs no grouping.
    n_eff = jnp.maximum(
        jnp.sum((valid & jnp.any(mask, axis=1)).astype(jnp.float32)), 1.0
    )
    cap = capacities(eps, n_eff, queue_limit=queue_limit)  # f32[M]

    # Kernel: masked Gibbs weights. Subtract per-row max for stability
    # (max reductions are exact, so tp sharding cannot perturb them).
    row_max = jnp.max(jnp.where(mask, scores, -jnp.inf), axis=1, keepdims=True)
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    k = jnp.where(mask, jnp.exp((scores - row_max) / tau), 0.0)

    # Warm start (round 5): column duals are per-endpoint capacity
    # pressure and traffic is wave-stable, so last wave's v is a better
    # prior than ones — but only insofar as the fleet is actually
    # LOADED. Caps are normalized to the wave mass (see capacities), so
    # they bind even on an idle fleet; carrying duals there splits
    # sessions off their warm home for no latency benefit (hit 0.866 ->
    # 0.847 at the 75 qps point before this gate existed). Scale the
    # retention exponent by fleet utilization u (1 - free queue x kv
    # headroom / idle headroom): idle -> v^0 = ones (cold start),
    # saturated -> v^0.5 (the sqrt blend that swept best contended —
    # within one solve v only ever decreases, so a raw carry would
    # collapse toward 0 over waves; the fractional power lets pressure
    # decay while persistent binding re-sharpens every wave).
    if v0 is None:
        v_init = jnp.ones(k.shape[1:], jnp.float32)
    else:
        free = _headroom(eps, queue_limit)
        idle_free = queue_limit * jnp.maximum(
            jnp.sum(eps.valid.astype(jnp.float32)), 1.0)
        u = jnp.clip(1.0 - _sum_m(free) / idle_free, 0.0, 1.0)
        v_init = jnp.clip(v0, 1e-6, 1.0) ** (0.5 * u)

    if use_pallas and mesh is None:
        # VMEM-resident iteration loop (one HBM write for the whole
        # solve). The kernel consumes the SAME warm-started duals as the
        # dual-form path below (ADVICE r5 #2): it seeds the plan with
        # diag(v_init) and carries the running column-scale product, so
        # its plan AND its returned duals match the XLA path's iterates —
        # flipping the flag mid-run keeps the learned pressure. Under a
        # mesh the grouped shard_map path runs instead: the kernel is a
        # single-device loop, and the solve must be bit-equal across
        # layouts (docs/MESH.md).
        from gie_tpu.ops import interpret_default
        from gie_tpu.ops.fused_sinkhorn import fused_sinkhorn_plan

        plan, v_out = fused_sinkhorn_plan(
            k, cap, v_init, iters=iters, interpret=interpret_default())
    else:
        plan, v_out = _solve_plan(k, cap, v_init, iters=iters, mesh=mesh)

    # Rounding: argmax of identical fractional rows would herd the whole
    # wave onto one endpoint again, so Gumbel noise (scaled by
    # rounding_temp) breaks symmetry. Note this is a GREEDY tie-breaking
    # rounding, not mass-proportional sampling: at rounding_temp < 1 picks
    # concentrate on each row's plan mode (~ plan^(1/temp)), which the
    # goodput sweep preferred over true proportional rounding (temp=1).
    # Runs at the GSPMD level: elementwise ops and the max/argmax top-k
    # are layout-exact, and jax_threefry_partitionable (gie_tpu.parallel)
    # makes the noise bits sharding-invariant.
    g = jax.random.gumbel(key, plan.shape, jnp.float32) * rounding_temp
    masked = jnp.where(mask & (plan > 0), jnp.log(plan + 1e-20) + g, NEG)
    return _finalize(masked, mask, shed, valid), v_out
