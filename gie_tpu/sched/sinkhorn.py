"""Sinkhorn optimal-transport picker: batched bin-packing of N requests
onto M endpoints (BASELINE configs[4] "learned bin-packing Picker").

The deterministic argmax picker routes every request of a wave to its
individually-best endpoint, herding onto the argmax until assumed-load
feedback catches up. The OT formulation assigns the whole wave at once:

  maximize   sum_{n,m} P[n,m] * score[n,m]
  subject to sum_m P[n,m] = 1           (each request placed once)
             sum_n P[n,m] <= cap[m]     (endpoint capacity this wave)

solved approximately by Sinkhorn iterations on K = exp(score / tau) with
alternating row normalization (exact) and column capping (projection), all
dense tensor algebra under jit — no data-dependent control flow. The final
per-request ordering comes from the transport plan, so two requests with the
same favorite endpoint split across it and the runner-up instead of
colliding.

Capacity model: each endpoint can absorb headroom proportional to its free
queue + KV space this wave; capacities are scaled so sum(cap) >= N, keeping
the problem feasible (best-effort overflow still lands somewhere).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from gie_tpu.sched import constants as C
from gie_tpu.sched.pickers import NEG, _finalize
from gie_tpu.sched.types import EndpointBatch, PickResult


def _headroom(eps: EndpointBatch, queue_limit: float) -> jax.Array:
    """Raw per-endpoint free capacity (queue room x kv room, zero on
    invalid slots) -> f32[M]. Single source for BOTH the wave caps and
    the warm-start utilization gate — the gate must measure exactly the
    quantity the caps are built from, or a tuning change to one silently
    desynchronizes the other."""
    queue = eps.metrics[:, C.Metric.QUEUE_DEPTH]
    kv = eps.metrics[:, C.Metric.KV_CACHE_UTIL]
    headroom = jnp.clip(queue_limit - queue, 0.0, queue_limit) * jnp.clip(
        1.0 - kv, 0.05, 1.0
    )
    return jnp.where(eps.valid, headroom, 0.0)


def capacities(
    eps: EndpointBatch, n_requests: jax.Array, *, queue_limit: float
) -> jax.Array:
    """Per-endpoint wave capacity -> f32[M_MAX], scaled to sum >= the
    EFFECTIVE request mass (valid, candidate-bearing rows — padded bucket
    rows carry no transport mass and must not inflate the caps, or small
    waves never bind them and the picker degenerates to argmax)."""
    headroom = jnp.where(
        eps.valid, _headroom(eps, queue_limit) + 1e-3, 0.0)
    total = jnp.maximum(jnp.sum(headroom), 1e-6)
    return headroom * (n_requests / total) * 1.25  # 25% slack for feasibility


def sinkhorn_picker(
    scores: jax.Array,   # f32[N, M_MAX]
    mask: jax.Array,     # bool[N, M_MAX]
    shed: jax.Array,
    valid: jax.Array,
    eps: EndpointBatch,
    key: jax.Array,
    *,
    queue_limit: float,
    tau: float,
    iters: int,
    rounding_temp: float,
    use_pallas: bool = False,
    v0: Optional[jax.Array] = None,  # f32[M] last wave's column duals
) -> tuple[PickResult, jax.Array]:
    # Effective transport mass: valid rows that still have candidates
    # (padded rows and empty-subset rows contribute nothing).
    n_eff = jnp.maximum(
        jnp.sum((valid & jnp.any(mask, axis=1)).astype(jnp.float32)), 1.0
    )
    cap = capacities(eps, n_eff, queue_limit=queue_limit)  # f32[M]

    # Kernel: masked Gibbs weights. Subtract per-row max for stability.
    row_max = jnp.max(jnp.where(mask, scores, -jnp.inf), axis=1, keepdims=True)
    row_max = jnp.where(jnp.isfinite(row_max), row_max, 0.0)
    k = jnp.where(mask, jnp.exp((scores - row_max) / tau), 0.0)

    # Warm start (round 5): column duals are per-endpoint capacity
    # pressure and traffic is wave-stable, so last wave's v is a better
    # prior than ones — but only insofar as the fleet is actually
    # LOADED. Caps are normalized to the wave mass (see capacities), so
    # they bind even on an idle fleet; carrying duals there splits
    # sessions off their warm home for no latency benefit (hit 0.866 ->
    # 0.847 at the 75 qps point before this gate existed). Scale the
    # retention exponent by fleet utilization u (1 - free queue x kv
    # headroom / idle headroom): idle -> v^0 = ones (cold start),
    # saturated -> v^0.5 (the sqrt blend that swept best contended —
    # within one solve v only ever decreases, so a raw carry would
    # collapse toward 0 over waves; the fractional power lets pressure
    # decay while persistent binding re-sharpens every wave).
    if v0 is None:
        v_init = jnp.ones(k.shape[1:], jnp.float32)
    else:
        free = _headroom(eps, queue_limit)
        idle_free = queue_limit * jnp.maximum(
            jnp.sum(eps.valid.astype(jnp.float32)), 1.0)
        u = jnp.clip(1.0 - jnp.sum(free) / idle_free, 0.0, 1.0)
        v_init = jnp.clip(v0, 1e-6, 1.0) ** (0.5 * u)

    if use_pallas:
        # VMEM-resident iteration loop (one HBM write for the whole
        # solve). The kernel consumes the SAME warm-started duals as the
        # dual-form path below (ADVICE r5 #2): it seeds the plan with
        # diag(v_init) and carries the running column-scale product, so
        # its plan AND its returned duals match the XLA path's iterates —
        # flipping the flag mid-run keeps the learned pressure.
        from gie_tpu.ops import interpret_default
        from gie_tpu.ops.fused_sinkhorn import fused_sinkhorn_plan

        plan, v_out = fused_sinkhorn_plan(
            k, cap, v_init, iters=iters, interpret=interpret_default())
    else:
        # DUAL-FORM iterations: the iterates of row-normalize-then-
        # column-cap compose into p_t = diag(u_t) K diag(v_t), so the
        # loop only needs two matvecs per iteration (K @ v and u @ K)
        # and carries two VECTORS — the full [N, M] plan is materialized
        # exactly once at the end. The equivalent matrix-form scan
        # carried (read + wrote) the 1 MiB plan every iteration: ~2.5x
        # the HBM traffic at 8 iterations (hack/cost_analysis.py).
        def body(carry, _):
            u, v = carry
            # Row normalize: each request's mass is u_n * (K @ v)_n = 1.
            r = k @ v                                   # f32[N]
            u = jnp.where(r > 0, 1.0 / r, u)
            # Column cap: load on endpoint m is v_m * (u @ K)_m.
            col = v * (u @ k)                           # f32[M]
            v = v * jnp.where(
                col > cap, cap / jnp.maximum(col, 1e-9), 1.0)
            return (u, v), None

        (u, v), _ = jax.lax.scan(
            body,
            (jnp.ones(k.shape[:1], jnp.float32), v_init),
            None, length=iters,
        )
        plan = k * u[:, None] * v[None, :]
        # Final row normalization so the plan is a proper per-request
        # distribution even where capacity clipped it.
        row = jnp.sum(plan, axis=1, keepdims=True)
        plan = jnp.where(row > 0, plan / row, plan)
        v_out = v

    # Rounding: argmax of identical fractional rows would herd the whole
    # wave onto one endpoint again, so Gumbel noise (scaled by
    # rounding_temp) breaks symmetry. Note this is a GREEDY tie-breaking
    # rounding, not mass-proportional sampling: at rounding_temp < 1 picks
    # concentrate on each row's plan mode (~ plan^(1/temp)), which the
    # goodput sweep preferred over true proportional rounding (temp=1).
    g = jax.random.gumbel(key, plan.shape, jnp.float32) * rounding_temp
    masked = jnp.where(mask & (plan > 0), jnp.log(plan + 1e-20) + g, NEG)
    return _finalize(masked, mask, shed, valid), v_out
