"""Rolling prefix-chunk hashing (host side).

Implements the chained chunk hash of the prefix-cache proposal (reference
docs/proposals/0602-prefix-cache/README.md:99:
``hash(chunk_i) = hash(content_i + hash(chunk_{i-1}))``): prompts are split
into fixed-size character chunks and each chunk's hash folds in the previous
chunk's hash, so equal hash at depth i implies equal prefix up to i.

Two implementations, bit-identical (both chained zlib CRC32):
  - native/libgiechunker.so (C++, batch API) — loaded via ctypes when built
    (`make -C native`); used by batch_chunk_hashes for whole micro-batches.
  - the pure-Python per-prompt loop below — always available fallback.
Hash 0 is reserved for "empty table slot" and remapped to 1.
"""

from __future__ import annotations

import ctypes
import zlib

import numpy as np

from gie_tpu.sched import constants as C


def _load_native():
    from gie_tpu.utils.nativelib import native_lib_path

    path = native_lib_path("giechunker")
    try:
        lib = ctypes.CDLL(path)
        fn = lib.gie_chunk_hashes_batch
    except (OSError, AttributeError):
        # Missing OR stale library (symbol absent): pure-Python fallback.
        return None
    fn.argtypes = [
        ctypes.c_char_p,                      # data
        np.ctypeslib.ndpointer(np.int64),     # offsets
        ctypes.c_int,                         # n_prompts
        ctypes.c_int,                         # chunk_bytes
        ctypes.c_int,                         # max_chunks
        np.ctypeslib.ndpointer(np.uint32),    # out_hashes
        np.ctypeslib.ndpointer(np.int32),     # out_counts
    ]
    fn.restype = None
    return fn


_NATIVE = _load_native()


def chunk_hashes(
    prompt: bytes,
    *,
    chunk_bytes: int = C.CHUNK_BYTES,
    max_chunks: int = C.MAX_CHUNKS,
) -> tuple[np.ndarray, int]:
    """Hash one prompt -> (u32[max_chunks] zero-padded, n_chunks).

    Only complete chunks are hashed (a trailing partial chunk can't match a
    cached block boundary), matching the fixed-size-chunk split of the
    reference design.
    """
    n = min(len(prompt) // chunk_bytes, max_chunks)
    out = np.zeros((max_chunks,), np.uint32)
    h = 0
    for i in range(n):
        chunk = prompt[i * chunk_bytes : (i + 1) * chunk_bytes]
        h = zlib.crc32(chunk, h) & 0xFFFFFFFF
        out[i] = h if h != 0 else 1
    return out, n


def batch_chunk_hashes(
    prompts: list[bytes],
    *,
    chunk_bytes: int = C.CHUNK_BYTES,
    max_chunks: int = C.MAX_CHUNKS,
) -> tuple[np.ndarray, np.ndarray]:
    """Hash a batch of prompts -> (u32[N, max_chunks], i32[N])."""
    n = len(prompts)
    hashes = np.zeros((n, max_chunks), np.uint32)
    counts = np.zeros((n,), np.int32)
    if _NATIVE is not None and n > 0:
        # Vectorized offsets: cumulative prompt lengths, no Python loop
        # (this runs per wave on the collector's hot host path).
        offsets = np.zeros((n + 1,), np.int64)
        np.cumsum(
            np.fromiter((len(p) for p in prompts), np.int64, n),
            out=offsets[1:],
        )
        data = b"".join(prompts)
        _NATIVE(data, offsets, n, chunk_bytes, max_chunks, hashes, counts)
        return hashes, counts
    for i, p in enumerate(prompts):
        hashes[i], counts[i] = chunk_hashes(
            p, chunk_bytes=chunk_bytes, max_chunks=max_chunks
        )
    return hashes, counts
