"""Rolling prefix-chunk hashing (host side).

Implements the chained chunk hash of the prefix-cache proposal (reference
docs/proposals/0602-prefix-cache/README.md:99:
``hash(chunk_i) = hash(content_i + hash(chunk_{i-1}))``): prompts are split
into fixed-size character chunks and each chunk's hash folds in the previous
chunk's hash, so equal hash at depth i implies equal prefix up to i.

This is the reference implementation (a C++ fast path under native/ is
planned and will dispatch from here once built). Hash 0 is reserved for
"empty table slot" and remapped to 1.
"""

from __future__ import annotations

import zlib

import numpy as np

from gie_tpu.sched import constants as C


def chunk_hashes(
    prompt: bytes,
    *,
    chunk_bytes: int = C.CHUNK_BYTES,
    max_chunks: int = C.MAX_CHUNKS,
) -> tuple[np.ndarray, int]:
    """Hash one prompt -> (u32[max_chunks] zero-padded, n_chunks).

    Only complete chunks are hashed (a trailing partial chunk can't match a
    cached block boundary), matching the fixed-size-chunk split of the
    reference design.
    """
    n = min(len(prompt) // chunk_bytes, max_chunks)
    out = np.zeros((max_chunks,), np.uint32)
    h = 0
    for i in range(n):
        chunk = prompt[i * chunk_bytes : (i + 1) * chunk_bytes]
        h = zlib.crc32(chunk, h) & 0xFFFFFFFF
        out[i] = h if h != 0 else 1
    return out, n


def batch_chunk_hashes(
    prompts: list[bytes],
    *,
    chunk_bytes: int = C.CHUNK_BYTES,
    max_chunks: int = C.MAX_CHUNKS,
) -> tuple[np.ndarray, np.ndarray]:
    """Hash a batch of prompts -> (u32[N, max_chunks], i32[N])."""
    hashes = np.zeros((len(prompts), max_chunks), np.uint32)
    counts = np.zeros((len(prompts),), np.int32)
    for i, p in enumerate(prompts):
        hashes[i], counts[i] = chunk_hashes(
            p, chunk_bytes=chunk_bytes, max_chunks=max_chunks
        )
    return hashes, counts
