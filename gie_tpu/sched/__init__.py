"""Batched TPU scheduler: the decision core of the framework.

See profile.scheduling_cycle for the full cycle and SURVEY.md section 7 for
how this replaces the reference's per-request plugin chain.
"""

from gie_tpu.sched.constants import (
    FALLBACKS,
    M_BUCKETS,
    M_MAX,
    MAX_CHUNKS,
    NUM_METRICS,
    Criticality,
    Metric,
    Status,
)
from gie_tpu.sched.profile import (
    PendingWave,
    ProfileConfig,
    Scheduler,
    scheduling_cycle,
)
from gie_tpu.sched.types import (
    EndpointBatch,
    PickResult,
    PrefixTable,
    RequestBatch,
    SchedState,
    Weights,
)

__all__ = [
    "FALLBACKS",
    "M_BUCKETS",
    "M_MAX",
    "MAX_CHUNKS",
    "NUM_METRICS",
    "Criticality",
    "Metric",
    "Status",
    "PendingWave",
    "ProfileConfig",
    "Scheduler",
    "scheduling_cycle",
    "EndpointBatch",
    "PickResult",
    "PrefixTable",
    "RequestBatch",
    "SchedState",
    "Weights",
]
