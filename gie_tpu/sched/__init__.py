"""Batched TPU scheduler: the decision core of the framework.

See profile.scheduling_cycle for the full cycle and SURVEY.md section 7 for
how this replaces the reference's per-request plugin chain.
"""

import jax as _jax

# Sharding-invariant PRNG, process-wide (docs/MESH.md). The legacy
# threefry lowering computes DIFFERENT bits when XLA partitions the
# random-bits op — the sampler pickers' Gumbel noise was the dominant
# term in the sinkhorn sharded-vs-single divergence (~60% of lanes).
# The partitionable form is value-stable under every layout, which the
# distributed-equivalence guarantee ("sharding is a layout choice,
# never a semantics change") requires. Set here rather than in the
# package root: every module that can draw random bits imports
# gie_tpu.sched (models/storm/parallel/simulator all pull its
# submodules), while host-only tools (lint CLI, fakeapi, controllers)
# stay free of the jax import. A pure config update — no backend
# initialization, no device constants.
_jax.config.update("jax_threefry_partitionable", True)

from gie_tpu.sched.constants import (  # noqa: E402
    FALLBACKS,
    M_BUCKETS,
    M_MAX,
    MAX_CHUNKS,
    NUM_METRICS,
    Criticality,
    Metric,
    Status,
)
from gie_tpu.sched.profile import (
    PendingWave,
    ProfileConfig,
    Scheduler,
    scheduling_cycle,
)
from gie_tpu.sched.types import (
    EndpointBatch,
    PickResult,
    PrefixTable,
    RequestBatch,
    SchedState,
    Weights,
)

__all__ = [
    "FALLBACKS",
    "M_BUCKETS",
    "M_MAX",
    "MAX_CHUNKS",
    "NUM_METRICS",
    "Criticality",
    "Metric",
    "Status",
    "PendingWave",
    "ProfileConfig",
    "Scheduler",
    "scheduling_cycle",
    "EndpointBatch",
    "PickResult",
    "PrefixTable",
    "RequestBatch",
    "SchedState",
    "Weights",
]
