"""Profile composition: the full scheduling cycle as one jitted program.

Reference architecture (docs/proposals/0845-scheduler-architecture-proposal/
README.md:49-91): a scheduling cycle = ProfileHandler -> Filter* -> Score*
(normalized, weighted) -> exactly one Pick -> ProcessProfilesResults. The
TPU-native inversion: all plugins become masked tensor algebra over the full
[N, M_MAX] grid and the cycle — including the assumed-load and prefix-index
state updates — is a single XLA program per request-count bucket.

Host-side, `Scheduler` is the facade the data plane calls: it pads incoming
micro-batches to a bucket, invokes the compiled cycle (donating the state
buffers so updates happen in place on device), and exposes the
request-termination feedback hook that reconciles assumed load (reference
docs/proposals/006-scheduler/README.md:156).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gie_tpu.sched import constants as C
from gie_tpu.sched import filters, pickers, prefix, scorers
from gie_tpu.sched.types import (
    EndpointBatch,
    PickResult,
    PrefixTable,
    RequestBatch,
    SchedState,
    Weights,
    bucket_for,
    m_bucket_for,
    pad_requests,
    resize_state,
)

# Optional learned scorer column:
# (params, reqs, eps, assumed_load) -> f32[N, M_MAX].
PredictorFn = Callable[
    [object, RequestBatch, EndpointBatch, jax.Array], jax.Array
]


@dataclasses.dataclass(frozen=True)
class ProfileConfig:
    """Static profile configuration — hashable, baked into the trace.

    Mirrors the declarative plugin/profile configuration of reference
    docs/proposals/0845-scheduler-architecture-proposal/README.md:92 (plugin
    enablement + thresholds); blend weights are dynamic (`Weights`) so tuning
    never recompiles.
    """

    queue_limit: float = 128.0   # saturation filter: max queue depth
    kv_limit: float = 0.95       # saturation filter: max KV-cache utilization
    # Disaggregated prefill/decode (reference roadmap README.md:115; role-
    # partitioned candidates anticipated by 006 README:158). When on, the
    # cycle runs a DUAL pick: prefill over PREFILL/BOTH-role endpoints with
    # the full blend (prefix/session locality lives on prefill workers),
    # decode over DECODE/BOTH-role endpoints with the locality columns
    # dropped plus a co-location bonus (same endpoint = no KV transfer).
    pd_disaggregation: bool = False
    pd_colocation_bonus: float = 0.25
    queue_norm: float = 64.0     # queue scorer normalization
    load_norm: float = 32.0      # assumed-load scorer normalization
    load_decay: float = 0.95     # per-cycle exponential decay of assumed load
    prefix_max_age: int = 50_000  # prefix-index staleness horizon, in cycles
    enable_saturation: bool = True
    enable_lora: bool = True
    enable_prefix: bool = True
    enable_session: bool = True   # consistent-hash session stickiness column
    session_key_chunks: int = 1   # prompt depth (in chunks) of the session key
    shed_sheddable: bool = True  # 429 sheddable traffic when saturated
    picker: str = "topk"         # "topk" | "random" | "sinkhorn"
    sample_temperature: float = 0.05
    sinkhorn_tau: float = 0.02   # OT temperature (lower = greedier)
    sinkhorn_iters: int = 8
    sinkhorn_rounding_temp: float = 0.1  # randomized-rounding noise scale
    # Fused pallas blend+topk kernel for the "topk" picker (single HBM pass
    # over the scorer columns; first-max tie-break instead of the rotating
    # quantized tie-break). Off by default; enable where profiling shows
    # the kernel wins on the target backend.
    use_pallas_topk: bool = False
    # VMEM-resident pallas loop for the sinkhorn iterations (same default-
    # off rationale).
    use_pallas_sinkhorn: bool = False
    # How the scorer columns combine into the total ("blend" = the
    # normalized weighted sum that has always been the default; "learned" =
    # the gie-learn multiplicative policy exp(sum w*log(col)) — one fused
    # elementwise op, weights trained offline by gie_tpu/learn/train.py).
    # Static so each form is its own trace; the weights stay dynamic either
    # way, so swapping a trained artifact in never recompiles.
    scorer: str = "blend"
    # Gather the chosen endpoint's prefix-match and session columns at the
    # primary pick, inside the cycle (PickResult.affinity — flight-record
    # schema v2). The device already holds both columns; recomputing them
    # host-side for the recorder would be a second (approximate) source of
    # truth. Off = affinity stays None and the compiled pytree matches v1.
    record_affinity: bool = True

    def __post_init__(self) -> None:
        # The noise temperatures are what guarantee pairwise-distinct
        # in-row scores for the random/sinkhorn pickers — the property
        # the threshold-descent top-k needs to enumerate ties as separate
        # fallback entries (pickers._topk). Zero would silently truncate
        # fallback lists under exact ties; reject it at config time.
        if self.sample_temperature <= 0.0:
            raise ValueError(
                f"sample_temperature must be > 0 (got "
                f"{self.sample_temperature}): zero noise permits exact "
                "score ties, which truncate the ordered fallback list")
        if self.sinkhorn_rounding_temp <= 0.0:
            raise ValueError(
                f"sinkhorn_rounding_temp must be > 0 (got "
                f"{self.sinkhorn_rounding_temp}): zero noise permits "
                "exact score ties, which truncate the fallback list")
        if self.scorer not in ("blend", "learned"):
            raise ValueError(
                f"scorer must be 'blend' or 'learned' (got {self.scorer!r})")
        if self.scorer == "learned" and self.use_pallas_topk:
            # fused_blend_topk recomputes the WEIGHTED-SUM blend from
            # (stacked, wvec) inside the kernel — it would silently ignore
            # a multiplicative total. Reject rather than mis-route.
            raise ValueError(
                "scorer='learned' is incompatible with use_pallas_topk: "
                "the fused kernel hard-codes the weighted-sum blend")
        if self.scorer == "learned" and self.pd_disaggregation:
            # _pd_cycle arithmetically de-blends the total (total*wsum -
            # dropped columns) / remaining-wsum — only valid for the linear
            # blend. The dual-pick learned form is future work.
            raise ValueError(
                "scorer='learned' is incompatible with pd_disaggregation: "
                "the dual pick de-blends the linear total arithmetically")


def _affinity_columns(
    named: dict, primary: jax.Array, picked_ok: jax.Array
) -> jax.Array:
    """Flight-record affinity provenance -> f32[N, 2]: the (prefix,
    session) scorer values at each request's primary pick. Disabled
    columns read as 0.0 (exactly what the recorder's tolerant loader
    defaults absent v1 columns to), non-OK rows likewise."""
    n = primary.shape[0]
    zero = jnp.zeros((n,), jnp.float32)
    safe = jnp.maximum(primary, 0)[:, None]

    def at_primary(col):
        if col is None:
            return zero
        return jnp.take_along_axis(col, safe, axis=1)[:, 0]

    pair = jnp.stack(
        [at_primary(named.get("prefix")), at_primary(named.get("session"))],
        axis=-1,
    )
    return jnp.where(picked_ok[:, None], pair, 0.0)


def request_cost(reqs: RequestBatch) -> jax.Array:
    """Assumed cost of admitting each request, in normalized units.

    1.0 for an average request, growing with prompt+decode length — the
    'assumed load' a pick adds to its endpoint until termination feedback
    arrives (reference docs/proposals/006-scheduler/README.md:156).
    """
    return jnp.clip((reqs.prompt_len + reqs.decode_len) / 2048.0, 0.25, 8.0)


def request_cost_host(prompt_len: float, decode_len: float = 0.0) -> float:
    """Host-side twin of request_cost — completion feedback MUST release
    exactly what pick time charged, so both paths share these constants."""
    return float(np.clip((prompt_len + decode_len) / 2048.0, 0.25, 8.0))


def pd_costs(reqs: RequestBatch) -> tuple[jax.Array, jax.Array]:
    """Split assumed costs for the dual pick: the prefill worker carries
    the prompt, the decode worker the generation."""
    prefill = jnp.clip(reqs.prompt_len / 2048.0, 0.125, 8.0)
    decode = jnp.clip(reqs.decode_len / 2048.0, 0.125, 8.0)
    return prefill, decode


def pd_costs_host(prompt_len: float, decode_len: float) -> tuple[float, float]:
    """Host-side twin of pd_costs (same release-what-you-charged contract
    as request_cost_host)."""
    return (
        float(np.clip(prompt_len / 2048.0, 0.125, 8.0)),
        float(np.clip(decode_len / 2048.0, 0.125, 8.0)),
    )


def feature_schema(
    cfg: ProfileConfig, *, has_predictor: bool = False
) -> tuple[str, ...]:
    """Ordered names of the scorer columns build_stages will stack for this
    config — the ONE source of truth a gie-learn policy artifact is
    validated against at load time (insertion order of `named` below)."""
    cols = ["queue", "kv_cache", "assumed_load"]
    if cfg.enable_prefix:
        cols.append("prefix")
    if cfg.enable_session:
        cols.append("session")
    if cfg.enable_lora:
        cols.append("lora")
    if has_predictor:
        cols.append("latency")
    return tuple(cols)


def build_stages(
    state: SchedState,
    reqs: RequestBatch,
    eps: EndpointBatch,
    weights: Weights,
    *,
    cfg: ProfileConfig,
    predictor_fn: Optional[PredictorFn],
    predictor_params,
):
    """Filter + score stages shared by scheduling_cycle and explain:
    -> (mask, shed, named column dict, stacked [S,N,M], wvec [S], total).

    Saturation is a soft filter (004 README:77-80 + 006 saturation
    semantics): when unsaturated candidates exist they are preferred; when
    ALL candidates are saturated, SHEDDABLE traffic is shed with 429 while
    STANDARD degrades to best-effort over the full candidate set (CRITICAL
    bypasses inside saturation_mask).
    """
    mask = filters.base_mask(reqs, eps)
    membership = filters.lora_membership(reqs, eps) if cfg.enable_lora else None
    if cfg.enable_lora:
        mask &= filters.lora_capacity_mask(reqs, eps, membership)
    if cfg.enable_saturation:
        sat_mask = mask & filters.saturation_mask(
            reqs, eps, queue_limit=cfg.queue_limit, kv_limit=cfg.kv_limit
        )
        had_candidates = jnp.any(mask, axis=-1)
        any_unsaturated = jnp.any(sat_mask, axis=-1)
        sheddable = reqs.criticality == C.Criticality.SHEDDABLE
        if cfg.shed_sheddable:
            shed = sheddable & had_candidates & ~any_unsaturated
            # Sheddable keeps the hard filter (empty -> shed); others fall
            # back to the unfiltered candidate set when all are saturated.
            keep_hard = sheddable | any_unsaturated
        else:
            shed = jnp.zeros(reqs.valid.shape, bool)
            keep_hard = any_unsaturated
        mask = jnp.where(keep_hard[:, None], sat_mask, mask)
    else:
        shed = jnp.zeros(reqs.valid.shape, bool)

    named: dict[str, jax.Array] = {
        "queue": jnp.broadcast_to(
            scorers.queue_score(eps, queue_norm=cfg.queue_norm)[None, :],
            mask.shape),
        "kv_cache": jnp.broadcast_to(
            scorers.kv_cache_score(eps)[None, :], mask.shape),
        "assumed_load": jnp.broadcast_to(
            scorers.assumed_load_score(
                state.assumed_load, load_norm=cfg.load_norm)[None, :],
            mask.shape),
    }
    if cfg.enable_prefix:
        named["prefix"] = prefix.match_scores(
            state.prefix, reqs, state.tick, max_age=cfg.prefix_max_age)
    if cfg.enable_session:
        named["session"] = scorers.session_affinity_score(
            reqs, eps, key_chunks=cfg.session_key_chunks)
    if cfg.enable_lora:
        named["lora"] = scorers.lora_affinity_score(reqs, eps, membership)
    if predictor_fn is not None:
        named["latency"] = predictor_fn(
            predictor_params, reqs, eps, state.assumed_load)

    stacked = jnp.stack(list(named.values()))       # [S, N, M]
    wvec = jnp.stack([getattr(weights, k) for k in named])  # [S]
    if cfg.scorer == "learned":
        from gie_tpu.learn.policy import multiplicative_total

        total = multiplicative_total(stacked, wvec)
    else:
        total = jnp.einsum("s,snm->nm", wvec, stacked) / jnp.maximum(
            jnp.sum(wvec), jnp.float32(1e-6)
        )
    return mask, shed, named, stacked, wvec, total


def _pick_stage(
    total: jax.Array,
    stacked: jax.Array,
    wvec: jax.Array,
    mask: jax.Array,
    shed: jax.Array,
    reqs: RequestBatch,
    eps: EndpointBatch,
    state: SchedState,
    key: jax.Array,
    cfg: ProfileConfig,
    mesh=None,
) -> tuple[PickResult, dict]:
    """The configured picker over one (total, mask) pair — shared by the
    classic single pick and the dual prefill/decode picks. The aux dict
    carries picker state to thread into SchedState (today: the sinkhorn
    column duals for the warm start); empty for stateless pickers."""
    if cfg.picker == "topk" and cfg.use_pallas_topk:
        from gie_tpu.ops import interpret_default
        from gie_tpu.ops.fused_topk import fused_blend_topk

        vals, idxs = fused_blend_topk(
            stacked, wvec, mask, k=C.FALLBACKS, interpret=interpret_default()
        )
        return pickers.finalize_from_topk(
            vals, idxs, mask, shed, reqs.valid), {}
    if cfg.picker == "random":
        return pickers.weighted_random_picker(
            total, mask, shed, reqs.valid, key,
            temperature=cfg.sample_temperature,
        ), {}
    if cfg.picker == "sinkhorn":
        from gie_tpu.sched.sinkhorn import sinkhorn_picker

        res, v_out = sinkhorn_picker(
            total, mask, shed, reqs.valid, eps, key,
            queue_limit=cfg.queue_limit,
            tau=cfg.sinkhorn_tau,
            iters=cfg.sinkhorn_iters,
            rounding_temp=cfg.sinkhorn_rounding_temp,
            use_pallas=cfg.use_pallas_sinkhorn,
            v0=state.ot_v,
            mesh=mesh,
        )
        return res, {"ot_v": v_out}
    return pickers.topk_picker(total, mask, shed, reqs.valid, state.rr), {}


def scheduling_cycle(
    state: SchedState,
    reqs: RequestBatch,
    eps: EndpointBatch,
    weights: Weights,
    key: jax.Array,
    predictor_params,
    *,
    cfg: ProfileConfig,
    predictor_fn: Optional[PredictorFn],
    mesh=None,
) -> tuple[PickResult, SchedState]:
    """One full scheduling cycle. Pure; jit-compiled per (N-bucket, cfg).

    `mesh` (static, supplied by parallel.mesh.sharded_cycle) scopes the
    sinkhorn solve's explicit collectives; None = single-device layout.
    """
    mask, shed, named, stacked, wvec, total = build_stages(
        state, reqs, eps, weights,
        cfg=cfg, predictor_fn=predictor_fn, predictor_params=predictor_params,
    )

    if cfg.pd_disaggregation:
        return _pd_cycle(
            state, reqs, eps, key, cfg,
            mask=mask, shed=shed, named=named, stacked=stacked, wvec=wvec,
            total=total, mesh=mesh,
        )

    # ---- Pick stage ------------------------------------------------------
    result, pick_aux = _pick_stage(
        total, stacked, wvec, mask, shed, reqs, eps, state, key, cfg, mesh)

    # ---- State update ----------------------------------------------------
    m = state.assumed_load.shape[0]
    primary = result.indices[:, 0]                  # i32[N], -1 on non-OK
    picked_ok = primary >= 0
    if cfg.record_affinity:
        result = result.replace(
            affinity=_affinity_columns(named, primary, picked_ok))
    cost = jnp.where(picked_ok, request_cost(reqs), 0.0)
    slot = jnp.where(picked_ok, primary, m - 1)
    added = jnp.zeros((m,), jnp.float32).at[slot].add(cost)
    new_load = state.assumed_load * cfg.load_decay + added

    new_prefix = (
        prefix.insert(state.prefix, reqs, primary, state.tick)
        if cfg.enable_prefix
        else state.prefix
    )
    new_state = SchedState(
        prefix=new_prefix,
        assumed_load=new_load,
        rr=state.rr + jnp.uint32(1),
        tick=state.tick + jnp.uint32(1),
        ot_v=pick_aux.get("ot_v", state.ot_v),
    )
    return result, new_state


# Locality columns that only describe the PREFILL side (the prefix cache
# and session affinity live where prefill runs); the decode blend drops
# them and uses load/queue/kv signals plus the co-location bonus.
_PREFILL_ONLY_COLUMNS = ("prefix", "session")


def _pd_cycle(
    state: SchedState,
    reqs: RequestBatch,
    eps: EndpointBatch,
    key: jax.Array,
    cfg: ProfileConfig,
    *,
    mask: jax.Array,
    shed: jax.Array,
    named: dict,
    stacked: jax.Array,
    wvec: jax.Array,
    total: jax.Array,
    mesh=None,
) -> tuple[PickResult, SchedState]:
    """Dual pick for disaggregated serving: prefill endpoint (full blend
    over PREFILL/BOTH roles) then decode endpoint (locality columns
    dropped, co-location bonus, over DECODE/BOTH roles). `indices` is the
    decode pick — the destination that owns the response stream — and
    `prefill` names the prefill worker (x-gateway-prefill-endpoint)."""
    prefill_ok = mask & (eps.role != C.Role.DECODE)[None, :]
    decode_ok = mask & (eps.role != C.Role.PREFILL)[None, :]
    key_p, key_d = jax.random.split(key)

    # pd runs two solves over different candidate masks; neither updates
    # the carried sinkhorn dual (cross-contaminating one shared vector
    # with two different capacity patterns would poison both warm starts).
    p_res, _ = _pick_stage(
        total, stacked, wvec, prefill_ok, shed, reqs, eps, state, key_p, cfg,
        mesh)
    p_primary = p_res.indices[:, 0]

    keep = jnp.asarray(
        [0.0 if k in _PREFILL_ONLY_COLUMNS else 1.0 for k in named],
        jnp.float32,
    )
    d_wvec = wvec * keep
    # Incremental de-blend: total already folded every column, so the
    # decode blend = (total * sum(w) - the prefill-only columns) /
    # sum(kept w) — two column reads instead of re-reducing the whole
    # [S, N, M] stack (~7 MB at the north-star shape).
    #
    # Degeneracy guard: when the kept weights are negligible relative to
    # the total mass (a locality-only tuning: prefix/session carry all
    # the weight), the subtraction leaves pure float32 cancellation
    # residue; dividing it by a tiny denominator would synthesize noise
    # bigger than the co-location bonus and scatter decode picks away
    # from the prefill worker. The honest value there is ZERO — no
    # decode-side signal exists. Threshold sizing: the residue is
    # ~wsum * a-few-ulps (~1e-6 relative), so at d_wsum = 1e-4 * wsum
    # the worst-case noise is ~1e-2 — 4% of the 0.25 bonus — while any
    # deliberately-configured small weight (even 0.1% of the blend)
    # stays live rather than being silently discarded.
    wsum = jnp.maximum(jnp.sum(wvec), jnp.float32(1e-6))
    d_wsum = jnp.sum(d_wvec)
    dropped = sum(
        (w * named[k] for k, w in zip(named, wvec)
         if k in _PREFILL_ONLY_COLUMNS),
        start=jnp.float32(0.0),
    )
    d_total = jnp.where(
        d_wsum > 1e-4 * wsum,
        (total * wsum - dropped) / jnp.maximum(d_wsum, jnp.float32(1e-6)),
        0.0,
    )
    # Same endpoint as the prefill pick = no KV transfer: bonus on that
    # column (only BOTH-role endpoints can win both picks).
    m = d_total.shape[1]
    colocated = (
        jax.lax.broadcasted_iota(jnp.int32, (1, m), 1) == p_primary[:, None]
    )
    d_total = d_total + jnp.float32(cfg.pd_colocation_bonus) * colocated

    # The fused pallas topk recomputes the blend from (stacked, wvec) and
    # would silently drop the co-location bonus carried by d_total — the
    # decode pick always takes the XLA path (the kernel stays available
    # for the prefill pick, whose total IS the plain blend).
    d_cfg = (
        dataclasses.replace(cfg, use_pallas_topk=False)
        if cfg.use_pallas_topk else cfg
    )
    d_res, _ = _pick_stage(
        d_total, stacked, d_wvec, decode_ok, shed, reqs, eps, state, key_d,
        d_cfg, mesh)
    d_primary = d_res.indices[:, 0]

    ok = (p_primary >= 0) & (d_primary >= 0)
    # SHED (from either pick) wins over NO_CAPACITY; OK requires both.
    status = jnp.maximum(p_res.status, d_res.status)
    status = jnp.where(ok & (status == C.Status.OK), C.Status.OK, status)
    status = jnp.where(
        ~ok & (status == C.Status.OK), C.Status.NO_CAPACITY, status)

    # ---- State update: charge each side's cost to its own worker --------
    m_state = state.assumed_load.shape[0]
    p_cost_all, d_cost_all = pd_costs(reqs)
    p_cost = jnp.where(ok, p_cost_all, 0.0)
    d_cost = jnp.where(ok, d_cost_all, 0.0)
    p_slot = jnp.where(ok, p_primary, m_state - 1)
    d_slot = jnp.where(ok, d_primary, m_state - 1)
    added = (
        jnp.zeros((m_state,), jnp.float32)
        .at[p_slot].add(p_cost)
        .at[d_slot].add(d_cost)
    )
    new_load = state.assumed_load * cfg.load_decay + added

    new_prefix = (
        # Only OK requests run: a rejected request must not record its
        # chunks as cached on the prefill worker (classic path gets this
        # for free via primary=-1 on non-OK rows).
        prefix.insert(
            state.prefix, reqs, jnp.where(ok, p_primary, -1), state.tick)
        if cfg.enable_prefix
        else state.prefix
    )
    new_state = SchedState(
        prefix=new_prefix,
        assumed_load=new_load,
        rr=state.rr + jnp.uint32(1),
        tick=state.tick + jnp.uint32(1),
        ot_v=state.ot_v,
    )
    result = PickResult(
        indices=d_res.indices,
        status=status,
        scores=d_res.scores,
        prefill=jnp.where(ok, p_primary, -1),
        # Affinity is a PREFILL-side property (the locality columns were
        # dropped from the decode blend on purpose) — gather at the
        # prefill pick, not the decode destination.
        affinity=(
            _affinity_columns(named, p_primary, ok)
            if cfg.record_affinity else None
        ),
    )
    return result, new_state


@dataclasses.dataclass
class PendingWave:
    """Handle to one async-dispatched scheduling cycle (the pipelined
    collector's unit of work, docs/PIPELINE.md).

    `result` holds the cycle's UN-materialized device arrays: XLA's async
    dispatch returns them as soon as the computation is enqueued, so the
    host can assemble and dispatch wave k+1 while wave k still runs on the
    device stream. `materialize()` blocks until the device delivers and
    returns exactly what the synchronous `Scheduler.pick` returns for the
    same wave — the async path changes WHEN the host waits, never what the
    cycle computes.
    """

    result: PickResult        # device arrays, rows [0, n) are live
    n: int                    # pre-padding request count
    load_snapshot: Optional[jax.Array] = None  # device COPY of post-cycle load

    def materialize(self) -> PickResult:
        return jax.tree.map(lambda x: np.asarray(x)[: self.n], self.result)

    def materialize_load(self) -> Optional[np.ndarray]:
        """Host view of the post-cycle assumed load (None unless the wave
        was dispatched with snapshot_load=True)."""
        if self.load_snapshot is None:
            return None
        return np.asarray(self.load_snapshot)


def check_state_shapes(state: SchedState) -> bool:
    """Cross-field shape consistency for a SchedState built from external
    bytes (checkpoint restore, replication digest install — ADVICE r5 #1
    generalized). A state that fails here would not crash immediately: it
    would surface later inside the jitted cycle as an opaque shape error,
    or worse, silently mis-index. Checks: the endpoint width is a real M
    bucket shared by load and duals, the packed presence matrix matches
    both the table's row count and the bucket's word width, scalars are
    scalars."""
    try:
        m = int(state.assumed_load.shape[0])
    except (TypeError, IndexError):
        return False
    px = state.prefix
    return (
        m in C.M_BUCKETS
        and state.assumed_load.shape == (m,)
        and state.ot_v.shape == (m,)
        and px.keys.ndim == 1
        and px.present.shape == (int(px.keys.shape[0]), m // 32)
        and px.ages.shape == px.keys.shape
        and tuple(state.rr.shape) == ()
        and tuple(state.tick.shape) == ()
    )


def _complete_update(state: SchedState, slots: jax.Array, costs: jax.Array) -> SchedState:
    """Request-termination feedback: subtract reconciled assumed load.

    Slots beyond the state's current M bucket are dropped, not clamped: a
    request picked before a shrink migration may complete after it, and
    its (already-truncated) charge must not land on an unrelated slot."""
    m = state.assumed_load.shape[0]
    ok = (slots >= 0) & (slots < m)
    safe = jnp.where(ok, slots, m)  # out of bounds -> scatter-drop
    sub = jnp.zeros((m,), jnp.float32).at[safe].add(
        jnp.where(ok, costs, 0.0), mode="drop"
    )
    return state.replace(assumed_load=jnp.maximum(state.assumed_load - sub, 0.0))


class Scheduler:
    """Host facade over the jitted scheduling cycle.

    Thread-safe: the data plane's stream handlers enqueue picks from many
    threads; calls serialize on a lock around the functional state (the
    reference datastore serializes with RWMutex + sync.Map,
    pkg/lwepp/datastore/datastore.go:99-104 — here the shared state is one
    device pytree swapped atomically under the lock).
    """

    def __init__(
        self,
        cfg: ProfileConfig = ProfileConfig(),
        weights: Optional[Weights] = None,
        predictor_fn: Optional[PredictorFn] = None,
        predictor_params=None,
        seed: int = 0,
        mesh=None,
    ):
        self.cfg = cfg
        self.weights = weights if weights is not None else Weights.default()
        # The configured latency weight is the CEILING the phase-in gate
        # scales toward (gate_latency_column); the live blend starts at 0
        # when a predictor column is present so an untrained model never
        # dilutes the heuristics.
        self.base_latency_weight = float(self.weights.latency)
        if predictor_fn is not None and self.base_latency_weight > 0.0:
            self.weights = self.weights.replace(latency=jnp.float32(0.0))
        self.predictor_fn = predictor_fn
        self.predictor_params = predictor_params
        # State starts at the smallest M bucket; the first pick migrates it
        # to whatever width the caller's EndpointBatch arrives with.
        # (_init_state, not SchedState.init directly: subclasses carry a
        # differently-shaped prefix index — fleet.FleetPicker's sketch.)
        self.state = self._init_state(C.M_BUCKETS[0])
        self._key = jax.random.PRNGKey(seed)
        self._lock = threading.Lock()
        # (monotonic ts, slot, stored, removed) of recent KV events —
        # replayed over digest installs (see _KV_JOURNAL_MAX below).
        self._kv_journal: collections.deque = collections.deque(
            maxlen=self._KV_JOURNAL_MAX)
        self._complete = jax.jit(_complete_update, donate_argnums=0)
        # No donation: resized buffers change size, so none can alias.
        self._resize = jax.jit(resize_state, static_argnames=("m",))
        self._ingest = jax.jit(prefix.ingest_keys, static_argnames=("remove",))
        self._clear_prefix = jax.jit(
            lambda st, slot: st.replace(
                prefix=prefix.clear_endpoint(st.prefix, slot)
            ),
            donate_argnums=0,
        )
        self._evict = jax.jit(
            # Clear the slot's prefix columns, its assumed load, AND its
            # sinkhorn dual: the endpoint (and its queue) is gone, and a
            # reused slot must not inherit the previous owner's charge or
            # capacity pressure.
            lambda st, slot: st.replace(
                prefix=prefix.clear_endpoint(st.prefix, slot),
                assumed_load=st.assumed_load.at[slot].set(0.0),
                ot_v=st.ot_v.at[slot].set(1.0),
            ),
            donate_argnums=0,
        )
        if mesh is not None:
            # Multi-chip serving: dp-shard the request axis of the cycle
            # over the mesh (ICI collectives inserted by GSPMD; identical
            # results to single-device — tests/test_distributed_equivalence).
            # Deferred import: parallel.mesh imports this module.
            from gie_tpu.parallel.mesh import sharded_cycle

            dp = int(mesh.shape["dp"])
            # Every padded batch must split evenly over the dp axis; the
            # N buckets are powers of two, so dp must be one too (a dp of
            # e.g. 3 would pass startup and crash the first pick inside
            # jit with an indivisible-axis error).
            if dp & (dp - 1) or dp > C.N_BUCKETS[-1]:
                raise ValueError(
                    f"mesh dp axis must be a power of two <= "
                    f"{C.N_BUCKETS[-1]} to divide the request buckets "
                    f"{C.N_BUCKETS}; got dp={dp}"
                )
            self._jit = sharded_cycle(
                mesh, self.cfg, self.predictor_fn, donate_state=True
            )
            self._min_bucket = dp
        else:
            self._jit = jax.jit(
                functools.partial(
                    scheduling_cycle, cfg=self.cfg,
                    predictor_fn=self.predictor_fn,
                ),
                donate_argnums=0,
            )
            self._min_bucket = 1
        self.mesh = mesh
        # Compiled-shape warm cache: (n_bucket, m, chunk_lanes).
        self._warm_buckets: set[tuple[int, int, int]] = set()
        self._warm_lock = threading.Lock()
        # Inline first-use compiles the dispatcher had to wait for (each
        # one is a wave stalled behind a jit compile — the stall the
        # background lattice warmer exists to remove). Observability +
        # test hook; reads are racy-but-monotonic, which is all the
        # consumers need.
        self.warm_inline_compiles = 0

    # Width-policy hooks — the two places the facade assumes "endpoint
    # width = dense M bucket", factored out so fleet.FleetPicker (whose
    # widths run past the dense buckets and whose prefix index is a
    # cell-granular sketch there) overrides policy, not plumbing.
    def _m_ok(self, m: int) -> bool:
        return m in C.M_BUCKETS

    def _init_state(self, m: int) -> SchedState:
        return SchedState.init(m=m)

    def _warm(self, reqs: RequestBatch, eps: EndpointBatch) -> None:
        """Compile a bucket shape OUTSIDE the state lock by running the cycle
        on a throwaway state, so first-use compilation never stalls
        concurrent pick()/complete() calls. The throwaway state is donated
        and discarded; the live state is untouched."""
        self._jit(
            self._init_state(int(eps.valid.shape[0])), reqs, eps,
            self.weights, jax.random.PRNGKey(0), self.predictor_params,
        )

    def warm_lattice_async(
        self, m: int, chunk_lanes: int
    ) -> threading.Thread:
        """Background-compile every still-cold N-bucket executable for the
        (m, chunk_lanes) shape lattice (ROADMAP follow-up: the dispatcher
        used to block on the first wave of each new request-count bucket —
        tens of seconds of inline jit under load spikes, paid exactly when
        the queue is deepest). Runs on a daemon thread with synthetic
        all-invalid waves: compilation is shape-keyed, so a masked wave
        compiles the same executable a live one would. Each bucket holds
        `_warm_lock` only for its own compile, so a live cold-shape pick
        interleaves per bucket instead of waiting for the whole lattice.
        Returns the thread (callers that need warm-before-serve join it).
        """
        buckets = [b for b in C.N_BUCKETS if b >= self._min_bucket]

        def _run() -> None:
            for n in buckets:
                key = (n, m, chunk_lanes)
                if key in self._warm_buckets:
                    continue
                reqs = RequestBatch.empty(n, m).replace(
                    chunk_hashes=jnp.zeros((n, chunk_lanes), jnp.uint32))
                eps = EndpointBatch.empty(m)
                with self._warm_lock:
                    if key in self._warm_buckets:
                        continue
                    self._warm(reqs, eps)
                    self._warm_buckets.add(key)

        t = threading.Thread(
            target=_run, name=f"warm-lattice-m{m}-c{chunk_lanes}",
            daemon=True)
        t.start()
        return t

    def pick(self, reqs: RequestBatch, eps: EndpointBatch) -> PickResult:
        """Schedule a micro-batch; returns host-side PickResult rows for the
        original (pre-padding) batch.

        The endpoint-axis width of `eps` (an M bucket — see
        constants.M_BUCKETS; the batching layer sizes it to the live
        high-water slot) selects the compiled cycle; the device state is
        migrated across bucket boundaries in place, carrying assumed load
        and prefix affinity for every surviving slot."""
        return self.pick_async(reqs, eps).materialize()

    def pick_async(
        self,
        reqs: RequestBatch,
        eps: EndpointBatch,
        *,
        snapshot_load: bool = False,
    ) -> PendingWave:
        """Dispatch one scheduling cycle WITHOUT waiting for its results.

        Returns immediately after the cycle is enqueued on the device
        stream; the caller materializes the PendingWave whenever it needs
        host numbers. Back-to-back calls are safe — and this is the whole
        point of the pipelined collector: the state pytree is device-
        resident and donated, so cycle k+1's dispatch simply queues behind
        cycle k via the state data dependency. Ordering is preserved by
        construction, and the host is free to assemble the next wave while
        the device works.

        `snapshot_load=True` additionally enqueues a device-side COPY of
        the post-cycle assumed load (trainer feature rows need the post-
        schedule snapshot). It must be a copy: the live buffer is donated
        by the NEXT cycle, so a bare reference would be deleted before the
        completer reads it.
        """
        n = int(np.asarray(reqs.valid).shape[0])
        bucket = bucket_for(max(n, self._min_bucket))
        reqs = pad_requests(reqs, bucket)
        m = int(eps.valid.shape[0])
        if not self._m_ok(m):
            raise ValueError(
                f"EndpointBatch width {m} is not an M bucket {C.M_BUCKETS} "
                f"(or a valid fleet width for this scheduler)")
        if int(reqs.subset_mask.shape[1]) != m:
            raise ValueError(
                f"subset_mask width {reqs.subset_mask.shape[1]} != "
                f"endpoint width {m}")
        # The chunk-axis width is a compiled shape too (C_BUCKETS): a wave
        # with a longer prompt mix must warm its own executable, or the
        # first long wave jit-compiles inside the state lock.
        warm_key = (bucket, m, int(reqs.chunk_hashes.shape[1]))
        if warm_key not in self._warm_buckets:
            with self._warm_lock:
                if warm_key not in self._warm_buckets:
                    # Inline stall: this wave waits for its own compile.
                    # The background lattice warmer (warm_lattice_async)
                    # exists to make this path unreachable in steady state.
                    self.warm_inline_compiles += 1
                    self._warm(reqs, eps)
                    self._warm_buckets.add(warm_key)
        with self._lock:
            if self.state.m != m:
                self.state = self._resize(self.state, m=m)
            self._key, sub = jax.random.split(self._key)
            result, self.state = self._jit(
                self.state, reqs, eps, self.weights, sub, self.predictor_params
            )
            # Enqueued under the lock, i.e. after cycle k and before any
            # cycle k+1 can dispatch — the copy observes exactly the
            # post-cycle-k load even though nothing has synced yet.
            snap = jnp.copy(self.state.assumed_load) if snapshot_load else None
        return PendingWave(result=result, n=n, load_snapshot=snap)

    def complete(self, endpoint_slots: np.ndarray, costs: np.ndarray) -> None:
        """Terminated-request feedback (served-endpoint signal, reference
        docs/proposals/004-endpoint-picker-protocol/README.md:84-101)."""
        slots = jnp.asarray(endpoint_slots, jnp.int32)
        costs = jnp.asarray(costs, jnp.float32)
        with self._lock:
            self.state = self._complete(self.state, slots, costs)

    def set_predictor_params(self, params) -> None:
        """Install retrained predictor params (online-training handoff).
        Swapped under the lock so in-flight cycles see a consistent tree."""
        with self._lock:
            self.predictor_params = params

    def gate_latency_column(self, confidence: float) -> float:
        """Phase the latency column into the blend as the predictor earns
        trust: live weight = configured weight x confidence in [0, 1]
        (OnlineTrainer.confidence). Weights are a dynamic argument of the
        jitted cycle, so this never recompiles. Returns the live weight."""
        w = self.base_latency_weight * float(np.clip(confidence, 0.0, 1.0))
        with self._lock:
            self.weights = self.weights.replace(latency=jnp.float32(w))
        return w

    def explain(
        self, reqs: RequestBatch, eps: EndpointBatch
    ) -> dict[str, np.ndarray]:
        """Debug surface: per-scorer columns + blended total + eligibility
        mask for a batch, WITHOUT touching scheduler state (the per-request
        CycleState introspection of 0845, as tensors). Uses the SAME
        build_stages the scheduling cycle runs, so the decomposition cannot
        diverge from the real pick (saturation and shedding included)."""
        n = int(np.asarray(reqs.valid).shape[0])
        bucket = bucket_for(n)
        reqs = pad_requests(reqs, bucket)
        with self._lock:
            # Host materialization: the live buffers are donated (deleted)
            # by the next pick, so a reference snapshot would race.
            state = jax.tree.map(np.asarray, self.state)
            weights = self.weights
            params = self.predictor_params
        m = int(eps.valid.shape[0])
        if int(state.assumed_load.shape[0]) != m:
            # Explaining against a different M bucket than the live state
            # (e.g. before the first pick after churn): resize the snapshot.
            state = resize_state(state, m)
        mask, shed, named, _stacked, _wvec, total = build_stages(
            state, reqs, eps, weights,
            cfg=self.cfg, predictor_fn=self.predictor_fn,
            predictor_params=params,
        )
        out = {name: np.asarray(col)[:n] for name, col in named.items()}
        out["total"] = np.asarray(total)[:n]
        out["mask"] = np.asarray(mask)[:n]
        out["shed"] = np.asarray(shed)[:n]
        return out

    # Event batches pad to these sizes so the jitted ingest compiles for a
    # handful of shapes, not one per batch.
    _EVENT_BUCKETS = (64, 512, 4096)
    # Locally observed KV events are journaled and REPLAYED over a
    # replication-digest install (commit_install): on a follower, an event
    # that arrived after the leader exported the digest would otherwise be
    # overwritten by it — ground truth lost to a stale snapshot until the
    # endpoint happens to re-report (ROADMAP PR 3 follow-up). Entries age
    # out: anything older than the TTL is presumed reflected in (or
    # superseded by) the digest stream. Replay is idempotent (the same
    # evict-then-OR fold), so replaying an event the digest already
    # carries is harmless.
    _KV_JOURNAL_MAX = 256
    _KV_REPLAY_TTL_S = 10.0

    def _fold_prefix_events_locked(
        self, state, slot: int, stored: np.ndarray, removed: np.ndarray
    ):
        """Fold one endpoint's stored/removed chunk hashes into ``state``'s
        prefix table (caller holds the lock). Oversized batches fold in
        chunks of the largest bucket."""
        if slot >= state.m:
            # The reporting endpoint lives beyond the current bucket
            # (events arrived before its first pick) — grow now so its
            # presence bits have somewhere to land.
            state = self._resize(state, m=m_bucket_for(slot + 1))
        # Both callers (apply_prefix_events, commit_install's journal
        # replay) hand in uint32 host arrays already — no conversion here,
        # this runs under the pick lock.
        for hashes, remove in ((stored, False), (removed, True)):
            for start in range(0, len(hashes), self._EVENT_BUCKETS[-1]):
                part = hashes[start:start + self._EVENT_BUCKETS[-1]]
                bucket = next(
                    b for b in self._EVENT_BUCKETS if len(part) <= b)
                padded = np.zeros((bucket,), np.uint32)
                padded[: len(part)] = part
                state = state.replace(prefix=self._ingest(
                    state.prefix, jnp.asarray(padded), jnp.int32(slot),
                    state.tick, remove=remove))
        return state

    def apply_prefix_events(
        self, slot: int, stored: np.ndarray, removed: np.ndarray
    ) -> None:
        """KV-cache event ingestion (reference roadmap item 1 'interfaces
        for remote caches'): fold a model server's reported stored/evicted
        chunk-chain hashes into the device prefix index, and journal the
        batch so a subsequent digest install replays it (see
        _KV_JOURNAL_MAX)."""
        stored = np.array(stored, np.uint32, copy=True)
        removed = np.array(removed, np.uint32, copy=True)
        with self._lock:
            self.state = self._fold_prefix_events_locked(
                self.state, slot, stored, removed)
            self._kv_journal.append(
                (time.monotonic(), slot, stored, removed))

    def evict_endpoint(self, slot: int) -> None:
        """Invalidate all prefix-cache knowledge of an endpoint slot (pod
        deleted or slot reassigned). Called by the datastore on PodDelete
        (reference pkg/lwepp/datastore/datastore.go:257-265)."""
        with self._lock:
            # Journaled events for a dead slot must not be replayed over a
            # later digest install — that would resurrect the dead pod's
            # presence bits on whatever reuses the slot.
            if any(e[1] == slot for e in self._kv_journal):
                self._kv_journal = collections.deque(
                    (e for e in self._kv_journal if e[1] != slot),
                    maxlen=self._KV_JOURNAL_MAX)
            if slot >= self.state.m:
                return  # beyond the live bucket: nothing was ever recorded
            self.state = self._evict(self.state, jnp.int32(slot))

    def clear_prefix_endpoint(self, slot: int) -> None:
        """Forget an endpoint's cached chunks WITHOUT touching its assumed
        load. The live-pod cache-reset path (vLLM emits AllBlocksCleared on
        cache reset, not pod death): the pod keeps its in-flight queue, so
        zeroing its charge would make it look idle and over-route it —
        eviction (prefix + load) is reserved for PodDelete."""
        with self._lock:
            if slot >= self.state.m:
                return  # beyond the live bucket: nothing was ever recorded
            self.state = self._clear_prefix(self.state, jnp.int32(slot))

    def debug_report(self) -> dict:
        """Scheduler zpage (/debugz/scheduler, gie_tpu/obs): the live
        blend weights, picker/profile identity, and compile-cache state.
        Lock-free on purpose — every read is a GIL-atomic reference
        (weights/state are immutable pytrees swapped whole) and the tiny
        weight scalars sync outside any lock, so this can never stall a
        pick."""
        weights = self.weights
        state = self.state
        return {
            "picker": self.cfg.picker,
            "pd_disaggregation": self.cfg.pd_disaggregation,
            "m_bucket": int(state.assumed_load.shape[0]),
            "tick": int(np.asarray(state.tick)),
            "weights": {
                f: round(float(getattr(weights, f)), 5)
                for f in weights.__dataclass_fields__
            },
            "latency_weight_ceiling": self.base_latency_weight,
            "warm_buckets": sorted(self._warm_buckets),
            "warm_inline_compiles": self.warm_inline_compiles,
        }

    def snapshot_assumed_load(self) -> np.ndarray:
        """Host copy of the assumed-load vector. Same discipline as
        export_state: the lock covers only a donation-safe DEVICE copy
        (the live buffer is deleted by the next pick's donation; the
        copy's is not), and the D2H sync runs outside it — this is on
        the metrics-exposition and autoscale-probe paths, which must not
        stall the pick hot path for a transfer (gie-lint GL002)."""
        with self._lock:
            load = jnp.copy(self.state.assumed_load)
        return np.asarray(load)

    def prefix_hot_keys(self, max_keys: int = 2048) -> np.ndarray:
        """Bounded sample of live prefix-table keys (the federation
        digest's fed.prefix export, docs/FEDERATION.md): peers fold
        these into their own tables against our imported slots so
        spilled sessions stick to the cluster already holding their
        prefix. Same lock discipline as snapshot_assumed_load: the lock
        covers only a donation-safe device copy, the D2H sync runs
        outside it (gie-lint GL002)."""
        with self._lock:
            keys = jnp.copy(self.state.prefix.keys)
        host = np.asarray(keys).reshape(-1)
        host = host[host != 0]
        return host[: max(int(max_keys), 0)].astype(np.uint32)

    # -- optional warm-restart persistence ---------------------------------
    # The reference explicitly accepts prefix-index loss on restart
    # (0602 README:93); offering a checkpoint anyway lets a restarted EPP
    # keep its cache affinity instead of relearning it from cold traffic.

    def save_state(self, directory: str) -> None:
        from gie_tpu.utils.checkpoint import save_pytree

        with self._lock:
            # Materialize under the lock: the live state's buffers are
            # donated (deleted) by the next pick; a reference snapshot
            # would intermittently fail mid-save under traffic.
            host_state = jax.tree.map(np.asarray, self.state)
        save_pytree(directory, host_state)

    def restore_state(self, directory: str) -> bool:
        from gie_tpu.utils.checkpoint import restore_pytree, restore_pytree_raw

        # The saved state was laid out for whichever M bucket was live at
        # save time; try each template until one round-trips. The next
        # pick migrates it to the current bucket as usual.
        restored = None
        for m in C.M_BUCKETS:
            restored = restore_pytree(directory, SchedState.init(m=m))
            if restored is not None and int(
                    restored.assumed_load.shape[0]) == m:
                break
            restored = None
        if restored is None:
            # Legacy layout: a checkpoint written before a SchedState
            # field existed fails the template restore above. Recover the
            # raw field dict and fill defaults for whatever is missing
            # (today: ot_v, round 5) — losing the prefix affinity the
            # checkpoint exists to preserve just because a new field
            # appeared would defeat warm restarts on every upgrade.
            raw = restore_pytree_raw(directory)
            if (not isinstance(raw, dict)
                    or "assumed_load" not in raw
                    or "prefix" not in raw):
                return False
            try:
                load = jnp.asarray(raw["assumed_load"], jnp.float32)
                m = int(load.shape[0])
                if m not in C.M_BUCKETS:
                    return False
                px = raw["prefix"]
                restored = SchedState(
                    prefix=PrefixTable(
                        keys=jnp.asarray(px["keys"], jnp.uint32),
                        present=jnp.asarray(px["present"], jnp.uint32),
                        ages=jnp.asarray(px["ages"], jnp.uint32),
                    ),
                    assumed_load=load,
                    rr=jnp.asarray(raw["rr"], jnp.uint32),
                    tick=jnp.asarray(raw["tick"], jnp.uint32),
                    ot_v=(jnp.asarray(raw["ot_v"], jnp.float32)
                          if "ot_v" in raw
                          else jnp.ones((m,), jnp.float32)),
                )
            except (KeyError, TypeError, ValueError):
                return False
        # Cross-field shape consistency (ADVICE r5 #1), on BOTH paths —
        # orbax's template restore hands back the checkpoint's own arrays,
        # so a mixed-layout checkpoint (e.g. ot_v saved at a different M
        # bucket than assumed_load) passes the width probe above. A
        # corrupted checkpoint must fail HERE with False, not later inside
        # the jitted cycle with an opaque shape error. (Shared with the
        # replication follower's digest install: check_state_shapes.)
        if not check_state_shapes(restored):
            return False
        with self._lock:
            self.state = restored
        return True

    # -- replication digest surface (gie_tpu/replication) ------------------

    def export_state(self) -> dict:
        """Flat host-array dict of the full scheduler state for the
        replication digest's "sched" section: the prefix table columns,
        the assumed-load vector, the sinkhorn warm-start duals, and the
        rr/tick counters.

        The lock is held only to enqueue DEVICE copies (donation safety:
        the live buffers are deleted by the next pick, so a bare
        reference would race — but a copy's buffers are fresh and never
        donated). The multi-MB device-to-host transfer then runs OUTSIDE
        the lock, so the leader's periodic digest refresh never stalls
        the pick hot path for the sync (unlike save_state, which is a
        rare shutdown-time call and keeps the simple form)."""
        from gie_tpu.sched.prefix import snapshot_table

        with self._lock:
            snap = jax.tree.map(jnp.copy, self.state)
        host = jax.tree.map(np.asarray, snap)
        table = snapshot_table(host.prefix)
        return {
            "prefix_keys": table["keys"],
            "prefix_present": table["present"],
            "prefix_ages": table["ages"],
            "assumed_load": host.assumed_load,
            "ot_v": host.ot_v,
            "rr": host.rr,
            "tick": host.tick,
        }

    def prepare_install(self, arrays: dict) -> Optional[SchedState]:
        """Validation half of install_state: build a SchedState from
        digest arrays and run the SAME cross-field checks as the
        checkpoint restore path, WITHOUT touching live state. Returns
        None on any malformation. Split from the commit so a multi-
        section digest can validate every section before mutating
        anything (replication manager: all-or-nothing installs)."""
        from gie_tpu.sched.prefix import table_from_arrays

        try:
            table = table_from_arrays({
                "keys": arrays["prefix_keys"],
                "present": arrays["prefix_present"],
                "ages": arrays["prefix_ages"],
            })
            if table is None:
                return None
            load = np.asarray(arrays["assumed_load"], np.float32)
            ot_v = np.asarray(arrays["ot_v"], np.float32)
            rr = np.asarray(arrays["rr"], np.uint32)
            tick = np.asarray(arrays["tick"], np.uint32)
        except (KeyError, TypeError, ValueError):
            return None
        restored = SchedState(
            prefix=table,
            assumed_load=jnp.asarray(load),
            rr=jnp.asarray(rr.reshape(()) if rr.size == 1 else rr),
            tick=jnp.asarray(tick.reshape(()) if tick.size == 1 else tick),
            ot_v=jnp.asarray(ot_v),
        )
        return restored if check_state_shapes(restored) else None

    def commit_install(self, state: SchedState) -> None:
        """Commit half: atomic swap under the lock — never inside the
        jitted cycle, and only ever with a prepare_install-validated
        state.

        Before the swap, locally journaled KV-cache events newer than the
        replay TTL are folded INTO the incoming state (ROADMAP PR 3
        follow-up): a follower's locally observed prefix ground truth —
        reported by the model servers after the leader exported this
        digest — survives the install instead of being overwritten until
        the next event push happens to repeat it."""
        with self._lock:
            now = time.monotonic()
            fresh = [e for e in self._kv_journal
                     if now - e[0] <= self._KV_REPLAY_TTL_S]
            self._kv_journal = collections.deque(
                fresh, maxlen=self._KV_JOURNAL_MAX)
            for _ts, slot, stored, removed in fresh:
                state = self._fold_prefix_events_locked(
                    state, slot, stored, removed)
            self.state = state

    def install_state(self, arrays: dict) -> bool:
        """Validated inverse of export_state (single-component form).
        Returns False (prior state kept) on any malformation."""
        prepared = self.prepare_install(arrays)
        if prepared is None:
            return False
        self.commit_install(prepared)
        return True
