"""Pick stage: scores + mask -> ordered endpoint lists + status.

Batched re-design of the reference Picker plugins (reference
docs/proposals/0845-scheduler-architecture-proposal/README.md:73-77 — exactly
one Pick per profile run) and of the protocol's ordered-fallback-list
semantics (reference docs/proposals/004-endpoint-picker-protocol/README.md:
50-82). Status semantics: 503 when a request has no eligible endpoint
(strict subsetting / no ready endpoints, 004 README:77-79), 429 when a
SHEDDABLE request is load-shed (004 README:80).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gie_tpu.sched import constants as C
from gie_tpu.sched.types import PickResult


NEG = C.NEG_SCORE

# Score quantization for tie-breaking: blended scores live in [0, 1]; deltas
# below _TIE_RESOLUTION are treated as ties and broken by rotation. The
# rotation increment stays strictly below one quantum so it can never invert
# a genuine (super-quantum) ordering, and above float32 ulp(1.0) so it is not
# absorbed.
_TIE_RESOLUTION = float(1.0 / 4096.0)            # ~2.4e-4
_TIE_EPS = _TIE_RESOLUTION / float(C.M_MAX + 1)  # ~2.4e-7 > ulp(1.0)~1.2e-7


def _topk(masked: jax.Array, k: int) -> tuple[jax.Array, jax.Array]:
    """Iterative top-k by strict threshold DESCENT.

    lax.top_k lowers to a full sort on TPU (~850 us for [1024, 512]); k
    rounds of masked reduction are plain VPU work and two orders of
    magnitude cheaper for the small k this pipeline needs. Round j takes
    the max over {x : x < bound_{j-1}} — an elementwise compare against a
    per-row scalar that fuses INTO the reduction, so no round rewrites the
    [N, M] operand (the round-5 rewrite: the previous mask-out-by-index
    form materialized a fresh [N, M] array per round; 8.5 -> 4.3 MB at
    1024x256, bit-identical picks).

    Requires pairwise-distinct in-row values to enumerate ties as separate
    entries — guaranteed for every caller: topk_picker's rotation makes
    equal scores distinct, and the sinkhorn/random paths get the
    _iota_tiebreak ulp nudge in _finalize (ADVICE r5 #4 — their Gumbel
    noise is continuous but f32-granular, so duplicate-endpoint lanes
    could still collide exactly and silently shorten the fallback list).
    The primary pick is the true argmax regardless.
    """
    vals, idxs = [], []
    bound = jnp.full(masked.shape[:-1], jnp.inf, masked.dtype)
    for _ in range(k):
        x = jnp.where(masked < bound[:, None], masked, NEG)
        i = jnp.argmax(x, axis=-1)
        v = jnp.max(x, axis=-1)
        vals.append(v)
        idxs.append(i.astype(jnp.int32))
        bound = v
    return jnp.stack(vals, axis=-1), jnp.stack(idxs, axis=-1)


def _iota_tiebreak(masked: jax.Array, mask: jax.Array) -> jax.Array:
    """Per-lane iota*ulp tiebreak (ADVICE r5 #4): bitcast the f32 scores
    to i32 and REPLACE the low ceil(log2(M)) mantissa bits with the lane
    index. In-row values become pairwise distinct BY CONSTRUCTION —
    lanes whose remaining high bits agree differ in the unique lane
    field, and lanes whose high bits differ were already further apart
    than the field can reach — so the threshold-descent _topk enumerates
    every tied lane as its own fallback entry instead of gating
    duplicates at NEG. (Merely ADDING the lane to the bits would relocate
    the defect: two lanes i<j exactly j-i ulps apart would collide.)

    Working in the bit domain makes the nudge magnitude-relative: a
    fixed additive epsilon sized for [0, 1] blends would be absorbed
    outright by the sinkhorn path's log-domain scores (ulp(-46) ~ 4e-6).
    The worst-case reorder is between values already within 2*M ulps of
    each other — far below any meaningful score difference, and strictly
    better than silently truncating the fallback list. Rewriting low
    mantissa bits cannot touch the exponent, so finite scores stay
    finite. Ineligible lanes keep the exact NEG sentinel (the
    ok-threshold compares against it).

    Shard-layout invariance (ISSUE 15, docs/MESH.md): the tiebreak is
    exactly as layout-stable as its inputs. The lane iota is GLOBAL —
    under a tp-sharded M axis GSPMD hands each shard its own global
    index block, so lane m gets the same field on every mesh shape —
    and bitcast/mask/or are elementwise, so given bit-identical scores
    (the grouped sinkhorn solve's contract) the nudged matrix is
    bit-identical too. Downstream, _topk's max/argmax reductions are
    EXACT (max has no rounding, and the field makes in-row values
    pairwise distinct, so there is no tie for a cross-shard combine to
    resolve arbitrarily) — the equivalence sweep
    (tests/test_distributed_equivalence: mesh {1,2,4,8} x picker x
    ragged-M) pins all of this bitwise."""
    m = masked.shape[-1]
    low = jnp.int32((1 << max((m - 1).bit_length(), 1)) - 1)
    lane = jnp.arange(m, dtype=jnp.int32)
    bits = jax.lax.bitcast_convert_type(masked, jnp.int32)
    bits = (bits & ~low) | lane[None, :]
    return jnp.where(
        mask, jax.lax.bitcast_convert_type(bits, jnp.float32), masked)


def finalize_from_topk(
    top_scores: jax.Array,  # f32[N, k] (NEG-filled where ineligible)
    top_idx: jax.Array,     # i32[N, k]
    mask: jax.Array,
    shed: jax.Array,
    valid: jax.Array,
) -> PickResult:
    """Status/index gating shared by every picker (including the pallas
    fused path): ok-threshold, NO_CAPACITY/SHED cascade, OK-only indices."""
    ok = top_scores > NEG / 2
    indices = jnp.where(ok, top_idx.astype(jnp.int32), -1)

    any_candidate = jnp.any(mask, axis=-1)
    status = jnp.where(any_candidate, C.Status.OK, C.Status.NO_CAPACITY)
    status = jnp.where(shed, C.Status.SHED, status)
    status = jnp.where(valid, status, C.Status.NO_CAPACITY).astype(jnp.int32)

    indices = jnp.where((status == C.Status.OK)[:, None], indices, -1)
    return PickResult(indices=indices, status=status, scores=top_scores)


def _finalize(
    masked: jax.Array,  # f32[N, M] score matrix with ineligible lanes at NEG
    mask: jax.Array,
    shed: jax.Array,
    valid: jax.Array,
    *,
    lane_tiebreak: bool = True,
) -> PickResult:
    """Shared pick postlude: top-k fallback list + status gating.

    `lane_tiebreak` applies the iota*ulp nudge so exact in-row ties still
    enumerate as separate fallback entries; topk_picker opts OUT because
    its rotation already guarantees distinctness, and a nudge of up to
    M_MAX ulps would overwhelm the _TIE_EPS-granular rotation ordering
    (breaking the round-robin fairness it exists to provide)."""
    if lane_tiebreak:
        masked = _iota_tiebreak(masked, mask)
    top_scores, top_idx = _topk(masked, C.FALLBACKS)
    return finalize_from_topk(top_scores, top_idx, mask, shed, valid)


def topk_picker(
    scores: jax.Array,   # f32[N, M_MAX]
    mask: jax.Array,     # bool[N, M_MAX]
    shed: jax.Array,     # bool[N] requests being shed (-> 429)
    valid: jax.Array,    # bool[N]
    rr: jax.Array,       # u32 tie-break counter
) -> PickResult:
    """Deterministic best-score picker with top-k fallback list.

    Scores are quantized to _TIE_RESOLUTION and ties broken by a rotating
    lane priority derived from `rr`, so equal-score endpoints round-robin
    across cycles (reference RoundRobinPicker,
    pkg/lwepp/handlers/server.go:85-101, generalized to the scored path)
    while genuine score differences always dominate.
    """
    m = scores.shape[-1]
    quantized = jnp.round(scores / _TIE_RESOLUTION) * _TIE_RESOLUTION
    lane = jnp.arange(m, dtype=jnp.uint32)
    rot = ((lane + rr) % jnp.uint32(m)).astype(jnp.float32)
    masked = jnp.where(mask, quantized + rot * _TIE_EPS, NEG)
    # The rotation already makes in-row values pairwise distinct; the
    # iota nudge would scramble its _TIE_EPS-granular ordering.
    return _finalize(masked, mask, shed, valid, lane_tiebreak=False)


def weighted_random_picker(
    scores: jax.Array,
    mask: jax.Array,
    shed: jax.Array,
    valid: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 0.05,
) -> PickResult:
    """Gumbel-top-k sampling picker.

    Spreads load across near-equal endpoints instead of herding every request
    of a cycle onto the single argmax — the batched analogue of the
    reference's weighted-random pick over normalized scores. Temperature
    scales how much score difference dominates the noise.
    """
    g = jax.random.gumbel(key, scores.shape, jnp.float32) * temperature
    masked = jnp.where(mask, scores + g, NEG)
    return _finalize(masked, mask, shed, valid)
