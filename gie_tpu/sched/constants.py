"""Scheduler tensor-layout constants.

The reference's data layer hands the scheduler a set of per-endpoint structs
(reference docs/proposals/1023-data-layer-architecture/README.md:104-164,
docs/proposals/003-model-server-protocol/README.md:28-57). The TPU-native
design flattens that into a dense `float32[M, NUM_METRICS]` tensor so one XLA
call can score every (request, endpoint) pair. This module pins the column
layout of that tensor and the global shape budget.
"""

from __future__ import annotations

import enum


class Metric(enum.IntEnum):
    """Columns of the endpoint metrics tensor.

    Names follow the model-server metrics protocol (reference
    docs/proposals/003-model-server-protocol/README.md:28-57): required gauges
    TotalQueuedRequests / TotalRunningRequests / KVCacheUtilization, optional
    BlockSize / NumBlocks, and the vllm:lora_requests_info max_lora label.
    """

    QUEUE_DEPTH = 0        # TotalQueuedRequests
    RUNNING_REQUESTS = 1   # TotalRunningRequests
    KV_CACHE_UTIL = 2      # KVCacheUtilization, in [0, 1]
    BLOCK_SIZE = 3         # optional; 0 when unreported
    NUM_BLOCKS = 4         # optional; 0 when unreported
    MAX_LORA = 5           # vllm:lora_requests_info max_lora label
    WAITING_LORA = 6       # number of waiting adapters
    METRICS_AGE_S = 7      # staleness of this row (seconds since scrape)


NUM_METRICS = len(Metric)

# Global endpoint-axis budget. The reference supports pods x up to 8 DP-rank
# target ports (api/v1/inferencepool_types.go:72-81) with an unbounded
# datastore (pkg/lwepp/datastore/datastore.go:181-193); 1024 endpoint slots
# cover the north-star 256-endpoint benchmark with 4x headroom. All device
# state (assumed load, prefix-table bitmasks) is laid out against a fixed
# axis so pod churn never changes a compiled shape — rows are masked, not
# resized. A fleet that outgrows M_MAX degrades GRACEFULLY, by design, to a
# schedulable subset: the datastore refuses the slot (the endpoint simply
# receives no traffic, re-entering via watch/resync when churn frees slots)
# and counts the refusal, which the runner surfaces as the
# endpoint_slot_overflow alert metric (runtime/metrics.py) — the compiled
# pick path itself can never see a slot id >= M_MAX.
M_MAX = 1024

# Words of a uint32 bitmask spanning M_MAX endpoints.
M_WORDS = M_MAX // 32

# Endpoint-axis buckets. Like N_BUCKETS for requests: device state and the
# compiled cycle are sized to the smallest bucket covering the live
# endpoint slots (high-water slot index), so an 8-pod pool pays for 64
# scoring lanes, the 256-endpoint north star for 256 — not M_MAX. Each
# bucket is a multiple of 32 (the packed prefix-word width) and a distinct
# compiled shape; crossing a boundary migrates state (types.resize_state),
# it never recompiles mid-cycle.
M_BUCKETS = (64, 256, 512, 1024)

# Request-axis buckets: incoming micro-batches are padded up to the nearest
# bucket so only a handful of shapes ever compile.
N_BUCKETS = (1, 8, 64, 256, 1024)

# Output-length hints arrive in TOKENS (the client's max_tokens cap, the
# decode-tokens header, or the simulator's workload cap) while the cost
# model blends prompt length in CHARS (request_cost, pd_costs). One
# conversion factor, applied at every ingestion point, keeps charge and
# release in the same unit; ~4 chars/token is the usual English-text rate.
CHARS_PER_TOKEN = 4.0

# Max rolling-hash chunks considered per request prompt (prefix-cache match
# depth, reference docs/proposals/0602-prefix-cache/README.md:95-112).
MAX_CHUNKS = 32

# Chunk-axis buckets: a wave's chunk_hashes are sliced to the smallest
# bucket covering its longest prompt's chunk count (the cycle is
# shape-polymorphic in C). Short-prompt waves — chat traffic is a few
# hundred bytes of shared system prefix — then run 8 prefix lanes per
# request instead of 32, quartering the match gather and insert scatter.
C_BUCKETS = (8, 16, MAX_CHUNKS)

# Default character-chunk size for the rolling hash. The reference leaves the
# chunk size to plugin config ("prefix plugin config",
# docs/proposals/003-model-server-protocol/README.md:33); 64 chars balances
# match granularity against table pressure.
CHUNK_BYTES = 64

# Per-endpoint resident/waiting LoRA adapter slots in the dense view
# (running_lora_adapters / waiting_lora_adapters labels, proposal 003).
LORA_SLOTS = 8

# Fallback list length returned per pick: primary + 3 fallbacks, matching the
# ordered fallback-list semantics of the endpoint-picker protocol (reference
# docs/proposals/004-endpoint-picker-protocol/README.md:50-82,
# pkg/lwepp/handlers/server.go:72-77 PickResult.Fallbacks).
FALLBACKS = 4

# Sentinel for masked/ineligible score lanes. A plain Python float on
# purpose: module-level jnp constants captured into jit dispatch ~80x
# slower on the axon backend.
NEG_SCORE = float(-1e9)

# Prefix-table slot count (power of two).
PREFIX_SLOTS = 1 << 15


class Status(enum.IntEnum):
    """Per-request scheduling outcome.

    Error codes follow the endpoint-picker protocol (reference
    docs/proposals/004-endpoint-picker-protocol/README.md:77-80): 503 when no
    eligible endpoint exists (strict subsetting included), 429 when load is
    shed for sheddable requests.
    """

    OK = 0
    NO_CAPACITY = 1   # -> HTTP 503
    SHED = 2          # -> HTTP 429


class Criticality(enum.IntEnum):
    """Request criticality bands (InferenceObjective, reference
    docs/proposals/1199-inference-objectives/README.md:64-80)."""

    CRITICAL = 0
    STANDARD = 1
    SHEDDABLE = 2


class Role(enum.IntEnum):
    """Endpoint serving role for disaggregated prefill/decode.

    The reference names disaggregated serving as roadmap item 8
    (README.md:115) and anticipates role-partitioned candidate sets in the
    scheduler's assignment informer (docs/proposals/006-scheduler/
    README.md:158 'heterogeneous server roles (prefill-heavy,
    prefill/decode split)'); neither is implemented there. Here roles are
    a first-class column of the endpoint tensor: BOTH serves the classic
    co-located path, PREFILL/DECODE partition the candidate masks of the
    dual pick (profile.scheduling_cycle with pd_disaggregation=True)."""

    BOTH = 0
    PREFILL = 1
    DECODE = 2
