"""Mesh + sharding: multi-chip scheduling and predictor training."""

from gie_tpu.parallel.mesh import (
    cycle_shardings,
    make_mesh,
    predictor_param_shardings,
    sharded_cycle,
    sharded_train_step,
)

__all__ = [
    "cycle_shardings",
    "make_mesh",
    "predictor_param_shardings",
    "sharded_cycle",
    "sharded_train_step",
]
