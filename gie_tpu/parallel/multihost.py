"""Multi-host distributed operation (the DCN scaling path).

The reference ecosystem scales its control plane by replication and its
model servers by NCCL/MPI (SURVEY.md 2.10); the TPU-native equivalents here
ride JAX's distributed runtime: `jax.distributed.initialize` forms the
multi-process system (coordination over DCN), every process contributes its
local chips to one GLOBAL mesh, and the same jitted programs (predictor
train step, scheduling cycle) run SPMD with XLA inserting cross-host
collectives.

Tested for real in tests/test_multihost.py: two OS processes form a
2-device global mesh on CPU and execute one dp-sharded predictor train step
whose gradients all-reduce across the process boundary (the CI stand-in for
ICI/DCN).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
) -> None:
    """Join the multi-process JAX system (call once, before device use).

    `coordinator_address` is "host:port" of process 0 — the jax.distributed
    analogue of the reference model servers' MPI rendezvous.
    """
    jax.distributed.initialize(
        coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh(tp: int = 1) -> Mesh:
    """("dp","tp") mesh over ALL processes' devices (layout owned by
    mesh.make_mesh; jax.devices() is already global across processes)."""
    from gie_tpu.parallel.mesh import make_mesh

    n = len(jax.devices())
    if tp <= 0 or n % tp != 0:
        raise ValueError(f"tp={tp} does not divide device count {n}")
    return make_mesh(n, tp=tp)


def host_local_batch_to_global(
    mesh: Mesh, local_batch: np.ndarray, spec: Optional[P] = None
) -> jax.Array:
    """Assemble a globally-sharded array from each process's local shard
    (each host loads its own slice — no host ever materializes the global
    batch, the multi-host data-loading contract)."""
    spec = spec if spec is not None else P("dp", *([None] * (local_batch.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_process_local_data(sharding, local_batch)


def multihost_train_step(mesh: Mesh, seed: int = 0):
    """Build (step_fn, params, opt_state) for the predictor on the global
    mesh: dp-sharded batch, replicated params; XLA all-reduces gradients
    across hosts. Optimizer hyperparameters come from the predictor config
    (same as OnlineTrainer); the sharded step is owned by
    mesh.sharded_train_step so single- and multi-host paths cannot diverge.
    """
    import optax

    from gie_tpu.models.latency import LatencyPredictor
    from gie_tpu.parallel.mesh import sharded_train_step

    predictor = LatencyPredictor()
    params = predictor.init(jax.random.PRNGKey(seed))
    tx = optax.adamw(
        predictor.cfg.learning_rate, weight_decay=predictor.cfg.weight_decay
    )
    opt_state = tx.init(params)
    step = sharded_train_step(mesh, predictor, tx)
    return step, params, opt_state
