"""Device mesh + shardings for multi-chip operation.

Parallelism map (docs/MESH.md; SURVEY.md section 2.10 — the reference is a
router, not a trainer; the honest multi-chip axes here are):

  dp — the request axis of the scheduling cycle: N pending requests
       sharded over chips. Every [N, ...] tensor (request batch, masks,
       scorer columns, the cost matrix rows, pick results) splits here.
  tp — the ENDPOINT axis: M endpoint slots sharded over chips. Every
       [M, ...] tensor (endpoint metrics, LoRA tables, assumed load, the
       sinkhorn column duals, the cost matrix columns, the packed
       prefix-presence words when divisible) splits here, so per-chip
       memory for the [N, M] score/cost tensors is O(N*M / (dp*tp)) and
       the M axis scales with chips instead of replicating onto each.
       The latency-predictor MLP's Dense kernels also split on tp
       (classic 2-layer tensor parallelism) in the training step.

Pipeline/sequence/expert parallelism have no analogue in this system: there
is no layer stack deep enough to pipeline, no sequence dimension on device
(prompts reduce to chunk-hash vectors host-side), and no experts. The design
keeps the mesh 2-D ("dp", "tp") so a deployment scales either axis by
reshaping the same program.

Where GSPMD's choices are load-bearing — the sinkhorn solve's coupled
row/column reductions — the cycle drops into an explicit shard_map with
ordered grouped all-reduces (sched/sinkhorn.py); everything else (masked
elementwise scoring, max/argmax top-k, the blend einsum over the replicated
scorer axis) is layout-exact under GSPMD by construction. The random bits
feeding the samplers are made sharding-invariant by jax_threefry_partitionable
(enabled at gie_tpu import), so sharded picks are BIT-IDENTICAL to
single-device picks: tests/test_distributed_equivalence pins it per mesh
size x picker x ragged-M.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gie_tpu.sched import constants as C
from gie_tpu.sched.profile import scheduling_cycle
from gie_tpu.sched.types import (
    EndpointBatch,
    PickResult,
    PrefixTable,
    RequestBatch,
    SchedState,
    Weights,
)


def make_mesh(n_devices: Optional[int] = None, tp: Optional[int] = None) -> Mesh:
    """2-D ("dp", "tp") mesh over the first n devices. `tp` defaults to 2
    when the device count allows, else 1."""
    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if n > len(devices):
        raise ValueError(
            f"requested a {n}-device mesh but only {len(devices)} "
            f"device(s) are available"
        )
    devices = devices[:n]
    if tp is None:
        tp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // tp
    grid = np.asarray(devices).reshape(dp, tp)
    return Mesh(grid, ("dp", "tp"))


def state_shardings(mesh: Mesh):
    """NamedShardings for the SchedState pytree under `mesh`: the
    endpoint-axis vectors (assumed load, sinkhorn column duals) tp-shard —
    the duals' explicit sharding is what lets the warm start flow through
    sharded_cycle wave to wave without an implicit replicate/reshard pair
    around every cycle — and the packed prefix-presence matrix always
    tp-shards: on the WORD axis when every M bucket's word count divides
    tp (tp <= 2: the smallest bucket packs M_BUCKETS[0]/32 words and one
    jitted cycle must accept every bucket), otherwise on the TABLE-SLOT
    axis (PREFIX_SLOTS = 32768 rows divides any power-of-two tp; the
    match gather and insert scatter both index rows independently, so the
    slot cut costs the same collectives the replicated fallback paid in
    full-table broadcasts — closes the PR 15 'present replicates at
    tp > 2' residual). Table keys/ages are M-independent and replicate;
    rr and tick are scalars."""
    repl = NamedSharding(mesh, P())
    ep = NamedSharding(mesh, P("tp"))
    tp = int(mesh.shape["tp"])
    words_ok = (C.M_BUCKETS[0] // 32) % tp == 0
    present = NamedSharding(
        mesh, P(None, "tp") if words_ok else P("tp", None))
    return SchedState(
        prefix=PrefixTable(keys=repl, present=present, ages=repl),
        assumed_load=ep,
        rr=repl,
        tick=repl,
        ot_v=ep,
    )


def cycle_shardings(mesh: Mesh):
    """in_shardings for profile.scheduling_cycle under `mesh`: requests
    dp-sharded on their leading axis, endpoint tensors tp-sharded on the
    M axis (the subset mask shards on both), scheduler state per
    state_shardings, weights / rng key replicated. GSPMD turns the
    cross-shard contributions (dense state scatters, top-k reductions)
    into ICI collectives; the sinkhorn solve's float-sum collectives are
    explicit in sched/sinkhorn.py."""
    repl = NamedSharding(mesh, P())
    ep_leading = NamedSharding(mesh, P("tp"))
    ep_matrix = NamedSharding(mesh, P("tp", None))

    def dp_leading(x):
        return NamedSharding(mesh, P("dp", *([None] * (np.ndim(x) - 1))))

    req_tmpl = RequestBatch.empty(8)
    req_sh = jax.tree.map(dp_leading, req_tmpl)
    # The candidate-subset hint spans requests x endpoints: both axes cut.
    req_sh = req_sh.replace(subset_mask=NamedSharding(mesh, P("dp", "tp")))

    eps_sh = EndpointBatch(
        metrics=ep_matrix,
        valid=ep_leading,
        lora_active=ep_matrix,
        lora_waiting=ep_matrix,
        role=ep_leading,
    )
    return (
        state_shardings(mesh),                                # state
        req_sh,                                               # requests
        eps_sh,                                               # endpoints
        jax.tree.map(lambda _: repl, Weights.default()),      # weights
        repl,                                                 # rng key
    )


def sharded_cycle(mesh: Mesh, cfg, predictor_fn=None, donate_state: bool = False):
    """Jit the scheduling cycle dp x tp-sharded over `mesh`. Predictor
    params (the trailing argument) are replicated. out_shardings are
    pinned so the state round-trips in its input layout — donation can
    alias the buffers (the Scheduler facade passes donate_state=True; its
    state updates in place on device) and the warm-start duals never
    bounce through a replicated intermediate between waves."""
    fn = functools.partial(
        scheduling_cycle, cfg=cfg, predictor_fn=predictor_fn, mesh=mesh)
    repl = NamedSharding(mesh, P())
    dp1 = NamedSharding(mesh, P("dp"))
    dp2 = NamedSharding(mesh, P("dp", None))
    in_sh = cycle_shardings(mesh) + (repl,)
    result_sh = PickResult(
        indices=dp2,
        status=dp1,
        scores=dp2,
        prefill=dp1 if getattr(cfg, "pd_disaggregation", False) else None,
        affinity=dp2 if getattr(cfg, "record_affinity", False) else None,
        # The hierarchical fleet cycle never runs under sharded_cycle (its
        # compressed block is deliberately unsharded) — dense results
        # carry fleet=None, matching the dense pytree.
        fleet=None,
    )
    out_sh = (result_sh, state_shardings(mesh))
    donate = (0,) if donate_state else ()
    return jax.jit(
        fn, in_shardings=in_sh, out_shardings=out_sh, donate_argnums=donate)


def predictor_param_shardings(mesh: Mesh, params):
    """Tensor-parallel layout for the LatencyMLP: even-indexed Dense kernels
    column-split P(None, "tp"), odd-indexed row-split P("tp", None) — the
    standard alternating MLP sharding, one psum per row-split matmul,
    inserted by XLA. Biases and non-matrix leaves stay replicated."""

    def spec_for(path: str, x) -> P:
        if getattr(x, "ndim", 0) == 2 and "Dense_" in path:
            idx = int(path.split("Dense_")[1].split("'")[0].split("]")[0])
            return P(None, "tp") if idx % 2 == 0 else P("tp", None)
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, x: NamedSharding(
            mesh, spec_for(jax.tree_util.keystr(path), x)
        ),
        params,
    )


def sharded_train_step(mesh: Mesh, predictor, tx: optax.GradientTransformation):
    """Jit the predictor train step: batch dp-sharded, params tp-sharded
    (layout inferred from the passed-in params' shardings)."""
    from gie_tpu.models.latency import make_train_step

    data = NamedSharding(mesh, P("dp", None))
    slots = NamedSharding(mesh, P("dp"))
    return make_train_step(
        predictor, tx, in_shardings=(None, None, data, slots, data, data)
    )
