"""Device mesh + shardings for multi-chip operation.

Parallelism map (SURVEY.md section 2.10 — the reference is a router, not a
trainer; the honest multi-chip axes here are):

  dp — the request axis of the scheduling cycle: N pending requests scored
       against all endpoints, sharded over chips; XLA inserts the all-gather
       of picks and the reduction of the (replicated) state updates over
       ICI. This is the "pjit over the request x endpoint score matrix"
       sharding BASELINE.json's north star names.
  tp — the hidden dimension of the latency-predictor MLP: Dense kernels
       split column-/row-wise so its matmuls ride the MXU of every chip
       (classic 2-layer tensor parallelism; XLA adds the psum).

Pipeline/sequence/expert parallelism have no analogue in this system: there
is no layer stack deep enough to pipeline, no sequence dimension on device
(prompts reduce to chunk-hash vectors host-side), and no experts. The design
keeps the mesh 2-D ("dp", "tp") so a deployment scales either axis by
reshaping the same program.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gie_tpu.sched.profile import scheduling_cycle
from gie_tpu.sched.types import EndpointBatch, RequestBatch, SchedState, Weights


def make_mesh(n_devices: Optional[int] = None, tp: Optional[int] = None) -> Mesh:
    """2-D ("dp", "tp") mesh over the first n devices. `tp` defaults to 2
    when the device count allows, else 1."""
    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if n > len(devices):
        raise ValueError(
            f"requested a {n}-device mesh but only {len(devices)} "
            f"device(s) are available"
        )
    devices = devices[:n]
    if tp is None:
        tp = 2 if n % 2 == 0 and n >= 2 else 1
    dp = n // tp
    grid = np.asarray(devices).reshape(dp, tp)
    return Mesh(grid, ("dp", "tp"))


def cycle_shardings(mesh: Mesh):
    """in_shardings for profile.scheduling_cycle under `mesh`: requests
    dp-sharded on their leading axis, endpoint tensors / scheduler state /
    weights / key replicated. GSPMD turns the dp-sharded contributions to
    the dense state scatters into ICI collectives."""
    repl = NamedSharding(mesh, P())

    def dp_leading(x):
        return NamedSharding(mesh, P("dp", *([None] * (np.ndim(x) - 1))))

    return (
        jax.tree.map(lambda _: repl, SchedState.init()),          # state
        jax.tree.map(dp_leading, RequestBatch.empty(8)),          # requests
        jax.tree.map(lambda _: repl, EndpointBatch.empty()),      # endpoints
        jax.tree.map(lambda _: repl, Weights.default()),          # weights
        repl,                                                     # rng key
    )


def sharded_cycle(mesh: Mesh, cfg, predictor_fn=None, donate_state: bool = False):
    """Jit the scheduling cycle with dp-sharded requests over `mesh`.
    Predictor params (the trailing argument) are replicated. The Scheduler
    facade passes donate_state=True (its state buffers update in place);
    equivalence tests keep the default so inputs stay readable."""
    fn = functools.partial(scheduling_cycle, cfg=cfg, predictor_fn=predictor_fn)
    repl = NamedSharding(mesh, P())
    in_sh = cycle_shardings(mesh) + (repl,)
    donate = (0,) if donate_state else ()
    return jax.jit(fn, in_shardings=in_sh, donate_argnums=donate)


def predictor_param_shardings(mesh: Mesh, params):
    """Tensor-parallel layout for the LatencyMLP: even-indexed Dense kernels
    column-split P(None, "tp"), odd-indexed row-split P("tp", None) — the
    standard alternating MLP sharding, one psum per row-split matmul,
    inserted by XLA. Biases and non-matrix leaves stay replicated."""

    def spec_for(path: str, x) -> P:
        if getattr(x, "ndim", 0) == 2 and "Dense_" in path:
            idx = int(path.split("Dense_")[1].split("'")[0].split("]")[0])
            return P(None, "tp") if idx % 2 == 0 else P("tp", None)
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, x: NamedSharding(
            mesh, spec_for(jax.tree_util.keystr(path), x)
        ),
        params,
    )


def sharded_train_step(mesh: Mesh, predictor, tx: optax.GradientTransformation):
    """Jit the predictor train step: batch dp-sharded, params tp-sharded
    (layout inferred from the passed-in params' shardings)."""
    from gie_tpu.models.latency import make_train_step

    data = NamedSharding(mesh, P("dp", None))
    slots = NamedSharding(mesh, P("dp"))
    return make_train_step(
        predictor, tx, in_shardings=(None, None, data, slots, data, data)
    )
