"""Small shared utilities (reference pkg/common + pkg/lwepp/util)."""
