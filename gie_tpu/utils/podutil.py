"""Pod readiness + pool mapping helpers (reference pkg/lwepp/util/pod/pod.go
and pkg/lwepp/util/pool/pool.go)."""

from __future__ import annotations

from gie_tpu.api.types import InferencePool
from gie_tpu.datastore.objects import EndpointPool, Pod


def is_pod_ready(pod: Pod) -> bool:
    """Ready condition true and not terminating (reference pod.go:24-36 +
    pod_reconciler.go deletionTimestamp eviction)."""
    return pod.ready and pod.deletionTimestamp is None and bool(pod.ip)


def to_endpoint_pool(pool: InferencePool) -> EndpointPool:
    """InferencePool -> scheduler-facing EndpointPool (reference
    pkg/lwepp/util/pool/pool.go:24-43)."""
    return EndpointPool(
        selector=dict(pool.spec.selector.matchLabels),
        target_ports=[p.number for p in pool.spec.targetPorts],
        namespace=pool.metadata.namespace,
        app_protocol=pool.spec.appProtocol,
    )
