"""Path resolution for the native/ fast-path libraries.

One place encodes the variant scheme: ``GIE_NATIVE_ASAN=1`` selects the
``make -C native asan`` sanitizer build (``libgie*-asan.so`` — LD_PRELOAD
libasan first; docs/ANALYSIS.md), so the whole Python parity suite can
run under ASan/UBSan. A future ``-tsan`` variant (ROADMAP item 7) slots
in here, not in every loader.
"""

from __future__ import annotations

import os

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def native_lib_path(stem: str) -> str:
    """Absolute path of ``native/lib<stem>[-asan].so`` for this tree."""
    # Value check, not presence: GIE_NATIVE_ASAN=0 must mean OFF (the
    # -asan .so fails to load without LD_PRELOADed libasan, and every
    # loader would silently fall back to the slow pure-Python path).
    asan = os.environ.get("GIE_NATIVE_ASAN", "") not in ("", "0")
    suffix = "-asan" if asan else ""
    return os.path.join(_REPO, "native", f"lib{stem}{suffix}.so")
