"""Shared HBM-traffic measurement for the compiled scheduling cycle.

One workload recipe, two consumers: `hack/cost_analysis.py` (the
developer-facing report) and `tests/test_cost_budget.py` (the CI gate) —
the gate's ceilings were calibrated against this exact fixture, so the
two must never drift apart.

The <=50 us pick-latency target (BASELINE.md) is an HBM-bandwidth budget
in disguise: one v5e moves ~819 GB/s, so bytes-accessed of the compiled
HLO is the first-order latency model for this memory-bound program.
"""

from __future__ import annotations

import functools

import jax
import numpy as np


def cycle_cost(cfg, n: int = 1024, m: int = 256) -> dict[str, float]:
    """-> {"flops": F, "bytes": B} of the jitted scheduling cycle on the
    north-star workload (shared system prompts, mixed LoRA ids, bucketed
    chunk axis — the same shaping the batching layer produces live).
    Raises if the backend's cost analysis stops reporting either metric:
    a silently-absent metric would turn the CI gate vacuous."""
    from gie_tpu.sched.profile import scheduling_cycle
    from gie_tpu.sched.types import SchedState, Weights, chunk_bucket_for
    from gie_tpu.utils.testing import make_endpoints, make_requests

    rng = np.random.default_rng(0)
    eps = make_endpoints(
        m, queue=rng.integers(0, 50, m).tolist(),
        kv=rng.uniform(0, 0.95, m).tolist(), max_lora=8, m_slots=m)
    base = b"SYSTEM: task %d. "
    prompts = [(base % (i % 16)) * 6 + b"u%d" % i for i in range(n)]
    reqs = make_requests(
        n, prompts=prompts, lora_id=rng.integers(-1, 12, n).tolist(),
        m_slots=m)
    cb = chunk_bucket_for(int(np.asarray(reqs.n_chunks).max()))
    reqs = reqs.replace(chunk_hashes=reqs.chunk_hashes[:, :cb])
    # donate_argnums matches production (Scheduler jits the cycle with the
    # state donated): scatters update in place instead of copying their
    # operands, and the model must count the traffic the shipped program
    # actually pays (29.6 -> 27.5 MB on the round-5 default cycle).
    fn = jax.jit(functools.partial(
        scheduling_cycle, cfg=cfg, predictor_fn=None), donate_argnums=(0,))
    ca = fn.lower(
        SchedState.init(m=m), reqs, eps, Weights.default(),
        jax.random.PRNGKey(0), None,
    ).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    if "bytes accessed" not in ca or "flops" not in ca:
        raise RuntimeError(
            "backend cost analysis no longer reports flops/bytes accessed "
            f"(keys: {sorted(ca)[:20]}) — the HBM-budget gate would pass "
            "vacuously; update gie_tpu/utils/costmodel.py for the new API")
    return {"flops": float(ca["flops"]), "bytes": float(ca["bytes accessed"])}
