"""Shared orbax checkpoint helpers.

One implementation of the save/restore pattern used by the latency
predictor and the scheduler warm-restart path. `save_pytree` materializes
leaves to host BEFORE serializing: callers' live pytrees may alias device
buffers that donating jits delete concurrently, so a reference snapshot
would intermittently fail mid-save under traffic.
"""

from __future__ import annotations

import os

import jax
import numpy as np


def save_pytree(directory: str, tree) -> None:
    import orbax.checkpoint as ocp

    host_tree = jax.tree.map(np.asarray, tree)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(directory), host_tree, force=True)


def restore_pytree(directory: str, template):
    """Restore into `template`'s structure; returns the restored tree or
    None when the directory is missing/unreadable."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    if not os.path.isdir(path):
        return None
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(path, item=template)
    except Exception:
        return None


def restore_pytree_raw(directory: str):
    """Restore WITHOUT a template: returns the checkpoint's own nested
    dict (field-name keyed), or None when missing/unreadable. The
    migration hook for checkpoints whose saved structure predates a new
    state field — the caller inspects the dict and fills defaults."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    if not os.path.isdir(path):
        return None
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(path)
    except Exception:
        return None
