"""Builder-pattern test fixtures.

Analogue of the reference's PodWrapper/InferencePoolWrapper builders
(pkg/lwepp/util/testing/wrappers.go:30-166): compact constructors for dense
scheduler inputs used across unit tests, conformance, and benchmarks.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from gie_tpu.sched import constants as C
from gie_tpu.sched.hashing import batch_chunk_hashes
from gie_tpu.sched.types import EndpointBatch, RequestBatch


def make_endpoints(
    m: int,
    *,
    queue: Optional[Sequence[float]] = None,
    kv: Optional[Sequence[float]] = None,
    running: Optional[Sequence[float]] = None,
    max_lora: float = 0.0,
    lora_active: Optional[Sequence[Sequence[int]]] = None,
    lora_waiting: Optional[Sequence[Sequence[int]]] = None,
    role: Optional[Sequence[int]] = None,
    m_slots: int = C.M_MAX,
) -> EndpointBatch:
    """Build an EndpointBatch with `m` valid endpoint slots laid out on an
    `m_slots`-wide axis (an M bucket; defaults to M_MAX so existing tests
    keep their shapes)."""
    if m > m_slots:
        raise ValueError(f"{m} endpoints do not fit m_slots={m_slots}")
    metrics = np.zeros((m_slots, C.NUM_METRICS), np.float32)
    if queue is not None:
        metrics[:m, C.Metric.QUEUE_DEPTH] = np.asarray(queue, np.float32)
    if kv is not None:
        metrics[:m, C.Metric.KV_CACHE_UTIL] = np.asarray(kv, np.float32)
    if running is not None:
        metrics[:m, C.Metric.RUNNING_REQUESTS] = np.asarray(running, np.float32)
    metrics[:m, C.Metric.MAX_LORA] = max_lora

    active = np.full((m_slots, C.LORA_SLOTS), -1, np.int32)
    waiting = np.full((m_slots, C.LORA_SLOTS), -1, np.int32)
    for table, src in ((active, lora_active), (waiting, lora_waiting)):
        if src is not None:
            for i, ids in enumerate(src):
                for j, a in enumerate(ids):
                    table[i, j] = a

    valid = np.zeros((m_slots,), bool)
    valid[:m] = True
    roles = np.zeros((m_slots,), np.int32)
    if role is not None:
        roles[:m] = np.asarray(role, np.int32)
    return EndpointBatch(
        metrics=jnp.asarray(metrics),
        valid=jnp.asarray(valid),
        lora_active=jnp.asarray(active),
        lora_waiting=jnp.asarray(waiting),
        role=jnp.asarray(roles),
    )


def make_requests(
    n: int,
    *,
    prompts: Optional[Sequence[bytes]] = None,
    lora_id: Optional[Sequence[int]] = None,
    criticality: Optional[Sequence[int]] = None,
    subset: Optional[Sequence[Optional[Sequence[int]]]] = None,
    prompt_len: Optional[Sequence[float]] = None,
    m_slots: int = C.M_MAX,
) -> RequestBatch:
    """Build a RequestBatch of `n` valid requests.

    `subset[i]` = endpoint-slot allowlist for request i (strict subsetting
    hint), or None for "no hint".
    """
    valid = np.ones((n,), bool)
    lora = np.asarray(lora_id, np.int32) if lora_id is not None else np.full((n,), -1, np.int32)
    crit = (
        np.asarray(criticality, np.int32)
        if criticality is not None
        else np.full((n,), C.Criticality.STANDARD, np.int32)
    )
    if prompts is not None:
        hashes, counts = batch_chunk_hashes(list(prompts))
        plen = np.asarray([len(p) for p in prompts], np.float32)
    else:
        hashes = np.zeros((n, C.MAX_CHUNKS), np.uint32)
        counts = np.zeros((n,), np.int32)
        plen = np.zeros((n,), np.float32)
    if prompt_len is not None:
        plen = np.asarray(prompt_len, np.float32)

    mask = np.ones((n, m_slots), bool)
    hint = np.zeros((n,), bool)
    if subset is not None:
        for i, allow in enumerate(subset):
            if allow is None:
                continue
            hint[i] = True
            mask[i] = False
            for s in allow:
                mask[i, s] = True

    return RequestBatch(
        valid=jnp.asarray(valid),
        lora_id=jnp.asarray(lora),
        criticality=jnp.asarray(crit),
        prompt_len=jnp.asarray(plen),
        decode_len=jnp.zeros((n,), jnp.float32),
        chunk_hashes=jnp.asarray(hashes),
        n_chunks=jnp.asarray(counts),
        subset_mask=jnp.asarray(mask),
    )
