"""LoRA adapter-name interning.

The scheduler's dense tensors carry adapter IDs (i32); adapter names arrive
as strings from two directions — request model names (proposal 003 "model
argument") and scraped `running_lora_adapters` labels. One shared registry
keeps the mapping consistent across both so affinity matching works.
"""

from __future__ import annotations

import threading


class LoraRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ids: dict[str, int] = {}

    def id_for(self, name: str) -> int:
        name = name.strip()
        if not name:
            return -1
        with self._lock:
            if name not in self._ids:
                self._ids[name] = len(self._ids) + 1
            return self._ids[name]

    def ids_for(self, names: list[str]) -> list[int]:
        return [self.id_for(n) for n in names if n.strip()]
