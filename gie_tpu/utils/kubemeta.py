"""Resource identity helpers (reference pkg/common/kubemeta.go:28-36)."""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class GKNN:
    """Group/Kind + Namespace/Name identity of a resource."""

    group: str
    kind: str
    namespace: str
    name: str

    def __str__(self) -> str:
        return f"{self.group}/{self.kind}/{self.namespace}/{self.name}"
