"""Fused Sinkhorn-iterations pallas kernel.

The OT picker alternates row normalization and column capping over the
[N, M] transport plan `iters` times (gie_tpu/sched/sinkhorn.py). Under XLA
each iteration's plan round-trips HBM; this kernel keeps the whole plan in
VMEM (4 MB even at the full 1024x1024 f32 axis — under the ~16 MB budget)
and runs the full loop on-chip, writing HBM once.

Single-program kernel (no grid): the column cap couples every row, so the
plan cannot tile over N without cross-tile reductions; holding it resident
is both simplest and fastest at these shapes.

Parity with the lax.scan reference is tested in interpret mode; behind
ProfileConfig(use_pallas_sinkhorn=True). Default off on merit: compiled
on the real chip (late round 2 — the axon tunnel's earlier pallas hang is
gone) the full sinkhorn cycle measures at par with the XLA path (~37-44 us
at 1024x256), so the VMEM-resident loop is a backend-tuning option, not a
default. See fused_topk.py for the measurement history.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(k_ref, cap_ref, v_ref, out_ref, v_out_ref, *, iters: int):
    cap = cap_ref[0, :]                                   # [M]

    def body(_, carry):
        p, v = carry
        row = jnp.sum(p, axis=1, keepdims=True)
        p = jnp.where(row > 0, p / row, p)
        col = jnp.sum(p, axis=0)
        scale = jnp.where(col > cap, cap / jnp.maximum(col, 1e-9), 1.0)
        return p * scale[None, :], v * scale

    # Warm start: seed the plan with the carried column duals (matrix form
    # of the dual iteration — p_t = diag(u_t) K diag(v_t) with v_0 = v_init
    # — so the iterates match sinkhorn.py's two-matvec reference exactly).
    # The dual vector rides through the loop as the running product of
    # column scales, giving the caller the same v_out the dual form yields.
    plan, v = jax.lax.fori_loop(
        0, iters, body, (k_ref[:] * v_ref[0, :][None, :], v_ref[0, :]))
    row = jnp.sum(plan, axis=1, keepdims=True)
    out_ref[:] = jnp.where(row > 0, plan / row, plan)
    v_out_ref[0, :] = v


@functools.partial(jax.jit, static_argnames=("iters", "interpret"))
def fused_sinkhorn_plan(
    kernel_matrix: jax.Array,  # f32[N, M] masked Gibbs weights (0 = masked)
    cap: jax.Array,            # f32[M] per-endpoint wave capacity
    v_init: jax.Array = None,  # f32[M] warm-start column duals (None = cold)
    *,
    iters: int = 8,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """-> (row-normalized transport plan f32[N, M], column duals f32[M])."""
    n, m = kernel_matrix.shape
    if v_init is None:
        v_init = jnp.ones((m,), jnp.float32)
    plan, v_out = pl.pallas_call(
        functools.partial(_kernel, iters=iters),
        in_specs=[
            pl.BlockSpec((n, m), lambda: (0, 0)),
            pl.BlockSpec((1, m), lambda: (0, 0)),
            pl.BlockSpec((1, m), lambda: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((n, m), lambda: (0, 0)),
            pl.BlockSpec((1, m), lambda: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, m), jnp.float32),
            jax.ShapeDtypeStruct((1, m), jnp.float32),
        ],
        interpret=interpret,
    )(kernel_matrix, cap.reshape(1, m), v_init.reshape(1, m))
    return plan, v_out.reshape(m)
