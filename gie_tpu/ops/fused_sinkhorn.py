"""Fused Sinkhorn-iterations pallas kernel.

The OT picker alternates row normalization and column capping over the
[N, M] transport plan `iters` times (gie_tpu/sched/sinkhorn.py). Under XLA
each iteration's plan round-trips HBM; this kernel keeps the whole plan in
VMEM (4 MB even at the full 1024x1024 f32 axis — under the ~16 MB budget)
and runs the full loop on-chip, writing HBM once.

Single-program kernel (no grid): the column cap couples every row, so the
plan cannot tile over N without cross-tile reductions; holding it resident
is both simplest and fastest at these shapes.

Parity with the lax.scan reference is tested in interpret mode; behind
ProfileConfig(use_pallas_sinkhorn=True). Default off on merit: compiled
on the real chip (late round 2 — the axon tunnel's earlier pallas hang is
gone) the full sinkhorn cycle measures at par with the XLA path (~37-44 us
at 1024x256), so the VMEM-resident loop is a backend-tuning option, not a
default. See fused_topk.py for the measurement history.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _kernel(k_ref, cap_ref, out_ref, *, iters: int):
    cap = cap_ref[0, :]                                   # [M]

    def body(_, p):
        row = jnp.sum(p, axis=1, keepdims=True)
        p = jnp.where(row > 0, p / row, p)
        col = jnp.sum(p, axis=0)
        scale = jnp.where(col > cap, cap / jnp.maximum(col, 1e-9), 1.0)
        return p * scale[None, :]

    plan = jax.lax.fori_loop(0, iters, body, k_ref[:])
    row = jnp.sum(plan, axis=1, keepdims=True)
    out_ref[:] = jnp.where(row > 0, plan / row, plan)


@functools.partial(jax.jit, static_argnames=("iters", "interpret"))
def fused_sinkhorn_plan(
    kernel_matrix: jax.Array,  # f32[N, M] masked Gibbs weights (0 = masked)
    cap: jax.Array,            # f32[M] per-endpoint wave capacity
    *,
    iters: int = 8,
    interpret: bool = False,
) -> jax.Array:
    """-> row-normalized transport plan f32[N, M]."""
    n, m = kernel_matrix.shape
    return pl.pallas_call(
        functools.partial(_kernel, iters=iters),
        in_specs=[
            pl.BlockSpec((n, m), lambda: (0, 0)),
            pl.BlockSpec((1, m), lambda: (0, 0)),
        ],
        out_specs=pl.BlockSpec((n, m), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, m), jnp.float32),
        interpret=interpret,
    )(kernel_matrix, cap.reshape(1, m))
