"""Fused scorer-blend + top-k pallas kernel.

The scheduling cycle's pick stage consumes S scorer columns [S, N, M], a
weight vector [S] and an eligibility mask [N, M], and needs the top-k
(scores, indices) per request row. The XLA path materializes the blended
[N, M] matrix to HBM and re-reads it k times for the iterative arg-max; this
kernel fuses blend + mask + k rounds of (max, index-extract, mask-out) into
one VMEM-resident pass per N-tile — each scorer column is read exactly once
from HBM and nothing [N, M]-shaped is written back.

Layout: grid over N tiles; each program holds its [S, BN, M] column slab and
a [BN, M] working copy in VMEM. Index extraction uses
min(where(x == rowmax, iota, M)) (first-max tie-break, matching jnp.argmax
semantics) — pure VPU reductions, no sort.

Used behind ProfileConfig(use_pallas_topk=True); parity with the reference
jnp implementation is tested in interpret mode on CPU.

NOTE (history): in rounds 1-2 pallas_call compilation through this
container's axon remote-compile tunnel hung indefinitely; re-tested later
in round 2 it compiles in <1 s and the kernel runs on the real chip with
EXACT pick parity against the XLA path at the north-star shape
(1024x256, k=4). Measured cycle time is at par with XLA (~40 us — XLA
already fuses this pattern well), so the flag stays off by default on
merit, not environment: enable it where profiling on the target backend
shows the single-HBM-pass layout winning (larger S, wider M).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gie_tpu.sched.constants import NEG_SCORE as NEG


def _kernel(stacked_ref, wvec_ref, mask_ref, vals_ref, idxs_ref, *, k: int):
    s = stacked_ref.shape[0]
    bn, m = mask_ref.shape
    # Blend: sum_s w[s] * col[s], normalized by sum(w) (profile semantics).
    w = wvec_ref[:]                                   # [S, 1] f32 (SMEM-ish)
    total = jnp.zeros((bn, m), jnp.float32)
    for si in range(s):
        total = total + w[si, 0] * stacked_ref[si]
    total = total / jnp.maximum(jnp.sum(w), 1e-6)
    x = jnp.where(mask_ref[:], total, NEG)

    iota = jax.lax.broadcasted_iota(jnp.int32, (bn, m), 1)
    for round_ in range(k):
        rowmax = jnp.max(x, axis=1, keepdims=True)            # [BN, 1]
        is_max = x == rowmax
        idx = jnp.min(jnp.where(is_max, iota, m), axis=1, keepdims=True)
        vals_ref[:, round_] = rowmax[:, 0]
        idxs_ref[:, round_] = idx[:, 0]
        x = jnp.where(iota == idx, NEG, x)


@functools.partial(jax.jit, static_argnames=("k", "block_n", "interpret"))
def fused_blend_topk(
    stacked: jax.Array,  # f32[S, N, M]
    wvec: jax.Array,     # f32[S]
    mask: jax.Array,     # bool[N, M]
    *,
    k: int = 4,
    block_n: int = 128,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """-> (values f32[N, k], indices i32[N, k]); ineligible rows yield NEG
    values (callers translate to -1 like pickers._finalize)."""
    s, n, m = stacked.shape
    block_n = min(block_n, n)
    if n % block_n != 0:
        raise ValueError(f"N={n} must be divisible by block_n={block_n}")
    grid = (n // block_n,)
    kernel = functools.partial(_kernel, k=k)
    vals, idxs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((s, block_n, m), lambda i: (0, i, 0)),
            pl.BlockSpec((s, 1), lambda i: (0, 0)),
            pl.BlockSpec((block_n, m), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, k), lambda i: (i, 0)),
            pl.BlockSpec((block_n, k), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, k), jnp.float32),
            jax.ShapeDtypeStruct((n, k), jnp.int32),
        ],
        interpret=interpret,
    )(stacked, wvec.reshape(s, 1), mask)
    return vals, idxs
