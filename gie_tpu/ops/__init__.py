"""Low-level TPU kernels (pallas)."""


def interpret_default() -> bool:
    """One policy for all pallas ops: compile only on real TPU backends,
    interpret elsewhere (CPU tests; the axon tunnel's pallas remote compile
    hangs — see fused_topk.py)."""
    import jax

    return jax.default_backend() != "tpu"


from gie_tpu.ops.fused_topk import fused_blend_topk  # noqa: E402

__all__ = ["fused_blend_topk", "interpret_default"]
