"""Low-level TPU kernels (pallas)."""

from gie_tpu.ops.fused_topk import fused_blend_topk

__all__ = ["fused_blend_topk"]
