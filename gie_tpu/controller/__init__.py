"""Reconcile layer: watch events -> datastore updates."""

from gie_tpu.controller.cluster import FakeCluster, WatchEvent
from gie_tpu.controller.reconcilers import (
    InferencePoolReconciler,
    PodReconciler,
    RequeueAfter,
)

__all__ = [
    "FakeCluster",
    "WatchEvent",
    "InferencePoolReconciler",
    "PodReconciler",
    "RequeueAfter",
]
