"""Multi-cluster inference: export annotation -> InferencePoolImport.

Port of reference docs/proposals/1374-multi-cluster-inference/README.md:36-53
and the InferencePoolImport API (apix/v1alpha1): a pool annotated
`inference.networking.x-k8s.io/export: ClusterSet` is exported from its home
cluster; the multi-cluster controller materializes a same-name
InferencePoolImport in every OTHER member cluster, recording the exporting
cluster(s) in status.controllers, and maintains the pool's Exported
condition (Exported / NotRequested / NotSupported,
reference api/v1/inferencepool_types.go:352-379).
"""

from __future__ import annotations

from typing import Optional

from gie_tpu.api import types as api

CONTROLLER_NAME = "gie-tpu.inference.networking.k8s.io/multicluster"


class ClusterSet:
    """A named set of member clusters, each holding pools and imports."""

    def __init__(self, members: list[str]):
        self.members = list(members)
        # (cluster, namespace, name) -> object
        self.pools: dict[tuple[str, str, str], api.InferencePool] = {}
        self.imports: dict[tuple[str, str, str], api.InferencePoolImport] = {}

    def apply_pool(self, cluster: str, pool: api.InferencePool) -> None:
        if cluster not in self.members:
            raise ValueError(f"unknown member cluster {cluster!r}")
        pool.validate()
        self.pools[(cluster, pool.metadata.namespace, pool.metadata.name)] = pool
        self.reconcile()

    def delete_pool(self, cluster: str, namespace: str, name: str) -> None:
        self.pools.pop((cluster, namespace, name), None)
        self.reconcile()

    def get_import(
        self, cluster: str, namespace: str, name: str
    ) -> Optional[api.InferencePoolImport]:
        return self.imports.get((cluster, namespace, name))

    # ------------------------------------------------------------------ #

    def reconcile(self) -> None:
        """Recompute all imports + Exported conditions from pool state."""
        desired: dict[tuple[str, str, str], list[str]] = {}
        for (cluster, ns, name), pool in self.pools.items():
            raw = pool.metadata.annotations.get(api.EXPORT_ANNOTATION)
            exported = raw == api.EXPORT_SCOPE_CLUSTERSET
            # Exported condition on the pool itself; a present-but-unknown
            # scope is NotSupported, absence is NotRequested
            # (reference inferencepool_types.go:352-379 reason set).
            self._set_exported_condition(pool, exported, raw)
            if not exported:
                continue
            for member in self.members:
                if member == cluster:
                    continue
                desired.setdefault((member, ns, name), []).append(cluster)

        # Materialize / update imports.
        for key, exporting in desired.items():
            member, ns, name = key
            imp = self.imports.get(key)
            if imp is None:
                imp = api.InferencePoolImport(
                    metadata=api.ObjectMeta(name=name, namespace=ns)
                )
                self.imports[key] = imp
            imp.status = api.InferencePoolImportStatus(
                controllers=[
                    api.ImportController(
                        name=CONTROLLER_NAME,
                        exportingClusters=[
                            api.ExportingCluster(name=c)
                            for c in sorted(exporting)
                        ],
                    )
                ]
            )
        # Prune imports whose export stopped.
        for key in [k for k in self.imports if k not in desired]:
            del self.imports[key]

    @staticmethod
    def _set_exported_condition(
        pool: api.InferencePool, exported: bool, raw_scope
    ) -> None:
        if exported:
            cond = api.Condition(api.COND_EXPORTED, "True",
                                 api.REASON_EXPORTED,
                                 "exported to ClusterSet")
        elif raw_scope is not None:
            cond = api.Condition(api.COND_EXPORTED, "False",
                                 api.REASON_NOT_SUPPORTED,
                                 f"unsupported export scope {raw_scope!r}")
        else:
            cond = api.Condition(api.COND_EXPORTED, "False",
                                 api.REASON_NOT_REQUESTED,
                                 "no export annotation")
        if not pool.status.parents:
            pool.status.parents = [api.ParentStatus(
                parentRef=api.ParentReference(name=CONTROLLER_NAME)
            )]
        for parent in pool.status.parents:
            if parent.parentRef.name == CONTROLLER_NAME:
                parent.set_condition(cond)
                return
        ps = api.ParentStatus(
            parentRef=api.ParentReference(name=CONTROLLER_NAME)
        )
        ps.set_condition(cond)
        pool.status.parents.append(ps)
