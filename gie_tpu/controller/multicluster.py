"""Multi-cluster inference: export annotation -> InferencePoolImport.

Port of reference docs/proposals/1374-multi-cluster-inference/README.md:36-53
and the InferencePoolImport API (apix/v1alpha1): a pool annotated
`inference.networking.x-k8s.io/export: ClusterSet` is exported from its home
cluster; the multi-cluster controller materializes a same-name
InferencePoolImport in every OTHER member cluster, recording the exporting
cluster(s) in status.controllers, and maintains the pool's Exported
condition (Exported / NotRequested / NotSupported,
reference api/v1/inferencepool_types.go:352-379).
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from gie_tpu.api import types as api
from gie_tpu.runtime.logging import get_logger

CONTROLLER_NAME = "gie-tpu.inference.networking.k8s.io/multicluster"

# Routing modes (reference 1374 README:48-53): an implementation must
# support at least one; this one supports both.
#   Endpoint: importing IG routes to endpoints selected by the EPP of the
#       exported pool (pod/service connectivity between clusters).
#   Parent: importing IG routes to a parent (Gateway) of the exported pool
#       (parent connectivity between clusters); the remote gateway performs
#       its own EPP exchange.
ROUTING_MODE_ENDPOINT = "Endpoint"
ROUTING_MODE_PARENT = "Parent"


class ClusterSet:
    """A named set of member clusters, each holding pools and imports."""

    def __init__(self, members: list[str]):
        self.members = list(members)
        # (cluster, namespace, name) -> object
        self.pools: dict[tuple[str, str, str], api.InferencePool] = {}
        self.imports: dict[tuple[str, str, str], api.InferencePoolImport] = {}

    def apply_pool(self, cluster: str, pool: api.InferencePool) -> None:
        if cluster not in self.members:
            raise ValueError(f"unknown member cluster {cluster!r}")
        pool.validate()
        self.pools[(cluster, pool.metadata.namespace, pool.metadata.name)] = pool
        self.reconcile()

    def delete_pool(self, cluster: str, namespace: str, name: str) -> None:
        self.pools.pop((cluster, namespace, name), None)
        self.reconcile()

    def get_import(
        self, cluster: str, namespace: str, name: str
    ) -> Optional[api.InferencePoolImport]:
        return self.imports.get((cluster, namespace, name))

    # ------------------------------------------------------------------ #

    def reconcile(self) -> None:
        """Recompute all imports + Exported conditions from pool state."""
        desired: dict[tuple[str, str, str], list[str]] = {}
        for (cluster, ns, name), pool in self.pools.items():
            raw = pool.metadata.annotations.get(api.EXPORT_ANNOTATION)
            exported = raw == api.EXPORT_SCOPE_CLUSTERSET
            # Exported condition on the pool itself; a present-but-unknown
            # scope is NotSupported, absence is NotRequested
            # (reference inferencepool_types.go:352-379 reason set).
            self._set_exported_condition(pool, exported, raw)
            if not exported:
                continue
            for member in self.members:
                if member == cluster:
                    continue
                desired.setdefault((member, ns, name), []).append(cluster)

        # Materialize / update imports.
        for key, exporting in desired.items():
            member, ns, name = key
            imp = self.imports.get(key)
            if imp is None:
                imp = api.InferencePoolImport(
                    metadata=api.ObjectMeta(name=name, namespace=ns)
                )
                self.imports[key] = imp
            # Update ONLY this controller's entry: controllers[] is shared
            # with importing-side controllers (e.g. the gateway controller's
            # parents entry), and each controller owns exactly its own
            # entries (1374 README ControllerName contract).
            entry = api.ImportController(
                name=CONTROLLER_NAME,
                exportingClusters=[
                    api.ExportingCluster(name=c) for c in sorted(exporting)
                ],
            )
            others = [c for c in imp.status.controllers
                      if c.name != CONTROLLER_NAME]
            imp.status.controllers = [entry] + others
        # Prune imports whose export stopped.
        for key in [k for k in self.imports if k not in desired]:
            del self.imports[key]

    @staticmethod
    def _set_exported_condition(
        pool: api.InferencePool, exported: bool, raw_scope
    ) -> None:
        """Maintain the export-controller parent entry: a parentRef of kind
        InferencePoolImport with the ns/name of the exported pool (1374
        README 'InferencePool Status' MUST), carrying the Exported
        condition (reasons Exported / NotRequested / NotSupported,
        reference api/v1/inferencepool_types.go:352-379)."""
        ours = [p for p in pool.status.parents
                if p.parentRef.kind == "InferencePoolImport"]
        others = [p for p in pool.status.parents
                  if p.parentRef.kind != "InferencePoolImport"]
        if exported:
            cond = api.Condition(api.COND_EXPORTED, "True",
                                 api.REASON_EXPORTED,
                                 "exported to ClusterSet")
        elif raw_scope is not None:
            cond = api.Condition(api.COND_EXPORTED, "False",
                                 api.REASON_NOT_SUPPORTED,
                                 f"unsupported export scope {raw_scope!r}")
        else:
            cond = api.Condition(api.COND_EXPORTED, "False",
                                 api.REASON_NOT_REQUESTED,
                                 "no export annotation")
        if ours:
            ps = ours[0]
        else:
            ps = api.ParentStatus(parentRef=api.ParentReference(
                name=pool.metadata.name,
                namespace=pool.metadata.namespace,
                group=api.GROUP_X,
                kind="InferencePoolImport",
            ))
        ps.set_condition(cond)
        pool.status.parents = others + [ps]


class MultiClusterController:
    """ClusterSet reconciliation over LIVE cluster watches
    (docs/FEDERATION.md "control plane"): one apiserver client per
    member cluster, pool watch events funneled through a single worker
    thread driving the in-memory :class:`ClusterSet`, whose outcome is
    pushed back out — InferencePoolImport objects materialized /
    updated / deleted in every importing member, and the Exported
    condition patched onto the exporting pool's status.

    Single-threaded by construction (one queue, one worker): no lock is
    held across the apiserver HTTP calls, and event order per cluster
    is the watch's own order. Clients need the KubeClusterClient
    surface (``_json`` + ``subscribe``/``start`` + pool paths); the
    fakeapi server drives the whole loop in tests
    (tests/test_federation.py)."""

    def __init__(self, clients: dict, namespace: str = "default"):
        self.clients = dict(clients)
        self.namespace = namespace
        self.cluster_set = ClusterSet(sorted(self.clients))
        self.log = get_logger("multicluster")
        self._queue: "queue.Queue[tuple]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (cluster, ns, name) keys of imports THIS controller wrote:
        # the delete sweep is written-minus-desired (we own exactly our
        # entries, never another controller's objects). Desired imports
        # are ALWAYS re-PUT on reconcile — level-triggered repair of
        # out-of-band deletions, see _push_imports.
        self._written: set = set()
        self.reconciles = 0

    # -- wiring ------------------------------------------------------------

    def start(self) -> None:
        for cluster, client in self.clients.items():
            client.subscribe(
                lambda ev, c=cluster: self._on_event(c, ev))
            client.start()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="multicluster", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        for client in self.clients.values():
            client.stop()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _on_event(self, cluster: str, ev) -> None:
        if getattr(ev, "kind", "") == "InferencePool":
            self._queue.put((cluster, ev))

    # -- worker ------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                cluster, ev = self._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            try:
                self._handle(cluster, ev)
                self.reconciles += 1
            except Exception as e:  # the control loop must never die
                self.log.error("multicluster reconcile failed",
                               cluster=cluster, err=e)

    def _handle(self, cluster: str, ev) -> None:
        from gie_tpu.controller.kube import ApiError, pool_status_to_dict

        cs = self.cluster_set
        if ev.type == "DELETED":
            cs.delete_pool(cluster, ev.namespace, ev.name)
        else:
            obj = getattr(ev, "object", None)
            pool = (api.pool_from_dict(obj) if isinstance(obj, dict)
                    else self.clients[cluster].get_pool(
                        ev.namespace, ev.name))
            if pool is None:
                cs.delete_pool(cluster, ev.namespace, ev.name)
            else:
                before = pool_status_to_dict(pool.status)
                cs.apply_pool(cluster, pool)
                # Exported condition back onto the exporting pool — only
                # when reconcile CHANGED it: our own status patch emits a
                # MODIFIED event, and an unconditional re-patch would
                # chase its own tail forever.
                if pool_status_to_dict(pool.status) != before:
                    try:
                        self.clients[cluster].patch_pool_status(
                            ev.namespace, ev.name, pool.status)
                    except ApiError as e:
                        if e.status != 404:
                            raise
                        # Deleted between the event and the patch: the
                        # DELETED event is already behind us in the queue.
        self._push_imports()

    def _imports_path(self, ns: str) -> str:
        return (f"/apis/{api.GROUP_X}/{api.VERSION_X}/namespaces/{ns}"
                "/inferencepoolimports")

    def _push_imports(self) -> None:
        from gie_tpu.controller.kube import ApiError

        desired = dict(self.cluster_set.imports)
        for (cluster, ns, name), imp in desired.items():
            client = self.clients.get(cluster)
            if client is None:
                continue
            body = api.import_to_dict(imp)
            body["metadata"]["namespace"] = ns
            path = f"{self._imports_path(ns)}/{name}"
            # Level-triggered: ALWAYS write the desired import on a
            # reconcile (an out-of-band deletion leaves no InferencePool
            # event, so a changed-body dedup would suppress the repair
            # forever; the controller has no import watch — noted as a
            # residual in docs/FEDERATION.md). PUT repairs in place,
            # POST covers the missing object.
            try:
                client._json("PUT", path, body)
            except Exception:
                try:
                    client._json("POST", self._imports_path(ns), body)
                except Exception as e:
                    self.log.error("import write failed", cluster=cluster,
                                   name=name, err=e)
                    continue
            self._written.add((cluster, ns, name))
        for key in sorted(self._written - set(desired)):
            cluster, ns, name = key
            client = self.clients.get(cluster)
            if client is None:
                continue
            try:
                client._json("DELETE", f"{self._imports_path(ns)}/{name}")
            except ApiError as e:
                if e.status != 404:
                    self.log.error("import delete failed", cluster=cluster,
                                   name=name, err=e)
                    continue
                # Already gone (out-of-band delete): the desired state
                # holds — forget it rather than retrying a 404 forever.
            except Exception as e:
                self.log.error("import delete failed", cluster=cluster,
                               name=name, err=e)
                continue
            self._written.discard(key)
