"""Multi-cluster inference: export annotation -> InferencePoolImport.

Port of reference docs/proposals/1374-multi-cluster-inference/README.md:36-53
and the InferencePoolImport API (apix/v1alpha1): a pool annotated
`inference.networking.x-k8s.io/export: ClusterSet` is exported from its home
cluster; the multi-cluster controller materializes a same-name
InferencePoolImport in every OTHER member cluster, recording the exporting
cluster(s) in status.controllers, and maintains the pool's Exported
condition (Exported / NotRequested / NotSupported,
reference api/v1/inferencepool_types.go:352-379).
"""

from __future__ import annotations

from typing import Optional

from gie_tpu.api import types as api

CONTROLLER_NAME = "gie-tpu.inference.networking.k8s.io/multicluster"

# Routing modes (reference 1374 README:48-53): an implementation must
# support at least one; this one supports both.
#   Endpoint: importing IG routes to endpoints selected by the EPP of the
#       exported pool (pod/service connectivity between clusters).
#   Parent: importing IG routes to a parent (Gateway) of the exported pool
#       (parent connectivity between clusters); the remote gateway performs
#       its own EPP exchange.
ROUTING_MODE_ENDPOINT = "Endpoint"
ROUTING_MODE_PARENT = "Parent"


class ClusterSet:
    """A named set of member clusters, each holding pools and imports."""

    def __init__(self, members: list[str]):
        self.members = list(members)
        # (cluster, namespace, name) -> object
        self.pools: dict[tuple[str, str, str], api.InferencePool] = {}
        self.imports: dict[tuple[str, str, str], api.InferencePoolImport] = {}

    def apply_pool(self, cluster: str, pool: api.InferencePool) -> None:
        if cluster not in self.members:
            raise ValueError(f"unknown member cluster {cluster!r}")
        pool.validate()
        self.pools[(cluster, pool.metadata.namespace, pool.metadata.name)] = pool
        self.reconcile()

    def delete_pool(self, cluster: str, namespace: str, name: str) -> None:
        self.pools.pop((cluster, namespace, name), None)
        self.reconcile()

    def get_import(
        self, cluster: str, namespace: str, name: str
    ) -> Optional[api.InferencePoolImport]:
        return self.imports.get((cluster, namespace, name))

    # ------------------------------------------------------------------ #

    def reconcile(self) -> None:
        """Recompute all imports + Exported conditions from pool state."""
        desired: dict[tuple[str, str, str], list[str]] = {}
        for (cluster, ns, name), pool in self.pools.items():
            raw = pool.metadata.annotations.get(api.EXPORT_ANNOTATION)
            exported = raw == api.EXPORT_SCOPE_CLUSTERSET
            # Exported condition on the pool itself; a present-but-unknown
            # scope is NotSupported, absence is NotRequested
            # (reference inferencepool_types.go:352-379 reason set).
            self._set_exported_condition(pool, exported, raw)
            if not exported:
                continue
            for member in self.members:
                if member == cluster:
                    continue
                desired.setdefault((member, ns, name), []).append(cluster)

        # Materialize / update imports.
        for key, exporting in desired.items():
            member, ns, name = key
            imp = self.imports.get(key)
            if imp is None:
                imp = api.InferencePoolImport(
                    metadata=api.ObjectMeta(name=name, namespace=ns)
                )
                self.imports[key] = imp
            # Update ONLY this controller's entry: controllers[] is shared
            # with importing-side controllers (e.g. the gateway controller's
            # parents entry), and each controller owns exactly its own
            # entries (1374 README ControllerName contract).
            entry = api.ImportController(
                name=CONTROLLER_NAME,
                exportingClusters=[
                    api.ExportingCluster(name=c) for c in sorted(exporting)
                ],
            )
            others = [c for c in imp.status.controllers
                      if c.name != CONTROLLER_NAME]
            imp.status.controllers = [entry] + others
        # Prune imports whose export stopped.
        for key in [k for k in self.imports if k not in desired]:
            del self.imports[key]

    @staticmethod
    def _set_exported_condition(
        pool: api.InferencePool, exported: bool, raw_scope
    ) -> None:
        """Maintain the export-controller parent entry: a parentRef of kind
        InferencePoolImport with the ns/name of the exported pool (1374
        README 'InferencePool Status' MUST), carrying the Exported
        condition (reasons Exported / NotRequested / NotSupported,
        reference api/v1/inferencepool_types.go:352-379)."""
        ours = [p for p in pool.status.parents
                if p.parentRef.kind == "InferencePoolImport"]
        others = [p for p in pool.status.parents
                  if p.parentRef.kind != "InferencePoolImport"]
        if exported:
            cond = api.Condition(api.COND_EXPORTED, "True",
                                 api.REASON_EXPORTED,
                                 "exported to ClusterSet")
        elif raw_scope is not None:
            cond = api.Condition(api.COND_EXPORTED, "False",
                                 api.REASON_NOT_SUPPORTED,
                                 f"unsupported export scope {raw_scope!r}")
        else:
            cond = api.Condition(api.COND_EXPORTED, "False",
                                 api.REASON_NOT_REQUESTED,
                                 "no export annotation")
        if ours:
            ps = ours[0]
        else:
            ps = api.ParentStatus(parentRef=api.ParentReference(
                name=pool.metadata.name,
                namespace=pool.metadata.namespace,
                group=api.GROUP_X,
                kind="InferencePoolImport",
            ))
        ps.set_condition(cond)
        pool.status.parents = others + [ps]
