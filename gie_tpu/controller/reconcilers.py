"""Pod + InferencePool reconcilers.

Faithful behavioral port of reference pkg/lwepp/controller/
{inferencepool,pod}_reconciler.go onto the ClusterClient abstraction:

  InferencePoolReconciler (inferencepool_reconciler.go:37-78):
    not-found / deleting  -> datastore.clear()
    otherwise             -> to_endpoint_pool -> pool_set (with pod lister
                             for the resync-on-change path)

  PodReconciler (pod_reconciler.go:37-102):
    pool not synced       -> requeue 5 s
    not-found             -> pod_delete
    ready && labels match -> pod_update_or_add, else pod_delete

One graceful-drain deviation from the reference (docs/RESILIENCE.md): a
label-matching pod that stops being ready WHILE it still has serving
endpoints — rolling-upgrade termination (deletionTimestamp) or a failed
readiness probe mid-serve — is marked DRAINING instead of hard-deleted.
Its endpoints leave new-pick candidacy immediately, in-flight waves and
open streams complete against the live slot, and the slot reclaims at
the bounded drain deadline or on the pod's actual deletion event,
whichever arrives first. A pod that was never serving (or whose labels
left the pool) still hard-deletes: there is nothing to drain.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from gie_tpu.controller.cluster import ClusterClient, WatchEvent
from gie_tpu.datastore.datastore import Datastore
from gie_tpu.utils.kubemeta import GKNN
from gie_tpu.utils.podutil import is_pod_ready, to_endpoint_pool


@dataclasses.dataclass
class RequeueAfter:
    """Reconcile result asking the driver to retry later (reference
    pod_reconciler.go:44-47 requeue-5s-until-pool-synced)."""

    seconds: float


class InferencePoolReconciler:
    def __init__(self, client: ClusterClient, datastore: Datastore, pool_gknn: GKNN):
        self.client = client
        self.datastore = datastore
        self.pool_gknn = pool_gknn

    def reconcile(self, namespace: str, name: str) -> Optional[RequeueAfter]:
        # Scoped cache: only the configured pool identity is watched
        # (reference controller_manager.go:45-68 field-selector scoping).
        if (namespace, name) != (self.pool_gknn.namespace, self.pool_gknn.name):
            return None
        pool = self.client.get_pool(namespace, name)
        if pool is None or pool.metadata.deletionTimestamp is not None:
            self.datastore.clear()
            return None
        self.datastore.pool_set(
            to_endpoint_pool(pool),
            pod_lister=lambda: self.client.list_pods(namespace),
        )
        return None


class PodReconciler:
    def __init__(self, client: ClusterClient, datastore: Datastore):
        self.client = client
        self.datastore = datastore

    def reconcile(self, namespace: str, name: str,
                  obj: Optional[dict] = None) -> Optional[RequeueAfter]:
        if not self.datastore.pool_has_synced():
            return RequeueAfter(5.0)
        pool = self.datastore.pool_get()
        if namespace != pool.namespace:
            return None
        if obj is not None:
            # Informer-style pass-through: the watch/relist already carried
            # the manifest at this event's resourceVersion — no re-GET
            # (the reference gets this from controller-runtime's cache).
            from gie_tpu.controller.kube import pod_from_k8s

            pod = pod_from_k8s(obj)
        else:
            pod = self.client.get_pod(namespace, name)
        if pod is None:
            self.datastore.pod_delete(namespace, name)
            return None
        labels_match = all(
            pod.labels.get(k) == v for k, v in pool.selector.items()
        )
        if is_pod_ready(pod) and labels_match:
            self.datastore.pod_update_or_add(pod)
        elif labels_match:
            # Still OUR pod, no longer ready: terminating (rolling
            # upgrade sets deletionTimestamp long before the pod object
            # disappears) or NotReady while serving. Drain instead of
            # hard-evicting — mark_draining returns False when the pod
            # has no serving endpoints, in which case there is nothing
            # to drain and the plain delete applies.
            if not self.datastore.pod_mark_draining(namespace, name):
                self.datastore.pod_delete(namespace, name)
        else:
            self.datastore.pod_delete(namespace, name)
        return None


def wire(
    cluster,
    pool_reconciler: InferencePoolReconciler,
    pod_reconciler: PodReconciler,
) -> None:
    """Subscribe both reconcilers to a cluster's watch stream (the manager
    wiring of reference runserver.go:78-93)."""

    def on_event(ev: WatchEvent) -> None:
        if ev.kind == "InferencePool":
            # Pool events always re-GET: there is one pool object, its
            # events are rare, and deletionTimestamp semantics stay in
            # one place (get_pool).
            pool_reconciler.reconcile(ev.namespace, ev.name)
        elif ev.kind == "Pod":
            pod_reconciler.reconcile(
                ev.namespace, ev.name, obj=getattr(ev, "object", None))

    cluster.subscribe(on_event)
