"""Cluster client abstraction + in-memory fake.

The reference consumes the kube-apiserver through controller-runtime's cached
client with namespace/name-scoped caches (reference
pkg/lwepp/server/controller_manager.go:45-68). This module defines the narrow
client surface the reconcilers need (get/list/watch) and an in-memory
FakeCluster implementing it — the test tier's stand-in for envtest/fake
client (reference test strategy, SURVEY.md section 4), and the seam where a
real kubernetes client plugs in when one is available.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Iterator, Optional, Protocol

from gie_tpu.api.types import InferencePool
from gie_tpu.datastore.objects import Pod


@dataclasses.dataclass
class WatchEvent:
    """ADDED / MODIFIED / DELETED event for a Pod or InferencePool."""

    type: str        # "ADDED" | "MODIFIED" | "DELETED"
    kind: str        # "Pod" | "InferencePool"
    namespace: str
    name: str


class ClusterClient(Protocol):
    def get_pod(self, namespace: str, name: str) -> Optional[Pod]: ...

    def list_pods(self, namespace: str) -> list[Pod]: ...

    def get_pool(self, namespace: str, name: str) -> Optional[InferencePool]: ...


class FakeCluster:
    """In-memory apiserver: objects + synchronous watch fan-out."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._pods: dict[tuple[str, str], Pod] = {}
        self._pools: dict[tuple[str, str], InferencePool] = {}
        self._subscribers: list[Callable[[WatchEvent], None]] = []

    # -- client surface ----------------------------------------------------

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        with self._lock:
            return self._pods.get((namespace, name))

    def list_pods(self, namespace: str) -> list[Pod]:
        with self._lock:
            return [p for (ns, _), p in self._pods.items() if ns == namespace]

    def get_pool(self, namespace: str, name: str) -> Optional[InferencePool]:
        with self._lock:
            return self._pools.get((namespace, name))

    # -- mutation (test driver / simulator side) ---------------------------

    def apply_pod(self, pod: Pod) -> None:
        with self._lock:
            key = (pod.namespace, pod.name)
            etype = "MODIFIED" if key in self._pods else "ADDED"
            self._pods[key] = pod
        self._emit(WatchEvent(etype, "Pod", pod.namespace, pod.name))

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            self._pods.pop((namespace, name), None)
        self._emit(WatchEvent("DELETED", "Pod", namespace, name))

    def apply_pool(self, pool: InferencePool) -> None:
        pool.validate()
        with self._lock:
            key = (pool.metadata.namespace, pool.metadata.name)
            etype = "MODIFIED" if key in self._pools else "ADDED"
            self._pools[key] = pool
        self._emit(
            WatchEvent(etype, "InferencePool", pool.metadata.namespace,
                       pool.metadata.name)
        )

    def delete_pool(self, namespace: str, name: str) -> None:
        with self._lock:
            self._pools.pop((namespace, name), None)
        self._emit(WatchEvent("DELETED", "InferencePool", namespace, name))

    # -- watch -------------------------------------------------------------

    def subscribe(self, fn: Callable[[WatchEvent], None]) -> None:
        self._subscribers.append(fn)

    def _emit(self, event: WatchEvent) -> None:
        for fn in list(self._subscribers):
            fn(event)

    def events(self) -> Iterator[WatchEvent]:  # pragma: no cover - helper
        raise NotImplementedError
