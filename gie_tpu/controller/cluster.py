"""Cluster client abstraction + in-memory fake.

The reference consumes the kube-apiserver through controller-runtime's cached
client with namespace/name-scoped caches (reference
pkg/lwepp/server/controller_manager.go:45-68). This module defines the narrow
client surface the reconcilers need (get/list/watch) and an in-memory
FakeCluster implementing it — the test tier's stand-in for envtest/fake
client (reference test strategy, SURVEY.md section 4), and the seam where a
real kubernetes client plugs in when one is available.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Callable, Iterator, Optional, Protocol

from gie_tpu.api.types import InferencePool
from gie_tpu.datastore.objects import Pod


@dataclasses.dataclass
class WatchEvent:
    """ADDED / MODIFIED / DELETED event for a Pod or InferencePool."""

    type: str        # "ADDED" | "MODIFIED" | "DELETED"
    kind: str        # "Pod" | "InferencePool"
    namespace: str
    name: str
    # Raw manifest carried by the watch stream / relist (informer-style
    # pass-through so per-event reconciles need no re-GET). None for
    # DELETED events and for sources that don't carry objects
    # (FakeCluster) — consumers fall back to a client GET.
    object: Optional[dict] = None


class ClusterClient(Protocol):
    def get_pod(self, namespace: str, name: str) -> Optional[Pod]: ...

    def list_pods(self, namespace: str) -> list[Pod]: ...

    def get_pool(self, namespace: str, name: str) -> Optional[InferencePool]: ...


class FakeCluster:
    """In-memory apiserver: objects + synchronous watch fan-out.

    Doubles as the fake-clientset analogue (reference C3
    client-go/clientset/versioned/fake/): every client call is recorded in
    `actions` as (verb, resource, "namespace/name") — the clienttesting
    Actions() surface — and `add_reactor(verb, resource, fn)` intercepts
    calls the way client-go reactors do: fn(action) returns
    (handled, result); handled short-circuits the real store, and fn may
    raise to simulate apiserver errors (conflicts, timeouts)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._pods: dict[tuple[str, str], Pod] = {}
        self._pools: dict[tuple[str, str], InferencePool] = {}
        self._subscribers: list[Callable[[WatchEvent], None]] = []
        # Bounded: FakeCluster also backs long-running simulated
        # deployments (runtime/main.py --demo), where unbounded action
        # history would be a slow leak; 10k covers any test's assertions.
        self.actions: "deque[tuple[str, str, str]]" = deque(maxlen=10_000)
        self._reactors: list[tuple[str, str, Callable]] = []

    # -- fake-clientset surface (actions + reactors) -----------------------

    def add_reactor(self, verb: str, resource: str, fn: Callable) -> None:
        """Intercept `verb` on `resource` ("*" wildcards allowed).
        fn((verb, resource, key)) -> (handled, result)."""
        self._reactors.append((verb, resource, fn))

    def _react(self, verb: str, resource: str, key: str):
        self.actions.append((verb, resource, key))
        for rv, rr, fn in self._reactors:
            if rv in (verb, "*") and rr in (resource, "*"):
                handled, result = fn((verb, resource, key))
                if handled:
                    return True, result
        return False, None

    # -- client surface ----------------------------------------------------

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        handled, result = self._react("get", "pods", f"{namespace}/{name}")
        if handled:
            return result
        with self._lock:
            return self._pods.get((namespace, name))

    def list_pods(self, namespace: str) -> list[Pod]:
        handled, result = self._react("list", "pods", namespace)
        if handled:
            return result
        with self._lock:
            return [p for (ns, _), p in self._pods.items() if ns == namespace]

    def get_pool(self, namespace: str, name: str) -> Optional[InferencePool]:
        handled, result = self._react(
            "get", "inferencepools", f"{namespace}/{name}")
        if handled:
            return result
        with self._lock:
            return self._pools.get((namespace, name))

    # -- mutation (test driver / simulator side) ---------------------------

    def apply_pod(self, pod: Pod) -> None:
        handled, _ = self._react(
            "apply", "pods", f"{pod.namespace}/{pod.name}")
        if handled:
            return
        with self._lock:
            key = (pod.namespace, pod.name)
            etype = "MODIFIED" if key in self._pods else "ADDED"
            self._pods[key] = pod
        self._emit(WatchEvent(etype, "Pod", pod.namespace, pod.name))

    def delete_pod(self, namespace: str, name: str) -> None:
        handled, _ = self._react("delete", "pods", f"{namespace}/{name}")
        if handled:
            return
        with self._lock:
            self._pods.pop((namespace, name), None)
        self._emit(WatchEvent("DELETED", "Pod", namespace, name))

    def apply_pool(self, pool: InferencePool) -> None:
        pool.validate()
        handled, _ = self._react(
            "apply", "inferencepools",
            f"{pool.metadata.namespace}/{pool.metadata.name}")
        if handled:
            return
        with self._lock:
            key = (pool.metadata.namespace, pool.metadata.name)
            etype = "MODIFIED" if key in self._pools else "ADDED"
            self._pools[key] = pool
        self._emit(
            WatchEvent(etype, "InferencePool", pool.metadata.namespace,
                       pool.metadata.name)
        )

    def delete_pool(self, namespace: str, name: str) -> None:
        handled, _ = self._react(
            "delete", "inferencepools", f"{namespace}/{name}")
        if handled:
            return
        with self._lock:
            self._pools.pop((namespace, name), None)
        self._emit(WatchEvent("DELETED", "InferencePool", namespace, name))

    # -- watch -------------------------------------------------------------

    def subscribe(self, fn: Callable[[WatchEvent], None]) -> None:
        self._subscribers.append(fn)

    def _emit(self, event: WatchEvent) -> None:
        for fn in list(self._subscribers):
            fn(event)

    def events(self) -> Iterator[WatchEvent]:  # pragma: no cover - helper
        raise NotImplementedError
