"""Pool status choreography: per-parent Accepted / ResolvedRefs conditions.

The reference's condition set lives on InferencePool.status.parents
(reference api/v1/inferencepool_types.go:192-379): one entry per parent
(Gateway), each carrying Accepted (the parent supports routing to the pool)
and ResolvedRefs (the endpointPickerRef resolves to an existing Service).
In the reference ecosystem the gateway implementation owns these writes;
this module exposes the same computation for BOTH consumers:

  - conformance/harness.py's in-process gateway controller, and
  - PoolStatusController, which publishes through a real apiserver via the
    kube adapter's status-subresource patch (KubeClusterClient.
    patch_pool_status), so a real-cluster deployment surfaces conditions.
"""

from __future__ import annotations

from typing import Callable, Iterable

from gie_tpu.api import types as api


def desired_parent_statuses(
    pool: api.InferencePool,
    parents: Iterable[str],
    service_exists: Callable[[str, str], bool],
) -> list[api.ParentStatus]:
    """The per-parent condition set for a pool referenced by `parents`.

    `service_exists(namespace, name)` answers whether the EPP Service the
    endpointPickerRef names is present. Parent entries owned by other
    controllers (the multi-cluster export entry with parentRef kind
    InferencePoolImport) are NOT produced here — callers preserve those
    separately (1374 README ControllerName contract)."""
    namespace = pool.metadata.namespace
    out: list[api.ParentStatus] = []
    for gw_name in sorted(parents):
        parent = api.ParentStatus(
            parentRef=api.ParentReference(name=gw_name)
        )
        parent.set_condition(api.Condition(
            api.COND_ACCEPTED, "True", api.REASON_ACCEPTED,
            "supported by parent"))
        epp = pool.spec.endpointPickerRef
        if epp is None:
            # This implementation supports EPP-less pools (plain
            # round-robin), so Accepted stays True
            # (InferencePoolMissingEPPRef allows either semantic).
            parent.set_condition(api.Condition(
                api.COND_RESOLVED_REFS, "True",
                api.REASON_RESOLVED_REFS, "no endpointPickerRef"))
        elif not service_exists(namespace, epp.name):
            parent.set_condition(api.Condition(
                api.COND_RESOLVED_REFS, "False",
                api.REASON_INVALID_EXTENSION_REF,
                f"BackendNotFound: Service {epp.name}"))
        else:
            parent.set_condition(api.Condition(
                api.COND_RESOLVED_REFS, "True",
                api.REASON_RESOLVED_REFS, "ok"))
        out.append(parent)
    return out


def merge_parent_statuses(
    existing: list[api.ParentStatus],
    computed: list[api.ParentStatus],
) -> list[api.ParentStatus]:
    """Foreign-controller entries (export controller's InferencePoolImport
    parentRef) survive; gateway-owned entries are replaced wholesale."""
    preserved = [p for p in existing
                 if p.parentRef.kind == "InferencePoolImport"]
    return preserved + computed


class PoolStatusController:
    """Publishes the pool's parent conditions to a real apiserver.

    The client needs `get_pool(ns, name)` and
    `patch_pool_status(ns, name, status)` (KubeClusterClient provides both;
    tests use a duck-typed fake). `parents` is the set of Gateways routing
    to the pool — on a real cluster this comes from the implementation's
    HTTPRoute view (flag-fed for a standalone EPP deployment)."""

    def __init__(
        self,
        client,
        namespace: str,
        pool_name: str,
        parents: Iterable[str],
        service_exists: Callable[[str, str], bool],
    ):
        self.client = client
        self.namespace = namespace
        self.pool_name = pool_name
        self.parents = list(parents)
        self.service_exists = service_exists

    def reconcile(self) -> bool:
        """Compute + patch; returns False when the pool is absent.

        metav1.Condition contract: lastTransitionTime moves only when the
        condition's status actually transitions — unchanged conditions
        carry their previous timestamp forward, and a patch is skipped
        entirely when nothing changed (no resourceVersion churn, no
        spurious watcher wakeups)."""
        pool = self.client.get_pool(self.namespace, self.pool_name)
        if pool is None:
            return False
        before = pool.status.parents
        computed = desired_parent_statuses(
            pool, self.parents, self.service_exists)
        _carry_transition_times(before, computed)
        merged = merge_parent_statuses(before, computed)
        if _conditions_equal(before, merged):
            return True
        pool.status.parents = merged
        pool.status.validate()
        self.client.patch_pool_status(
            self.namespace, self.pool_name, pool.status)
        return True


def _carry_transition_times(
    existing: list[api.ParentStatus],
    computed: list[api.ParentStatus],
) -> None:
    """Copy lastTransitionTime from existing conditions whose (parentRef,
    type) matches and whose status did not change."""
    by_ref = {
        (p.parentRef.kind, p.parentRef.name): p for p in existing
    }
    for parent in computed:
        prev = by_ref.get((parent.parentRef.kind, parent.parentRef.name))
        if prev is None:
            continue
        for cond in parent.conditions:
            old = prev.get_condition(cond.type)
            if old is not None and old.status == cond.status:
                cond.lastTransitionTime = old.lastTransitionTime


def _conditions_equal(
    a: list[api.ParentStatus], b: list[api.ParentStatus]
) -> bool:
    def key(parents):
        return [
            (
                p.parentRef.kind, p.parentRef.name, p.parentRef.namespace,
                [(c.type, c.status, c.reason, c.message,
                  c.lastTransitionTime) for c in p.conditions],
            )
            for p in parents
        ]

    return key(a) == key(b)
