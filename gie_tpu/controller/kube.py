"""Kubernetes watch adapter: the real-cluster ClusterClient.

The in-process FakeCluster serves tests and the demo; this adapter plugs
an actual kube-apiserver into the same seam (reference analogue:
controller-runtime's cached client + watches,
pkg/lwepp/server/controller_manager.go:45-68).

Deliberately STDLIB-ONLY HTTP (urllib + ssl): the official `kubernetes`
client is a heavyweight optional dependency this image doesn't ship, and
the protocol surface the EPP needs — GET/PATCH JSON plus chunked
list/watch streams with resourceVersion bookkeeping and 410-Gone relist
(the semantics reference controllers get from client-go reflectors) — is
small enough to own. That also makes the watch loop, backoff, and resync
paths testable against an in-process HTTP apiserver
(tests/test_kube_apiserver.py) instead of only duck-typed dicts.

Auth: in-cluster service account (token + CA from the serviceaccount
mount, host from KUBERNETES_SERVICE_* envs) or a kubeconfig file
(server / bearer token / CA / client cert-key contexts).
"""

from __future__ import annotations

import json
import os
import ssl
import threading
import urllib.error
import urllib.request
from typing import Callable, Optional

from gie_tpu.api import types as api
from gie_tpu.controller.cluster import WatchEvent
from gie_tpu.datastore.objects import Pod

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def pod_from_k8s(obj) -> Pod:
    """corev1.Pod -> datastore Pod.

    Accepts BOTH key shapes seen in practice: camelCase (raw watch-event /
    manifest dicts) and snake_case (the kubernetes client's .to_dict()
    output). Readiness = PodReady condition True (reference pod.go:24-36).
    """
    if hasattr(obj, "to_dict"):
        obj = obj.to_dict()
    meta = obj.get("metadata") or {}
    status = obj.get("status") or {}

    def get(o, camel, default=None):
        if not isinstance(o, dict):
            return default
        value = o.get(camel)
        if value is None:
            value = o.get(_snake(camel))
        return default if value is None else value

    conditions = get(status, "conditions", []) or []
    ready = any(
        get(c, "type") == "Ready" and get(c, "status") == "True"
        for c in conditions
        if isinstance(c, dict)
    )
    return Pod(
        name=get(meta, "name", ""),
        namespace=get(meta, "namespace", "default"),
        labels=dict(get(meta, "labels", {}) or {}),
        annotations=dict(get(meta, "annotations", {}) or {}),
        ip=get(status, "podIP", "") or "",
        ready=ready,
        deletionTimestamp=get(meta, "deletionTimestamp", None),
    )


def _snake(camel: str) -> str:
    """camelCase -> snake_case matching the kubernetes client's to_dict
    keys (podIP -> pod_ip, deletionTimestamp -> deletion_timestamp)."""
    out = []
    prev_lower = False
    for ch in camel:
        if ch.isupper():
            if prev_lower:
                out.append("_")
            out.append(ch.lower())
            prev_lower = False
        else:
            out.append(ch)
            prev_lower = True
    return "".join(out)


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"apiserver returned {status}: {message}")
        self.status = status


class KubeClusterClient:
    """ClusterClient over a real kube-apiserver (stdlib HTTP).

    Explicit `server`/`token` parameters exist for tests and custom
    wiring; otherwise `kubeconfig` (a path) or the in-cluster service
    account is used, in that order.
    """

    def __init__(
        self,
        namespace: str,
        pool_name: str,
        kubeconfig: Optional[str] = None,
        *,
        server: Optional[str] = None,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        client_cert: Optional[tuple[str, str]] = None,
        insecure_skip_verify: bool = False,
        request_timeout_s: float = 30.0,
        watch_timeout_s: int = 60,
        backoff_s: float = 1.0,
    ):
        self.namespace = namespace
        self.pool_name = pool_name
        self.request_timeout_s = request_timeout_s
        self.watch_timeout_s = watch_timeout_s
        self.backoff_s = backoff_s
        ca_data: Optional[str] = None
        if server is None:
            if kubeconfig:
                (server, token, ca_cert, ca_data, client_cert,
                 insecure_skip_verify) = _load_kubeconfig(kubeconfig)
            else:
                server, token, ca_cert = _load_incluster()
        self._server = server.rstrip("/")
        self._token = token
        self._ssl = self._make_ssl(ca_cert, ca_data, client_cert,
                                   insecure_skip_verify)
        self._subscribers: list[Callable[[WatchEvent], None]] = []
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    @staticmethod
    def _make_ssl(ca_cert, ca_data, client_cert,
                  insecure) -> Optional[ssl.SSLContext]:
        ctx = ssl.create_default_context(cafile=ca_cert, cadata=ca_data)
        if insecure:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        if client_cert:
            ctx.load_cert_chain(certfile=client_cert[0],
                                keyfile=client_cert[1])
        return ctx

    # -- HTTP core ---------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 content_type: str = "application/json",
                 timeout: Optional[float] = None):
        req = urllib.request.Request(
            self._server + path,
            method=method,
            data=json.dumps(body).encode() if body is not None else None,
        )
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        if body is not None:
            req.add_header("Content-Type", content_type)
        kwargs = {"timeout": timeout or self.request_timeout_s}
        if self._server.startswith("https"):
            kwargs["context"] = self._ssl
        return urllib.request.urlopen(req, **kwargs)

    def _json(self, method: str, path: str, body: Optional[dict] = None,
              content_type: str = "application/json") -> dict:
        try:
            with self._request(method, path, body, content_type) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode(errors="replace")) from e

    # -- ClusterClient surface --------------------------------------------

    def _pods_path(self, namespace: str) -> str:
        return f"/api/v1/namespaces/{namespace}/pods"

    def _pools_path(self, namespace: str) -> str:
        return (f"/apis/{api.GROUP}/{api.VERSION}/namespaces/{namespace}"
                "/inferencepools")

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        try:
            return pod_from_k8s(
                self._json("GET", f"{self._pods_path(namespace)}/{name}"))
        except ApiError as e:
            # Only a confirmed 404 means "deleted" (the reconciler evicts
            # on None); transient apiserver failures must NOT drop
            # endpoints.
            if e.status == 404:
                return None
            raise

    def list_pods(self, namespace: str) -> list[Pod]:
        body = self._json("GET", self._pods_path(namespace))
        return [pod_from_k8s(item) for item in body.get("items", [])]

    def get_pool(self, namespace: str, name: str) -> Optional[api.InferencePool]:
        try:
            return api.pool_from_dict(
                self._json("GET", f"{self._pools_path(namespace)}/{name}"))
        except ApiError as e:
            if e.status == 404:
                return None
            raise

    def patch_pool_status(self, namespace: str, name: str,
                          status: api.InferencePoolStatus) -> None:
        self._json(
            "PATCH",
            f"{self._pools_path(namespace)}/{name}/status",
            {"status": pool_status_to_dict(status)},
            content_type="application/merge-patch+json",
        )

    def service_exists(self, namespace: str, name: str) -> bool:
        """EPP Service resolution for the ResolvedRefs condition."""
        try:
            self._json(
                "GET", f"/api/v1/namespaces/{namespace}/services/{name}")
            return True
        except ApiError as e:
            if e.status == 404:
                return False
            raise

    # -- watch fan-out (reconciler wiring seam) ----------------------------

    def subscribe(self, fn: Callable[[WatchEvent], None]) -> None:
        self._subscribers.append(fn)

    def start(self) -> None:
        """Run pod + pool watches, fanning events to subscribers."""
        for path, kind in (
            (self._pods_path(self.namespace), "Pod"),
            (self._pools_path(self.namespace), "InferencePool"),
        ):
            t = threading.Thread(
                target=self._watch_loop, args=(path, kind), daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def _emit(self, event: WatchEvent) -> None:
        for fn in list(self._subscribers):
            fn(event)

    def _watch_loop(self, path: str, kind: str) -> None:
        """client-go-reflector semantics on stdlib HTTP: LIST to learn the
        resourceVersion (emitting one synthetic event per listed item —
        the reconcilers are level-triggered, so a relist is a resync),
        then WATCH from it, following per-event resourceVersions; 410
        Gone (either an ERROR event or an HTTP 410) drops back to relist;
        transport errors back off and retry; a server-side timeout close
        resumes from the last seen resourceVersion without relisting.

        The reflector's Replace semantics are honored: `known` tracks
        every (namespace, name) this watch has surfaced, and a relist
        emits synthetic DELETED events for names that vanished while the
        watch was down — without them, a pod deleted during an outage
        would stay in the datastore as a routable endpoint forever.
        Listed/watched objects ride on the events (WatchEvent.object) so
        reconciles don't re-GET what the stream already carried."""
        rv: Optional[str] = None
        known: set[tuple[str, str]] = set()
        while not self._stop.is_set():
            try:
                if rv is None:
                    body = self._json("GET", path)
                    rv = (body.get("metadata") or {}).get(
                        "resourceVersion", "0")
                    current: set[tuple[str, str]] = set()
                    for item in body.get("items", []):
                        meta = item.get("metadata") or {}
                        ns = meta.get("namespace", self.namespace)
                        name = meta.get("name", "")
                        current.add((ns, name))
                        self._emit(WatchEvent(
                            type="MODIFIED", kind=kind, namespace=ns,
                            name=name, object=item))
                        if self._stop.is_set():
                            return
                    for ns, name in sorted(known - current):
                        self._emit(WatchEvent(
                            type="DELETED", kind=kind,
                            namespace=ns, name=name))
                    known = current
                rv = self._watch_once(path, kind, rv, known)
            except ApiError as e:
                if e.status == 410:
                    rv = None  # compacted away: relist
                else:
                    self._stop.wait(self.backoff_s)
            except Exception:
                self._stop.wait(self.backoff_s)

    def _watch_once(self, path: str, kind: str, rv: str,
                    known: set[tuple[str, str]]) -> Optional[str]:
        """One watch stream until server close; returns the next
        resourceVersion to resume from (None = relist needed). Maintains
        `known` incrementally so the next relist can diff correctly."""
        url = (f"{path}?watch=1&resourceVersion={rv}"
               f"&timeoutSeconds={self.watch_timeout_s}"
               "&allowWatchBookmarks=true")
        try:
            resp = self._request(
                "GET", url, timeout=self.watch_timeout_s + 15)
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode(errors="replace")) from e
        with resp:
            for line in resp:
                if self._stop.is_set():
                    return rv
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                obj = ev.get("object") or {}
                if ev.get("type") == "ERROR":
                    if obj.get("code") == 410:
                        return None
                    raise ApiError(int(obj.get("code") or 500),
                                   str(obj.get("message", "")))
                new_rv = (obj.get("metadata") or {}).get("resourceVersion")
                if new_rv:
                    rv = new_rv
                if ev.get("type") == "BOOKMARK":
                    continue
                event = watch_event_from_k8s(ev, kind)
                key = (event.namespace, event.name)
                if event.type == "DELETED":
                    known.discard(key)
                else:
                    known.add(key)
                self._emit(event)
        return rv


def _load_incluster() -> tuple[str, Optional[str], Optional[str]]:
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = os.path.join(_SA_DIR, "token")
    if not host or not os.path.exists(token_path):
        raise RuntimeError(
            "no usable Kubernetes configuration: pass --kubeconfig outside "
            "a cluster, or run in-cluster with a service account")
    with open(token_path) as f:
        token = f.read().strip()
    ca = os.path.join(_SA_DIR, "ca.crt")
    return (f"https://{host}:{port}", token,
            ca if os.path.exists(ca) else None)


def _load_kubeconfig(path: str):
    """Minimal kubeconfig reader: current-context -> (server, token,
    CA file, CA PEM data, client cert/key pair, skip-verify).

    Handles BOTH kubeconfig shapes: file references
    (certificate-authority / client-certificate / client-key) and the
    inline base64 `*-data` fields kind/minikube/GKE emit. CA data stays
    in memory (ssl cadata=); client cert/key data must become files for
    load_cert_chain, so they are materialized 0600 in a private 0700
    tempdir. Exec/auth-provider plugins are out of scope and raise a
    clear error rather than silently failing every request."""
    import base64
    import tempfile

    try:
        import yaml
    except ImportError as e:  # pragma: no cover - env without pyyaml
        raise RuntimeError(
            "--kubeconfig needs PyYAML to parse the file (the adapter "
            "itself is stdlib-only); install pyyaml, or pass server="
            "/token= explicitly, or run in-cluster"
        ) from e

    with open(path) as f:
        cfg = yaml.safe_load(f) or {}

    def by_name(section, name):
        for entry in cfg.get(section, []) or []:
            if entry.get("name") == name:
                return entry
        return {}

    ctx_name = cfg.get("current-context", "")
    ctx = by_name("contexts", ctx_name).get("context", {})
    cluster = by_name("clusters", ctx.get("cluster", "")).get("cluster", {})
    user = by_name("users", ctx.get("user", "")).get("user", {})
    server = cluster.get("server")
    if not server:
        raise RuntimeError(
            f"kubeconfig {path}: current-context names no cluster server")

    ca_data = None
    if cluster.get("certificate-authority-data"):
        ca_data = base64.b64decode(
            cluster["certificate-authority-data"]).decode()

    client_cert = None
    if user.get("client-certificate") and user.get("client-key"):
        client_cert = (user["client-certificate"], user["client-key"])
    elif (user.get("client-certificate-data")
          and user.get("client-key-data")):
        d = tempfile.mkdtemp(prefix="gie-kubeconfig-", dir=None)
        os.chmod(d, 0o700)
        paths = []
        for fname, b64 in (("client.crt", user["client-certificate-data"]),
                           ("client.key", user["client-key-data"])):
            p = os.path.join(d, fname)
            fd = os.open(p, os.O_CREAT | os.O_WRONLY | os.O_EXCL, 0o600)
            with os.fdopen(fd, "wb") as fh:
                fh.write(base64.b64decode(b64))
            paths.append(p)
        client_cert = (paths[0], paths[1])

    token = user.get("token")
    if token is None and client_cert is None and (
            user.get("exec") or user.get("auth-provider")):
        raise RuntimeError(
            f"kubeconfig {path}: user {ctx.get('user', '')!r} authenticates "
            "via an exec/auth-provider plugin, which this stdlib adapter "
            "does not run — use a token or client-certificate credential, "
            "or pass server=/token= explicitly")

    return (
        server,
        token,
        cluster.get("certificate-authority"),
        ca_data,
        client_cert,
        bool(cluster.get("insecure-skip-tls-verify", False)),
    )


def pool_status_to_dict(status: api.InferencePoolStatus) -> dict:
    """InferencePoolStatus -> the status-subresource patch body's `status`
    value (manifest-shaped, empties pruned like api.pool_to_dict).

    metav1.Condition requires lastTransitionTime: conditions built without
    one (the shared desired_parent_statuses computation leaves it empty)
    are stamped here so the patch is admitted by clusters running the
    upstream CRD, not just this repo's committed one."""
    import dataclasses as _dc
    import datetime as _dt

    now = (
        _dt.datetime.now(_dt.timezone.utc)
        .replace(microsecond=0)
        .isoformat()
        .replace("+00:00", "Z")
    )
    parents = []
    for p in status.parents:
        d = _dc.asdict(p)
        for cond in d.get("conditions", []):
            if not cond.get("lastTransitionTime"):
                cond["lastTransitionTime"] = now
        parents.append(d)
    return api.clean_manifest({"parents": parents})


def patch_pool_status(custom_api, namespace: str, name: str,
                      status: api.InferencePoolStatus) -> None:
    """Publish pool status through a duck-typed CustomObjectsApi-shaped
    client (kept for callers wired to the official client or test fakes;
    KubeClusterClient.patch_pool_status is the in-tree HTTP path)."""
    custom_api.patch_namespaced_custom_object_status(
        api.GROUP, api.VERSION, namespace, "inferencepools", name,
        {"status": pool_status_to_dict(status)},
    )


def watch_event_from_k8s(ev: dict, kind: str) -> WatchEvent:
    """kubernetes watch event dict -> WatchEvent (pure; tested).

    The manifest rides on non-DELETED events (informer-style object
    pass-through); a DELETED event's object is its LAST state — carrying
    it would make a level-triggered consumer resurrect the pod, so
    deletions deliberately carry None and force the client-GET path
    (which confirms the 404)."""
    obj = ev.get("object", {})
    if hasattr(obj, "to_dict"):
        obj = obj.to_dict()
    meta = obj.get("metadata", {}) or {}
    etype = ev.get("type", "MODIFIED")
    return WatchEvent(
        type=etype,
        kind=kind,
        namespace=meta.get("namespace", "default"),
        name=meta.get("name", ""),
        object=None if etype == "DELETED" else obj,
    )
