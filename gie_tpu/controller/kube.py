"""Kubernetes watch adapter: the real-cluster ClusterClient.

The in-process FakeCluster serves tests and the demo; this adapter plugs an
actual kube-apiserver into the same seam (reference analogue:
controller-runtime's cached client + watches, controller_manager.go:45-68).
The `kubernetes` package is not available in the build container, so imports
are lazy and failure is a clear actionable error; the translation logic
(k8s objects -> gie_tpu objects, watch events -> reconciler fan-out) is
factored into pure functions tested against duck-typed fakes.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from gie_tpu.api import types as api
from gie_tpu.controller.cluster import WatchEvent
from gie_tpu.datastore.objects import Pod


def pod_from_k8s(obj) -> Pod:
    """corev1.Pod -> datastore Pod.

    Accepts BOTH key shapes seen in practice: camelCase (raw watch-event /
    manifest dicts) and snake_case (the kubernetes client's .to_dict()
    output). Readiness = PodReady condition True (reference pod.go:24-36).
    """
    if hasattr(obj, "to_dict"):
        obj = obj.to_dict()
    meta = obj.get("metadata") or {}
    status = obj.get("status") or {}

    def get(o, camel, default=None):
        if not isinstance(o, dict):
            return default
        value = o.get(camel)
        if value is None:
            value = o.get(_snake(camel))
        return default if value is None else value

    conditions = get(status, "conditions", []) or []
    ready = any(
        get(c, "type") == "Ready" and get(c, "status") == "True"
        for c in conditions
        if isinstance(c, dict)
    )
    return Pod(
        name=get(meta, "name", ""),
        namespace=get(meta, "namespace", "default"),
        labels=dict(get(meta, "labels", {}) or {}),
        annotations=dict(get(meta, "annotations", {}) or {}),
        ip=get(status, "podIP", "") or "",
        ready=ready,
        deletionTimestamp=get(meta, "deletionTimestamp", None),
    )


def _snake(camel: str) -> str:
    """camelCase -> snake_case matching the kubernetes client's to_dict
    keys (podIP -> pod_ip, deletionTimestamp -> deletion_timestamp)."""
    out = []
    prev_lower = False
    for ch in camel:
        if ch.isupper():
            if prev_lower:
                out.append("_")
            out.append(ch.lower())
            prev_lower = False
        else:
            out.append(ch)
            prev_lower = True
    return "".join(out)


class KubeClusterClient:
    """ClusterClient over a real kube-apiserver.

    Requires the `kubernetes` Python client at runtime; constructing without
    it raises ImportError with instructions (tests exercise the translation
    functions above directly, which need no client)."""

    def __init__(self, namespace: str, pool_name: str,
                 kubeconfig: Optional[str] = None):
        try:
            from kubernetes import client, config, watch  # noqa: F401
        except ImportError as e:  # pragma: no cover - env without kubernetes
            raise ImportError(
                "KubeClusterClient needs the `kubernetes` package; install "
                "it in the deployment image (the build container ships "
                "without it — use FakeCluster/--demo there)"
            ) from e
        try:
            if kubeconfig:
                config.load_kube_config(kubeconfig)
            else:
                config.load_incluster_config()
        except Exception as e:
            raise RuntimeError(
                "no usable Kubernetes configuration: pass --kubeconfig "
                "outside a cluster, or run in-cluster with a service "
                f"account ({type(e).__name__}: {e})"
            ) from e
        self._core = client.CoreV1Api()
        self._custom = client.CustomObjectsApi()
        self._watchmod = watch
        self.namespace = namespace
        self.pool_name = pool_name
        self._subscribers: list[Callable[[WatchEvent], None]] = []
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- ClusterClient surface --------------------------------------------

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        try:
            return pod_from_k8s(
                self._core.read_namespaced_pod(name, namespace).to_dict()
            )
        except Exception as e:
            # Only a confirmed 404 means "deleted" (the reconciler evicts on
            # None); transient apiserver failures must NOT drop endpoints.
            if getattr(e, "status", None) == 404:
                return None
            raise

    def list_pods(self, namespace: str) -> list[Pod]:
        pods = self._core.list_namespaced_pod(namespace).items
        return [pod_from_k8s(p.to_dict()) for p in pods]

    def get_pool(self, namespace: str, name: str) -> Optional[api.InferencePool]:
        try:
            obj = self._custom.get_namespaced_custom_object(
                api.GROUP, api.VERSION, namespace, "inferencepools", name
            )
            return api.pool_from_dict(obj)
        except Exception as e:
            if getattr(e, "status", None) == 404:
                return None
            raise

    def patch_pool_status(self, namespace: str, name: str,
                          status: api.InferencePoolStatus) -> None:
        patch_pool_status(self._custom, namespace, name, status)

    def service_exists(self, namespace: str, name: str) -> bool:
        """EPP Service resolution for the ResolvedRefs condition."""
        try:
            self._core.read_namespaced_service(name, namespace)
            return True
        except Exception as e:
            if getattr(e, "status", None) == 404:
                return False
            raise

    # -- watch fan-out (reconciler wiring seam) ----------------------------

    def subscribe(self, fn: Callable[[WatchEvent], None]) -> None:
        self._subscribers.append(fn)

    def start(self) -> None:
        """Run pod + pool watches, fanning events to subscribers."""
        for target in (self._watch_pods, self._watch_pools):
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    def _emit(self, event: WatchEvent) -> None:
        for fn in list(self._subscribers):
            fn(event)

    def _watch_pods(self) -> None:  # pragma: no cover - needs a cluster
        w = self._watchmod.Watch()
        while not self._stop.is_set():
            try:
                for ev in w.stream(self._core.list_namespaced_pod,
                                   self.namespace, timeout_seconds=60):
                    self._emit(watch_event_from_k8s(ev, "Pod"))
                    if self._stop.is_set():
                        return
            except Exception:
                self._stop.wait(1.0)

    def _watch_pools(self) -> None:  # pragma: no cover - needs a cluster
        w = self._watchmod.Watch()
        while not self._stop.is_set():
            try:
                for ev in w.stream(
                    self._custom.list_namespaced_custom_object,
                    api.GROUP, api.VERSION, self.namespace, "inferencepools",
                    timeout_seconds=60,
                ):
                    self._emit(watch_event_from_k8s(ev, "InferencePool"))
                    if self._stop.is_set():
                        return
            except Exception:
                self._stop.wait(1.0)


def pool_status_to_dict(status: api.InferencePoolStatus) -> dict:
    """InferencePoolStatus -> the status-subresource patch body's `status`
    value (manifest-shaped, empties pruned like api.pool_to_dict).

    metav1.Condition requires lastTransitionTime: conditions built without
    one (the shared desired_parent_statuses computation leaves it empty)
    are stamped here so the patch is admitted by clusters running the
    upstream CRD, not just this repo's committed one."""
    import dataclasses as _dc
    import datetime as _dt

    now = (
        _dt.datetime.now(_dt.timezone.utc)
        .replace(microsecond=0)
        .isoformat()
        .replace("+00:00", "Z")
    )
    parents = []
    for p in status.parents:
        d = _dc.asdict(p)
        for cond in d.get("conditions", []):
            if not cond.get("lastTransitionTime"):
                cond["lastTransitionTime"] = now
        parents.append(d)
    return api.clean_manifest({"parents": parents})


def patch_pool_status(custom_api, namespace: str, name: str,
                      status: api.InferencePoolStatus) -> None:
    """Publish pool status through the status subresource (the write path
    of the reference's per-parent condition choreography,
    api/v1/inferencepool_types.go:192-379). `custom_api` is duck-typed
    (kubernetes CustomObjectsApi or a test fake)."""
    custom_api.patch_namespaced_custom_object_status(
        api.GROUP, api.VERSION, namespace, "inferencepools", name,
        {"status": pool_status_to_dict(status)},
    )


def watch_event_from_k8s(ev: dict, kind: str) -> WatchEvent:
    """kubernetes watch event dict -> WatchEvent (pure; tested)."""
    obj = ev.get("object", {})
    if hasattr(obj, "to_dict"):
        obj = obj.to_dict()
    meta = obj.get("metadata", {}) or {}
    return WatchEvent(
        type=ev.get("type", "MODIFIED"),
        kind=kind,
        namespace=meta.get("namespace", "default"),
        name=meta.get("name", ""),
    )
