"""Leader-side digest publication: epoch-versioned snapshots over HTTP.

The publisher periodically snapshots a set of EXPORTERS (callables
returning flat array dicts — Scheduler.export_state, OnlineTrainer
.export_state, CapacityModel.export_state), fingerprints each section's
encoded payload, and bumps a single state EPOCH whenever anything changed.
Followers address digests by (era, epoch):

  era    a random token minted per publisher incarnation. Epochs are only
         comparable within one era — a failover elects a NEW leader whose
         counter restarts, and a follower that carried the old era must
         resync a full snapshot rather than misread epoch 3 of the new
         leader as older state than epoch 40 of the dead one.
  epoch  monotonically increasing per state change; doubles as the HTTP
         ETag, so an unchanged-state poll is one 304 with no body.

Delta frames: ``?since=N&era=E`` returns only the sections whose state
changed after epoch N (base_epoch=N in the digest header). A follower at
the current epoch short-circuits via If-None-Match; anything the publisher
cannot serve incrementally (era mismatch, future epoch) falls back to a
full snapshot — anti-entropy must always converge, delta is only an
optimization.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
import zlib
from typing import Callable, Optional

from gie_tpu.replication.codec import build_digest, encode_section
from gie_tpu.resilience import faults
from gie_tpu.runtime.logging import get_logger

DIGEST_PATH = "/replication/digest"
STATUS_PATH = "/replication/status"
ERA_HEADER = "X-Replication-Era"
EPOCH_HEADER = "X-Replication-Epoch"


class StatePublisher:
    """Snapshots exporters into versioned digests; thread-safe."""

    def __init__(
        self,
        exporters: dict,
        *,
        era: Optional[str] = None,
    ):
        self.exporters = dict(exporters)
        self.era = era if era is not None else uuid.uuid4().hex[:12]
        self.log = get_logger("replication.publisher")
        self._lock = threading.Lock()
        self._payloads: dict[str, bytes] = {}
        self._crcs: dict[str, int] = {}
        self._section_epoch: dict[str, int] = {}
        self._epoch = 0
        self.last_refresh_at = 0.0   # monotonic
        self.digest_bytes = 0        # size of the current FULL digest

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def refresh(self) -> int:
        """Snapshot every exporter; bump the epoch if any section's encoded
        payload changed. Exporters run OUTSIDE the publisher lock (they take
        their own component locks and may force a device sync); a failing
        exporter keeps its previous payload rather than tearing a section
        out of the digest mid-flight."""
        fresh: dict[str, bytes] = {}
        for name, fn in self.exporters.items():
            try:
                arrays = fn()
                if arrays:
                    fresh[name] = encode_section(arrays)
            except Exception as e:
                self.log.error(
                    "replication exporter failed", section=name, err=e)
        with self._lock:
            changed = [
                name for name, payload in fresh.items()
                if self._crcs.get(name) != zlib.crc32(payload) & 0xFFFFFFFF
            ]
            if changed:
                self._epoch += 1
                for name in changed:
                    self._payloads[name] = fresh[name]
                    self._crcs[name] = zlib.crc32(fresh[name]) & 0xFFFFFFFF
                    self._section_epoch[name] = self._epoch
            self.last_refresh_at = time.monotonic()
            self.digest_bytes = sum(len(p) for p in self._payloads.values())
            return self._epoch

    def _etag(self) -> str:
        return f'"{self.era}:{self._epoch}"'

    def serve(
        self,
        *,
        since: Optional[int] = None,
        era: Optional[str] = None,
        if_none_match: Optional[str] = None,
        leader: bool = True,
    ) -> tuple[int, dict, bytes]:
        """One digest request -> (status, headers, body). Shared by the
        HTTP handler and the in-memory transport tests use, so the two
        paths cannot diverge on protocol semantics."""
        if not leader:
            # A non-leader must not serve digests: a follower's copy lags
            # the leader's, and chaining syncs through it would let stale
            # state win the anti-entropy race.
            return 503, {}, b"not leader"
        verdict = None
        if faults.ENABLED:
            # gie-chaos: drawn OUTSIDE the publisher lock (a latency/hang
            # verdict sleeps in fire()). ERROR models a leader that stops
            # serving; CORRUPT flips a byte in the outgoing frame — the
            # codec's CRC guard on the follower is what must absorb it.
            verdict = faults.fire("replication.publish")
            if verdict.kind == faults.ERROR:
                return 503, {}, b"injected fault"
        with self._lock:
            if self._epoch == 0:
                return 503, {}, b"no digest published yet"
            etag = self._etag()
            headers = {
                "ETag": etag,
                ERA_HEADER: self.era,
                EPOCH_HEADER: str(self._epoch),
            }
            if if_none_match == etag:
                return 304, headers, b""
            delta = (
                era == self.era
                and since is not None
                and 0 <= since <= self._epoch
            )
            if delta:
                payloads = {
                    n: p for n, p in self._payloads.items()
                    if self._section_epoch[n] > since
                }
                blob = build_digest(
                    self._epoch, payloads, delta=True, base_epoch=since)
            else:
                blob = build_digest(self._epoch, dict(self._payloads))
            headers["Content-Type"] = "application/octet-stream"
        if verdict is not None and verdict.kind == faults.CORRUPT:
            flipped = bytearray(blob)
            flipped[len(flipped) // 2] ^= 0xFF
            blob = bytes(flipped)
        return 200, headers, blob

    def status(self) -> dict:
        with self._lock:
            return {
                "era": self.era,
                "epoch": self._epoch,
                "sections": dict(self._section_epoch),
                "digest_bytes": self.digest_bytes,
            }


class ReplicationHTTPServer:
    """Digest transport on the gateway's control surface.

    Same posture as the KV-events listener (this is control-plane state;
    a forged digest steers routing): loopback bind by default, the pod-
    network interface is an explicit decision. GET-only."""

    def __init__(
        self,
        publisher: StatePublisher,
        port: int = 0,
        *,
        bind: str = "127.0.0.1",
        role_fn: Callable[[], bool] = lambda: True,
    ):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        from urllib.parse import parse_qs, urlparse

        pub = publisher

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                parsed = urlparse(self.path)
                if parsed.path == STATUS_PATH:
                    body = json.dumps({
                        **pub.status(), "leader": bool(role_fn())}).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parsed.path != DIGEST_PATH:
                    self.send_error(404)
                    return
                q = parse_qs(parsed.query)
                since = None
                try:
                    if "since" in q:
                        since = int(q["since"][0])
                except (ValueError, IndexError):
                    since = None
                era = q.get("era", [None])[0]
                status, headers, body = pub.serve(
                    since=since,
                    era=era,
                    if_none_match=self.headers.get("If-None-Match"),
                    leader=bool(role_fn()),
                )
                self.send_response(status)
                for k, v in headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if body:
                    self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self._httpd = ThreadingHTTPServer((bind, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="replication-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
