"""HA state replication: warm-standby EPP followers via anti-entropy sync.

The reference's HA story is leader election with IDLE followers (readiness
NOT_SERVING, all EPP state a soft cache whose loss on restart is explicitly
accepted — SURVEY 5.3/5.4). This subsystem closes the gap that acceptance
opened as the EPP grew state that is expensive to re-learn: the prefix-cache
table, the scheduler's assumed-load vector and sinkhorn warm-start duals,
the learned TTFT/TPOT predictor parameters, and the autoscale per-replica
capacity EWMA. A failover that serves prefix-cold, predictor-cold picks
until everything re-converges is exactly the misrouting regime scheduling
quality depends on avoiding — routing decisions are only as good as the
state behind them.

Shape (docs/REPLICATION.md):

  codec.py      versioned, chunked, CRC-guarded digest wire format
  publisher.py  leader-side: epoch-versioned digest snapshots over HTTP
                (ETag = state epoch; delta frames since a known epoch)
  follower.py   non-leader loop: discover the leader from the Lease holder
                identity, poll with jittered backoff, validate, install
  manager.py    role-transition wiring: promote warm on election win,
                flip back to syncing on demotion
"""

from gie_tpu.replication.codec import (
    Digest,
    decode_digest,
    encode_digest,
    encode_section,
)
from gie_tpu.replication.follower import FollowerSync
from gie_tpu.replication.manager import (
    ReplicationManager,
    advertise_from_identity,
    replication_identity,
)
from gie_tpu.replication.publisher import ReplicationHTTPServer, StatePublisher

__all__ = [
    "Digest",
    "decode_digest",
    "encode_digest",
    "encode_section",
    "FollowerSync",
    "ReplicationManager",
    "ReplicationHTTPServer",
    "StatePublisher",
    "advertise_from_identity",
    "replication_identity",
]
