"""Follower sync loop: poll the leader's digest, validate, install.

The follower's one invariant: its live state only ever moves FORWARD to a
digest that decoded cleanly, passed the installers' cross-field validation,
and belongs to the (era, epoch) lineage it is tracking. Everything else —
corrupt bytes, epoch regressions, deltas against a base it never installed,
fetch failures — leaves the prior state untouched and is absorbed by
jittered backoff, never raised out of the loop.

Discovery is indirect on purpose: the leader is whoever holds the election
Lease, and the Lease holder identity carries the leader's advertised
replication address (manager.replication_identity). The follower re-reads
the holder every poll, so a failover redirects the sync without any
follower-side configuration.
"""

from __future__ import annotations

import random
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

from gie_tpu.replication import codec
from gie_tpu.replication.publisher import DIGEST_PATH, EPOCH_HEADER, ERA_HEADER
from gie_tpu.resilience import faults
from gie_tpu.resilience.policy import Backoff, BackoffPolicy
from gie_tpu.runtime.logging import get_logger

# poll_once outcomes (metric label values; see runtime/metrics.py).
INSTALLED = "installed"
NOT_MODIFIED = "not_modified"
NO_LEADER = "no_leader"
FETCH_ERROR = "fetch_error"
CORRUPT = "corrupt"
STALE_EPOCH = "stale_epoch"
DELTA_MISMATCH = "delta_mismatch"
REJECTED = "rejected"


def _header(headers: dict, name: str) -> Optional[str]:
    for k, v in headers.items():
        if k.lower() == name.lower():
            return v
    return None


class FollowerSync:
    """Anti-entropy pull loop body. The manager drives `poll_once` from its
    role loop (no thread of its own), so a role flip to leader simply stops
    the polling without any pause/resume handshake."""

    def __init__(
        self,
        leader_url: Callable[[], Optional[str]],
        install: Callable[..., bool],
        *,
        interval_s: float = 1.0,
        timeout_s: float = 3.0,
        backoff_max_s: float = 8.0,
        jitter: float = 0.25,
        fetch: Optional[Callable] = None,
        seed: Optional[int] = None,
    ):
        self.leader_url = leader_url
        self.install = install
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        # fetch(base_url, since, era, etag) -> (status, headers, body);
        # injectable for the in-memory round-trip smoke test.
        self._fetch = fetch if fetch is not None else self._http_fetch
        self._rng = random.Random(seed)
        self.log = get_logger("replication.follower")

        self.installed_epoch = 0
        self.installed_era: Optional[str] = None
        self.leader_epoch = 0          # newest epoch seen from the leader
        self.last_etag: Optional[str] = None
        self.last_contact_at = 0.0     # monotonic; 0 = never
        self.last_install_at = 0.0
        self.last_install_s = 0.0      # wall time of the last install
        self.installs = 0
        self.rejects = 0
        self.fetch_errors = 0
        self.last_delta = False        # last install was a delta frame
        self._want_full = True
        # Shared jittered-backoff policy (resilience/policy.py) replacing
        # the hand-rolled double-from-base arithmetic: same shape —
        # interval*2**streak capped at backoff_max_s, jitter strictly
        # upward from this follower's seeded RNG (parity pinned by
        # tests/test_resilience.py).
        self._backoff = Backoff(
            BackoffPolicy(base_s=interval_s, max_s=max(backoff_max_s,
                                                       interval_s),
                          jitter=jitter),
            rng=self._rng)
        self._next_poll = 0.0          # monotonic deadline

    # ------------------------------------------------------------------ #

    def staleness_s(self, now: Optional[float] = None) -> float:
        """Seconds since the follower last CONFIRMED the leader's state
        (install or 304); inf before first contact."""
        if self.last_contact_at == 0.0:
            return float("inf")
        now = time.monotonic() if now is None else now
        return max(now - self.last_contact_at, 0.0)

    def epoch_lag(self) -> int:
        return max(self.leader_epoch - self.installed_epoch, 0)

    def _schedule(self, now: float, *, failed: bool) -> None:
        delay = self._backoff.fail() if failed else self._backoff.ok()
        self._next_poll = now + delay

    def _http_fetch(self, base_url, since, era, etag):
        query = {}
        if since is not None and era:
            query = {"since": str(since), "era": era}
        url = base_url.rstrip("/") + DIGEST_PATH
        if query:
            url += "?" + urllib.parse.urlencode(query)
        headers = {"If-None-Match": etag} if etag else {}
        req = urllib.request.Request(url, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.status, dict(r.headers), r.read()
        except urllib.error.HTTPError as e:
            body = b""
            try:
                body = e.read()
            except Exception:
                pass
            return e.code, dict(e.headers or {}), body

    # ------------------------------------------------------------------ #

    def poll_once(self, now: Optional[float] = None) -> Optional[str]:
        """One backoff-gated sync attempt; returns the outcome label or
        None when the backoff window has not elapsed yet."""
        now = time.monotonic() if now is None else now
        if now < self._next_poll:
            return None
        url = self.leader_url()
        if not url:
            self._schedule(now, failed=True)
            return NO_LEADER
        since = None
        if not self._want_full and self.installed_era is not None:
            since = self.installed_epoch
        try:
            if faults.ENABLED:
                # gie-chaos: a replication partition is a failing digest
                # poll. FaultError is ConnectionError-shaped, so the
                # handler below absorbs it into FETCH_ERROR + backoff —
                # exactly the real-world path (and injected transports
                # see the same schedule the HTTP one would).
                faults.check("replication.poll", key=url)
            status, headers, body = self._fetch(
                url, since, self.installed_era, self.last_etag)
        except Exception as e:
            self.fetch_errors += 1
            self.log.v(3).info("digest fetch failed", url=url, err=str(e))
            self._schedule(now, failed=True)
            return FETCH_ERROR
        if status == 304:
            self.last_contact_at = now
            epoch = _header(headers, EPOCH_HEADER)
            if epoch is not None and epoch.isdigit():
                self.leader_epoch = int(epoch)
            self._schedule(now, failed=False)
            return NOT_MODIFIED
        if status != 200:
            self.fetch_errors += 1
            self._schedule(now, failed=True)
            return FETCH_ERROR

        digest = codec.decode_digest(body)
        if digest is None:
            self.rejects += 1
            self._schedule(now, failed=True)
            return CORRUPT
        era = _header(headers, ERA_HEADER) or ""
        self.leader_epoch = max(digest.epoch, 0)
        if digest.delta and (
            era != self.installed_era
            or digest.base_epoch != self.installed_epoch
        ):
            # A delta against a base we never installed (leader changed,
            # or we missed a window): force a full snapshot next poll.
            self._want_full = True
            self._schedule(now, failed=False)
            self._next_poll = now  # re-poll immediately with since=None
            return DELTA_MISMATCH
        if era == self.installed_era and digest.epoch <= self.installed_epoch:
            # Epoch regression within one era (a replayed or reordered
            # response): state only moves forward.
            self.rejects += 1
            self._schedule(now, failed=False)
            return STALE_EPOCH

        t0 = time.perf_counter()
        try:
            ok = bool(self.install(digest.sections, delta=digest.delta))
        except Exception as e:
            # Installer bugs must degrade to "kept prior state", exactly
            # like corrupt bytes.
            self.log.error("digest install raised", err=e)
            ok = False
        self.last_install_s = time.perf_counter() - t0
        if not ok:
            self.rejects += 1
            self._schedule(now, failed=True)
            return REJECTED
        self.installed_epoch = digest.epoch
        self.installed_era = era
        self.last_delta = digest.delta
        self.last_etag = _header(headers, "ETag")
        self.last_contact_at = now
        self.last_install_at = now
        self.installs += 1
        self._want_full = False
        self._schedule(now, failed=False)
        return INSTALLED
