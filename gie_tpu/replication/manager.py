"""ReplicationManager: role-transition wiring for warm-standby followers.

One loop thread serves both roles and flips with the election:

  leader    refresh the publisher every interval (exporters snapshot the
            live scheduler/predictor/autoscale state; the epoch bumps only
            when something changed) and serve /replication/digest.
  follower  drive FollowerSync.poll_once: discover the leader from the
            Lease holder identity, pull digests, validate, and install
            into the SAME live objects the scheduler serves from — so
            winning an election later needs no restore step at all. The
            promotion IS the warm state already sitting in place.

On demotion (lost lease, partition healed against us) the ex-leader's next
tick simply polls again; its publisher era survives, but followers of the
NEW leader resync full snapshots by era mismatch, so no stale state wins.

The Lease is also the discovery channel: `replication_identity` suffixes
the elector's holder identity with the advertised digest address
(``<identity>|host:port``), and `advertise_from_identity` parses it back on
the follower side. A deployment that disables replication keeps the plain
identity and nothing changes on the wire.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Optional

from gie_tpu.replication import follower as follower_mod
from gie_tpu.replication.follower import FollowerSync
from gie_tpu.replication.publisher import ReplicationHTTPServer, StatePublisher
from gie_tpu.runtime import metrics as own_metrics
from gie_tpu.runtime.logging import get_logger

_ADDR_SEP = "|"


def replication_identity(advertise: str, base: Optional[str] = None) -> str:
    """Elector holder identity carrying the replication advertise address."""
    base = base or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
    return f"{base}{_ADDR_SEP}{advertise}"


def advertise_from_identity(holder: Optional[str]) -> Optional[str]:
    """Parse the advertised ``host:port`` back out of a Lease holder
    identity; None when the holder does not advertise (replication off on
    the leader, or a pre-replication build holding the lease)."""
    if not holder or _ADDR_SEP not in holder:
        return None
    addr = holder.rsplit(_ADDR_SEP, 1)[1].strip()
    if not addr or ":" not in addr:
        return None
    return addr


class ReplicationManager:
    def __init__(
        self,
        *,
        scheduler,
        trainer=None,
        capacity_model=None,
        elector=None,
        port: int = 0,
        bind: str = "127.0.0.1",
        advertise: Optional[str] = None,
        interval_s: float = 1.0,
        stale_after_s: float = 10.0,
        era: Optional[str] = None,
    ):
        self.scheduler = scheduler
        self.trainer = trainer
        self.capacity_model = capacity_model
        self.elector = elector
        self.interval_s = interval_s
        self.stale_after_s = stale_after_s
        self.log = get_logger("replication")

        exporters = {"sched": scheduler.export_state}
        if trainer is not None:
            exporters["predictor"] = trainer.export_state
        if capacity_model is not None:
            exporters["autoscale"] = capacity_model.export_state
        self.publisher = StatePublisher(exporters, era=era)
        self.http = ReplicationHTTPServer(
            self.publisher, port, bind=bind, role_fn=self.is_leader)
        self.advertise = advertise or f"{bind}:{self.http.port}"
        self.follower = FollowerSync(
            self._leader_url, self._install, interval_s=interval_s)

        self.promoted_with_epoch: Optional[int] = None
        self._was_leader: Optional[bool] = None
        self._last_refresh = 0.0  # monotonic
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- role plumbing ----------------------------------------------------- #

    def attach_elector(self, elector) -> None:
        """Late binding for the port=0 bootstrap order: the elector
        identity needs the bound advertise address, which needs the HTTP
        server, which the manager owns."""
        self.elector = elector

    def is_leader(self) -> bool:
        # No elector = single-replica deployment: this process publishes
        # (so an operator can point a cold standby at it) and never syncs.
        return self.elector is None or bool(self.elector.is_leader())

    def on_role_change(self, leader: bool) -> None:
        """Elector role callback (leader.py). Runs on the elector's renew
        thread — keep it cheap; the manager loop does the actual work on
        its next tick."""
        if leader:
            self.promoted_with_epoch = self.follower.installed_epoch
            self.log.info(
                "promoted to leader with warm replicated state",
                epoch=self.follower.installed_epoch,
                era=self.follower.installed_era,
                staleness_s=round(self.follower.staleness_s(), 3),
            )
        else:
            self.log.info("demoted to follower; resuming digest sync")
        own_metrics.REPLICATION_ROLE.set(1.0 if leader else 0.0)

    def _leader_url(self) -> Optional[str]:
        if self.elector is None:
            return None
        holder = None
        try:
            holder = self.elector.holder_identity()
        except Exception:
            return None
        if not holder or holder == getattr(self.elector, "identity", None):
            return None
        addr = advertise_from_identity(holder)
        return f"http://{addr}" if addr else None

    # -- install ----------------------------------------------------------- #

    def _install(self, sections: dict, *, delta: bool) -> bool:
        """Dispatch digest sections to their installers, in TWO phases:
        validate every known section first, then commit them all. A
        digest whose 'predictor' section rejects must not leave the
        scheduler already swapped to the new epoch — a mixed-epoch state
        would be exactly what a promotion then serves. Unknown sections
        are skipped (forward compat: a newer leader may ship state this
        build has no home for)."""
        handlers = {
            "sched": (self.scheduler.prepare_install,
                      self.scheduler.commit_install),
        }
        if self.trainer is not None:
            handlers["predictor"] = (
                self.trainer.prepare_install, self._commit_predictor)
        if self.capacity_model is not None:
            handlers["autoscale"] = (
                self.capacity_model.prepare_install,
                self.capacity_model.commit_install)
        staged = []
        for name, arrays in sections.items():
            entry = handlers.get(name)
            if entry is None:
                continue
            prepare, commit = entry
            prepared = prepare(arrays)
            if prepared is None:
                self.log.error("digest section rejected", section=name)
                return False  # nothing committed yet
            staged.append((commit, prepared))
        # All known sections validated: commit them all.
        for commit, prepared in staged:
            commit(prepared)
        return True

    def _commit_predictor(self, staged) -> None:
        self.trainer.commit_install(staged)
        # The scheduler holds its own reference to the params tree; a
        # cycle compiled with a predictor column must see the replicated
        # weights, gated by the replicated confidence.
        if self.scheduler.predictor_fn is not None:
            self.scheduler.set_predictor_params(self.trainer.params)
            self.scheduler.gate_latency_column(self.trainer.confidence())

    # -- health ------------------------------------------------------------ #

    def healthy(self) -> bool:
        """Replication health for the probe surface: a leader is healthy by
        definition (it IS the source); a follower is healthy once synced
        and not stale. Before any leader exists to sync from, report
        unhealthy — a probe asking "is this standby warm?" must not get a
        yes from a cold one."""
        if self.is_leader():
            return True
        return (
            self.follower.installed_epoch > 0
            and self.follower.staleness_s() <= self.stale_after_s
        )

    # -- loop -------------------------------------------------------------- #

    def step(self, now: Optional[float] = None) -> Optional[str]:
        """One tick (test seam): leader refreshes, follower polls."""
        now = time.monotonic() if now is None else now
        leader = self.is_leader()
        if leader != self._was_leader:
            self._was_leader = leader
            own_metrics.REPLICATION_ROLE.set(1.0 if leader else 0.0)
            if leader:
                self._last_refresh = 0.0  # publish immediately on promotion
        if leader:
            if now - self._last_refresh < self.interval_s:
                return "idle"
            self._last_refresh = now
            epoch = self.publisher.refresh()
            own_metrics.REPLICATION_EPOCH.set(epoch)
            own_metrics.REPLICATION_EPOCH_LAG.set(0.0)
            own_metrics.REPLICATION_DIGEST_BYTES.set(
                self.publisher.digest_bytes)
            own_metrics.REPLICATION_STALENESS.set(0.0)
            return "published"
        outcome = self.follower.poll_once(now)
        if outcome is not None:
            own_metrics.REPLICATION_SYNCS.labels(outcome=outcome).inc()
            if outcome == follower_mod.INSTALLED:
                own_metrics.REPLICATION_INSTALL_SECONDS.observe(
                    self.follower.last_install_s)
        own_metrics.REPLICATION_EPOCH.set(self.follower.installed_epoch)
        own_metrics.REPLICATION_EPOCH_LAG.set(self.follower.epoch_lag())
        staleness = self.follower.staleness_s()
        own_metrics.REPLICATION_STALENESS.set(
            staleness if staleness != float("inf") else -1.0)
        return outcome

    def _loop(self) -> None:
        # The loop granularity is finer than interval_s so a role flip is
        # picked up quickly; the follower's own backoff and the leader's
        # _last_refresh gate bound the actual work to once per interval.
        # A leader refresh is NOT free even when nothing changed — it
        # exports + encodes every section to fingerprint it (the state
        # has no cheap cross-component dirty bit; see docs/REPLICATION.md
        # follow-ups) — which is why refresh never runs at loop
        # granularity, only at interval_s.
        granularity = min(max(self.interval_s, 0.01), 0.25)
        while not self._stop.wait(granularity):
            try:
                self.step()
            except Exception as e:  # sync must never take the EPP down
                self.log.error("replication step failed", err=e)

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="replication", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.http.close()
