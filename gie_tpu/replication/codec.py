"""Digest wire format: numpy-native, length-prefixed, CRC-guarded frames.

The replication digest is a snapshot of the EPP's soft state as named
SECTIONS ("sched", "predictor", "autoscale", ...), each a flat dict of
named numpy arrays. The codec's failure contract is the load-bearing
property: a follower feeds it bytes from the network, and ANY corruption —
truncation, bit flips, absurd lengths, unknown versions — must come back
as ``None`` (keep prior state), never as an exception into the sync loop.

Layout (all integers little-endian):

  header   MAGIC "GIER" | version u16 | flags u16 | epoch u64 |
           base_epoch u64 | nsections u32 | header_crc32 u32
           (header_crc32 covers every preceding header byte, so a bit
           flip in the epoch/flags fields is caught, not installed)
  section  name_len u16 | payload_len u32 | crc32 u32 | name utf-8 |
           payload   (crc32 covers name + payload: a flipped NAME must
           reject, not silently become an unknown — skipped — section)
  payload  repeated arrays:
           key_len u16 | key utf-8 | dtype_len u8 | dtype-str | ndim u8 |
           dims u32 * ndim | raw bytes (C order)

Forward compatibility is skip-unknown at the SEMANTIC layer, not here:
sections and array keys a given build does not understand decode fine and
are simply ignored by the installers (manager.py), so a newer leader can
ship new state to an older follower without breaking the sync. The version
field guards the FRAMING only — a version bump means this very layout
changed and the digest is rejected whole.

``flags`` bit 0 marks a DELTA digest: it carries only the sections whose
state changed after ``base_epoch``, and is only installable on a follower
whose installed epoch equals ``base_epoch`` (otherwise it re-fetches a full
snapshot). Unknown flag bits reject — they would change semantics this
decoder cannot honor.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib

import numpy as np

MAGIC = b"GIER"
VERSION = 1
FLAG_DELTA = 0x1
_KNOWN_FLAGS = FLAG_DELTA

_HEADER = struct.Struct("<4sHHQQI")   # magic, version, flags, epoch, base, n
_HEADER_CRC = struct.Struct("<I")     # crc32 of the _HEADER bytes
_SECTION = struct.Struct("<HII")      # name_len, payload_len, crc32
_ARRAY = struct.Struct("<HBB")        # key_len, dtype_len, ndim

# Hard bounds: a corrupt length field must fail fast, not allocate.
MAX_SECTIONS = 64
MAX_ARRAYS_PER_SECTION = 4096
MAX_NAME_BYTES = 256
MAX_NDIM = 8
MAX_PAYLOAD_BYTES = 1 << 30

# Only plain numeric buffers ride the wire (bool/int/uint/float/complex);
# object/str dtypes could smuggle pickle-adjacent payloads.
_DTYPE_KINDS = frozenset("biufc")


@dataclasses.dataclass(frozen=True)
class Digest:
    """Decoded digest: epoch + named sections of named arrays."""

    epoch: int
    base_epoch: int
    delta: bool
    sections: dict  # name -> {key -> np.ndarray}


def _encode_array(key: str, arr: np.ndarray) -> bytes:
    a = np.asarray(arr)
    if not a.flags.c_contiguous:
        # NB: ascontiguousarray alone would promote 0-d scalars to 1-d
        # (shape round-trip breakage); 0-d is always contiguous, so the
        # reshape below only ever applies to ndim >= 1.
        a = np.ascontiguousarray(a).reshape(a.shape)
    if a.dtype.kind not in _DTYPE_KINDS:
        raise ValueError(f"array {key!r}: dtype {a.dtype} not replicable")
    kb = key.encode("utf-8")
    db = a.dtype.str.encode("ascii")
    if len(kb) > MAX_NAME_BYTES or a.ndim > MAX_NDIM:
        raise ValueError(f"array {key!r}: name/ndim out of bounds")
    return b"".join((
        _ARRAY.pack(len(kb), len(db), a.ndim),
        kb,
        db,
        struct.pack(f"<{a.ndim}I", *a.shape),
        a.tobytes(),
    ))


def encode_section(arrays: dict) -> bytes:
    """Serialize one section's arrays to its payload bytes (the unit the
    publisher fingerprints for change detection)."""
    if len(arrays) > MAX_ARRAYS_PER_SECTION:
        raise ValueError("too many arrays in section")
    return b"".join(
        _encode_array(k, np.asarray(v)) for k, v in arrays.items())


def build_digest(
    epoch: int,
    payloads: dict,
    *,
    delta: bool = False,
    base_epoch: int = 0,
) -> bytes:
    """Assemble a digest from pre-encoded section payloads (name -> bytes).
    The publisher caches payloads per section and reuses them across full
    and delta digests, so encoding cost is paid once per state change."""
    if len(payloads) > MAX_SECTIONS:
        raise ValueError("too many sections")
    header = _HEADER.pack(
        MAGIC, VERSION, FLAG_DELTA if delta else 0,
        int(epoch), int(base_epoch), len(payloads))
    parts = [header, _HEADER_CRC.pack(zlib.crc32(header) & 0xFFFFFFFF)]
    for name, payload in payloads.items():
        nb = name.encode("utf-8")
        if len(nb) > MAX_NAME_BYTES or len(payload) > MAX_PAYLOAD_BYTES:
            raise ValueError(f"section {name!r} out of bounds")
        parts.append(_SECTION.pack(
            len(nb), len(payload), zlib.crc32(nb + payload) & 0xFFFFFFFF))
        parts.append(nb)
        parts.append(payload)
    return b"".join(parts)


def encode_digest(
    epoch: int,
    sections: dict,
    *,
    delta: bool = False,
    base_epoch: int = 0,
) -> bytes:
    """Convenience: encode sections of arrays straight to a digest blob."""
    return build_digest(
        epoch,
        {name: encode_section(arrays) for name, arrays in sections.items()},
        delta=delta,
        base_epoch=base_epoch,
    )


def _decode_payload(payload: bytes) -> dict:
    """Payload bytes -> {key: array}. Raises on any inconsistency (the
    caller converts to a whole-digest rejection)."""
    out: dict = {}
    off = 0
    while off < len(payload):
        if len(out) >= MAX_ARRAYS_PER_SECTION:
            raise ValueError("too many arrays")
        klen, dlen, ndim = _ARRAY.unpack_from(payload, off)
        off += _ARRAY.size
        if klen > MAX_NAME_BYTES or ndim > MAX_NDIM:
            raise ValueError("array header out of bounds")
        key = payload[off:off + klen].decode("utf-8")
        if len(payload[off:off + klen]) != klen:
            raise ValueError("truncated key")
        off += klen
        dtype_str = payload[off:off + dlen].decode("ascii")
        if len(dtype_str) != dlen:
            raise ValueError("truncated dtype")
        off += dlen
        dtype = np.dtype(dtype_str)
        if dtype.kind not in _DTYPE_KINDS:
            raise ValueError(f"dtype {dtype} not replicable")
        shape = struct.unpack_from(f"<{ndim}I", payload, off)
        off += 4 * ndim
        count = 1
        for d in shape:
            count *= d
        nbytes = count * dtype.itemsize
        if nbytes > MAX_PAYLOAD_BYTES or off + nbytes > len(payload):
            raise ValueError("array data out of bounds")
        if key in out:
            raise ValueError(f"duplicate array key {key!r}")
        out[key] = np.frombuffer(
            payload[off:off + nbytes], dtype=dtype).reshape(shape).copy()
        off += nbytes
    if off != len(payload):
        raise ValueError("trailing bytes in section payload")
    return out


def decode_digest(blob: bytes):
    """bytes -> Digest, or None on ANY malformation. Never raises: the
    follower loop calls this on network bytes, and a corrupt digest must
    mean "keep prior state", not a crashed sync thread."""
    try:
        magic, version, flags, epoch, base_epoch, nsections = (
            _HEADER.unpack_from(blob, 0))
        if magic != MAGIC or version != VERSION:
            return None
        (header_crc,) = _HEADER_CRC.unpack_from(blob, _HEADER.size)
        if zlib.crc32(blob[:_HEADER.size]) & 0xFFFFFFFF != header_crc:
            return None  # flipped epoch/flags/count field
        if flags & ~_KNOWN_FLAGS:
            return None
        if nsections > MAX_SECTIONS:
            return None
        sections: dict = {}
        off = _HEADER.size + _HEADER_CRC.size
        for _ in range(nsections):
            nlen, plen, crc = _SECTION.unpack_from(blob, off)
            off += _SECTION.size
            if nlen > MAX_NAME_BYTES or plen > MAX_PAYLOAD_BYTES:
                return None
            name_bytes = blob[off:off + nlen]
            if len(name_bytes) != nlen:
                return None
            name = name_bytes.decode("utf-8")
            off += nlen
            payload = blob[off:off + plen]
            if len(payload) != plen:
                return None  # truncated frame
            off += plen
            if zlib.crc32(name_bytes + payload) & 0xFFFFFFFF != crc:
                return None  # bit flip / corruption (name or payload)
            if name in sections:
                return None
            sections[name] = _decode_payload(payload)
        if off != len(blob):
            return None  # trailing junk
        return Digest(
            epoch=int(epoch),
            base_epoch=int(base_epoch),
            delta=bool(flags & FLAG_DELTA),
            sections=sections,
        )
    except Exception:
        return None
