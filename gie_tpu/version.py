"""Version / bundle metadata.

Mirrors reference version/version.go and the CRD bundle-version annotation
`inference.networking.k8s.io/bundle-version` (reference pkg/generator/main.go:35-106).
"""

__version__ = "0.1.0"

# Stamped into generated CRDs and the conformance report, like the reference's
# bundle-version annotation.
BUNDLE_VERSION = "v0.1.0-tpu"

BUNDLE_VERSION_ANNOTATION = "inference.networking.k8s.io/bundle-version"
