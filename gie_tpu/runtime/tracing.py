"""Lightweight span tracing for the request path.

The reference has no in-tree tracing (SURVEY.md 5.1 — OTLP appears only as
an indirect dependency); this greenfield implementation records span
durations into a per-span prometheus histogram and, at TRACE verbosity,
emits structured span logs. Spans nest via a context manager; the overhead
when nobody scrapes/logs is two clock reads.
"""

from __future__ import annotations

import time

import prometheus_client as prom

from gie_tpu.runtime import logging as own_logging
from gie_tpu.runtime.logging import TRACE, get_logger
from gie_tpu.runtime.metrics import REGISTRY

SPANS = prom.Histogram(
    "gie_span_seconds",
    "Duration of traced request-path spans",
    ["span"],
    buckets=(1e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0),
    registry=REGISTRY,
)

_log = get_logger("trace")

# Label-child cache: SPANS.labels() takes a lock and hashes the label
# tuple on every call; span names are a small fixed set on the admission
# hot path (2 spans per request), so resolve each child once.
_CHILDREN: dict = {}


def _child(name: str):
    child = _CHILDREN.get(name)
    if child is None:
        child = _CHILDREN[name] = SPANS.labels(span=name)
    return child


class _Span:
    """Slotted context manager: the generator/contextlib machinery plus
    the suppressed-log record build cost more than the spans' useful work
    on the admission hot path (hundreds of thousands of requests/s per
    core); the histogram observe is always live, the TRACE log record is
    only constructed when TRACE verbosity is actually enabled."""

    __slots__ = ("name", "attrs", "started")

    def __init__(self, name: str, attrs):
        self.name = name
        self.attrs = attrs
        self.started = 0.0

    def __enter__(self):
        self.started = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        elapsed = time.monotonic() - self.started
        _child(self.name).observe(elapsed)
        if own_logging.trace_enabled():
            _log.v(TRACE).info(
                "span", name=self.name, seconds=round(elapsed, 6),
                **self.attrs
            )
        return False


def span(name: str, **attrs) -> _Span:
    """Time a request-path section: prometheus histogram always, TRACE-level
    structured log when verbosity allows."""
    return _Span(name, attrs)
