"""Lightweight span tracing for the request path.

The reference has no in-tree tracing (SURVEY.md 5.1 — OTLP appears only as
an indirect dependency); this greenfield implementation records span
durations into a per-span prometheus histogram and, at TRACE verbosity,
emits structured span logs. Spans nest via a context manager; the overhead
when nobody scrapes/logs is two clock reads.
"""

from __future__ import annotations

import contextlib
import time

import prometheus_client as prom

from gie_tpu.runtime.logging import TRACE, get_logger
from gie_tpu.runtime.metrics import REGISTRY

SPANS = prom.Histogram(
    "gie_span_seconds",
    "Duration of traced request-path spans",
    ["span"],
    buckets=(1e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0),
    registry=REGISTRY,
)

_log = get_logger("trace")


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a request-path section: prometheus histogram always, TRACE-level
    structured log when verbosity allows."""
    started = time.monotonic()
    try:
        yield
    finally:
        elapsed = time.monotonic() - started
        SPANS.labels(span=name).observe(elapsed)
        _log.v(TRACE).info("span", name=name, seconds=round(elapsed, 6), **attrs)
