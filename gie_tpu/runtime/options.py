"""CLI options with the AddFlags/Complete/Validate lifecycle.

Mirror of reference pkg/lwepp/server/options.go:25-94 (defaults: ext-proc
gRPC 9002, dedicated health 9003, metrics 9090, pool group
inference.networking.k8s.io, TLS on) plus the TPU scheduler's knobs.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from gie_tpu.api.types import GROUP


@dataclasses.dataclass
class Options:
    grpc_port: int = 9002
    grpc_health_port: int = 9003
    metrics_port: int = 9090
    pool_name: str = ""
    pool_namespace: str = "default"
    pool_group: str = GROUP
    secure_serving: bool = True
    cert_path: Optional[str] = None     # mounted cert dir (hot-reload)
    verbosity: int = 2
    # TPU scheduler knobs
    batch_window_ms: float = 2.0
    scrape_interval_ms: float = 50.0
    # Scrape-engine worker shards (metricsio/engine.py): a FIXED pool of
    # threads multiplexing every endpoint over keep-alive connections.
    # 0 = auto (min(8, cpu)). This replaces the seed's thread-per-endpoint
    # polling; the shard count bounds scrape-path threads regardless of
    # pool size.
    scrape_workers: int = 0
    model_server_type: str = "vllm"
    # Learned latency predictor (BASELINE configs[3])
    enable_predictor: bool = False
    predictor_checkpoint_dir: Optional[str] = None
    predictor_train_interval_s: float = 5.0
    # Multi-replica leader election (readiness gates on leadership).
    leader_elect: bool = False
    leader_lease_path: str = "/tmp/gie-tpu-epp.lease"
    # InferenceObjective declarations: "name=criticality" pairs (the CLI
    # stand-in for the CRD until a kube watch adapter supplies them).
    objectives: list = dataclasses.field(default_factory=list)
    # Declarative scheduler profile (YAML: picker/thresholds/plugins/weights).
    scheduler_config: Optional[str] = None
    # Multi-chip serving: dp-shard the scheduling cycle over the first N
    # local devices (0 = single-device). Results are bit-identical to
    # single-device (tests/test_distributed_equivalence.py).
    mesh_devices: int = 0
    # Hierarchical two-level pick cycle (gie_tpu/fleet, docs/FLEET.md):
    # a coarse stage over per-cell rows selects the top K candidate
    # cells per wave, and the dense scorer chain runs only over their
    # gathered endpoints. 0 = off, the dense path stays byte-identical;
    # picks are bitwise-identical to dense whenever K covers every cell
    # (tests/test_fleet.py).
    fleet_topk: int = 0
    # Endpoint slots per fleet cell (multiple of 32; cells are contiguous
    # slot ranges — a federation peer's imported block or a pool shard).
    fleet_cell_cap: int = 64
    # KV-cache event ingestion (reference roadmap item 1, remote-cache
    # interface): HTTP port accepting JSON-lines BlockStored/BlockRemoved/
    # AllBlocksCleared pushes from model servers or cache sidecars
    # (0 = disabled).
    kv_events_port: int = 0
    # Bind address for the KV-events listener. Loopback by default: this is
    # a control-plane input (forged events steer routing); binding the pod
    # network is an explicit decision, ideally with --kv-events-token.
    kv_events_bind: str = "127.0.0.1"
    # Shared bearer token required on KV-event POSTs (None = no auth).
    kv_events_token: Optional[str] = None
    # Admission fast lane (extproc/server.py, docs/EXTPROC.md): zero-parse
    # native JSON field scan, needed-keys header copy, and pooled
    # ProcessingResponse templates on the per-request pick path. Outputs
    # are byte-identical to the legacy path (pinned by tests); the flag
    # exists for safe rollout and for custom pickers that read request
    # headers outside server.NEEDED_REQUEST_HEADERS.
    extproc_fast_lane: bool = True
    # Wire lane (extproc/wire.py, docs/EXTPROC.md): identity gRPC
    # deserializers plus a native serialized-frame walker — classified
    # admission frames never materialize as ProcessingRequest objects.
    # Requires the fast lane (the walker feeds the same native header
    # scan); any unclassified frame falls back to the legacy
    # choreography with byte-identical responses (pinned by tests).
    extproc_wire: bool = True
    # SO_REUSEPORT acceptor count (extproc/workers.py): N in-process
    # gRPC servers sharing one port, one datastore snapshot, and one
    # metrics registry. 1 keeps the single-server layout.
    extproc_workers: int = 1
    # Flow-control queue bounds (reference flow-controller overload policy,
    # proposal 0683): max picks waiting (0 = unbounded) and max seconds a
    # non-critical pick may queue before shedding 429 (0 = unbounded).
    queue_bound: int = 0
    queue_max_age_s: float = 0.0
    # Autoscaling recommender (gie_tpu/autoscale, docs/AUTOSCALE.md):
    # "off" disables the loop; "recommend" runs signals->recommendation
    # and exports gie_autoscale_* metrics without writing; "apply"
    # additionally SSA-patches spec.replicas on --autoscale-target
    # (leader-gated when --leader-elect).
    autoscale_mode: str = "off"
    autoscale_target: Optional[str] = None  # Deployment name to scale
    autoscale_min: int = 1
    autoscale_max: int = 16
    autoscale_interval_s: float = 2.0
    autoscale_shed_high: float = 0.5       # sustained 429/s -> scale up
    autoscale_down_cooldown_s: float = 60.0
    # TTFT SLO for the capacity model's predictor cross-check (0 = off):
    # with --enable-predictor, the controller probes the predicted TTFT of
    # a pool-typical request and derates capacity when it exceeds this
    # bound, so scale-up starts while answers are merely late.
    autoscale_ttft_slo_ms: float = 0.0
    # Persisted per-pool capacity estimate (ROADMAP): directory where the
    # leader checkpoints the capacity EWMA on shutdown, and from which a
    # restarting EPP seeds the model instead of default_per_replica.
    autoscale_state_dir: Optional[str] = None
    # HA state replication (gie_tpu/replication, docs/REPLICATION.md):
    # warm-standby followers sync the leader's soft state (prefix table,
    # assumed load + OT duals, predictor params, capacity EWMA) so a
    # failover promotes warm instead of prefix-/predictor-cold. Port 0 =
    # disabled. The digest listener is control-plane state (a forged
    # digest steers routing): loopback bind by default; set --replication-
    # bind/-advertise to the pod network explicitly for real deployments.
    replication_port: int = 0
    replication_bind: str = "127.0.0.1"
    replication_advertise: str = ""   # host:port peers dial; default bind:port
    replication_interval_s: float = 1.0
    # Follower staleness bound for the "replication" health sub-service:
    # a standby that has not confirmed the leader's state within this
    # window reports NOT_SERVING (it would promote cold-ish).
    replication_stale_after_s: float = 10.0
    # Unified resilience layer (gie_tpu/resilience, docs/RESILIENCE.md):
    # per-endpoint circuit breakers fed by scrape outcomes, the pick-path
    # degradation ladder (full TPU pick -> cached-snapshot -> weighted
    # round-robin -> static subset), and the "resilience" health
    # sub-service. On by default; --no-resilience restores seed behavior
    # (device/dispatch failures fail the affected wave's requests).
    resilience: bool = True
    # STATIC rung pool size: the fixed endpoint subset the bottom ladder
    # rung rotates over.
    resilience_static_subset: int = 4
    # Degradation-ladder calibration knobs (docs/RESILIENCE.md "ladder
    # calibration"): the CACHED rung's queue + w*kv weight (default from
    # the storm sweep) and the pool-wide serve-outcome floor thresholds.
    ladder_cached_kv_weight: float = 8.0
    ladder_serve_window_s: float = 10.0
    ladder_serve_error_rate: float = 0.5
    ladder_serve_min_samples: int = 20
    # ROUND_ROBIN-rung smooth-WRR queue-shape exponent (weight =
    # (1+queue)^-alpha; docs/RESILIENCE.md "ladder calibration").
    ladder_wrr_alpha: float = 1.0
    # Multi-tenant fairness (gie_tpu/fairness, docs/FAIRNESS.md):
    # "tenant=weight" pairs for the weighted-DRR flow queue (repeatable,
    # comma-joinable; unlisted tenants weigh 1.0 — uniform by default).
    fairness_weights: list = dataclasses.field(default_factory=list)
    # Over-fair-share factor: a tenant offering more than factor x its
    # weighted fair share of windowed cost becomes eligible for the
    # preemptive SHEDDABLE shed under saturation.
    fairness_over_factor: float = 2.0
    # Sliding window for every per-tenant budget ledger.
    fairness_window_s: float = 10.0
    # gie_tenant_* label cardinality: top-K tenants by traffic keep
    # their own label value, the long tail exports as "other".
    fairness_top_k: int = 8
    # p99 serve-latency outlier ejection (resilience/outlier.py): a
    # consistently-slow endpoint (windowed per-endpoint quantile above
    # --outlier-ratio x the pool median) is quarantined via the breaker
    # serve plane. Off by default until real-hardware latency
    # distributions confirm the defaults (ROADMAP item 10).
    outlier_ejection: bool = False
    outlier_window_s: float = 30.0
    outlier_ratio: float = 3.0
    outlier_quantile: float = 0.99
    # /debugz peer gate (docs/OBSERVABILITY.md "bind hardening"): the
    # zpages answer loopback peers only unless this names a non-loopback
    # address (e.g. the pod IP, or 0.0.0.0). /metrics is unaffected —
    # Prometheus keeps scraping from off-pod either way.
    debugz_bind: str = "127.0.0.1"
    # Bearer token required from NON-loopback peers on /debugz paths
    # (constant-time compare, 401 without it). Stronger than — and, when
    # set, overriding — the --debugz-bind opt-out for remote peers;
    # loopback access and /metrics are unaffected.
    debugz_token: Optional[str] = None
    # gie-chaos fault injection (resilience/faults.py): repeatable
    # "point=kind:prob[:arg],..." specs plus the schedule seed. Empty =
    # injection disabled (zero hot-path cost beyond one flag check).
    fault_specs: list = dataclasses.field(default_factory=list)
    fault_seed: int = 0
    # Recorded chaos scenario (resilience/scenarios.py): a JSON file (or
    # a shipped-library name like "mixed-soak") whose seed + rules arm
    # the injector at startup — the replayable form of --fault/--fault-
    # seed. Mutually exclusive with --fault (a scenario IS a recorded
    # spec; mixing the two would break its bit-for-bit replay claim).
    fault_scenario: str = ""
    # Graceful drain (docs/RESILIENCE.md): how long a DRAINING endpoint
    # (terminating / NotReady-while-serving pod) may finish its in-flight
    # streams before its scheduler slot is reclaimed anyway.
    drain_deadline_s: float = 30.0
    # Budget-aware pd split (docs/RESILIENCE.md): disaggregated picks
    # whose remaining deadline budget is under this floor collapse to
    # the decode worker only (no cross-worker prefill hop). 0 disables.
    pd_budget_floor_ms: float = 250.0
    # gie-obs (gie_tpu/obs, docs/OBSERVABILITY.md): the pick flight
    # recorder + /debugz introspection plane. On by default — records
    # are written at wave-completion cadence, off the admission hot
    # path; --no-obs removes even that.
    obs: bool = True
    # Head-sampling rate for end-to-end request traces, deterministic
    # per trace ID. 0 (default) installs no tracer at all — the
    # admission path pays one module-attribute load and a falsy branch
    # (bench_extproc's regression guard pins it). At any rate > 0,
    # errors/sheds/deadline breaches/latency tail outliers export
    # regardless of the head decision.
    obs_sample_rate: float = 0.0
    # Deterministic sampling seed: same seed + same trace ID = same
    # keep/drop on every replica.
    obs_sample_seed: int = 0
    # Flight-recorder ring capacity (records, fixed at startup).
    obs_ring: int = 512
    # Latency tail-outlier threshold: a request slower than this exports
    # its trace even when head sampling dropped it.
    obs_slow_ms: float = 250.0
    # Per-tenant trace-rate overrides ("tenant=rate", repeatable): one
    # noisy tenant traced at 1.0 while the fleet stays at
    # --obs-sample-rate. A tenant map alone (fleet rate 0) still
    # installs the tracer — only the mapped tenants head-sample.
    obs_tenant_sample: list = dataclasses.field(default_factory=list)
    # Where --fault-scenario runs (and failed chaos tests) dump the
    # flight-recorder JSON artifact.
    obs_dump_dir: str = "/tmp/gie-obs"
    # Periodic flight-recorder harvesting (gie-learn's training feed,
    # docs/LEARNED.md): every interval the recorder ring is dumped into
    # --obs-dump-dir as a rotation-numbered JSON file, keeping at most
    # --obs-dump-keep files (oldest deleted first). 0 = no rotation
    # thread at all (the default; chaos dumps are unaffected).
    obs_dump_interval_s: float = 0.0
    obs_dump_keep: int = 8
    # gie-learn (gie_tpu/learn, docs/LEARNED.md): which scorer the cycle
    # blends. "blend" is the heuristic weighted sum (the production
    # default, byte-identical to the pre-learn path); "learned" is the
    # offline-trained multiplicative policy and requires
    # --policy-artifact.
    scorer: str = "blend"
    # Trained policy artifact (gie-learn-policy/1 JSON): checksum-
    # verified and schema-validated against the live profile's feature
    # columns at startup — a stale artifact fails fast, never scores.
    policy_artifact: str = ""
    # OTLP span export (gie_tpu/obs/otlp.py, docs/OBSERVABILITY.md):
    # exported traces additionally POST as OTLP/HTTP JSON spans to
    # <endpoint>/v1/traces, batched on a background thread — never the
    # hot path. Empty = disabled. Needs a tracer (--obs-sample-rate > 0
    # or --obs-tenant-sample).
    obs_otlp_endpoint: str = ""
    # Multi-cluster federation (gie_tpu/federation, docs/FEDERATION.md):
    # this cluster's name in the ClusterSet, the digest-exchange
    # listener, and the peer set ("name=http://host:port", repeatable).
    # Federation is on when peers are configured or the listener port is
    # set; imported peer endpoints become schedulable with a cost
    # penalty, and the exchange runs push/long-poll digest sync.
    fed_cluster: str = ""
    fed_peers: list = dataclasses.field(default_factory=list)
    fed_port: int = 0
    fed_bind: str = "127.0.0.1"
    # Cross-cluster cost penalty in queue-depth units (staleness
    # inflates it; see docs/FEDERATION.md "penalty model").
    fed_penalty: float = 4.0
    # Staleness at which the penalty has doubled.
    fed_stale_inflate_s: float = 5.0
    # Staleness past which a peer is LOCAL-ONLY (excluded from
    # spillover; lifts hysteretically at half this bound).
    fed_local_only_after_s: float = 10.0
    # Long-poll window peers park on the digest listener (push
    # semantics: a state change answers a parked poll in one RTT).
    fed_wait_s: float = 10.0
    # Publisher refresh cadence (the epoch heartbeat).
    fed_interval_s: float = 1.0
    # Bound on exported/imported endpoints per fed.load summary.
    fed_max_endpoints: int = 64
    # Start with the whole-cluster drain flag raised: new picks bleed to
    # healthy peers, peers stop spilling in (rollout/decommission mode).
    fed_drain: bool = False

    @staticmethod
    def add_flags(parser: argparse.ArgumentParser) -> None:
        d = Options()
        parser.add_argument("--grpc-port", type=int, default=d.grpc_port,
                            help="ext-proc gRPC port")
        parser.add_argument("--grpc-health-port", type=int,
                            default=d.grpc_health_port,
                            help="dedicated health gRPC port")
        parser.add_argument("--metrics-port", type=int, default=d.metrics_port,
                            help="prometheus metrics port")
        parser.add_argument("--pool-name", default=d.pool_name,
                            help="InferencePool to serve (required)")
        parser.add_argument("--pool-namespace", default=d.pool_namespace)
        parser.add_argument("--pool-group", default=d.pool_group)
        parser.add_argument("--secure-serving", action="store_true",
                            default=d.secure_serving)
        parser.add_argument("--insecure-serving", dest="secure_serving",
                            action="store_false")
        parser.add_argument("--cert-path", default=d.cert_path,
                            help="mounted TLS cert dir (tls.crt/tls.key); "
                                 "self-signed when unset")
        parser.add_argument("-v", "--verbosity", type=int, default=d.verbosity)
        parser.add_argument("--batch-window-ms", type=float,
                            default=d.batch_window_ms,
                            help="micro-batch collection window")
        parser.add_argument("--scrape-interval-ms", type=float,
                            default=d.scrape_interval_ms)
        parser.add_argument("--scrape-workers", type=int,
                            default=d.scrape_workers,
                            help="scrape-engine worker shards multiplexing "
                                 "all endpoint polls (0 = min(8, cpu))")
        parser.add_argument("--model-server-type", default=d.model_server_type,
                            choices=["vllm", "triton-tensorrt-llm",
                                     "trtllm-serve", "sglang"])
        parser.add_argument("--enable-predictor", action="store_true",
                            default=d.enable_predictor,
                            help="learned TTFT predictor scorer column with "
                                 "online training")
        parser.add_argument("--predictor-checkpoint-dir",
                            default=d.predictor_checkpoint_dir)
        parser.add_argument("--predictor-train-interval-s", type=float,
                            default=d.predictor_train_interval_s)
        parser.add_argument("--leader-elect", action="store_true",
                            default=d.leader_elect)
        parser.add_argument("--leader-lease-path", default=d.leader_lease_path)
        parser.add_argument("--scheduler-config", default=d.scheduler_config,
                            help="YAML scheduler profile "
                                 "(picker/thresholds/plugins/weights)")
        parser.add_argument("--mesh-devices", type=int, default=d.mesh_devices,
                            help="dp-shard the scheduling cycle over the "
                                 "first N local devices (0 = single-device)")
        parser.add_argument("--fleet-topk", type=int, default=d.fleet_topk,
                            help="hierarchical pick: score only the top-K "
                                 "candidate cells per wave (0 = off, dense "
                                 "path byte-identical)")
        parser.add_argument("--fleet-cell-cap", type=int,
                            default=d.fleet_cell_cap,
                            help="endpoint slots per fleet cell (multiple "
                                 "of 32)")
        parser.add_argument("--kv-events-port", type=int,
                            default=d.kv_events_port,
                            help="HTTP port for KV-cache event pushes "
                                 "(JSON lines; 0 = disabled)")
        parser.add_argument("--kv-events-bind", default=d.kv_events_bind,
                            help="bind address for the KV-events listener "
                                 "(default loopback; set the pod-network "
                                 "address explicitly to accept pushes)")
        parser.add_argument("--kv-events-token", default=d.kv_events_token,
                            help="shared bearer token required on KV-event "
                                 "POSTs (default: no auth)")
        parser.add_argument("--extproc-fast-lane", dest="extproc_fast_lane",
                            action="store_true",
                            default=d.extproc_fast_lane,
                            help="zero-parse admission fast path (native "
                                 "JSON field scan + pooled response "
                                 "templates + needed-keys header copy)")
        parser.add_argument("--no-extproc-fast-lane",
                            dest="extproc_fast_lane", action="store_false",
                            help="disable the admission fast lane (full "
                                 "json.loads + per-request response "
                                 "build; use when a custom picker reads "
                                 "headers beyond the needed-keys set)")
        parser.add_argument("--extproc-wire", dest="extproc_wire",
                            action="store_true", default=d.extproc_wire,
                            help="zero-protobuf wire lane: walk serialized "
                                 "ProcessingRequest frames natively and "
                                 "reply with pre-built bytes (needs the "
                                 "fast lane; unclassified frames fall "
                                 "back to the legacy path)")
        parser.add_argument("--no-extproc-wire", dest="extproc_wire",
                            action="store_false",
                            help="disable the wire lane (materialize "
                                 "every ext-proc frame as a protobuf)")
        parser.add_argument("--extproc-workers", type=int,
                            default=d.extproc_workers,
                            help="SO_REUSEPORT gRPC acceptors sharing the "
                                 "ext-proc port, datastore snapshot, and "
                                 "metrics registry (default 1)")
        parser.add_argument("--queue-bound", type=int, default=d.queue_bound,
                            help="max picks waiting in the flow-control "
                                 "queue; a full queue sheds by criticality "
                                 "(0 = unbounded)")
        parser.add_argument("--queue-max-age-s", type=float,
                            default=d.queue_max_age_s,
                            help="shed non-critical picks queued longer "
                                 "than this many seconds (0 = unbounded)")
        parser.add_argument("--autoscale-mode", default=d.autoscale_mode,
                            choices=["off", "recommend", "apply"],
                            help="closed-loop replica control: recommend "
                                 "(export gie_autoscale_* only) or apply "
                                 "(SSA-patch the target Deployment)")
        parser.add_argument("--autoscale-target", default=d.autoscale_target,
                            help="Deployment to scale in apply mode")
        parser.add_argument("--autoscale-min", type=int,
                            default=d.autoscale_min)
        parser.add_argument("--autoscale-max", type=int,
                            default=d.autoscale_max)
        parser.add_argument("--autoscale-interval-s", type=float,
                            default=d.autoscale_interval_s,
                            help="seconds between control cycles")
        parser.add_argument("--autoscale-shed-high", type=float,
                            default=d.autoscale_shed_high,
                            help="sustained shed rate (429/s) that "
                                 "triggers fast scale-up")
        parser.add_argument("--autoscale-down-cooldown-s", type=float,
                            default=d.autoscale_down_cooldown_s,
                            help="min seconds between scaling actions "
                                 "before one downward step (flap damping)")
        parser.add_argument("--autoscale-ttft-slo-ms", type=float,
                            default=d.autoscale_ttft_slo_ms,
                            help="TTFT SLO for the capacity model's "
                                 "latency-predictor cross-check (needs "
                                 "--enable-predictor; 0 = off)")
        parser.add_argument("--autoscale-state-dir",
                            default=d.autoscale_state_dir,
                            help="directory persisting the per-pool "
                                 "capacity EWMA across restarts (leader "
                                 "writes on shutdown, startup seeds from "
                                 "it)")
        parser.add_argument("--replication-port", type=int,
                            default=d.replication_port,
                            help="HTTP port serving /replication/digest "
                                 "for warm-standby state sync (0 = "
                                 "disabled)")
        parser.add_argument("--replication-bind", default=d.replication_bind,
                            help="bind address for the replication "
                                 "listener (default loopback; set the "
                                 "pod-network address explicitly)")
        parser.add_argument("--replication-advertise",
                            default=d.replication_advertise,
                            help="host:port peers reach this replica's "
                                 "digest on (carried in the election "
                                 "Lease holder identity; default "
                                 "bind:port)")
        parser.add_argument("--replication-interval-s", type=float,
                            default=d.replication_interval_s,
                            help="leader digest refresh / follower poll "
                                 "interval")
        parser.add_argument("--replication-stale-after-s", type=float,
                            default=d.replication_stale_after_s,
                            help="follower staleness bound for the "
                                 "replication health sub-service")
        parser.add_argument("--objective", action="append", default=[],
                            dest="objectives", metavar="NAME=CRITICALITY",
                            help="register an InferenceObjective "
                                 "(repeatable), e.g. premium-chat=3")
        parser.add_argument("--resilience", dest="resilience",
                            action="store_true", default=d.resilience,
                            help="circuit breakers + pick-path "
                                 "degradation ladder (docs/RESILIENCE.md)")
        parser.add_argument("--no-resilience", dest="resilience",
                            action="store_false",
                            help="disable the resilience layer (seed "
                                 "behavior: device failures fail the "
                                 "affected wave)")
        parser.add_argument("--resilience-static-subset", type=int,
                            default=d.resilience_static_subset,
                            help="endpoint pool size of the STATIC "
                                 "ladder rung")
        parser.add_argument("--ladder-cached-kv-weight", type=float,
                            default=d.ladder_cached_kv_weight,
                            help="CACHED-rung score weight: queue + "
                                 "w*kv_util (default from the storm "
                                 "sweep, docs/RESILIENCE.md)")
        parser.add_argument("--ladder-serve-window-s", type=float,
                            default=d.ladder_serve_window_s,
                            help="sliding window for the ladder's pool-"
                                 "wide serve-outcome floor")
        parser.add_argument("--ladder-serve-error-rate", type=float,
                            default=d.ladder_serve_error_rate,
                            help="pool-wide serve error rate that pins "
                                 "the ladder at ROUND_ROBIN")
        parser.add_argument("--ladder-serve-min-samples", type=int,
                            default=d.ladder_serve_min_samples,
                            help="min serve outcomes in the window "
                                 "before the serve floor may engage")
        parser.add_argument("--ladder-wrr-alpha", type=float,
                            default=d.ladder_wrr_alpha,
                            help="ROUND_ROBIN-rung WRR queue-shape "
                                 "exponent: weight=(1+queue)^-alpha; 0 "
                                 "= uniform rotation (default from the "
                                 "storm sweep, docs/RESILIENCE.md)")
        parser.add_argument("--fairness-weights", action="append",
                            default=[], dest="fairness_weights",
                            metavar="TENANT=WEIGHT[,TENANT=WEIGHT...]",
                            help="weighted-DRR tenant weights for the "
                                 "flow queue (repeatable; unlisted "
                                 "tenants weigh 1.0 — docs/FAIRNESS.md)")
        parser.add_argument("--fairness-over-factor", type=float,
                            default=d.fairness_over_factor,
                            help="over-fair-share factor: offered-cost "
                                 "share beyond factor x fair share "
                                 "makes a tenant's SHEDDABLE traffic "
                                 "shed first under saturation")
        parser.add_argument("--fairness-window-s", type=float,
                            default=d.fairness_window_s,
                            help="sliding window for per-tenant budget "
                                 "ledgers (cost shares, shed/error "
                                 "rates)")
        parser.add_argument("--fairness-top-k", type=int,
                            default=d.fairness_top_k,
                            help="gie_tenant_* label cardinality: top-K "
                                 "tenants by traffic keep their own "
                                 "label, the long tail exports 'other'")
        parser.add_argument("--outlier-ejection", dest="outlier_ejection",
                            action="store_true",
                            default=d.outlier_ejection,
                            help="p99 serve-latency outlier ejection: "
                                 "quarantine endpoints whose windowed "
                                 "latency quantile exceeds --outlier-"
                                 "ratio x the pool median "
                                 "(docs/RESILIENCE.md)")
        parser.add_argument("--outlier-window-s", type=float,
                            default=d.outlier_window_s,
                            help="sliding serve-latency window per "
                                 "endpoint")
        parser.add_argument("--outlier-ratio", type=float,
                            default=d.outlier_ratio,
                            help="ejection threshold: endpoint quantile "
                                 "vs pool median")
        parser.add_argument("--outlier-quantile", type=float,
                            default=d.outlier_quantile,
                            help="per-endpoint latency quantile compared "
                                 "against the pool median")
        parser.add_argument("--fault", action="append", default=[],
                            dest="fault_specs",
                            metavar="POINT=KIND:PROB[:ARG],...",
                            help="gie-chaos fault injection spec "
                                 "(repeatable), e.g. "
                                 "scrape.fetch=error:0.2,latency:0.1:80ms")
        parser.add_argument("--fault-seed", type=int, default=d.fault_seed,
                            help="seed for the deterministic fault "
                                 "schedule")
        parser.add_argument("--fault-scenario", default=d.fault_scenario,
                            metavar="FILE|NAME",
                            help="recorded chaos scenario JSON to arm at "
                                 "startup (a path, or a shipped-library "
                                 "name like 'mixed-soak'); mutually "
                                 "exclusive with --fault")
        parser.add_argument("--drain-deadline-s", type=float,
                            default=d.drain_deadline_s,
                            help="bounded graceful-drain window: how long "
                                 "a terminating pod's endpoints may finish "
                                 "in-flight streams before slot reclaim")
        parser.add_argument("--pd-budget-floor-ms", type=float,
                            default=d.pd_budget_floor_ms,
                            help="disaggregated picks with less deadline "
                                 "budget than this collapse to the decode "
                                 "worker only (0 disables)")
        parser.add_argument("--obs", dest="obs", action="store_true",
                            default=d.obs,
                            help="pick flight recorder + /debugz "
                                 "introspection plane "
                                 "(docs/OBSERVABILITY.md)")
        parser.add_argument("--no-obs", dest="obs", action="store_false",
                            help="disable the observability layer "
                                 "entirely (no recorder, no tracer, "
                                 "bare /metrics only)")
        parser.add_argument("--obs-sample-rate", type=float,
                            default=d.obs_sample_rate,
                            help="head-sampling rate for request traces "
                                 "in [0, 1]; 0 installs no tracer (errors "
                                 "always export at any rate > 0)")
        parser.add_argument("--obs-sample-seed", type=int,
                            default=d.obs_sample_seed,
                            help="deterministic sampling seed (same seed "
                                 "+ trace ID = same keep/drop everywhere)")
        parser.add_argument("--obs-ring", type=int, default=d.obs_ring,
                            help="flight-recorder ring capacity (records)")
        parser.add_argument("--obs-slow-ms", type=float,
                            default=d.obs_slow_ms,
                            help="latency tail-outlier threshold: slower "
                                 "traces export even when unsampled")
        parser.add_argument("--obs-dump-dir", default=d.obs_dump_dir,
                            help="directory for chaos-scenario flight-"
                                 "recorder JSON artifacts")
        parser.add_argument("--obs-dump-interval-s", type=float,
                            default=d.obs_dump_interval_s,
                            help="periodic flight-recorder dump rotation "
                                 "into --obs-dump-dir (gie-learn's "
                                 "training feed); 0 = off")
        parser.add_argument("--obs-dump-keep", type=int,
                            default=d.obs_dump_keep,
                            help="rotation bound: at most this many "
                                 "periodic dump files kept (oldest "
                                 "deleted first)")
        parser.add_argument("--scorer", default=d.scorer,
                            choices=("blend", "learned"),
                            help="cycle scorer: the heuristic weighted-"
                                 "sum blend (default) or the gie-learn "
                                 "multiplicative policy (needs "
                                 "--policy-artifact)")
        parser.add_argument("--policy-artifact", default=d.policy_artifact,
                            metavar="PATH",
                            help="trained gie-learn-policy/1 artifact "
                                 "(checksum-verified, feature schema "
                                 "validated at startup)")
        parser.add_argument("--obs-tenant-sample", action="append",
                            default=[], dest="obs_tenant_sample",
                            metavar="TENANT=RATE",
                            help="per-tenant trace-rate override "
                                 "(repeatable): trace one noisy tenant "
                                 "at 1.0 while the fleet stays at "
                                 "--obs-sample-rate")
        parser.add_argument("--obs-otlp-endpoint",
                            default=d.obs_otlp_endpoint,
                            help="OTLP/HTTP collector base URL (spans "
                                 "POST to <endpoint>/v1/traces, batched "
                                 "off the hot path); empty = disabled")
        parser.add_argument("--fed-cluster", default=d.fed_cluster,
                            help="this cluster's name in the federation "
                                 "ClusterSet (required with --fed-peer "
                                 "or --fed-port)")
        parser.add_argument("--fed-peer", action="append", default=[],
                            dest="fed_peers", metavar="NAME=URL",
                            help="peer cluster digest endpoint "
                                 "(repeatable), e.g. "
                                 "west=http://epp.west:9010")
        parser.add_argument("--fed-port", type=int, default=d.fed_port,
                            help="HTTP port serving /federation/digest "
                                 "to peers (0 = do not serve)")
        parser.add_argument("--fed-bind", default=d.fed_bind,
                            help="bind address for the federation "
                                 "listener (default loopback; set the "
                                 "pod-network address explicitly)")
        parser.add_argument("--fed-penalty", type=float,
                            default=d.fed_penalty,
                            help="cross-cluster cost penalty in queue-"
                                 "depth units (staleness-inflated; "
                                 "docs/FEDERATION.md)")
        parser.add_argument("--fed-stale-inflate-s", type=float,
                            default=d.fed_stale_inflate_s,
                            help="link staleness at which the penalty "
                                 "has doubled")
        parser.add_argument("--fed-local-only-after-s", type=float,
                            default=d.fed_local_only_after_s,
                            help="link staleness past which the peer is "
                                 "excluded from spillover entirely "
                                 "(lifts hysteretically at half this)")
        parser.add_argument("--fed-wait-s", type=float,
                            default=d.fed_wait_s,
                            help="long-poll window peers park on the "
                                 "digest listener")
        parser.add_argument("--fed-interval-s", type=float,
                            default=d.fed_interval_s,
                            help="federation publisher refresh cadence")
        parser.add_argument("--fed-max-endpoints", type=int,
                            default=d.fed_max_endpoints,
                            help="bound on endpoints per exported load "
                                 "summary (lowest-queue rows kept)")
        parser.add_argument("--fed-drain", action="store_true",
                            default=d.fed_drain,
                            help="start with the whole-cluster drain "
                                 "flag raised: new picks bleed to "
                                 "healthy peers, peers stop spilling in")
        parser.add_argument("--debugz-bind", default=d.debugz_bind,
                            help="peer gate for the /debugz zpages: "
                                 "loopback-only by default; name a non-"
                                 "loopback address (pod IP, 0.0.0.0) to "
                                 "expose them (/metrics is unaffected)")
        parser.add_argument("--debugz-token", default=d.debugz_token,
                            help="bearer token required from non-"
                                 "loopback peers on /debugz paths "
                                 "(constant-time compare, 401 without "
                                 "it; /metrics unaffected)")

    @classmethod
    def from_args(cls, args: argparse.Namespace) -> "Options":
        return cls(
            grpc_port=args.grpc_port,
            grpc_health_port=args.grpc_health_port,
            metrics_port=args.metrics_port,
            pool_name=args.pool_name,
            pool_namespace=args.pool_namespace,
            pool_group=args.pool_group,
            secure_serving=args.secure_serving,
            cert_path=args.cert_path,
            verbosity=args.verbosity,
            batch_window_ms=args.batch_window_ms,
            scrape_interval_ms=args.scrape_interval_ms,
            scrape_workers=args.scrape_workers,
            model_server_type=args.model_server_type,
            enable_predictor=args.enable_predictor,
            predictor_checkpoint_dir=args.predictor_checkpoint_dir,
            predictor_train_interval_s=args.predictor_train_interval_s,
            leader_elect=args.leader_elect,
            leader_lease_path=args.leader_lease_path,
            objectives=list(args.objectives),
            scheduler_config=args.scheduler_config,
            mesh_devices=args.mesh_devices,
            fleet_topk=args.fleet_topk,
            fleet_cell_cap=args.fleet_cell_cap,
            kv_events_port=args.kv_events_port,
            kv_events_bind=args.kv_events_bind,
            kv_events_token=args.kv_events_token,
            extproc_fast_lane=args.extproc_fast_lane,
            extproc_wire=args.extproc_wire,
            extproc_workers=args.extproc_workers,
            queue_bound=args.queue_bound,
            queue_max_age_s=args.queue_max_age_s,
            autoscale_mode=args.autoscale_mode,
            autoscale_target=args.autoscale_target,
            autoscale_min=args.autoscale_min,
            autoscale_max=args.autoscale_max,
            autoscale_interval_s=args.autoscale_interval_s,
            autoscale_shed_high=args.autoscale_shed_high,
            autoscale_down_cooldown_s=args.autoscale_down_cooldown_s,
            autoscale_ttft_slo_ms=args.autoscale_ttft_slo_ms,
            autoscale_state_dir=args.autoscale_state_dir,
            replication_port=args.replication_port,
            replication_bind=args.replication_bind,
            replication_advertise=args.replication_advertise,
            replication_interval_s=args.replication_interval_s,
            replication_stale_after_s=args.replication_stale_after_s,
            resilience=args.resilience,
            resilience_static_subset=args.resilience_static_subset,
            ladder_cached_kv_weight=args.ladder_cached_kv_weight,
            ladder_serve_window_s=args.ladder_serve_window_s,
            ladder_serve_error_rate=args.ladder_serve_error_rate,
            ladder_serve_min_samples=args.ladder_serve_min_samples,
            ladder_wrr_alpha=args.ladder_wrr_alpha,
            fairness_weights=list(args.fairness_weights),
            fairness_over_factor=args.fairness_over_factor,
            fairness_window_s=args.fairness_window_s,
            fairness_top_k=args.fairness_top_k,
            outlier_ejection=args.outlier_ejection,
            outlier_window_s=args.outlier_window_s,
            outlier_ratio=args.outlier_ratio,
            outlier_quantile=args.outlier_quantile,
            debugz_bind=args.debugz_bind,
            debugz_token=args.debugz_token,
            fault_specs=list(args.fault_specs),
            fault_seed=args.fault_seed,
            fault_scenario=args.fault_scenario,
            drain_deadline_s=args.drain_deadline_s,
            pd_budget_floor_ms=args.pd_budget_floor_ms,
            obs=args.obs,
            obs_sample_rate=args.obs_sample_rate,
            obs_sample_seed=args.obs_sample_seed,
            obs_ring=args.obs_ring,
            obs_slow_ms=args.obs_slow_ms,
            obs_tenant_sample=list(args.obs_tenant_sample),
            obs_dump_dir=args.obs_dump_dir,
            obs_dump_interval_s=args.obs_dump_interval_s,
            obs_dump_keep=args.obs_dump_keep,
            scorer=args.scorer,
            policy_artifact=args.policy_artifact,
            obs_otlp_endpoint=args.obs_otlp_endpoint,
            fed_cluster=args.fed_cluster,
            fed_peers=list(args.fed_peers),
            fed_port=args.fed_port,
            fed_bind=args.fed_bind,
            fed_penalty=args.fed_penalty,
            fed_stale_inflate_s=args.fed_stale_inflate_s,
            fed_local_only_after_s=args.fed_local_only_after_s,
            fed_wait_s=args.fed_wait_s,
            fed_interval_s=args.fed_interval_s,
            fed_max_endpoints=args.fed_max_endpoints,
            fed_drain=args.fed_drain,
        )

    def validate(self) -> None:
        """reference options.go:84-94."""
        if not self.pool_name:
            raise ValueError("--pool-name is required")
        for name, port in (
            ("grpc-port", self.grpc_port),
            ("grpc-health-port", self.grpc_health_port),
            ("metrics-port", self.metrics_port),
        ):
            if not (0 < port < 65536):
                raise ValueError(f"--{name} {port} out of range")
        if self.verbosity < 0 or self.verbosity > 5:
            raise ValueError("-v must be 0..5")
        if self.mesh_devices < 0:
            raise ValueError("--mesh-devices must be >= 0")
        if self.scrape_workers < 0:
            raise ValueError("--scrape-workers must be >= 0 (0 = auto)")
        if self.scrape_interval_ms <= 0:
            raise ValueError("--scrape-interval-ms must be > 0")
        # One completion queue per worker plus a 64-thread pool each:
        # beyond ~64 acceptors the thread count, not the port spread, is
        # the binding constraint, and the value is surely a typo.
        if not (1 <= self.extproc_workers <= 64):
            raise ValueError("--extproc-workers must be 1..64")
        # With tp=1 the dp axis equals the device count, and dp must be a
        # power of two to divide the request buckets (sched/profile.py).
        if self.mesh_devices > 1 and self.mesh_devices & (self.mesh_devices - 1):
            raise ValueError("--mesh-devices must be a power of two")
        if self.fleet_topk < 0:
            raise ValueError("--fleet-topk must be >= 0 (0 = off)")
        if self.fleet_topk:
            if self.fleet_cell_cap < 32 or self.fleet_cell_cap % 32:
                raise ValueError(
                    "--fleet-cell-cap must be a positive multiple of 32")
            # The candidate block must fit one dense cycle (the largest
            # compressed M bucket) — reject at startup, not first wave.
            from gie_tpu.sched import constants as _C
            if self.fleet_topk * self.fleet_cell_cap > _C.M_BUCKETS[-1]:
                raise ValueError(
                    f"--fleet-topk x --fleet-cell-cap = "
                    f"{self.fleet_topk * self.fleet_cell_cap} exceeds the "
                    f"largest compressed bucket {_C.M_BUCKETS[-1]}")
        if not (0 <= self.kv_events_port < 65536):
            raise ValueError("--kv-events-port out of range")
        if not (0 <= self.replication_port < 65536):
            raise ValueError("--replication-port out of range")
        if self.replication_port > 0:
            if self.replication_interval_s <= 0:
                raise ValueError("--replication-interval-s must be > 0")
            if self.replication_stale_after_s <= 0:
                raise ValueError("--replication-stale-after-s must be > 0")
            if self.replication_advertise and ":" not in self.replication_advertise:
                raise ValueError(
                    "--replication-advertise must be host:port")
            if (not self.replication_advertise
                    and self.replication_bind in ("0.0.0.0", "::", "")):
                # A wildcard bind cannot default the advertise address:
                # the Lease would carry "0.0.0.0:port" and every follower
                # would dial ITSELF (and get 503 "not leader") — a
                # standby that silently never syncs.
                raise ValueError(
                    "--replication-bind on a wildcard address requires "
                    "an explicit --replication-advertise host:port")
        if self.autoscale_mode not in ("off", "recommend", "apply"):
            raise ValueError(
                f"--autoscale-mode {self.autoscale_mode!r} must be "
                "off|recommend|apply")
        if self.autoscale_mode == "apply" and not self.autoscale_target:
            raise ValueError(
                "--autoscale-mode apply requires --autoscale-target")
        if self.autoscale_mode != "off":
            if not (0 <= self.autoscale_min <= self.autoscale_max):
                raise ValueError(
                    "need 0 <= --autoscale-min <= --autoscale-max")
            if self.autoscale_interval_s <= 0:
                raise ValueError("--autoscale-interval-s must be > 0")
            if self.autoscale_ttft_slo_ms < 0:
                raise ValueError("--autoscale-ttft-slo-ms must be >= 0")
        if self.resilience_static_subset < 1:
            raise ValueError("--resilience-static-subset must be >= 1")
        if self.ladder_cached_kv_weight < 0:
            raise ValueError("--ladder-cached-kv-weight must be >= 0")
        if self.ladder_serve_window_s <= 0:
            raise ValueError("--ladder-serve-window-s must be > 0")
        if not (0.0 < self.ladder_serve_error_rate <= 1.0):
            raise ValueError(
                "--ladder-serve-error-rate must be in (0, 1]")
        if self.ladder_serve_min_samples < 1:
            raise ValueError("--ladder-serve-min-samples must be >= 1")
        if self.ladder_wrr_alpha < 0:
            raise ValueError("--ladder-wrr-alpha must be >= 0")
        if self.fairness_weights:
            from gie_tpu.fairness import parse_weights

            try:
                parse_weights(self.fairness_weights)
            except ValueError as e:
                raise ValueError(f"--fairness-weights: {e}") from None
        if self.fairness_over_factor <= 1.0:
            raise ValueError("--fairness-over-factor must be > 1")
        if self.fairness_window_s <= 0:
            raise ValueError("--fairness-window-s must be > 0")
        if self.fairness_top_k < 1:
            raise ValueError("--fairness-top-k must be >= 1")
        for spec in self.obs_tenant_sample:
            name, sep, raw = str(spec).partition("=")
            if not sep or not name:
                raise ValueError(
                    f"--obs-tenant-sample {spec!r} must be TENANT=RATE")
            try:
                rate = float(raw)
            except ValueError:
                raise ValueError(
                    f"--obs-tenant-sample {spec!r}: rate must be a "
                    "number") from None
            if not (0.0 <= rate <= 1.0):
                raise ValueError(
                    f"--obs-tenant-sample {spec!r}: rate must be in "
                    "[0, 1]")
        if self.outlier_ejection:
            if self.outlier_window_s <= 0:
                raise ValueError("--outlier-window-s must be > 0")
            if self.outlier_ratio <= 1.0:
                raise ValueError("--outlier-ratio must be > 1")
            if not (0.5 <= self.outlier_quantile < 1.0):
                raise ValueError(
                    "--outlier-quantile must be in [0.5, 1)")
        if self.fault_specs:
            from gie_tpu.resilience import faults as _faults

            try:
                _faults.parse_spec(self.fault_specs)
            except ValueError as e:
                raise ValueError(f"--fault: {e}") from None
        if self.fault_scenario:
            if self.fault_specs:
                # A scenario IS a recorded spec; merging ad-hoc rules in
                # would break its bit-for-bit replay claim.
                raise ValueError(
                    "--fault-scenario and --fault are mutually exclusive")
            from gie_tpu.resilience import scenarios as _scenarios

            try:
                _scenarios.load(self.fault_scenario)
            except ValueError as e:
                raise ValueError(f"--fault-scenario: {e}") from None
        if self.drain_deadline_s <= 0:
            raise ValueError("--drain-deadline-s must be > 0")
        if self.fed_peers or self.fed_port > 0 or self.fed_drain:
            if not self.fed_cluster:
                raise ValueError(
                    "--fed-cluster is required with --fed-peer/--fed-"
                    "port/--fed-drain (peers must know who we are)")
            if not (0 <= self.fed_port < 65536):
                raise ValueError("--fed-port out of range")
            for spec in self.fed_peers:
                name, sep, url = str(spec).partition("=")
                if not sep or not name or "://" not in url:
                    raise ValueError(
                        f"--fed-peer {spec!r} must be NAME=http://host:port")
                if name == self.fed_cluster:
                    raise ValueError(
                        f"--fed-peer {spec!r} names this cluster itself")
            if self.fed_penalty < 0:
                raise ValueError("--fed-penalty must be >= 0")
            if self.fed_stale_inflate_s <= 0:
                raise ValueError("--fed-stale-inflate-s must be > 0")
            if self.fed_local_only_after_s <= 0:
                raise ValueError("--fed-local-only-after-s must be > 0")
            if self.fed_wait_s < 0:
                raise ValueError("--fed-wait-s must be >= 0")
            if self.fed_interval_s <= 0:
                raise ValueError("--fed-interval-s must be > 0")
            if self.fed_max_endpoints < 1:
                raise ValueError("--fed-max-endpoints must be >= 1")
        if not (0.0 <= self.obs_sample_rate <= 1.0):
            raise ValueError("--obs-sample-rate must be in [0, 1]")
        if self.obs_ring < 1:
            raise ValueError("--obs-ring must be >= 1")
        if self.obs_slow_ms <= 0:
            raise ValueError("--obs-slow-ms must be > 0")
        if self.obs_dump_interval_s < 0:
            raise ValueError("--obs-dump-interval-s must be >= 0")
        if self.obs_dump_interval_s > 0:
            if not self.obs:
                raise ValueError(
                    "--obs-dump-interval-s needs the flight recorder "
                    "(drop --no-obs)")
            if self.obs_dump_keep < 1:
                raise ValueError("--obs-dump-keep must be >= 1")
        if self.scorer not in ("blend", "learned"):
            raise ValueError(
                f"--scorer {self.scorer!r} must be blend|learned")
        if self.scorer == "learned" and not self.policy_artifact:
            raise ValueError(
                "--scorer learned requires --policy-artifact (a "
                "gie-learn-policy/1 file; see docs/LEARNED.md)")
        if self.policy_artifact and self.scorer != "learned":
            raise ValueError(
                "--policy-artifact is only read with --scorer learned "
                "(refusing to silently ignore a trained policy)")
        if self.pd_budget_floor_ms < 0:
            raise ValueError("--pd-budget-floor-ms must be >= 0")
        for spec in self.objectives:
            name, sep, crit = spec.partition("=")
            if not sep or not name:
                raise ValueError(
                    f"--objective {spec!r} must be NAME=CRITICALITY"
                )
            try:
                int(crit)
            except ValueError:
                raise ValueError(
                    f"--objective {spec!r}: criticality must be an integer"
                ) from None
