"""Runtime: options, logging, TLS, health, metrics, server runner."""
