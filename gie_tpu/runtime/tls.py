"""TLS: self-signed server certs + hot-reloading credentials.

Mirror of reference internal/tls/tls.go:33-74 (10-year self-signed cert,
generated at startup when no cert dir is mounted) and pkg/common/certs.go:
35-103 (filesystem watcher + debounce hot-reload). The reloader plugs into
grpc.dynamic_ssl_server_credentials so mounted cert rotations apply without
restarting the listener.
"""

from __future__ import annotations

import datetime
import os
import threading
from typing import Optional

import grpc


def create_self_signed_cert(
    common_name: str = "gie-tpu-epp", days: int = 3650
) -> tuple[bytes, bytes]:
    """(cert_pem, key_pem); RSA-4096, 10-year validity like the reference
    (tls.go:38-52).

    cryptography imports lazily: only the self-signed path needs it, and
    containers serving with mounted certs (or --insecure-serving) must not
    fail to IMPORT the runtime because an optional generator dependency is
    absent."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=4096)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName(common_name),
                                         x509.DNSName("localhost")]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM)
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )
    return cert_pem, key_pem


class CertReloader:
    """Poll-based cert hot-reloader (fsnotify equivalent; 250 ms debounce
    like reference certs.go:60-80)."""

    def __init__(self, cert_path: str, key_path: str, poll_s: float = 0.25):
        self.cert_path = cert_path
        self.key_path = key_path
        self.poll_s = poll_s
        self._lock = threading.Lock()
        self._mtimes: tuple[float, float] = (0.0, 0.0)
        self._current: Optional[tuple[bytes, bytes]] = None
        self._stop = threading.Event()
        self._load()
        self._thread = threading.Thread(target=self._watch, daemon=True)
        self._thread.start()

    def current(self) -> tuple[bytes, bytes]:
        with self._lock:
            assert self._current is not None
            return self._current

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)

    def _load(self) -> None:
        with open(self.cert_path, "rb") as f:
            cert = f.read()
        with open(self.key_path, "rb") as f:
            key = f.read()
        with self._lock:
            self._current = (cert, key)
            self._mtimes = (
                os.path.getmtime(self.cert_path),
                os.path.getmtime(self.key_path),
            )

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                m = (
                    os.path.getmtime(self.cert_path),
                    os.path.getmtime(self.key_path),
                )
                if m != self._mtimes:
                    # Debounce: let the writer finish both files.
                    self._stop.wait(0.25)
                    self._load()
            except OSError:
                continue  # mid-rotation; retry next poll


def server_credentials(
    cert_dir: Optional[str] = None,
) -> tuple[grpc.ServerCredentials, Optional[CertReloader]]:
    """Server creds: mounted cert dir (hot-reloading) when given, else a
    fresh self-signed pair (reference runserver.go:99-114 behavior)."""
    if cert_dir:
        reloader = CertReloader(
            os.path.join(cert_dir, "tls.crt"), os.path.join(cert_dir, "tls.key")
        )

        def fetch():
            cert, key = reloader.current()
            return grpc.ssl_server_certificate_configuration([(key, cert)])

        creds = grpc.dynamic_ssl_server_credentials(
            fetch(), lambda: fetch(), require_client_authentication=False
        )
        return creds, reloader
    cert, key = create_self_signed_cert()
    return grpc.ssl_server_credentials([(key, cert)]), None
