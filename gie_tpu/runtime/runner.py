"""ExtProcServerRunner: wiring + lifecycle.

Mirror of reference pkg/lwepp/server/runserver.go:45-157 + cmd/lwepp/main.go:
build the full stack (datastore + reconcilers + scraper + scheduler +
batching picker + ext-proc gRPC + dual health + metrics), start the
dedicated health listener before cache sync, serve, and stop gracefully on
context/signal (internal/runnable/grpc.go:44-57 GracefulStop).
"""

from __future__ import annotations

import threading
from typing import Optional

import grpc

from gie_tpu.api.types import GROUP
from gie_tpu.controller.cluster import ClusterClient
from gie_tpu.controller.reconcilers import (
    InferencePoolReconciler,
    PodReconciler,
    wire,
)
from gie_tpu.datastore import Datastore
from gie_tpu.sched import constants as C
from gie_tpu.extproc.server import StreamingServer
from gie_tpu.extproc.workers import ExtProcWorkerPool
from gie_tpu.metricsio import MetricsStore
from gie_tpu.metricsio.engine import ScrapeEngine
from gie_tpu.metricsio.mappings import BY_NAME
from gie_tpu.runtime import metrics as own_metrics
from gie_tpu.runtime.health import HealthService, start_dedicated_health_server
from gie_tpu.runtime.logging import get_logger
from gie_tpu.runtime.options import Options
from gie_tpu.runtime.tls import server_credentials
from gie_tpu.sched.batching import BatchingTPUPicker
from gie_tpu.sched.profile import Scheduler
from gie_tpu.utils.kubemeta import GKNN
from gie_tpu.utils.lora import LoraRegistry


class ExtProcServerRunner:
    def __init__(
        self,
        opts: Options,
        cluster: ClusterClient,
        scheduler: Optional[Scheduler] = None,
    ):
        self.opts = opts
        self.log = get_logger("runner")
        self.cluster = cluster
        self.lora_registry = LoraRegistry()
        self.trainer = None
        # gie-learn (gie_tpu/learn, docs/LEARNED.md): the loaded policy
        # artifact, when --scorer learned; None on the heuristic path
        # (and with an injected scheduler — tests own that config).
        self.policy_artifact = None
        if scheduler is not None:
            self.scheduler = scheduler
        else:
            # Production default: the swept tuned profile; an explicit
            # --scheduler-config replaces it wholesale.
            from gie_tpu.sched.config import (
                load_scheduler_config_file,
                tuned_profile,
            )

            cfg, weights = tuned_profile()
            if opts.scheduler_config:
                cfg, weights = load_scheduler_config_file(opts.scheduler_config)
            if opts.scorer == "learned":
                # Trained multiplicative policy (docs/LEARNED.md):
                # checksum-verified, feature schema validated against
                # THIS profile's live columns — a stale artifact fails
                # startup loudly, never scores silently wrong. The
                # artifact's exponents REPLACE the blend weights
                # wholesale (absent columns ride at 0.0 = multiplicative
                # no-op), and the blend itself swaps via the static
                # ProfileConfig.scorer field.
                import dataclasses

                from gie_tpu.learn import artifact as artifact_mod
                from gie_tpu.learn.policy import weights_from_mapping
                from gie_tpu.sched.profile import feature_schema

                art = artifact_mod.load_artifact(opts.policy_artifact)
                artifact_mod.validate_feature_schema(
                    art, feature_schema(
                        cfg, has_predictor=opts.enable_predictor))
                cfg = dataclasses.replace(cfg, scorer="learned")
                weights = weights_from_mapping(
                    artifact_mod.artifact_weight_values(art))
                self.policy_artifact = art
                self.log.info(
                    "learned policy loaded",
                    path=opts.policy_artifact,
                    checksum=art["checksum"],
                    columns=list(art["feature_schema"]),
                    promoted=bool(
                        (art.get("judgment") or {}).get("promote")))
            predictor_fn = predictor_params = None
            if opts.enable_predictor:
                # Learned TTFT column with online training (configs[3]);
                # COMPOSES with --scheduler-config rather than ignoring it.
                from gie_tpu.models.latency import (
                    LatencyPredictor,
                    OnlineTrainer,
                    predictor_score_fn,
                )

                predictor = LatencyPredictor()
                self.trainer = OnlineTrainer(predictor)
                if opts.predictor_checkpoint_dir:
                    if self.trainer.restore(opts.predictor_checkpoint_dir):
                        self.log.info("predictor checkpoint restored",
                                      dir=opts.predictor_checkpoint_dir)
                # Bind the scorer column into the jitted cycle ONLY when a
                # weight ceiling is configured: SLO admission runs its own
                # host-side forward (OnlineTrainer.predict_ttft), so with
                # ceiling 0 the cycle would pay the [N, M] MLP forward
                # every pick for a column multiplied by zero.
                if float(weights.latency) > 0.0:
                    predictor_fn = predictor_score_fn(predictor)
                    predictor_params = self.trainer.params
                # The configured latency weight is a CEILING, not a live
                # weight: the Scheduler zeroes the column at startup and
                # _train_loop phases it in via gate_latency_column as
                # OnlineTrainer.confidence converges. The round-2 ablation
                # (docs/BENCH_NOTES.md) is why — an under-trained column at
                # full weight scored noise (goodput 474 vs 635), while
                # SLO-aware admission (x-gateway-inference-ttft-slo-ms) pays
                # from the first converged model. Opt into the column via
                # weights.latency in --scheduler-config.
            mesh = None
            if opts.mesh_devices > 1:
                from gie_tpu.parallel.mesh import make_mesh

                # The full dp x tp layout (docs/MESH.md): since PR 15 the
                # serving path tp-shards the ENDPOINT axis too (metrics,
                # cost-matrix columns, assumed load, sinkhorn duals), so
                # per-chip memory is O(M/tp) and the tp axis pays at
                # serve time, not just in the training step. make_mesh's
                # default split (tp=2 when even) serves the production
                # batching picker; picks are bit-identical to
                # single-device at every layout
                # (tests/test_distributed_equivalence).
                mesh = make_mesh(opts.mesh_devices)
                self.log.info("multi-chip scheduling mesh",
                              shape=dict(mesh.shape))
            if opts.fleet_topk > 0:
                # Hierarchical two-level pick (gie_tpu/fleet,
                # docs/FLEET.md): coarse cell stage + candidate-compressed
                # dense stage. Drop-in Scheduler facade; with
                # fleet_topk == 0 this branch never runs and the dense
                # path is byte-identical to the flag not existing.
                from gie_tpu.fleet import FleetPicker

                self.scheduler = FleetPicker(
                    cfg,
                    weights=weights,
                    predictor_fn=predictor_fn,
                    predictor_params=predictor_params,
                    mesh=mesh,
                    topk=opts.fleet_topk,
                    cell_cap=opts.fleet_cell_cap,
                )
                self.log.info(
                    "fleet picker armed", topk=opts.fleet_topk,
                    cell_cap=opts.fleet_cell_cap)
            else:
                self.scheduler = Scheduler(
                    cfg,
                    weights=weights,
                    predictor_fn=predictor_fn,
                    predictor_params=predictor_params,
                    mesh=mesh,
                )
            if self.trainer is not None:
                # A restored checkpoint carries its confidence state: apply
                # it now, or a converged opted-in column would sit at weight
                # 0 until ~batch_size fresh observations trigger the first
                # train tick (indefinitely under low traffic).
                self.scheduler.gate_latency_column(self.trainer.confidence())
        self.metrics_store = MetricsStore()
        self.mapping = BY_NAME[opts.model_server_type]
        # gie-obs (gie_tpu/obs, docs/OBSERVABILITY.md): the pick flight
        # recorder (always, when obs is on — written at wave cadence)
        # and the request tracer (only at a sampling rate > 0; rate 0
        # leaves the admission path at one module-attr load + branch).
        self._obs_installed = False
        self._otlp = None
        if opts.obs:
            from gie_tpu import obs
            from gie_tpu.obs.recorder import FlightRecorder
            from gie_tpu.obs.trace import Tracer

            tenant_rates = {
                spec.partition("=")[0]: float(spec.partition("=")[2])
                for spec in opts.obs_tenant_sample
            }
            tracer = None
            if opts.obs_sample_rate > 0 or tenant_rates:
                # A tenant-rate map alone (fleet rate 0) still installs
                # the tracer: "one noisy tenant at 1.0 while the fleet
                # stays dark" is exactly the per-tenant override's job.
                tracer = Tracer(
                    opts.obs_sample_rate, seed=opts.obs_sample_seed,
                    slow_s=opts.obs_slow_ms / 1000.0,
                    tenant_rates=tenant_rates)
            if tracer is not None and opts.obs_otlp_endpoint:
                # OTLP span export (obs/otlp.py): exported traces also
                # POST to the collector as OTLP/HTTP JSON, batched on a
                # background thread — finish() only enqueues. Federation
                # hops ride along as child spans, so a cross-cluster
                # pick is one joined trace (docs/OBSERVABILITY.md).
                from gie_tpu.obs.otlp import OtlpSpanExporter

                self._otlp = OtlpSpanExporter(opts.obs_otlp_endpoint)
                tracer.on_export = self._otlp.export
                self.log.info("otlp span export armed",
                              endpoint=opts.obs_otlp_endpoint)
            obs.install(tracer=tracer,
                        recorder=FlightRecorder(opts.obs_ring))
            self._obs_installed = True
        # Unified resilience layer (gie_tpu/resilience, docs/RESILIENCE.md):
        # one breaker board (scrape outcomes write, pick path reads), one
        # degradation ladder (batching collector drives), the scrape
        # engine's own staleness clock as the blackout signal.
        self.resilience = None
        if opts.resilience:
            from gie_tpu.resilience import (
                DegradationLadder,
                LadderConfig,
                OutlierConfig,
                OutlierEjector,
                ResilienceState,
            )

            ejector = None
            if opts.outlier_ejection:
                # p99 serve-latency outlier ejection (docs/RESILIENCE.md):
                # fed by the serve-outcome path, evaluated at wave
                # cadence, tripping the breaker serve plane.
                ejector = OutlierEjector(OutlierConfig(
                    window_s=opts.outlier_window_s,
                    quantile=opts.outlier_quantile,
                    ratio=opts.outlier_ratio))
            self.resilience = ResilienceState(
                ladder=DegradationLadder(LadderConfig(
                    cached_kv_weight=opts.ladder_cached_kv_weight,
                    serve_window_s=opts.ladder_serve_window_s,
                    serve_error_rate=opts.ladder_serve_error_rate,
                    serve_min_samples=opts.ladder_serve_min_samples,
                    wrr_queue_alpha=opts.ladder_wrr_alpha)),
                static_subset=opts.resilience_static_subset,
                ejector=ejector)
        # Multi-tenant fairness (gie_tpu/fairness, docs/FAIRNESS.md):
        # weighted-DRR flow ordering + per-tenant budgets; uniform
        # weights unless --fairness-weights names tenants.
        from gie_tpu.fairness import (
            FairnessConfig,
            FairnessState,
            parse_weights,
        )

        self.fairness = FairnessState(FairnessConfig(
            weights=parse_weights(opts.fairness_weights),
            over_share_factor=opts.fairness_over_factor,
            window_s=opts.fairness_window_s,
            top_k=opts.fairness_top_k))
        # Multiplexed keep-alive scrape engine (metricsio/engine.py,
        # docs/METRICSIO.md): a fixed shard pool polls every endpoint at
        # the fast-poll cadence; attach/detach below are O(1) so endpoint
        # churn never blocks a reconcile on a hung fetch. The attribute
        # keeps the historical `scraper` name — the lifecycle surface
        # (attach/detach/close) is API-identical.
        self.scraper = ScrapeEngine(
            self.metrics_store,
            lora=self.lora_registry,
            interval_s=opts.scrape_interval_ms / 1000.0,
            workers=opts.scrape_workers or None,
            breaker_board=(self.resilience.board
                           if self.resilience is not None else None),
        )
        if self.resilience is not None:
            # The engine's last-success clocks are the blackout signal:
            # they cover ingestion-side outages (every endpoint
            # unreachable and backing off, a wedged shard) that row ages
            # alone miss.
            self.resilience.staleness_fn = self.scraper.staleness_seconds
        self.datastore = Datastore(
            on_slot_reclaimed=self._slot_reclaimed,
            drain_deadline_s=opts.drain_deadline_s)
        self._overflow_logged = 0
        # Multi-cluster federation (gie_tpu/federation,
        # docs/FEDERATION.md): imported peer pools become schedulable
        # endpoints with a staleness-inflated cost penalty; the digest
        # exchange long-polls every configured peer.
        self.federation = None
        self.fed_exchange = None
        if opts.fed_peers or opts.fed_port > 0 or opts.fed_drain:
            from gie_tpu.federation import (
                FederationExchange,
                FederationState,
            )

            peers = {}
            for spec in opts.fed_peers:
                name, _, url = str(spec).partition("=")
                peers[name] = url
            self.federation = FederationState(
                self.datastore, self.metrics_store,
                scheduler=self.scheduler,
                cluster=opts.fed_cluster,
                penalty=opts.fed_penalty,
                stale_inflate_s=opts.fed_stale_inflate_s,
                local_only_after_s=opts.fed_local_only_after_s,
                spill_queue_limit=float(self.scheduler.cfg.queue_limit),
            )
            self.federation.draining = opts.fed_drain
            self.fed_exchange = FederationExchange(
                self.federation,
                cluster=opts.fed_cluster,
                peers=peers,
                port=opts.fed_port,
                bind=opts.fed_bind,
                serve=opts.fed_port > 0,
                interval_s=opts.fed_interval_s,
                wait_s=opts.fed_wait_s,
                max_endpoints=opts.fed_max_endpoints,
                prefix_keys_fn=self.scheduler.prefix_hot_keys,
            )
        self.picker = BatchingTPUPicker(
            self.scheduler,
            self.datastore,
            self.metrics_store,
            max_wait_s=opts.batch_window_ms / 1000.0,
            lora_registry=self.lora_registry,
            trainer=self.trainer,
            queue_bound=opts.queue_bound,
            queue_max_age_s=opts.queue_max_age_s,
            pd_budget_floor_s=opts.pd_budget_floor_ms / 1000.0,
            # Production path: first contact with a new wave-shape lattice
            # background-compiles its remaining N buckets, so a load spike
            # never stalls the dispatcher on first-use jit (ROADMAP item).
            background_warm=True,
            resilience=self.resilience,
            fairness=self.fairness,
            federation=self.federation,
        )
        own_metrics.register_pool_aggregates(self._pool_snapshot)
        self._train_stop = threading.Event()
        self._train_thread: Optional[threading.Thread] = None
        self._dump_thread: Optional[threading.Thread] = None
        self.elector = None
        # With replication enabled, the elector's holder identity carries
        # this replica's advertised digest address — the Lease doubles as
        # the followers' leader-discovery channel (docs/REPLICATION.md).
        repl_advertise = repl_identity = None
        if opts.replication_port > 0:
            from gie_tpu.replication import replication_identity

            repl_advertise = (
                opts.replication_advertise
                or f"{opts.replication_bind}:{opts.replication_port}")
            repl_identity = replication_identity(repl_advertise)
        if opts.leader_elect:
            # Kube deployments elect on a coordination.k8s.io Lease
            # (reference internal/runnable/leader_election.go) — any
            # cluster client exposing the adapter's _json HTTP core
            # qualifies; the file lease covers single-host/demo runs.
            if hasattr(cluster, "_json"):
                from gie_tpu.runtime.leader import KubeLeaseElector

                self.elector = KubeLeaseElector(
                    cluster, opts.pool_namespace,
                    f"{opts.pool_name}-epp-leader",
                    identity=repl_identity)
            else:
                from gie_tpu.runtime.leader import LeaseFileElector

                self.elector = LeaseFileElector(
                    opts.leader_lease_path, identity=repl_identity)
        # Objective registry (proposal 1199): named objectives -> bands,
        # populated from --objective NAME=CRITICALITY declarations (the CRD
        # watch adapter feeds the same registry in a kube deployment).
        from gie_tpu.api.objectives import InferenceObjective, ObjectiveRegistry

        self.objectives = ObjectiveRegistry()
        for spec in opts.objectives:
            name, _, crit = spec.partition("=")
            self.objectives.apply(
                InferenceObjective(
                    name=name,
                    pool_ref=opts.pool_name,
                    criticality=int(crit),
                    namespace=opts.pool_namespace,
                )
            )
        self.picker.objective_registry = self.objectives
        # Closed-loop replica control (gie_tpu/autoscale, docs/AUTOSCALE.md)
        # behind --autoscale-mode: the collector differentiates the pick
        # path's own counters, the recommender sizes the pool, and the
        # actuator SSA-patches the target Deployment (apply mode; leader-
        # gated) or just exports gie_autoscale_* (recommend mode).
        self.autoscaler = None
        self.capacity_model = None
        if opts.autoscale_mode != "off":
            from gie_tpu.autoscale import (
                AutoscaleController,
                AutoscaleRecommender,
                CapacityModel,
                RecommenderConfig,
                ReplicaActuator,
                SignalCollector,
            )

            # Persisted per-pool capacity estimate (ROADMAP): seed the
            # EWMA from the last leader's checkpoint instead of
            # default_per_replica, so a restarted EPP does not re-learn
            # capacity from scratch. The replication digest carries the
            # same state live between replicas; the checkpoint covers the
            # single-replica restart where there is no leader to sync
            # from.
            self.capacity_model = CapacityModel()
            if opts.autoscale_state_dir:
                if self.capacity_model.restore(opts.autoscale_state_dir):
                    self.log.info(
                        "capacity estimate restored",
                        dir=opts.autoscale_state_dir,
                        per_replica=self.capacity_model.per_replica())
            collector = SignalCollector(
                self.metrics_store,
                # Local endpoints only: the autoscaler sizes THIS
                # cluster's Deployment; counting imported peer capacity
                # as local replicas would scale against phantom pods.
                self.datastore.local_endpoints,
                queue_limit=self.scheduler.cfg.queue_limit,
                kv_limit=self.scheduler.cfg.kv_limit,
                # Stale = several scrape periods missed, floored well above
                # jitter so a slow scrape tick never freezes the loop.
                staleness_s=max(10 * opts.scrape_interval_ms / 1000.0, 1.0),
                # Second staleness source: the engine's own last-success
                # clocks cover ingestion-side outages (all endpoints
                # backing off, wedged shard) that row ages alone miss
                # when a row was re-attached and its age reset.
                scrape_engine=self.scraper,
            )
            recommender = AutoscaleRecommender(RecommenderConfig(
                min_replicas=opts.autoscale_min,
                max_replicas=opts.autoscale_max,
                shed_high_per_s=opts.autoscale_shed_high,
                down_cooldown_s=opts.autoscale_down_cooldown_s,
            ), model=self.capacity_model)
            actuator = ReplicaActuator(
                cluster if hasattr(cluster, "_json") else None,
                opts.pool_namespace,
                opts.autoscale_target,
                dry_run=opts.autoscale_mode != "apply",
                is_leader=(self.elector.is_leader
                           if self.elector is not None else None),
            )
            self.autoscaler = AutoscaleController(
                collector, recommender, actuator,
                interval_s=opts.autoscale_interval_s,
                ttft_probe=(self._autoscale_ttft_probe
                            if self.trainer is not None
                            and opts.autoscale_ttft_slo_ms > 0 else None),
                # Followers sample but never recommend: their pick
                # counters are zero by construction (NOT_SERVING), which
                # would otherwise export a standing scale-down signal.
                is_leader=(self.elector.is_leader
                           if self.elector is not None else None),
            )
        # HA state replication (gie_tpu/replication, docs/REPLICATION.md):
        # the leader publishes its soft state, non-leaders sync it into
        # their LIVE scheduler/predictor/capacity objects, and winning an
        # election later promotes warm with no restore step.
        self.replication = None
        if opts.replication_port > 0:
            from gie_tpu.replication import ReplicationManager

            self.replication = ReplicationManager(
                scheduler=self.scheduler,
                trainer=self.trainer,
                capacity_model=self.capacity_model,
                elector=self.elector,
                port=opts.replication_port,
                bind=opts.replication_bind,
                advertise=repl_advertise,
                interval_s=opts.replication_interval_s,
                stale_after_s=opts.replication_stale_after_s,
            )
            if self.elector is not None:
                self.elector.on_role_change = self.replication.on_role_change
        self.streaming = StreamingServer(
            self.datastore, self.picker,
            on_served=self.picker.observe_served,
            on_response_complete=self.picker.observe_response_complete,
            on_stream_aborted=self.picker.observe_stream_aborted,
            fast_lane=opts.extproc_fast_lane,
        )
        self.grpc_server: Optional[ExtProcWorkerPool] = None
        self.health_server: Optional[grpc.Server] = None
        self.debugz_server = None
        self.kv_events = None
        self.kv_events_server = None
        self._cert_reloader = None
        self._scenario_name: Optional[str] = None
        self._stopped = threading.Event()

    def ready(self) -> bool:
        """Readiness per 004 README:111-115: datastore synced AND (leader
        when electing)."""
        if not self.datastore.pool_has_synced():
            return False
        if self.elector is not None and not self.elector.is_leader():
            return False
        return True

    def _pool_snapshot(self) -> dict:
        """Aggregates for the HPA gauges (metrics.register_pool_aggregates)
        — evaluated lazily at metrics-scrape time. Saturation comes from
        MetricsStore.pool_aggregates, the SAME derivation the autoscale
        SignalCollector reads, so the exported series and the replica
        controller cannot disagree on pool state."""
        from gie_tpu.sched import constants as C

        # Local endpoints only: the HPA gauges size THIS cluster's
        # replica count — imported peer capacity must not read as local.
        endpoints = self.datastore.local_endpoints()
        slots = [ep.slot for ep in endpoints if 0 <= ep.slot < C.M_MAX]
        n = len(slots)
        if n == 0:
            return {"ready_endpoints": 0.0}
        cfg = self.scheduler.cfg
        agg = self.metrics_store.pool_aggregates(
            slots, queue_limit=cfg.queue_limit, kv_limit=cfg.kv_limit)
        load = self.scheduler.snapshot_assumed_load()
        # The assumed-load vector is sized to the scheduler's CURRENT M
        # bucket; a slot beyond it (endpoint registered but not yet picked
        # at the grown width) carries zero assumed load by definition.
        in_bucket = [s for s in slots if s < load.shape[0]]
        return {
            "ready_endpoints": float(n),
            "queue_depth_total": agg["queue_depth_total"],
            "kv_cache_util_mean": agg["kv_cache_util_mean"],
            "assumed_load_total": float(load[in_bucket].sum()),
            "saturated_fraction": agg["saturated_fraction"],
        }

    def _debugz_providers(self) -> dict:
        """The /debugz zpage catalog (gie_tpu/obs/debugz.py): closures
        over the live subsystems. Every provider reads a snapshot/report
        surface that takes at most a leaf lock briefly — never the pick
        lock — and all JSON serialization happens in the HTTP layer."""
        from gie_tpu import obs
        from gie_tpu.version import __version__

        def traces(q: dict):
            t = obs.TRACER
            if t is None:
                return {"disabled":
                        "tracing off (--obs-sample-rate 0 or --no-obs)"}
            return {"tracer": t.report(),
                    "traces": t.traces(q.get("kind", "recent"),
                                       n=int(q.get("n", "50")))}

        def trace(q: dict):
            t = obs.TRACER
            if t is None:
                return {"disabled": "tracing off"}
            found = t.get(q.get("id", ""))
            return found if found is not None else {
                "error": "no such trace (feed wrapped, or it was never "
                         "exported — unsampled and uneventful)"}

        def picks(q: dict):
            r = obs.RECORDER
            if r is None:
                return {"disabled": "--no-obs"}
            return r.snapshot(n=int(q.get("n", "100")))

        def pick(q: dict):
            # The per-request pick EXPLANATION: the flight-recorder
            # decision record joined with its exported trace (when one
            # exists) — "why did request X land on pod Y".
            r = obs.RECORDER
            if r is None:
                return {"disabled": "--no-obs"}
            seq = q.get("seq")
            rec = r.find(trace_id=q.get("trace", ""),
                         seq=int(seq) if seq is not None else None)
            if rec is None:
                return {"error": "no record for that trace/seq (ring "
                                 "wrapped, or the pick predates obs)"}
            out = {"record": rec}
            t = obs.TRACER
            if t is not None and rec.get("trace_id"):
                tr = t.get(rec["trace_id"])
                if tr is not None:
                    out["trace"] = tr
            return out

        def drain(q: dict):
            report = self.datastore.debug_report()
            return {
                "draining": report["draining"],
                "drain_deadline_s": report["drain_deadline_s"],
                "endpoints": [e for e in report["endpoints"]
                              if e["draining"]],
            }

        providers = {
            "traces": traces,
            "trace": trace,
            "picks": picks,
            "pick": pick,
            "queue": lambda q: self.picker.queue_report(),
            "tenants": lambda q: self.picker.tenants_report(),
            "datastore": lambda q: self.datastore.debug_report(),
            "scheduler": lambda q: self.scheduler.debug_report(),
            "drain": drain,
            "policy": lambda q: self._policy_report(),
            "buildinfo": lambda q: {
                "version": __version__,
                "fast_lane": self.opts.extproc_fast_lane,
                "resilience": self.opts.resilience,
                "obs": self._obs_installed,
                "obs_sample_rate": self.opts.obs_sample_rate,
                "fault_scenario": self.opts.fault_scenario or None,
            },
        }
        if self.resilience is not None:
            providers["breakers"] = (
                lambda q: self.resilience.board.report())
            providers["ladder"] = (
                lambda q: self.resilience.report())
            if self.resilience.ejector is not None:
                providers["outlier"] = (
                    lambda q: self.resilience.ejector.report())
        if self.fed_exchange is not None:
            # The federation zpage: peer links (era, staleness, breaker),
            # the per-cluster capacity matrix, and this cluster's drain
            # flag — the full spill-policy explanation.
            providers["federation"] = (
                lambda q: self.fed_exchange.report())
        if hasattr(self.scheduler, "fleet_report"):
            # /debugz/fleet (docs/FLEET.md): fleet geometry, compression
            # ratio, the top-K hit histogram (K-bounded) and the hottest
            # cells (row-bounded) — cardinality stays fixed no matter how
            # many cells the fleet grows, same rule obs-check enforces.
            providers["fleet"] = (
                lambda q: self.scheduler.fleet_report(
                    max_cells=int(q.get("cells", "32"))))
        return providers

    def _policy_report(self) -> dict:
        """/debugz/policy (docs/LEARNED.md): which scorer this replica
        runs, the LIVE blend/exponent weights the cycle reads, and —
        with --scorer learned — the loaded artifact's identity,
        provenance, and promotion verdict. Mirrors gie_policy_info; the
        zpage carries the detail the bounded label set cannot."""
        import dataclasses

        w = self.scheduler.weights
        report = {
            "scorer": getattr(self.scheduler.cfg, "scorer", "blend"),
            "weights": {
                f.name: float(getattr(w, f.name))
                for f in dataclasses.fields(type(w))},
        }
        art = self.policy_artifact
        if art is not None:
            judgment = art.get("judgment") or {}
            report["artifact"] = {
                "path": self.opts.policy_artifact,
                "schema": art.get("schema"),
                "checksum": art.get("checksum"),
                "feature_schema": list(art.get("feature_schema", ())),
                "provenance": art.get("provenance", {}),
                "judgment_promote": judgment.get("promote"),
                "judgment_scenarios": [
                    {"name": row.get("name"), "passed": row.get("passed")}
                    for row in judgment.get("scenarios", [])],
            }
        return report

    def _autoscale_ttft_probe(self):
        """-> (predicted_ttft_s, ttft_slo_s) for the autoscale capacity
        model's SLO cross-check, or None while unusable. Predicts the TTFT
        of a pool-TYPICAL request (nominal prompt/decode, no LoRA) on every
        ready endpoint under the live metrics + assumed load, and reports
        the median — the derate should reflect the pool's center, not one
        hot pod the scheduler already steers around."""
        import numpy as np

        from gie_tpu.models.latency import host_features
        from gie_tpu.sched import constants as C

        if getattr(self.trainer, "last_loss", None) is None:
            return None  # untrained predictor: forecasts are noise
        slots = [ep.slot for ep in self.datastore.local_endpoints()
                 if 0 <= ep.slot < C.M_MAX]
        if not slots:
            return None
        rows, ages = self.metrics_store.pool_rows(slots)
        rows[:, C.Metric.METRICS_AGE_S] = np.clip(
            np.nan_to_num(ages, posinf=1e6), 0.0, 1e6)
        load = self.scheduler.snapshot_assumed_load()
        nominal_prompt = 2048.0                       # chars
        nominal_decode = 128.0 * C.CHARS_PER_TOKEN
        feats = np.stack([
            host_features(
                rows[i],
                float(load[s]) if s < load.shape[0] else 0.0,
                nominal_prompt, nominal_decode, False)
            for i, s in enumerate(slots)
        ])
        pred = self.trainer.predict_ttft(
            feats, np.asarray(slots, np.int32))
        return (float(np.median(pred)),
                self.opts.autoscale_ttft_slo_ms / 1000.0)

    # -- scrape lifecycle follows endpoint lifecycle -----------------------

    def _slot_reclaimed(self, slot: int) -> None:
        self.scheduler.evict_endpoint(slot)
        self.scraper.detach(slot)
        if self.resilience is not None and self.resilience.ejector is not None:
            # Latency history must not outlive the endpoint: a reused
            # slot's new pod starts with a clean quantile window (the
            # breaker's own drop rides the scrape detach above).
            self.resilience.ejector.drop(slot)

    def _sync_scrapers(self) -> None:
        # Local endpoints only: imported peer endpoints' rows come from
        # the federation digest, and scraping a pod two clusters away
        # would race those installs (docs/FEDERATION.md).
        for ep in self.datastore.local_endpoints():
            self.scraper.attach(
                ep.slot, f"http://{ep.hostport}/metrics", self.mapping
            )
        overflow = self.datastore.overflow_count()
        own_metrics.SLOT_OVERFLOW.set(overflow)
        if overflow > self._overflow_logged:
            # Capacity exhaustion must be operator-visible: some pods are
            # receiving no traffic until churn frees slots or M_MAX grows.
            self.log.error(
                "endpoint capacity exhausted: admissions refused",
                refused=overflow, m_max=C.M_MAX,
            )
            self._overflow_logged = overflow

    # ---------------------------------------------------------------------

    def setup(self) -> None:
        """Wire reconcilers (reference SetupWithManager, runserver.go:78-93)."""
        gknn = GKNN(GROUP, "InferencePool", self.opts.pool_namespace,
                    self.opts.pool_name)
        pool_rec = InferencePoolReconciler(self.cluster, self.datastore, gknn)
        pod_rec = PodReconciler(self.cluster, self.datastore)
        wire(self.cluster, pool_rec, pod_rec)

        # Scrapers follow datastore content after every event.
        original_pod = pod_rec.reconcile
        original_pool = pool_rec.reconcile

        def pod_reconcile(ns, name, *args, **kw):
            res = original_pod(ns, name, *args, **kw)
            self._sync_scrapers()
            return res

        def pool_reconcile(ns, name, *args, **kw):
            res = original_pool(ns, name, *args, **kw)
            self._sync_scrapers()
            return res

        pod_rec.reconcile = pod_reconcile
        pool_rec.reconcile = pool_reconcile

        # Initial sync: reconcile pre-existing state (the cache-sync pass of
        # controller-runtime; watch events only cover changes from now on).
        pool_reconcile(self.opts.pool_namespace, self.opts.pool_name)
        for pod in self.cluster.list_pods(self.opts.pool_namespace):
            pod_reconcile(pod.namespace, pod.name)

    def start(self) -> int:
        """Start health, metrics, and the ext-proc listener; returns the
        bound ext-proc port."""
        # Dedicated health first — NOT_SERVING beats connection-refused
        # during startup (reference main.go:104-109).
        if self.elector is not None:
            self.elector.start()
        if self.replication is not None:
            self.replication.start()
            self.log.info(
                "replication manager started",
                advertise=self.replication.advertise,
                interval_s=self.opts.replication_interval_s,
            )
        if self.fed_exchange is not None:
            self.fed_exchange.start()
            self.log.info(
                "federation exchange started",
                cluster=self.opts.fed_cluster,
                peers=sorted(self.fed_exchange.links),
                port=(self.fed_exchange.server.port
                      if self.fed_exchange.server is not None else None),
                draining=self.federation.draining,
            )
        if self.opts.fault_specs:
            # gie-chaos (resilience/faults.py): arm the seeded injector.
            # Operator-driven chaos experiments only — production runs
            # leave this off and pay one flag check per woven site.
            from gie_tpu.resilience import faults

            faults.install(faults.FaultInjector(
                self.opts.fault_seed,
                faults.parse_spec(self.opts.fault_specs)))
            self.log.info("fault injection armed",
                          seed=self.opts.fault_seed,
                          specs=self.opts.fault_specs)
        elif self.opts.fault_scenario:
            # Recorded chaos scenario (resilience/scenarios.py): the
            # file carries its own seed + rules — the replayable form of
            # --fault/--fault-seed, bit-for-bit across runs.
            from gie_tpu.resilience import scenarios

            scn = scenarios.load(self.opts.fault_scenario)
            scn.arm()
            self._scenario_name = scn.name
            self.log.info("chaos scenario armed", name=scn.name,
                          seed=scn.seed, path=scn.path)
        self.health_server, _ = start_dedicated_health_server(
            self.ready, self.opts.grpc_health_port,
            self.replication.healthy if self.replication is not None
            else None,
            self.resilience.healthy if self.resilience is not None
            else None,
        )
        # The wire lane rides on the fast lane's native header scan:
        # --no-extproc-fast-lane quietly implies the legacy gRPC lane.
        wire_lane = self.opts.extproc_wire and self.opts.extproc_fast_lane
        own_metrics.set_build_info(
            fast_lane=self.opts.extproc_fast_lane,
            resilience=self.opts.resilience,
            obs=self._obs_installed,
            wire=wire_lane, workers=self.opts.extproc_workers)
        # gie_policy_info (docs/LEARNED.md): scorer identity, stamped
        # from the SAME live weights the cycle blends — dashboards can
        # join goodput series against the policy that produced them.
        import dataclasses as _dc

        _w = self.scheduler.weights
        own_metrics.set_policy_info(
            scorer=getattr(self.scheduler.cfg, "scorer", "blend"),
            weights={f.name: float(getattr(_w, f.name))
                     for f in _dc.fields(type(_w))},
            artifact=self.policy_artifact)
        try:
            self.debugz_server = own_metrics.start_metrics_server(
                self.opts.metrics_port,
                providers=self._debugz_providers(),
                debugz_bind=self.opts.debugz_bind,
                debugz_token=self.opts.debugz_token)
        except OSError as e:
            self.log.error("metrics server failed to start", err=e)

        # Colocated health on the ext-proc port (runserver.go:117-123) —
        # registered per acceptor so probes hit the same socket spread
        # real traffic does.
        def _add_health(srv):
            HealthService(
                self.ready,
                self.replication.healthy if self.replication is not None
                else None,
                self.resilience.healthy if self.resilience is not None
                else None,
            ).add_to_server(srv)

        pool = ExtProcWorkerPool(
            self.streaming, self.opts.extproc_workers, wire=wire_lane,
            health_factory=_add_health)
        addr = f"0.0.0.0:{self.opts.grpc_port}"
        creds = None
        if self.opts.secure_serving:
            creds, self._cert_reloader = server_credentials(self.opts.cert_path)
        port = pool.bind(addr, creds)
        pool.start()
        self.grpc_server = pool
        if self.opts.kv_events_port > 0:
            from gie_tpu.sched.kvevents import (
                KVEventAggregator,
                KVEventHTTPServer,
            )

            def _resolve(hostport: str):
                ep = self.datastore.endpoint_by_hostport(hostport)
                return None if ep is None else ep.slot

            self.kv_events = KVEventAggregator(self.scheduler, _resolve)
            self.kv_events_server = KVEventHTTPServer(
                self.kv_events, self.opts.kv_events_port,
                bind=self.opts.kv_events_bind,
                token=self.opts.kv_events_token)
            self.log.info("kv-events ingest listening",
                          port=self.kv_events_server.port,
                          bind=self.opts.kv_events_bind,
                          auth=self.opts.kv_events_token is not None)
        if self.trainer is not None:
            self._train_thread = threading.Thread(
                target=self._train_loop, daemon=True
            )
            self._train_thread.start()
        if self.opts.obs_dump_interval_s > 0 and self._obs_installed:
            # Periodic flight-recorder harvesting (--obs-dump-interval-s,
            # docs/LEARNED.md): gie-learn's training feed. The rotator
            # bounds the file count itself; the thread holds no gie_tpu
            # lock across the dump (GL002 — export I/O is in the
            # blocking set).
            from gie_tpu.obs.recorder import DumpRotator

            rotator = DumpRotator(self.opts.obs_dump_dir,
                                  keep=self.opts.obs_dump_keep)

            def _dump_loop():
                while not self._stopped.wait(self.opts.obs_dump_interval_s):
                    path = rotator.rotate_once()
                    if path:
                        self.log.v(3).info("flight recorder rotated",
                                           path=path)

            self._dump_thread = threading.Thread(
                target=_dump_loop, daemon=True)
            self._dump_thread.start()
            self.log.info("obs dump rotation started",
                          dir=self.opts.obs_dump_dir,
                          interval_s=self.opts.obs_dump_interval_s,
                          keep=self.opts.obs_dump_keep)
        if self.autoscaler is not None:
            self.autoscaler.start()
            self.log.info(
                "autoscale loop started",
                mode=self.opts.autoscale_mode,
                target=self.opts.autoscale_target,
                bounds=(self.opts.autoscale_min, self.opts.autoscale_max),
            )
        self.log.info(
            "ext-proc server started",
            port=port,
            secure=self.opts.secure_serving,
            workers=self.opts.extproc_workers,
            wire=wire_lane,
            health_port=self.opts.grpc_health_port,
            metrics_port=self.opts.metrics_port,
        )
        return port

    def _train_loop(self) -> None:
        """Periodic online training + params handoff + checkpointing."""
        while not self._train_stop.wait(self.opts.predictor_train_interval_s):
            try:
                loss = self.trainer.train(steps=10)
                if loss is None:
                    continue
                if self.scheduler.predictor_fn is not None:
                    # Only hand off params when the cycle actually binds
                    # the column: installing a params tree into a cycle
                    # compiled with predictor_params=None flips the jit
                    # argument's pytree structure and recompiles every
                    # warmed bucket inside the pick lock.
                    self.scheduler.set_predictor_params(self.trainer.params)
                    live_w = self.scheduler.gate_latency_column(
                        self.trainer.confidence())
                else:
                    live_w = 0.0
                self.log.v(3).info("predictor trained", loss=loss,
                                   latency_weight=live_w)
                if self.opts.predictor_checkpoint_dir:
                    self.trainer.save(self.opts.predictor_checkpoint_dir)
            except Exception as e:  # training must never take the EPP down
                self.log.error("predictor training failed", err=e)

    def wait(self) -> None:
        if self.grpc_server is not None:
            self.grpc_server.wait_for_termination()

    def stop(self, grace: float = 5.0) -> None:
        """Graceful stop (reference grpc.go:44-57)."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if self.replication is not None:
            self.replication.stop()
        if self.fed_exchange is not None:
            self.fed_exchange.stop()
        # Persist the capacity EWMA on LEADER shutdown (ROADMAP): the
        # next single-replica start seeds from it instead of the default.
        # Followers skip the write — their copy lags the leader's, and
        # the last writer would win the directory.
        if (self.capacity_model is not None
                and self.opts.autoscale_state_dir
                and (self.elector is None or self.elector.is_leader())):
            try:
                self.capacity_model.save(self.opts.autoscale_state_dir)
            except Exception as e:  # shutdown must finish regardless
                self.log.error("capacity checkpoint failed", err=e)
        self._train_stop.set()
        if self._train_thread is not None:
            self._train_thread.join(timeout=5)
        if self._dump_thread is not None:
            # _stopped is already set; the wait()-gated loop exits on
            # its next wake.
            self._dump_thread.join(timeout=5)
        if self.grpc_server is not None:
            self.grpc_server.stop(grace).wait()
        if self.health_server is not None:
            self.health_server.stop(0)
        if self.kv_events_server is not None:
            self.kv_events.flush()
            self.kv_events_server.close()
        self.picker.close()
        self.scraper.close()
        if self.debugz_server is not None:
            try:
                self.debugz_server.close()
            except Exception:
                pass  # listener teardown must not block shutdown
        if self._obs_installed:
            from gie_tpu import obs

            if self.opts.fault_scenario:
                # Chaos-scenario artifact (docs/OBSERVABILITY.md): the
                # ring buffer IS the explanation of what the scenario
                # did to the pick path — dump it so a failed run reads
                # back its own decisions.
                path = obs.dump_artifact(
                    self.opts.obs_dump_dir,
                    name=self._scenario_name or "scenario")
                if path:
                    self.log.info("flight recorder dumped", path=path)
            obs.uninstall()
        if self._otlp is not None:
            self._otlp.close()
        if self.opts.fault_specs:
            from gie_tpu.resilience import faults

            faults.uninstall()
        if self.elector is not None:
            self.elector.stop()
        if self._cert_reloader is not None:
            self._cert_reloader.close()
        self.log.info("shutdown complete")
