"""Leader election for multi-replica EPP deployments.

Reference semantics (internal/runnable/leader_election.go + the endpoint-
picker protocol's readiness rules, 004 README:111-115): multiple replicas
run, exactly one leads; followers keep liveness SERVING but readiness
NOT_SERVING so the data plane only routes ext-proc traffic to the leader.

Two electors share the start/stop/is_leader surface:

  KubeLeaseElector — coordination.k8s.io/v1 Lease objects through the
      stdlib kube adapter (the reference's client-go leaderelection
      equivalent): acquire-on-404/expiry, holder-only renew, optimistic
      concurrency via resourceVersion (a 409 means another replica won),
      graceful release on stop. The real-cluster elector.
  LeaseFileElector — a filesystem lease with atomic primitives: the
      single-host/demo fallback.

File-lease mutual exclusion:

  takeover of an expired lease = rename(lease -> lease.expired.<id>)
      (exactly one contender's rename succeeds; losers get ENOENT), then
      exclusive-create (O_CREAT|O_EXCL) of the fresh lease;
  absent lease                 = exclusive-create directly;
  renewal by the holder        = write-temp + rename (atomic, holder-only).

A lease whose timestamp is in the FUTURE beyond the TTL is treated as
corrupt and eligible for takeover (clock steps / pre-created files must not
brick the deployment). Leadership is derived from what the lease file
actually says, so a transiently failed renewal does not drop a leadership
the file still grants, and stop() only releases a lease this replica still
holds.
"""

from __future__ import annotations

import datetime
import os
import threading
import time
import uuid
from typing import Optional

_LEASES = "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"


class _RoleCallbackBase:
    """Shared role-transition plumbing for both electors.

    `on_role_change` is an optional (is_leader: bool) -> None invoked
    from the renew thread on every leadership flip (the replication
    manager's promotion/demotion hook). Exceptions are swallowed: a
    callback bug must not kill the election loop. All `_leader` writes
    on the loop/stop paths go through `_set_leader` so observers can
    never miss a flip."""

    on_role_change = None
    _leader = False

    def is_leader(self) -> bool:
        return self._leader

    def _set_leader(self, leader: bool) -> None:
        was, self._leader = self._leader, leader
        if leader != was and self.on_role_change is not None:
            try:
                self.on_role_change(leader)
            except Exception:
                pass


def _microtime(t: Optional[float] = None) -> str:
    """metav1.MicroTime wire format. (Written, never parsed: expiry is
    judged by locally-observed record CHANGES, not by wall-clock
    comparison — see KubeLeaseElector.)"""
    return (
        datetime.datetime.fromtimestamp(
            time.time() if t is None else t, datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%S.%fZ")
    )


class KubeLeaseElector(_RoleCallbackBase):
    """Distributed leader election on a coordination.k8s.io/v1 Lease.

    `client` is the stdlib kube adapter (controller/kube.py
    KubeClusterClient) — anything exposing its `_json(method, path,
    body)` HTTP core works. Contention rules (reference
    internal/runnable/leader_election.go via client-go leaderelection):

      404                -> POST create with our holderIdentity; a 409
                            means another replica created first.
      holder == us       -> PUT renewTime refresh carrying the observed
                            resourceVersion; 409 = someone took the
                            lease from under us -> follower.
      holder empty/other -> take over ONLY when the lease is expired;
                            the PUT carries the observed resourceVersion
                            so exactly one contender wins the takeover.

    Expiry is judged by LOCAL observation, never by the record's own
    timestamps (client-go leaderelection's rule): a foreign lease is
    expired when its (holder, renewTime) pair has not CHANGED for
    leaseDurationSeconds of this replica's monotonic clock. Comparing
    the holder's wall-clock renewTime against our wall clock would let
    a replica with a skewed clock steal a live lease — two ready
    leaders.

    Failed renews get a grace window: a transient apiserver error keeps
    locally-confirmed leadership until the lease we last wrote would
    have expired anyway (client-go's renewDeadline tolerance) — without
    it, one 5xx blips readiness fleet-wide while the unexpired Lease
    still blocks every other replica. A 409 (someone else holds the
    lease) always drops leadership immediately.

    stop() releases a lease we still hold by blanking holderIdentity, so
    failover needs no TTL wait on clean shutdown."""

    def __init__(
        self,
        client,
        namespace: str,
        lease_name: str,
        *,
        identity: Optional[str] = None,
        lease_ttl_s: float = 15.0,
        renew_interval_s: float = 2.0,
        on_role_change=None,
    ):
        self.client = client
        self.path = _LEASES.format(ns=namespace) + f"/{lease_name}"
        self.create_path = _LEASES.format(ns=namespace)
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.lease_ttl_s = lease_ttl_s
        self.renew_interval_s = renew_interval_s
        self.on_role_change = on_role_change  # see _RoleCallbackBase
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Local-observation record for skew-safe expiry:
        # (holder, renewTime-string) -> monotonic time we FIRST saw it.
        self._observed: Optional[tuple[str, str]] = None
        self._observed_at = 0.0
        # Monotonic deadline until which a transient renew failure keeps
        # locally-confirmed leadership (see class docstring).
        self._good_until = 0.0

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._leader:
            try:
                lease = self.client._json("GET", self.path)
                spec = lease.get("spec") or {}
                if spec.get("holderIdentity") == self.identity:
                    spec["holderIdentity"] = ""
                    spec["renewTime"] = _microtime()
                    lease["spec"] = spec
                    self.client._json("PUT", self.path, lease)
            except Exception:
                pass  # release is best-effort; the TTL backstops it
        self._set_leader(False)  # demotion observers fire on clean stop too

    def holder_identity(self) -> Optional[str]:
        """Lease holder identity as last OBSERVED by the renew loop —
        the replication follower's leader-discovery channel: the holder
        string carries the leader's advertised digest address
        (replication.manager.replication_identity). Served from the
        `_observed` record `_tick` already maintains (at most
        renew_interval_s stale) instead of a fresh GET: the follower
        polls this every sync interval, and doubling the apiserver's
        lease-read QPS per follower just to re-learn what the elector
        read moments ago would scale badly across pools and replicas."""
        rec = self._observed
        if rec is not None and rec[0]:
            return rec[0]
        # Before the loop's first successful GET (or while we hold the
        # lease ourselves via the create path): our own leadership is
        # authoritative locally.
        return self.identity if self._leader else None

    # ------------------------------------------------------------------ #

    def _lease_body(self, acquire: bool, base: Optional[dict] = None) -> dict:
        lease = base if base is not None else {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.lease_name,
                         "namespace": self.namespace},
        }
        spec = dict(lease.get("spec") or {})
        now = _microtime()
        if acquire:
            spec["acquireTime"] = now
            spec["leaseTransitions"] = int(
                spec.get("leaseTransitions") or 0) + 1
        spec["holderIdentity"] = self.identity
        spec["renewTime"] = now
        spec["leaseDurationSeconds"] = int(max(self.lease_ttl_s, 1))
        lease["spec"] = spec
        return lease

    def _tick(self) -> bool:
        from gie_tpu.controller.kube import ApiError

        try:
            lease = self.client._json("GET", self.path)
        except ApiError as e:
            if e.status != 404:
                raise
            try:
                self.client._json(
                    "POST", self.create_path, self._lease_body(acquire=True))
                return True
            except ApiError as e2:
                if e2.status == 409:
                    return False  # another replica created first
                raise
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity") or ""
        ttl = float(spec.get("leaseDurationSeconds")
                    or self.lease_ttl_s)
        # Skew-safe expiry: the lease is stale only when ITS OWN record
        # (holder + renewTime string) has sat unchanged for ttl seconds
        # of OUR monotonic clock. The record's wall-clock value is never
        # compared against ours.
        record = (holder, str(spec.get("renewTime") or ""))
        now_mono = time.monotonic()
        if record != self._observed:
            self._observed = record
            self._observed_at = now_mono
        expired = (now_mono - self._observed_at) > ttl
        if holder == self.identity:
            body = self._lease_body(acquire=False, base=lease)
        elif not holder or expired:
            body = self._lease_body(acquire=True, base=lease)
        else:
            return False  # live foreign lease
        try:
            self.client._json("PUT", self.path, body)
            return True
        except ApiError as e:
            if e.status == 409:
                return False  # lost the optimistic-concurrency race
            raise

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._set_leader(self._tick())
                if self._leader:
                    # The lease we just wrote blocks every other replica
                    # for ttl; transient failures inside that window keep
                    # leadership (renewDeadline grace).
                    self._good_until = (
                        time.monotonic() + self.lease_ttl_s)
            except Exception:
                # Apiserver unreachable: keep locally-confirmed
                # leadership while our last written lease is still
                # unexpired (no one else can hold it), then fail safe to
                # follower. Followers stay followers.
                self._set_leader(
                    self._leader
                    and time.monotonic() < self._good_until
                )
            self._stop.wait(self.renew_interval_s)


class LeaseFileElector(_RoleCallbackBase):
    def __init__(
        self,
        lease_path: str,
        *,
        identity: Optional[str] = None,
        lease_ttl_s: float = 5.0,
        renew_interval_s: float = 1.0,
        on_role_change=None,
    ):
        self.lease_path = lease_path
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.lease_ttl_s = lease_ttl_s
        self.renew_interval_s = renew_interval_s
        self.on_role_change = on_role_change  # see _RoleCallbackBase
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Release only a lease we still hold (we may have lost it while
        # stalled; unlinking the new leader's lease would cause a second
        # avoidable takeover race).
        holder, _ = self._read_lease()
        if holder == self.identity:
            try:
                os.unlink(self.lease_path)
            except OSError:
                pass
        self._set_leader(False)  # demotion observers fire on clean stop too

    def holder_identity(self) -> Optional[str]:
        """Current live lease holder (None when absent/expired) — same
        leader-discovery contract as KubeLeaseElector.holder_identity.
        The file read is local and cheap, so no observation cache is
        needed here."""
        holder, ts = self._read_lease()
        if holder is None or not self._lease_valid(ts, time.time()):
            return None
        return holder

    # ------------------------------------------------------------------ #

    def _read_lease(self) -> tuple[Optional[str], float]:
        try:
            with open(self.lease_path) as f:
                holder, ts = f.read().strip().split("\n")
            return holder, float(ts)
        except (OSError, ValueError):
            return None, 0.0

    def _lease_valid(self, ts: float, now: float) -> bool:
        """Within TTL, in either direction — a far-future timestamp is
        corruption, not an eternal lease."""
        return abs(now - ts) <= self.lease_ttl_s

    def _renew(self) -> bool:
        """Holder-only atomic refresh."""
        tmp = f"{self.lease_path}.{self.identity}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(f"{self.identity}\n{time.time()}")
            os.replace(tmp, self.lease_path)
            return True
        except OSError:
            return False

    def _exclusive_create(self) -> bool:
        """Claim an absent lease; exactly one contender's O_EXCL wins."""
        try:
            fd = os.open(self.lease_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            return False
        try:
            os.write(fd, f"{self.identity}\n{time.time()}".encode())
        finally:
            os.close(fd)
        return True

    def _take_over_expired(self) -> bool:
        """Atomically retire the dead lease (one rename wins), then claim."""
        retired = f"{self.lease_path}.expired.{self.identity}"
        try:
            os.rename(self.lease_path, retired)
        except OSError:
            return False  # someone else won the takeover
        try:
            os.unlink(retired)
        except OSError:
            pass
        return self._exclusive_create()

    def _tick(self) -> bool:
        holder, ts = self._read_lease()
        now = time.time()
        if holder == self.identity:
            if self._renew():
                return True
            # Transient write failure: the file still grants us the lease
            # while it is fresh — do not flap readiness over one EIO.
            holder, ts = self._read_lease()
            return holder == self.identity and self._lease_valid(ts, now)
        if holder is None:
            return self._exclusive_create()
        if not self._lease_valid(ts, now):
            return self._take_over_expired()
        return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._set_leader(self._tick())
            except Exception:
                self._set_leader(False)
            self._stop.wait(self.renew_interval_s)
