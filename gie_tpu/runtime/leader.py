"""Leader election for multi-replica EPP deployments.

Reference semantics (internal/runnable/leader_election.go + the endpoint-
picker protocol's readiness rules, 004 README:111-115): multiple replicas
run, exactly one leads; followers keep liveness SERVING but readiness
NOT_SERVING so the data plane only routes ext-proc traffic to the leader.

Implementation: a filesystem lease with atomic primitives — the right shape
for single-host/demo deployments and the seam where a Kubernetes Lease
object plugs in for real clusters. Mutual exclusion:

  takeover of an expired lease = rename(lease -> lease.expired.<id>)
      (exactly one contender's rename succeeds; losers get ENOENT), then
      exclusive-create (O_CREAT|O_EXCL) of the fresh lease;
  absent lease                 = exclusive-create directly;
  renewal by the holder        = write-temp + rename (atomic, holder-only).

A lease whose timestamp is in the FUTURE beyond the TTL is treated as
corrupt and eligible for takeover (clock steps / pre-created files must not
brick the deployment). Leadership is derived from what the lease file
actually says, so a transiently failed renewal does not drop a leadership
the file still grants, and stop() only releases a lease this replica still
holds.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Optional


class LeaseFileElector:
    def __init__(
        self,
        lease_path: str,
        *,
        identity: Optional[str] = None,
        lease_ttl_s: float = 5.0,
        renew_interval_s: float = 1.0,
    ):
        self.lease_path = lease_path
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.lease_ttl_s = lease_ttl_s
        self.renew_interval_s = renew_interval_s
        self._leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------ #

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        # Release only a lease we still hold (we may have lost it while
        # stalled; unlinking the new leader's lease would cause a second
        # avoidable takeover race).
        holder, _ = self._read_lease()
        if holder == self.identity:
            try:
                os.unlink(self.lease_path)
            except OSError:
                pass
        self._leader = False

    def is_leader(self) -> bool:
        return self._leader

    # ------------------------------------------------------------------ #

    def _read_lease(self) -> tuple[Optional[str], float]:
        try:
            with open(self.lease_path) as f:
                holder, ts = f.read().strip().split("\n")
            return holder, float(ts)
        except (OSError, ValueError):
            return None, 0.0

    def _lease_valid(self, ts: float, now: float) -> bool:
        """Within TTL, in either direction — a far-future timestamp is
        corruption, not an eternal lease."""
        return abs(now - ts) <= self.lease_ttl_s

    def _renew(self) -> bool:
        """Holder-only atomic refresh."""
        tmp = f"{self.lease_path}.{self.identity}.tmp"
        try:
            with open(tmp, "w") as f:
                f.write(f"{self.identity}\n{time.time()}")
            os.replace(tmp, self.lease_path)
            return True
        except OSError:
            return False

    def _exclusive_create(self) -> bool:
        """Claim an absent lease; exactly one contender's O_EXCL wins."""
        try:
            fd = os.open(self.lease_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            return False
        try:
            os.write(fd, f"{self.identity}\n{time.time()}".encode())
        finally:
            os.close(fd)
        return True

    def _take_over_expired(self) -> bool:
        """Atomically retire the dead lease (one rename wins), then claim."""
        retired = f"{self.lease_path}.expired.{self.identity}"
        try:
            os.rename(self.lease_path, retired)
        except OSError:
            return False  # someone else won the takeover
        try:
            os.unlink(retired)
        except OSError:
            pass
        return self._exclusive_create()

    def _tick(self) -> bool:
        holder, ts = self._read_lease()
        now = time.time()
        if holder == self.identity:
            if self._renew():
                return True
            # Transient write failure: the file still grants us the lease
            # while it is fresh — do not flap readiness over one EIO.
            holder, ts = self._read_lease()
            return holder == self.identity and self._lease_valid(ts, now)
        if holder is None:
            return self._exclusive_create()
        if not self._lease_valid(ts, now):
            return self._take_over_expired()
        return False

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._leader = self._tick()
            except Exception:
                self._leader = False
            self._stop.wait(self.renew_interval_s)
