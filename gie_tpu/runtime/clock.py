"""The Clock seam: one time authority for every clock-governed path
(docs/STORM.md "virtual clock").

Every subsystem whose BEHAVIOR depends on time — breaker dwells, ladder
probe cadence, outlier windows, deadline budgets, backoff pacing, the
scrape engine's shard heaps, the autoscale loop, the federation
staleness clocks, and the storm engine's whole timeline — reads time
and blocks through a :class:`Clock` instead of calling ``time`` /
``threading`` primitives directly (lint rule GC001 enforces this for
the storm/resilience/metricsio/autoscale/federation packages).
Observability timestamps (trace events, bench numbers, flight-record
``ts`` fields) deliberately stay on the real clock: they describe when
something happened in the world, not when the simulation said it did.

Two implementations:

:class:`MonotonicClock` (the module singleton :data:`MONOTONIC`) is a
thin passthrough — ``now`` is ``time.monotonic``, ``sleep`` is
``time.sleep``, the wait/notify surface maps 1:1 onto the underlying
``threading`` primitive. Production behavior is bit-identical to the
pre-seam code.

:class:`VirtualClock` is a deterministic discrete-event clock for the
gie-twin digital twin (ROADMAP item 6): time is a number that advances
only when every REGISTERED ACTOR is parked in a clock primitive. The
rules that make a multi-threaded simulation deterministic:

  * an *actor* is a thread registered via :meth:`actor_begin` /
    :meth:`actor_thread`; unregistered threads that park are counted as
    ephemeral actors for the duration of the park (warmup helpers,
    teardown), never between parks;
  * ``sleep``/``wait``/``wait_event`` PARK the calling actor: a heap
    entry records its virtual deadline (untimed condition waits have
    none — they wake only by notification);
  * ``notify``/``set_event`` never wake a waiter directly: they move
    its entry to the READY queue *at the current virtual instant* and
    the waiter stays parked until the clock fires it;
  * when the last actor parks, the clock fires exactly ONE entry —
    READY entries first (FIFO: notification order), then the earliest
    heap deadline (advancing ``now`` to it; ties break by registration
    sequence). The fired actor runs to its next park before anything
    else is woken, so execution is a serialized run-to-completion
    schedule and two same-seed runs replay the identical interleaving
    (the storm scorecard's ``decision_fingerprint`` pins this).

The actual wake actions (``Event.set`` / ``Condition.notify_all``) run
on a dedicated non-actor waker thread so the advancing thread never
acquires another actor's condition lock while holding the clock lock.

Lock order: an actor may call into the clock while holding the
condition it waits on/notifies, so ``VirtualClock._lock`` ranks below
every such condition in the declared hierarchy (lockorder.toml) and no
clock method takes any other lock while holding it.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Optional


class Clock:
    """The seam's surface. ``wait``/``notify`` take a
    ``threading.Condition`` whose lock the caller holds (the stdlib
    contract); ``wait_event``/``set_event`` take a ``threading.Event``.
    On the real clock every method is a passthrough; on the virtual
    clock they are the park/wake points the simulation is built from."""

    is_virtual = False

    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def wait(self, cond, timeout: Optional[float] = None) -> bool:
        raise NotImplementedError

    def notify(self, cond) -> None:
        raise NotImplementedError

    def notify_all(self, cond) -> None:
        raise NotImplementedError

    def wait_event(self, event, timeout: Optional[float] = None) -> bool:
        raise NotImplementedError

    def set_event(self, event) -> None:
        raise NotImplementedError

    # -- actor registration (no-ops on the real clock) ---------------------

    def actor_begin(self, name: str = ""):
        """Register the CURRENT thread as an actor; returns a token for
        :meth:`actor_end`."""
        return None

    def actor_end(self, token) -> None:
        pass

    def actor_thread(self, target, name: Optional[str] = None,
                     args: tuple = ()) -> threading.Thread:
        """An unstarted daemon thread pre-registered as an actor (the
        registration counts from NOW, so the clock cannot advance past
        work the spawner just scheduled)."""
        return threading.Thread(target=target, name=name, args=args,
                                daemon=True)


class MonotonicClock(Clock):
    """Production clock: a thin ``time.monotonic`` passthrough."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    def wait(self, cond, timeout: Optional[float] = None) -> bool:
        return cond.wait(timeout)

    def notify(self, cond) -> None:
        cond.notify()

    def notify_all(self, cond) -> None:
        cond.notify_all()

    def wait_event(self, event, timeout: Optional[float] = None) -> bool:
        return event.wait(timeout)

    def set_event(self, event) -> None:
        event.set()


MONOTONIC = MonotonicClock()

# Wall-clock callable for subsystems whose historical convention is
# epoch-seconds stamps (MetricsStore rows, the autoscale signal
# windows). A virtual-time harness swaps in its own callable; what
# matters is that producers and consumers of one timestamp family share
# a single source — GC001 keeps direct ``time.time()`` calls out of the
# clock-governed packages so the swap point stays unique.
REALTIME = time.time


# _Entry states.
_PARKED = 0
_READY = 1
_FIRED = 2
_DONE = 3


class _Entry:
    """One parked actor's wake record."""

    __slots__ = ("kind", "cond", "watch", "wake", "deadline", "state",
                 "timed_out", "seq")

    def __init__(self, kind: str, cond=None, watch=None, wake=None):
        self.kind = kind          # "sleep" | "cond" | "evt"
        self.cond = cond          # the Condition a "cond" entry waits on
        self.watch = watch        # the Event an "evt" entry waits for
        self.wake = wake          # private Event for "sleep"/"evt" parks
        self.deadline: Optional[float] = None
        self.state = _PARKED
        self.timed_out = False
        self.seq = 0


class VirtualClock(Clock):
    """Deterministic event-heap clock (module docstring has the rules)."""

    is_virtual = True

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._lock = threading.Lock()
        self._seqs = itertools.count()
        self._actors = 0
        self._parked = 0
        self._heap: list[tuple[float, int, _Entry]] = []
        self._ready: deque[_Entry] = deque()
        # id(obj) -> (obj, [entries]) — the obj reference keeps the id
        # stable while entries exist.
        self._cond_waiters: dict[int, tuple] = {}
        self._evt_waiters: dict[int, tuple] = {}
        self._tl = threading.local()
        # Wake executor: a NON-actor daemon performing the real
        # Event.set / Condition.notify_all for fired entries, so the
        # thread that triggered an advance never takes another actor's
        # condition lock itself.
        self._fire_q: deque[_Entry] = deque()
        self._fire_wake = threading.Event()
        self._stopped = False
        self._waker = threading.Thread(
            target=self._waker_loop, name="virtual-clock-waker", daemon=True)
        self._waker.start()

    # -- introspection -----------------------------------------------------

    def now(self) -> float:
        return self._now

    def shutdown(self) -> None:
        """Stop the waker thread (engine teardown). Idempotent."""
        self._stopped = True
        self._fire_wake.set()

    # -- actor registry ----------------------------------------------------

    def actor_begin(self, name: str = ""):
        self._tl.actor = True
        with self._lock:
            self._actors += 1
        return name or "actor"

    def actor_end(self, token) -> None:
        self._tl.actor = False
        with self._lock:
            self._actors -= 1
            self._maybe_advance_locked()

    def actor_thread(self, target, name: Optional[str] = None,
                     args: tuple = ()) -> threading.Thread:
        with self._lock:
            self._actors += 1

        def run():
            self._tl.actor = True
            try:
                target(*args)
            finally:
                self._tl.actor = False
                with self._lock:
                    self._actors -= 1
                    self._maybe_advance_locked()

        return threading.Thread(target=run, name=name, daemon=True)

    def _ephemeral_begin(self) -> bool:
        """Unregistered thread about to park: count it as an actor for
        the duration of the park only (warmup/teardown helpers must not
        stall the advance rule while blocked, and must not gate it while
        running)."""
        if getattr(self._tl, "actor", False):
            return False
        with self._lock:
            self._actors += 1
        return True

    def _ephemeral_end_locked(self) -> None:
        self._actors -= 1
        self._maybe_advance_locked()

    # -- the advance rule --------------------------------------------------

    def _maybe_advance_locked(self) -> None:
        """Fire exactly one entry once every registered actor is parked.
        Caller holds ``_lock``."""
        if self._actors <= 0 or self._parked < self._actors:
            return
        while self._ready:
            e = self._ready.popleft()
            if e.state == _READY:
                self._fire_locked(e)
                return
        while self._heap:
            deadline, _seq, e = self._heap[0]
            heapq.heappop(self._heap)
            if e.state != _PARKED:
                continue  # notified/fired since scheduling: lazy-dropped
            if deadline > self._now:
                self._now = deadline
            e.timed_out = True
            self._fire_locked(e)
            return
        # All actors parked with nothing scheduled and nothing ready:
        # the simulation is idle (pre-traffic construction, post-run
        # teardown). Progress resumes when an external thread posts
        # work; a genuine mid-run deadlock surfaces as the caller's own
        # bounded timeout (every daemon loop's waits are GR001-bounded).

    def _fire_locked(self, e: _Entry) -> None:
        e.state = _FIRED
        self._parked -= 1
        self._fire_q.append(e)
        self._fire_wake.set()

    def _waker_loop(self) -> None:
        while not self._stopped:
            self._fire_wake.wait(0.2)
            self._fire_wake.clear()
            while self._fire_q:
                e = self._fire_q.popleft()
                if e.kind == "cond":
                    with e.cond:
                        e.cond.notify_all()
                else:
                    e.wake.set()

    # -- parks -------------------------------------------------------------

    def sleep(self, seconds: float) -> None:
        eph = self._ephemeral_begin()
        e = _Entry("sleep", wake=threading.Event())
        with self._lock:
            e.seq = next(self._seqs)
            e.deadline = self._now + max(float(seconds), 0.0)
            heapq.heappush(self._heap, (e.deadline, e.seq, e))
            self._parked += 1
            self._maybe_advance_locked()
        e.wake.wait()
        with self._lock:
            e.state = _DONE
            if eph:
                self._ephemeral_end_locked()

    def wait(self, cond, timeout: Optional[float] = None) -> bool:
        """Park on ``cond`` (caller holds its lock, stdlib contract)
        until notified through the clock or the virtual timeout elapses.
        Returns False only on timeout."""
        eph = self._ephemeral_begin()
        e = _Entry("cond", cond=cond)
        with self._lock:
            e.seq = next(self._seqs)
            self._cond_waiters.setdefault(id(cond), (cond, []))[1].append(e)
            if timeout is not None:
                e.deadline = self._now + max(float(timeout), 0.0)
                heapq.heappush(self._heap, (e.deadline, e.seq, e))
            self._parked += 1
            self._maybe_advance_locked()
        # The check-then-wait is race-free because the caller holds the
        # condition's lock: the waker cannot notify until cond.wait()
        # releases it.
        while e.state in (_PARKED, _READY):
            cond.wait()
        with self._lock:
            e.state = _DONE
            pair = self._cond_waiters.get(id(cond))
            if pair is not None:
                try:
                    pair[1].remove(e)
                except ValueError:
                    pass
                if not pair[1]:
                    del self._cond_waiters[id(cond)]
            if eph:
                self._ephemeral_end_locked()
        return not e.timed_out

    def wait_event(self, event, timeout: Optional[float] = None) -> bool:
        if event.is_set():
            return True
        eph = self._ephemeral_begin()
        e = _Entry("evt", watch=event, wake=threading.Event())
        parked = False
        with self._lock:
            if event.is_set():  # set_event raced in under the clock lock
                if eph:
                    self._ephemeral_end_locked()
            else:
                e.seq = next(self._seqs)
                self._evt_waiters.setdefault(
                    id(event), (event, []))[1].append(e)
                if timeout is not None:
                    e.deadline = self._now + max(float(timeout), 0.0)
                    heapq.heappush(self._heap, (e.deadline, e.seq, e))
                self._parked += 1
                parked = True
                self._maybe_advance_locked()
        if not parked:
            return True
        e.wake.wait()
        with self._lock:
            e.state = _DONE
            pair = self._evt_waiters.get(id(event))
            if pair is not None:
                try:
                    pair[1].remove(e)
                except ValueError:
                    pass
                if not pair[1]:
                    del self._evt_waiters[id(event)]
            if eph:
                self._ephemeral_end_locked()
        return event.is_set()

    # -- wakes (defer to the advance rule; see module docstring) -----------

    def _ready_cond_locked(self, cond, limit: Optional[int] = None) -> None:
        pair = self._cond_waiters.get(id(cond))
        if pair is None:
            return
        n = 0
        for e in pair[1]:
            if e.state == _PARKED:
                e.state = _READY
                self._ready.append(e)
                n += 1
                if limit is not None and n >= limit:
                    break

    def notify(self, cond) -> None:
        with self._lock:
            self._ready_cond_locked(cond, limit=1)
            self._maybe_advance_locked()
        # Real notify too: a non-clock waiter on the same condition (or
        # a clock waiter re-checking its state) must not be stranded.
        cond.notify_all()

    def notify_all(self, cond) -> None:
        with self._lock:
            self._ready_cond_locked(cond)
            self._maybe_advance_locked()
        cond.notify_all()

    def set_event(self, event) -> None:
        with self._lock:
            event.set()
            pair = self._evt_waiters.get(id(event))
            if pair is not None:
                for e in pair[1]:
                    if e.state == _PARKED:
                        e.state = _READY
                        self._ready.append(e)
            self._maybe_advance_locked()
