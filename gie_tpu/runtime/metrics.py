"""Prometheus metrics OUT: the EPP's own observability.

The reference exposes controller-runtime's metrics endpoint on :9090
(cmd/lwepp/main.go:75-77); the full-EPP spec adds scheduler metrics. Here:
pick counts/latency, shed/unavailable counts, batch sizes, assumed load.
"""

from __future__ import annotations

import prometheus_client as prom

REGISTRY = prom.CollectorRegistry()

PICKS = prom.Counter(
    "gie_picks_total", "Endpoint picks by outcome", ["outcome"], registry=REGISTRY
)
PICK_LATENCY = prom.Histogram(
    "gie_pick_latency_seconds",
    "End-to-end pick latency (enqueue to result)",
    buckets=(1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0),
    registry=REGISTRY,
)
BATCH_SIZE = prom.Histogram(
    "gie_sched_batch_size",
    "Requests per scheduling cycle",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
    registry=REGISTRY,
)
STREAMS = prom.Gauge(
    "gie_active_streams", "Open ext-proc streams", registry=REGISTRY
)
SLOT_OVERFLOW = prom.Gauge(
    "gie_endpoint_slot_overflow_total",
    "Endpoint admissions refused because every scheduler slot (M_MAX) was "
    "taken — the pool outgrew the compiled capacity",
    registry=REGISTRY,
)


def start_metrics_server(port: int) -> None:
    prom.start_http_server(port, registry=REGISTRY)
