"""Prometheus metrics OUT: the EPP's own observability.

The reference exposes controller-runtime's metrics endpoint on :9090
(cmd/lwepp/main.go:75-77); the full-EPP spec adds scheduler metrics. Here:
pick counts/latency, shed/unavailable counts, batch sizes, assumed load.
"""

from __future__ import annotations

from typing import Optional

import prometheus_client as prom

REGISTRY = prom.CollectorRegistry()

PICKS = prom.Counter(
    "gie_picks_total", "Endpoint picks by outcome", ["outcome"], registry=REGISTRY
)
PICK_LATENCY = prom.Histogram(
    "gie_pick_latency_seconds",
    "End-to-end pick latency (enqueue to result)",
    buckets=(1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0),
    registry=REGISTRY,
)
BATCH_SIZE = prom.Histogram(
    "gie_sched_batch_size",
    "Requests per scheduling cycle",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
    registry=REGISTRY,
)
STREAMS = prom.Gauge(
    "gie_active_streams", "Open ext-proc streams", registry=REGISTRY
)
# Multi-core acceptors (extproc/workers.py, --extproc-workers): streams
# accepted per SO_REUSEPORT worker. The label is the worker index —
# bounded by the flag value. A one-worker skew here means the kernel is
# not spreading connections (storm-ci pins balance; docs/EXTPROC.md).
WORKER_ACCEPTS = prom.Counter(
    "gie_extproc_worker_accepted_streams_total",
    "ext-proc streams accepted, by SO_REUSEPORT worker index",
    ["worker"],
    registry=REGISTRY,
)
# Admission fast lane (extproc/server.py, docs/EXTPROC.md): per-request
# EPP overhead between "request fully received" and "routing decision
# sent" — pick + body scan/parse + response build. The lane label splits
# the zero-parse fast path from the legacy build-everything path so a
# --extproc-fast-lane rollout compares both live; the scheduler's own
# batching wait is measured separately by gie_pick_latency_seconds.
ADMISSION_SECONDS = prom.Histogram(
    "gie_extproc_admission_seconds",
    "Per-request admission processing time (pick + parse/scan + response "
    "build) by lane (fast = zero-parse scan path, legacy = full parse)",
    ["lane"],
    buckets=(1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3),
    registry=REGISTRY,
)
QUEUE_DEPTH = prom.Gauge(
    "gie_flow_queue_depth",
    "Picks waiting in the flow-control queue (reference flow-controller "
    "queue, proposal 0683)",
    registry=REGISTRY,
)
QUEUE_SHED = prom.Counter(
    "gie_flow_queue_shed_total",
    "Picks shed by the flow-control queue bounds",
    ["reason", "band"],  # reason: depth|evicted|age
    registry=REGISTRY,
)
HOST_ASSEMBLY = prom.Histogram(
    "gie_host_assembly_seconds",
    "Pipeline stage-1 host work per wave: queue-drain decisions, vectorized "
    "column assembly, and the async cycle dispatch (docs/PIPELINE.md)",
    buckets=(1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1),
    registry=REGISTRY,
)
DEVICE_WAIT = prom.Histogram(
    "gie_device_wait_seconds",
    "Pipeline stage-2 wait per wave: async dispatch until the device "
    "results materialize on the host (the overlap window the two-stage "
    "collector hides behind the next wave's assembly)",
    buckets=(1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1),
    registry=REGISTRY,
)
PIPELINE_DEPTH = prom.Gauge(
    "gie_pipeline_waves_in_flight",
    "Waves dispatched to the device but not yet fanned out (bounded by the "
    "collector's pipeline depth); >0 under load means the overlap is live",
    registry=REGISTRY,
)
PIPELINE_WAVES = prom.Counter(
    "gie_pipeline_waves_total",
    "Waves through the two-stage collector. Occupancy over a window = "
    "rate(gie_device_wait_seconds_sum) /"
    " (rate(gie_device_wait_seconds_sum) + dispatcher idle time); the "
    "per-wave histograms above give both terms",
    registry=REGISTRY,
)
# Multiplexed scrape engine (gie_tpu/metricsio/engine.py,
# docs/METRICSIO.md): metrics-ingestion health. Staleness is the achieved
# per-row refresh interval — the quantity every picker decision and the
# autoscale stale-hold actually depend on; at a 50 ms target, p99 beyond
# ~3x the interval means the shard budget (or the pool's reachability) is
# the bottleneck, not the schedule.
SCRAPE_STALENESS = prom.Histogram(
    "gie_scrape_staleness_seconds",
    "Time between consecutive successful scrapes of the same endpoint "
    "(attach-to-first-scrape for new endpoints)",
    buckets=(0.01, 0.025, 0.05, 0.075, 0.1, 0.15, 0.25, 0.5, 1.0, 2.5),
    registry=REGISTRY,
)
SCRAPE_FETCH = prom.Histogram(
    "gie_scrape_fetch_seconds",
    "Per-endpoint fetch + parse latency on the scrape-engine shards",
    buckets=(1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0),
    registry=REGISTRY,
)
SCRAPE_REUSE = prom.Gauge(
    "gie_scrape_connection_reuse_ratio",
    "Fraction of keep-alive HTTP fetches that reused a live connection "
    "(low values mean model servers are closing idle keep-alives faster "
    "than the scrape interval)",
    registry=REGISTRY,
)
SCRAPE_FAILS_MAX = prom.Gauge(
    "gie_scrape_consecutive_failures_max",
    "Largest consecutive-failure streak among attached endpoints (the "
    "worst endpoint's adaptive-backoff driver)",
    registry=REGISTRY,
)
SCRAPE_ENDPOINTS = prom.Gauge(
    "gie_scrape_endpoints",
    "Endpoints currently attached to the scrape engine",
    registry=REGISTRY,
)
SLOT_OVERFLOW = prom.Gauge(
    "gie_endpoint_slot_overflow_total",
    "Endpoint admissions refused because every scheduler slot (M_MAX) was "
    "taken — the pool outgrew the compiled capacity",
    registry=REGISTRY,
)
# Autoscaling recommender (gie_tpu/autoscale, docs/AUTOSCALE.md): the
# closed-loop replica controller's own observability. In recommend-only
# mode these gauges ARE the product — operators compare the desired
# series against their HPA before handing over actuation.
AUTOSCALE_DESIRED = prom.Gauge(
    "gie_autoscale_desired_replicas",
    "Replica count the recommender currently wants for the pool workload",
    registry=REGISTRY,
)
AUTOSCALE_CURRENT = prom.Gauge(
    "gie_autoscale_current_replicas",
    "Configured replica count the recommendation was made against",
    registry=REGISTRY,
)
AUTOSCALE_CAPACITY = prom.Gauge(
    "gie_autoscale_capacity_per_replica",
    "Online per-replica capacity estimate (admitted picks/s near "
    "saturation, EWMA, SLO-derated)",
    registry=REGISTRY,
)
AUTOSCALE_SHED_RATE = prom.Gauge(
    "gie_autoscale_shed_per_s",
    "Windowed shed rate (all 429 sources) the last recommendation saw",
    registry=REGISTRY,
)
AUTOSCALE_STALE = prom.Gauge(
    "gie_autoscale_signals_stale",
    "1 while the recommender is holding because pool metrics are stale "
    "(scrape outage / never-scraped pods) — never scale on stale data",
    registry=REGISTRY,
)
AUTOSCALE_RECS = prom.Counter(
    "gie_autoscale_recommendations_total",
    "Recommendations by direction",
    ["direction"],  # up|down|hold
    registry=REGISTRY,
)
AUTOSCALE_APPLIED = prom.Counter(
    "gie_autoscale_apply_total",
    "Actuation outcomes",
    ["outcome"],  # patched|noop|dry_run|not_leader|no_target|error
    registry=REGISTRY,
)
# HA state replication (gie_tpu/replication, docs/REPLICATION.md): the
# warm-standby sync loop's own observability. On a leader the epoch is the
# publisher's; on a follower it is the last INSTALLED epoch, and lag /
# staleness quantify how cold a takeover would be right now.
REPLICATION_ROLE = prom.Gauge(
    "gie_replication_role",
    "1 while this replica leads (publishes digests), 0 while it syncs",
    registry=REGISTRY,
)
REPLICATION_EPOCH = prom.Gauge(
    "gie_replication_epoch",
    "State epoch: published (leader) or last installed (follower)",
    registry=REGISTRY,
)
REPLICATION_EPOCH_LAG = prom.Gauge(
    "gie_replication_epoch_lag",
    "Leader epoch minus last installed epoch, as observed by the follower",
    registry=REGISTRY,
)
REPLICATION_DIGEST_BYTES = prom.Gauge(
    "gie_replication_digest_bytes",
    "Encoded size of the current full state digest",
    registry=REGISTRY,
)
REPLICATION_STALENESS = prom.Gauge(
    "gie_replication_staleness_seconds",
    "Seconds since the follower last confirmed the leader's state "
    "(install or 304); -1 before first contact, 0 while leading",
    registry=REGISTRY,
)
REPLICATION_INSTALL_SECONDS = prom.Histogram(
    "gie_replication_install_seconds",
    "Digest decode-to-installed latency on the follower",
    buckets=(1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0),
    registry=REGISTRY,
)
REPLICATION_SYNCS = prom.Counter(
    "gie_replication_sync_total",
    "Follower sync attempts by outcome",
    # installed|not_modified|no_leader|fetch_error|corrupt|stale_epoch|
    # delta_mismatch|rejected
    ["outcome"],
    registry=REGISTRY,
)
# Unified resilience layer (gie_tpu/resilience, docs/RESILIENCE.md): the
# degradation ladder's current rung (0 = full TPU pick, 1 = cached-
# snapshot pick, 2 = weighted round-robin, 3 = static subset), breaker
# quarantine, deadline shedding, and degraded-pick volume.
DEGRADED_MODE = prom.Gauge(
    "gie_degraded_mode",
    "Pick-path degradation ladder rung (0 full TPU pick, 1 cached-"
    "snapshot pick, 2 weighted round-robin, 3 static subset)",
    registry=REGISTRY,
)
DEGRADED_PICKS = prom.Counter(
    "gie_degraded_picks_total",
    "Picks served by a degraded ladder rung",
    ["rung"],  # cached|round_robin|static
    registry=REGISTRY,
)
BREAKER_OPEN = prom.Gauge(
    "gie_breaker_open_endpoints",
    "Endpoints currently quarantined by an OPEN circuit breaker",
    registry=REGISTRY,
)
DEADLINE_SHED = prom.Counter(
    "gie_deadline_shed_total",
    "Requests shed with 503 because their propagated deadline expired",
    ["stage"],  # admission|queue
    registry=REGISTRY,
)
# Data-plane feedback loop (ISSUE 8, docs/RESILIENCE.md): serve outcomes
# harvested at the ext-proc response hop (Envoy :status class, or
# "reset" for streams that abort after a pick but before response
# headers), the observed pick-to-first-byte serve latency, endpoints in
# graceful drain, and the budget-aware scheduling adjustments.
SERVE_OUTCOME = prom.Counter(
    "gie_serve_outcome_total",
    "Data-plane serve outcomes observed on the response path",
    ["class"],  # 2xx|3xx|4xx|5xx|reset
    registry=REGISTRY,
)
SERVE_LATENCY = prom.Histogram(
    "gie_serve_latency_seconds",
    "Observed pick-to-response-headers serve latency",
    buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0),
    registry=REGISTRY,
)
OUTLIER_EJECTIONS = prom.Counter(
    "gie_outlier_ejections_total",
    "Endpoints quarantined by p99 serve-latency outlier ejection "
    "(windowed per-endpoint quantile vs pool median, --outlier-ejection)",
    registry=REGISTRY,
)
DRAINING_ENDPOINTS = prom.Gauge(
    "gie_draining_endpoints",
    "Endpoints in graceful DRAINING state (excluded from new picks, "
    "in-flight streams completing)",
    registry=REGISTRY,
)
HOLD_BUDGET_BYPASS = prom.Counter(
    "gie_hold_budget_bypass_total",
    "Saturation holds skipped because the request's remaining deadline "
    "budget could not survive another hold retry (picked best-effort "
    "now instead of held to die)",
    registry=REGISTRY,
)
PD_BUDGET_SINGLEHOP = prom.Counter(
    "gie_pd_budget_singlehop_total",
    "Disaggregated picks collapsed to the decode worker only because "
    "the request's remaining deadline budget could not afford the "
    "cross-worker prefill hop",
    registry=REGISTRY,
)
# gie-obs (gie_tpu/obs, docs/OBSERVABILITY.md): build identity + the
# tracing/flight-recorder plane's own counters. BUILD_INFO is the
# standard constant-1 info gauge — joinable onto any other series to
# slice dashboards by version/feature-flag mix during rollouts.
BUILD_INFO = prom.Gauge(
    "gie_build_info",
    "Constant 1 with build/runtime identity labels: package version and "
    "the lane/resilience/obs/wire feature-flag mix (plus acceptor count) "
    "this replica runs",
    ["version", "fast_lane", "resilience", "obs", "wire", "workers"],
    registry=REGISTRY,
)
STREAM_ERRORS = prom.Counter(
    "gie_extproc_stream_errors_total",
    "Stream-fatal ext-proc failures surfaced to Envoy, by gRPC status "
    "code (label values are the bounded grpc.StatusCode enum)",
    ["code"],
    registry=REGISTRY,
)
TRACES_EXPORTED = prom.Counter(
    "gie_obs_traces_exported_total",
    "Request traces exported to the /debugz feeds, by why they were "
    "kept (head sample, error-class outcome, latency tail outlier)",
    ["reason"],  # sampled|error|slow
    registry=REGISTRY,
)
# gie-fair (gie_tpu/fairness, docs/FAIRNESS.md): per-tenant flow-control
# accounting. The tenant label is BOUNDED by construction: the fairness
# labeler exports the top-K tenants by traffic under their own value and
# folds the long tail into "other" (empty fairness ID -> "default"), so
# these series scale with K, never with the tenant population.
TENANT_REQUESTS = prom.Counter(
    "gie_tenant_requests_total",
    "Flow-queue enqueues by tenant (x-gateway-inference-fairness-id; "
    "top-K tenants labeled individually, the long tail as 'other')",
    ["tenant"],
    registry=REGISTRY,
)
TENANT_COST = prom.Counter(
    "gie_tenant_cost_total",
    "Drained request cost (scheduler request_cost units) by tenant — "
    "the capacity each tenant actually consumed through the flow queue",
    ["tenant"],
    registry=REGISTRY,
)
TENANT_SHED = prom.Counter(
    "gie_tenant_shed_total",
    "Requests shed (429) by tenant and criticality band, all shed "
    "sources: queue bounds, cycle saturation, SLO reversal, and the "
    "over-fair-share preemptive shed",
    ["tenant", "band"],
    registry=REGISTRY,
)
TENANT_SERVE_ERRORS = prom.Counter(
    "gie_tenant_serve_errors_total",
    "Data-plane serve errors (5xx/reset) observed per tenant at the "
    "response hop — the per-tenant half of gie_serve_outcome_total",
    ["tenant"],
    registry=REGISTRY,
)
# gie-fed (gie_tpu/federation, docs/FEDERATION.md): multi-cluster
# federation. The peer label is BOUNDED by configuration (--fed-peer
# entries), never by workload — a handful of clusters, not a
# cardinality bomb.
FED_PEERS = prom.Gauge(
    "gie_federation_peers",
    "Configured federation peer clusters",
    registry=REGISTRY,
)
FED_REMOTE_ENDPOINTS = prom.Gauge(
    "gie_federation_remote_endpoints",
    "Imported peer endpoints currently schedulable, per peer cluster",
    ["peer"],
    registry=REGISTRY,
)
FED_STALENESS = prom.Gauge(
    "gie_federation_staleness_seconds",
    "Seconds since the peer digest was last confirmed (install or 304); "
    "-1 before first contact",
    ["peer"],
    registry=REGISTRY,
)
FED_LOCAL_ONLY = prom.Gauge(
    "gie_federation_local_only",
    "1 while the peer is excluded from spillover (stale link past the "
    "local-only floor), else 0",
    ["peer"],
    registry=REGISTRY,
)
FED_PENALTY = prom.Gauge(
    "gie_federation_penalty_queue_units",
    "Effective cross-cluster cost penalty applied to the peer's "
    "imported endpoints, in queue-depth units (staleness-inflated)",
    ["peer"],
    registry=REGISTRY,
)
FED_SYNCS = prom.Counter(
    "gie_federation_syncs_total",
    "Peer digest exchange attempts by outcome (installed, not_modified, "
    "fetch_error, corrupt, stale_epoch, era_regression, ...)",
    ["peer", "outcome"],
    registry=REGISTRY,
)
FED_SPILL = prom.Counter(
    "gie_federation_spill_total",
    "Picks that landed on an imported peer endpoint, by peer cluster "
    "and criticality band",
    ["peer", "band"],
    registry=REGISTRY,
)
FED_ERA_FLIPS = prom.Counter(
    "gie_federation_era_flips_total",
    "Peer publisher era changes observed (peer failover / partition "
    "heal; the split-brain convergence events)",
    ["peer"],
    registry=REGISTRY,
)
FED_DRAINING = prom.Gauge(
    "gie_federation_cluster_draining",
    "1 while THIS cluster is draining its traffic to peers, else 0",
    registry=REGISTRY,
)


# gie-learn (gie_tpu/learn, docs/LEARNED.md): scorer identity. Same
# constant-1 info idiom as gie_build_info — which scorer this replica
# runs, which trained artifact (if any) backs it, and the live blend
# exponents, joinable onto goodput/SLO series during a policy rollout.
POLICY_INFO = prom.Gauge(
    "gie_policy_info",
    "Constant 1 with scheduling-policy identity labels: active scorer "
    "kind (blend|learned), the loaded policy artifact's schema version/"
    "checksum/trained-at (empty for the heuristic), and the live blend "
    "weights as name=value pairs",
    ["scorer", "artifact_schema", "checksum", "trained_at", "weights"],
    registry=REGISTRY,
)


def set_policy_info(scorer: str, weights: dict,
                    artifact: Optional[dict] = None) -> None:
    """Stamp the constant-1 policy-identity series (runner startup).

    ``weights`` is {column: float} — the LIVE values the cycle blends,
    whatever their provenance (tuned profile, --scheduler-config, or a
    learned artifact's exponents)."""
    prov = (artifact or {}).get("provenance", {})
    POLICY_INFO.labels(
        scorer=str(scorer),
        artifact_schema=str((artifact or {}).get("schema", "")),
        checksum=str((artifact or {}).get("checksum", "")),
        trained_at=str(prov.get("trained_at", "")),
        weights=",".join(
            f"{name}={float(val):g}" for name, val in sorted(
                weights.items())),
    ).set(1)


def set_build_info(fast_lane: bool, resilience: bool, obs: bool,
                   wire: bool = False, workers: int = 1) -> None:
    """Stamp the constant-1 build-identity series (runner startup)."""
    from gie_tpu.version import __version__

    BUILD_INFO.labels(
        version=__version__,
        fast_lane=str(bool(fast_lane)).lower(),
        resilience=str(bool(resilience)).lower(),
        obs=str(bool(obs)).lower(),
        wire=str(bool(wire)).lower(),
        workers=str(int(workers)),
    ).set(1)


_POOL_SNAPSHOT = {"fn": lambda: {}, "registered": False,
                  "cache": {}, "cached_at": -1.0}


def _pool_snapshot_cached() -> dict:
    """One snapshot per scrape, not one per gauge: the 5 gauges evaluate
    within the same exposition pass, and each uncached call would take the
    scheduler lock and force a device sync (snapshot_assumed_load)."""
    import time

    now = time.monotonic()
    if now - _POOL_SNAPSHOT["cached_at"] > 0.25:
        _POOL_SNAPSHOT["cache"] = _POOL_SNAPSHOT["fn"]()
        _POOL_SNAPSHOT["cached_at"] = now
    return _POOL_SNAPSHOT["cache"]


def register_pool_aggregates(snapshot) -> None:
    """Pool-level aggregate gauges for autoscaling (reference roadmap item
    4, README.md:111: 'HPA support for autoscaling on aggregate metrics
    derived from the load balancer'). `snapshot` is a callable returning a
    dict with keys ready_endpoints / queue_depth_total / kv_cache_util_mean
    / assumed_load_total / saturated_fraction; each gauge evaluates it at
    scrape time (set_function), so the exposition always reflects the live
    datastore + metrics tensor with no update loop to maintain.

    An HPA targeting e.g. gie_pool_queue_depth_total / gie_pool_endpoints
    scales the model-server Deployment on load the EPP actually routes on —
    truer than per-pod CPU for token workloads.

    Re-registration swaps the snapshot source instead of duplicating the
    gauges (the registry is process-global; tests build several runners)."""
    _POOL_SNAPSHOT["fn"] = snapshot
    _POOL_SNAPSHOT["cached_at"] = -1.0  # new source: drop any cache
    if _POOL_SNAPSHOT["registered"]:
        return
    _POOL_SNAPSHOT["registered"] = True
    specs = [
        ("gie_pool_endpoints", "Ready routable endpoints in the pool",
         "ready_endpoints"),
        ("gie_pool_queue_depth_total",
         "Sum of scraped queue depth across ready endpoints",
         "queue_depth_total"),
        ("gie_pool_kv_cache_util_mean",
         "Mean scraped KV-cache utilization across ready endpoints",
         "kv_cache_util_mean"),
        ("gie_pool_assumed_load_total",
         "Total in-flight assumed load (picks not yet reconciled)",
         "assumed_load_total"),
        ("gie_pool_saturated_fraction",
         "Fraction of ready endpoints past the saturation thresholds",
         "saturated_fraction"),
    ]
    for name, doc, field in specs:
        g = prom.Gauge(name, doc, registry=REGISTRY)
        g.set_function(
            lambda field=field: float(
                _pool_snapshot_cached().get(field, 0.0)))


def start_metrics_server(port: int, providers=None,
                         debugz_bind: str = "127.0.0.1",
                         debugz_token=None):
    """Start the operator HTTP listener: /metrics (Prometheus text, or
    OpenMetrics-with-exemplars under content negotiation) plus the
    /debugz introspection plane (gie_tpu/obs/debugz.py) for whatever
    zpage providers the caller registers — /debugz answers loopback
    peers only unless ``debugz_bind`` names a non-loopback address
    (--debugz-bind, docs/OBSERVABILITY.md). Returns the server (close()
    to stop); replaces prometheus_client's bare start_http_server."""
    from gie_tpu.obs.debugz import start_debugz_server

    return start_debugz_server(port, REGISTRY, providers,
                               debugz_bind=debugz_bind,
                               debugz_token=debugz_token)
