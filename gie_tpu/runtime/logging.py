"""Leveled structured JSON logging.

Mirror of reference pkg/common/observability/logging: zap-style JSON lines,
a shared atomic level adjustable at runtime, and the custom verbosity
mapping V(1-3)->info, V(4)->debug, V(5)->trace
(logger.go:35-52 customLevelEncoder; const.go:20-25 DEFAULT=2..TRACE=5).
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from typing import Any

# Verbosity levels (reference logging/const.go:20-25).
ERROR = 0
WARNING = 1
DEFAULT = 2
VERBOSE = 3
DEBUG = 4
TRACE = 5

_LEVEL_NAMES = {0: "error", 1: "warn", 2: "info", 3: "info", 4: "debug", 5: "trace"}


class _AtomicLevel:
    def __init__(self, v: int = DEFAULT):
        self._v = v
        self._lock = threading.Lock()

    def get(self) -> int:
        return self._v

    def set(self, v: int) -> None:
        with self._lock:
            self._v = v


_level = _AtomicLevel()


def set_verbosity(v: int) -> None:
    """Runtime level change (the -v flag bridge, reference
    logging/options.go:60-75)."""
    _level.set(v)


def trace_enabled() -> bool:
    """Public accessor: is TRACE verbosity live right now? Hot paths
    (runtime/tracing.py span exits) gate record construction on this
    instead of reaching into the private ``_level`` holder."""
    return _level.get() >= TRACE


class Logger:
    """JSON-lines logger with key-value context (zap sugar analogue)."""

    def __init__(self, name: str = "", stream=None, **context: Any):
        self.name = name
        self.stream = stream if stream is not None else sys.stderr
        self.context = context

    def with_values(self, **kv: Any) -> "Logger":
        merged = dict(self.context)
        merged.update(kv)
        return Logger(self.name, self.stream, **merged)

    def with_name(self, name: str) -> "Logger":
        full = f"{self.name}.{name}" if self.name else name
        return Logger(full, self.stream, **self.context)

    def v(self, level: int):
        return _Leveled(self, level)

    def info(self, msg: str, **kv: Any) -> None:
        self._emit(DEFAULT, msg, kv)

    def error(self, msg: str, err: Exception | None = None, **kv: Any) -> None:
        if err is not None:
            kv["error"] = f"{type(err).__name__}: {err}"
        self._emit(ERROR, msg, kv)

    def _emit(self, level: int, msg: str, kv: dict) -> None:
        if level > _level.get():
            return
        record = {
            "ts": round(time.time(), 6),
            "level": _LEVEL_NAMES.get(level, "info"),
            "logger": self.name,
            "msg": msg,
        }
        record.update(self.context)
        record.update(kv)
        try:
            self.stream.write(json.dumps(record, default=str) + "\n")
            self.stream.flush()
        except Exception:  # logging must never take the server down
            pass


class _Leveled:
    def __init__(self, logger: Logger, level: int):
        self._logger = logger
        self._level = level

    def info(self, msg: str, **kv: Any) -> None:
        self._logger._emit(self._level, msg, kv)


def get_logger(name: str = "gie") -> Logger:
    return Logger(name)
