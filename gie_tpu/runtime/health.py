"""gRPC health service (grpc.health.v1) with pool-sync-gated readiness.

Mirror of reference runserver.go:117-123,132-157: the ext-proc server
exposes health BOTH colocated (on the ext-proc port, under the ext-proc
service name) and on a dedicated health port whose readiness flips to
SERVING only once the datastore has synced the InferencePool (100 ms poll),
per the protocol's liveness/readiness semantics (004 README:103-137).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

import grpc

import gie_tpu.extproc  # noqa: F401 — installs the pb path hook
from gie_tpu.extproc.pb import health_pb2
from gie_tpu.extproc.service import SERVICE_NAME as EXTPROC_SERVICE

HEALTH_SERVICE = "grpc.health.v1.Health"

# Named sub-services per the endpoint-picker protocol (004 README:103-137):
# liveness = process alive (no datastore/leader dependency); readiness and
# the ext-proc service name = synced AND leading. "replication" (when a
# replication manager is wired) = this replica is a warm takeover target:
# leading, or synced within the staleness bound (docs/REPLICATION.md) —
# the probe a rollout controller asks before trusting a standby.
# "resilience" (when the resilience layer is wired) = the pick path is on
# the FULL ladder rung with no open circuit breakers; NOT_SERVING means
# degraded-but-serving (docs/RESILIENCE.md) — an alerting signal, never a
# traffic gate (readiness stays SERVING on purpose while degraded).
LIVENESS_SERVICE = "liveness"
READINESS_SERVICE = "readiness"
REPLICATION_SERVICE = "replication"
RESILIENCE_SERVICE = "resilience"

SERVING = health_pb2.HealthCheckResponse.SERVING
NOT_SERVING = health_pb2.HealthCheckResponse.NOT_SERVING


class HealthService:
    """Check/Watch backed by a ready-predicate per service name."""

    def __init__(
        self,
        ready_fn: Callable[[], bool],
        replication_fn: Callable[[], bool] | None = None,
        resilience_fn: Callable[[], bool] | None = None,
    ):
        self.ready_fn = ready_fn
        self.replication_fn = replication_fn
        self.resilience_fn = resilience_fn

    def _status(self, service: str) -> int:
        if service == LIVENESS_SERVICE:
            return SERVING  # answering at all == alive
        if service == REPLICATION_SERVICE:
            if self.replication_fn is None:
                return health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
            return SERVING if self.replication_fn() else NOT_SERVING
        if service == RESILIENCE_SERVICE:
            if self.resilience_fn is None:
                return health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
            return SERVING if self.resilience_fn() else NOT_SERVING
        known = ("", READINESS_SERVICE, EXTPROC_SERVICE, HEALTH_SERVICE)
        if service not in known:
            return health_pb2.HealthCheckResponse.SERVICE_UNKNOWN
        return SERVING if self.ready_fn() else NOT_SERVING

    def check(self, request, context):
        return health_pb2.HealthCheckResponse(status=self._status(request.service))

    def watch(self, request, context):
        # Poll-based watch (reference HealthServerRunnable polls at 100 ms,
        # runserver.go:147-149); emits on every state change.
        last = None
        while context.is_active():
            status = self._status(request.service)
            if status != last:
                last = status
                yield health_pb2.HealthCheckResponse(status=status)
            time.sleep(0.1)

    def add_to_server(self, server: grpc.Server) -> None:
        handlers = {
            "Check": grpc.unary_unary_rpc_method_handler(
                self.check,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
            "Watch": grpc.unary_stream_rpc_method_handler(
                self.watch,
                request_deserializer=health_pb2.HealthCheckRequest.FromString,
                response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
            ),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(HEALTH_SERVICE, handlers),)
        )


def start_dedicated_health_server(
    ready_fn: Callable[[], bool],
    port: int,
    replication_fn: Callable[[], bool] | None = None,
    resilience_fn: Callable[[], bool] | None = None,
) -> tuple[grpc.Server, int]:
    """The dedicated health listener, started BEFORE the manager/cache sync
    so probes get NOT_SERVING instead of connection refused (reference
    cmd/lwepp/main.go:104-109)."""
    from concurrent import futures

    # Watch handlers hold a worker for their stream's lifetime; size the
    # pool so long-lived watchers cannot starve Check probes.
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=10))
    HealthService(ready_fn, replication_fn, resilience_fn).add_to_server(
        server)
    bound = server.add_insecure_port(f"0.0.0.0:{port}")
    if bound == 0:
        raise OSError(f"failed to bind health port {port}")
    server.start()
    return server, bound
