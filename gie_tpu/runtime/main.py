"""lwepp-equivalent entrypoint (reference cmd/lwepp/main.go:36-116).

    python -m gie_tpu.runtime.main --pool-name my-pool [--demo]

Without a real kube-apiserver in this environment, the ClusterClient seam
(gie_tpu/controller/cluster.py) is served either by an external integration
(a kubernetes watch adapter implementing ClusterClient) or — with --demo —
by an in-process FakeCluster populated with simulated vLLM pods whose
/metrics endpoints are real HTTP servers backed by VLLMStub dynamics, so the
whole binary is drivable end to end on one machine.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def _demo_cluster(opts, n_pods: int):
    """FakeCluster + stub fleet with live HTTP /metrics."""
    import http.server

    from gie_tpu.api import types as api
    from gie_tpu.controller import FakeCluster
    from gie_tpu.datastore.objects import Pod
    from gie_tpu.simulator import StubConfig, VLLMStub

    cluster = FakeCluster()
    stubs, servers = [], []
    n_pods = min(n_pods, 8)  # one targetPort per pod, max 8 (API limit)
    for i in range(n_pods):
        stub = VLLMStub(StubConfig(), name=f"demo-pod-{i}")

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self, s=stub):
                body = s.metrics_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                # Which pod served — lets a data-plane smoke test (an
                # Envoy routing on x-gateway-destination-endpoint via
                # original_dst, hack/envoy_smoke.sh) assert the EPP's
                # steering was honored end to end.
                self.send_header("X-Served-By",
                                 "%s:%d" % self.server.server_address)
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                # Drain the body so keep-alive connections stay in sync.
                n = int(self.headers.get("Content-Length", 0) or 0)
                if n:
                    self.rfile.read(n)
                self.do_GET()

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        stubs.append(stub)
        servers.append(httpd)

    # Clock driver so stub queues evolve.
    def tick():
        import time

        while True:
            for s in stubs:
                s.step(0.05)
            time.sleep(0.05)

    threading.Thread(target=tick, daemon=True).start()

    # Every stub listens on its own localhost port; the pool lists them all
    # as targetPorts (max 8) and each pod's active-ports annotation narrows
    # to its own stub, exercising the per-pod rank filtering.
    ports = [s.server_address[1] for s in servers]
    cluster.apply_pool(
        api.InferencePool(
            metadata=api.ObjectMeta(
                name=opts.pool_name, namespace=opts.pool_namespace
            ),
            spec=api.InferencePoolSpec(
                selector=api.LabelSelector(matchLabels={"app": "demo"}),
                targetPorts=[api.Port(p) for p in ports],
                endpointPickerRef=api.EndpointPickerRef(
                    name="epp", port=api.Port(opts.grpc_port)
                ),
            ),
        )
    )
    for i, httpd in enumerate(servers):
        cluster.apply_pod(
            Pod(
                name=f"demo-pod-{i}",
                namespace=opts.pool_namespace,
                labels={"app": "demo"},
                ip="127.0.0.1",
                annotations={
                    api.ACTIVE_PORTS_ANNOTATION: str(httpd.server_address[1])
                },
            )
        )
    return cluster


def main(argv=None) -> int:
    from gie_tpu.runtime.logging import get_logger, set_verbosity
    from gie_tpu.runtime.options import Options
    from gie_tpu.runtime.runner import ExtProcServerRunner

    parser = argparse.ArgumentParser(prog="gie-tpu-epp")
    Options.add_flags(parser)
    parser.add_argument(
        "--demo", action="store_true",
        help="run against an in-process simulated cluster",
    )
    parser.add_argument("--demo-pods", type=int, default=4)
    parser.add_argument(
        "--kube", action="store_true",
        help="connect to a real kube-apiserver (in-cluster config, or "
             "--kubeconfig); stdlib HTTP list/watch, no client dependency",
    )
    parser.add_argument("--kubeconfig", default=None)
    parser.add_argument(
        "--publish-status-parents", default=None,
        help="comma-separated Gateway names to publish InferencePool "
             "parent status for (Accepted/ResolvedRefs conditions through "
             "the status subresource); kube mode only",
    )
    args = parser.parse_args(argv)
    opts = Options.from_args(args)
    opts.validate()
    set_verbosity(opts.verbosity)
    log = get_logger("main")

    kube_client = None
    if args.demo:
        cluster = _demo_cluster(opts, args.demo_pods)
    elif args.kube:
        from gie_tpu.controller.kube import KubeClusterClient

        kube_client = KubeClusterClient(
            opts.pool_namespace, opts.pool_name, kubeconfig=args.kubeconfig
        )
        cluster = kube_client
    else:
        log.error(
            "no cluster integration configured; run with --demo (simulated) "
            "or --kube (real apiserver over stdlib HTTP list/watch)"
        )
        return 2

    if opts.autoscale_mode == "apply" and kube_client is None:
        # The demo FakeCluster has no apiserver to patch; the actuator
        # degrades to metrics-only and every apply counts as no_target.
        log.error(
            "--autoscale-mode apply needs --kube (an apiserver to patch "
            "spec.replicas on); running recommend-only against the demo "
            "cluster"
        )

    runner = ExtProcServerRunner(opts, cluster)
    runner.setup()
    if kube_client is not None:
        kube_client.start()  # watches begin after reconcilers subscribe
    runner.start()

    status_stop = None
    if kube_client is not None and args.publish_status_parents:
        # Periodic parent-condition publication (controller/status.py):
        # unchanged cycles skip the patch, so the loop is churn-free.
        from gie_tpu.controller.status import PoolStatusController

        status_ctrl = PoolStatusController(
            kube_client, opts.pool_namespace, opts.pool_name,
            parents=[p.strip()
                     for p in args.publish_status_parents.split(",")
                     if p.strip()],
            service_exists=kube_client.service_exists,
        )
        status_stop = threading.Event()

        def status_loop():
            while not status_stop.wait(10.0):
                try:
                    status_ctrl.reconcile()
                except Exception as e:  # status must never take us down
                    log.error("pool status publication failed", err=e)

        threading.Thread(target=status_loop, daemon=True).start()

    stop = threading.Event()

    def on_signal(signum, frame):
        log.info("signal received, shutting down", signal=signum)
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    log.info("serving", pool=opts.pool_name)
    stop.wait()
    if status_stop is not None:
        status_stop.set()
    if kube_client is not None:
        kube_client.stop()
    runner.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
