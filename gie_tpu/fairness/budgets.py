"""Per-tenant isolation budgets: windowed accounting, the
over-fair-share verdict, and the bounded-cardinality tenant labeler
(docs/FAIRNESS.md "budget windows" / "tenant-label cardinality").

Three ledgers per tenant, all sliding-window so a reformed abuser ages
out instead of being punished forever:

  arrival cost   offered load at enqueue (cost units) — the over-share
                 input. DRR already caps what a flooding tenant DRAINS
                 at its fair share, so the abuse signal must be what it
                 OFFERS, not what it wins.
  drained cost   what actually entered waves (capacity consumed).
  shed / serve   outcome rates via the breaker's WindowedRate pattern
                 (resilience/breaker.py): sheds vs admissions, serve
                 errors (5xx/reset) vs clean serves.

The labeler bounds ``gie_tenant_*`` series cardinality (OC004's intent
applied to tenants): the top-K tenants by cumulative traffic keep their
own label value, everyone else exports as ``"other"``, the empty
fairness ID exports as ``"default"``, and at most ``label_cap`` distinct
tenants are ever promoted process-wide — an adversarial tenant-ID churn
cannot mint unbounded series.

One leaf lock (lockorder.toml rank 83) held for dict math only; the
wave-cadence ``over_share_set`` read is a cached frozenset recomputed at
``eval_interval_s``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from gie_tpu.fairness.drr import FairnessConfig
from gie_tpu.resilience.breaker import BucketWindow, WindowedRate


class WindowedSum(BucketWindow):
    """Time-bucketed float accumulator on the shared BucketWindow core
    (costs arrive at request cadence; rates need counts, budgets need
    magnitudes). Not thread-safe; callers hold their own lock."""

    __slots__ = ()
    _ZERO = (0.0,)

    def note(self, value: float, now: float) -> None:
        self._live_bucket(now)[1] += value

    def total(self, now: float) -> float:
        self._prune(now)
        return sum(b[1] for b in self._buckets)


class _Account:
    __slots__ = ("arrival_cost", "drained_cost", "shed_window",
                 "serve_window", "requests", "last_seen")

    def __init__(self, window_s: float, now: float):
        self.arrival_cost = WindowedSum(window_s)
        self.drained_cost = WindowedSum(window_s)
        # ok=arrival, err=shed. A shed request notes BOTH (it arrived,
        # then shed): report() divides sheds by arrivals, never by the
        # raw note count.
        self.shed_window = WindowedRate(window_s)
        self.serve_window = WindowedRate(window_s)  # ok=clean, err=5xx/reset
        self.requests = 0
        self.last_seen = now


class TenantBudgets:
    def __init__(self, cfg: FairnessConfig | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg if cfg is not None else FairnessConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._accounts: dict[str, _Account] = {}
        # Labeler state: promoted tenants keep their own label value.
        self._topk: frozenset = frozenset()
        self._promoted: set[str] = set()
        self._notes_since_rank = 0
        # Cached over-share verdict (wave-cadence reads).
        self._over: frozenset = frozenset()
        self._over_at = -1.0

    # -- accounting feeds --------------------------------------------------

    def _account_locked(self, tenant: str, now: float) -> _Account:
        acct = self._accounts.get(tenant)
        if acct is None:
            if len(self._accounts) >= self.cfg.max_tracked:
                # Evict the least-traffic account: a long-tail tenant's
                # ledger, never the heavy hitters the verdicts watch.
                victim = min(self._accounts,
                             key=lambda t: self._accounts[t].requests)
                del self._accounts[victim]
            acct = self._accounts[tenant] = _Account(self.cfg.window_s, now)
        acct.last_seen = now
        return acct

    def note_arrival(self, tenant: str, cost: float) -> str:
        """One enqueue: offered-cost + traffic count. Returns the
        bounded metric label for the caller's series."""
        now = self.clock()
        with self._lock:
            acct = self._account_locked(tenant, now)
            acct.requests += 1
            acct.arrival_cost.note(max(cost, 0.0), now)
            acct.shed_window.note(True, now)
            self._notes_since_rank += 1
            if self._notes_since_rank >= 256 or not self._topk:
                self._notes_since_rank = 0
                self._rerank_locked()
            return self._label_locked(tenant)

    def note_drained(self, tenant: str, cost: float) -> str:
        now = self.clock()
        with self._lock:
            acct = self._account_locked(tenant, now)
            acct.drained_cost.note(max(cost, 0.0), now)
            return self._label_locked(tenant)

    def note_shed(self, tenant: str) -> str:
        now = self.clock()
        with self._lock:
            acct = self._account_locked(tenant, now)
            acct.shed_window.note(False, now)
            return self._label_locked(tenant)

    def note_serve(self, tenant: str, ok: bool) -> str:
        now = self.clock()
        with self._lock:
            acct = self._account_locked(tenant, now)
            acct.serve_window.note(ok, now)
            return self._label_locked(tenant)

    # -- over-fair-share verdict -------------------------------------------

    def over_share_set(self) -> frozenset:
        """Tenants whose windowed OFFERED-cost share exceeds their
        over-share threshold. Fair share = weight / sum of ACTIVE
        tenants' weights; the threshold is ``factor x fair`` CAPPED at
        the midpoint between fair and 1.0 — without the cap, a pool of
        two equal tenants has fair share 0.5 and ``2 x 0.5 = 1.0`` is a
        share no tenant can exceed, so a 2-tenant flooder would never
        flag. The cap keeps the lone-tenant case self-guarding (fair =
        1.0 -> threshold 1.0, unreachable strictly). Cached; recomputed
        at eval_interval_s so the wave-cadence caller pays a frozenset
        read."""
        now = self.clock()
        with self._lock:
            if now - self._over_at < self.cfg.eval_interval_s:
                return self._over
            self._over_at = now
            shares: dict[str, float] = {}
            total = 0.0
            for t, acct in self._accounts.items():
                c = acct.arrival_cost.total(now)
                if c > 0.0:
                    shares[t] = c
                    total += c
            if total <= 0.0 or len(shares) < 2:
                self._over = frozenset()
                return self._over
            weight_sum = sum(self.cfg.weight(t) for t in shares)
            factor = self.cfg.over_share_factor
            over = set()
            for t, c in shares.items():
                fair = self.cfg.weight(t) / weight_sum
                threshold = min(factor * fair, (1.0 + fair) / 2.0)
                if c / total > threshold:
                    over.add(t)
            self._over = frozenset(over)
            return self._over

    # -- bounded-cardinality labels ----------------------------------------

    def _rerank_locked(self) -> None:
        ranked = sorted(self._accounts,
                        key=lambda t: self._accounts[t].requests,
                        reverse=True)[: self.cfg.top_k]
        topk = set()
        for t in ranked:
            if t in self._promoted or len(self._promoted) < self.cfg.label_cap:
                self._promoted.add(t)
                topk.add(t)
        self._topk = frozenset(topk)

    def _label_locked(self, tenant: str) -> str:
        if tenant in self._topk:
            return tenant or "default"
        return "other" if tenant else "default"

    def label(self, tenant: str) -> str:
        with self._lock:
            return self._label_locked(tenant)

    # -- introspection -----------------------------------------------------

    def report(self, limit: int = 32) -> dict:
        """/debugz/tenants core: per-tenant windowed ledgers + verdicts,
        heaviest tenants first, row count bounded."""
        over = self.over_share_set()
        now = self.clock()
        with self._lock:
            ranked = sorted(self._accounts.items(),
                            key=lambda kv: kv[1].requests, reverse=True)
            tenants = {}
            for t, acct in ranked[:limit]:
                # shed_window notes ok=arrival and err=shed, and a shed
                # request appears as BOTH (it arrived, then shed), so
                # WindowedRate.rate's err/(ok+err) would saturate at 0.5
                # for a fully-shed tenant. The operator-facing quantity
                # is sheds/ARRIVALS: recover the raw counts and divide.
                frac, shed_n = acct.shed_window.rate(now)
                sheds = round(frac * shed_n)
                arrivals = shed_n - sheds
                shed_rate = (min(sheds / arrivals, 1.0) if arrivals
                             else (1.0 if sheds else 0.0))
                err_rate, err_n = acct.serve_window.rate(now)
                tenants[t or "default"] = {
                    "label": self._label_locked(t),
                    "requests_total": acct.requests,
                    "arrival_cost_w": round(acct.arrival_cost.total(now), 3),
                    "drained_cost_w": round(acct.drained_cost.total(now), 3),
                    "shed_rate_w": round(shed_rate, 4),
                    "shed_samples_w": shed_n,
                    "serve_error_rate_w": round(err_rate, 4),
                    "serve_samples_w": err_n,
                    "weight": self.cfg.weight(t),
                    "over_share": t in over,
                }
            return {
                "window_s": self.cfg.window_s,
                "over_share_factor": self.cfg.over_share_factor,
                "top_k": self.cfg.top_k,
                "tracked": len(self._accounts),
                "over_share": sorted(t or "default" for t in over),
                "tenants": tenants,
            }
