"""gie-fair: per-tenant isolation for the flow-control plane
(ISSUE 11, docs/FAIRNESS.md).

The fairness key (``x-gateway-inference-fairness-id``, proposal 1199)
was parsed but unenforced: ``_fair_order`` interleaved tenants by
request COUNT, so one tenant sending 8k-prompt/4k-decode requests took
an order of magnitude more capacity per drained slot than a neighbor
sending chat turns — and nothing shed the abuser first, traced the
abuser harder, or explained per-tenant state. This package is the
isolation layer the batching picker threads through admission, the
flow queue, the shed path, and the serve-outcome loop:

  drr.py      band-scoped weighted deficit-round-robin ordering: each
              drained request charges its COST (the scheduler's own
              request_cost units) against a per-(band, tenant) deficit
              counter, with configurable weights — Gavel's max-min
              formulation (PAPERS.md) specialized to cost shares, so a
              learned weight function can later replace the static map.
  budgets.py  windowed per-tenant accounting (arrival/drained cost,
              shed and serve-error rates via the breaker's WindowedRate
              pattern), the over-fair-share verdict driving preemptive
              SHEDDABLE sheds under saturation, and the bounded-
              cardinality tenant labeler (top-K by traffic + "other")
              behind every ``gie_tenant_*`` series.

``FairnessState`` is the bundle the picker owns (one per picker, like
``ResilienceState``); the runner configures it from
``--fairness-weights`` and /debugz/tenants reads its report.
"""

from __future__ import annotations

import time
from typing import Optional

from gie_tpu.fairness.budgets import TenantBudgets
from gie_tpu.fairness.drr import DeficitRoundRobin, FairnessConfig

__all__ = [
    "DeficitRoundRobin",
    "FairnessConfig",
    "FairnessState",
    "TenantBudgets",
    "parse_weights",
]


def parse_weights(specs) -> dict[str, float]:
    """``["tenant=weight", ...]`` (or one comma-joined string) -> weight
    map for FairnessConfig. Rejects malformed and non-positive entries
    loudly — a typoed weight silently defaulting to 1.0 would un-isolate
    exactly the tenant the operator meant to constrain."""
    out: dict[str, float] = {}
    if isinstance(specs, str):
        specs = [specs]
    for spec in specs or ():
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, raw = part.partition("=")
            if not sep or not name:
                raise ValueError(
                    f"fairness weight {part!r} must be TENANT=WEIGHT")
            try:
                w = float(raw)
            except ValueError:
                raise ValueError(
                    f"fairness weight {part!r}: {raw!r} is not a number"
                ) from None
            if w <= 0:
                raise ValueError(
                    f"fairness weight {part!r} must be > 0")
            out[name] = w
    return out


class FairnessState:
    """The per-picker fairness bundle: one DRR orderer (collector-thread
    state), one budget ledger (its own leaf lock — admission, collector
    and response threads all feed it), and the metric fan-out. Every
    method is cheap enough for its call site: ``note_arrival`` is one
    short lock on the pick path, ``order``/``note_wave`` run at wave
    cadence on the collector, ``over_share_set`` returns a cached
    frozenset recomputed at a bounded interval."""

    def __init__(self, cfg: Optional[FairnessConfig] = None,
                 clock=time.monotonic):
        self.cfg = cfg if cfg is not None else FairnessConfig()
        self.drr = DeficitRoundRobin(self.cfg)
        self.budgets = TenantBudgets(self.cfg, clock=clock)

    # -- flow queue (collector thread) ------------------------------------

    def order(self, items, take: int = 0):
        """Band-scoped weighted-DRR ordering of the pending queue; only
        the first ``take`` items' costs persist into the deficit state
        (they are the ones the next wave drains)."""
        return self.drr.order(items, take=take)

    def note_wave(self, items) -> None:
        """Charge one drained wave's costs to the tenants' windowed
        drained-cost ledgers + gie_tenant_cost_total."""
        from gie_tpu.runtime import metrics as own_metrics

        for it in items:
            label = self.budgets.note_drained(it.tenant, it.cost)
            own_metrics.TENANT_COST.labels(tenant=label).inc(it.cost)

    # -- admission path ----------------------------------------------------

    def note_arrival(self, tenant: str, cost: float) -> None:
        from gie_tpu.runtime import metrics as own_metrics

        label = self.budgets.note_arrival(tenant, cost)
        own_metrics.TENANT_REQUESTS.labels(tenant=label).inc()

    # -- shed / serve feedback --------------------------------------------

    def note_shed(self, tenant: str, band: str) -> None:
        from gie_tpu.runtime import metrics as own_metrics

        label = self.budgets.note_shed(tenant)
        own_metrics.TENANT_SHED.labels(tenant=label, band=band).inc()

    def note_serve(self, tenant: str, ok: bool, cls: str = "") -> None:
        from gie_tpu.runtime import metrics as own_metrics

        label = self.budgets.note_serve(tenant, ok)
        if not ok:
            own_metrics.TENANT_SERVE_ERRORS.labels(tenant=label).inc()

    # -- isolation verdicts -----------------------------------------------

    def over_share_set(self) -> frozenset:
        """Tenants currently over their weighted fair share of OFFERED
        load (cached; see TenantBudgets.over_share_set)."""
        return self.budgets.over_share_set()

    def label(self, tenant: str) -> str:
        return self.budgets.label(tenant)

    def report(self) -> dict:
        """/debugz/tenants payload: budgets + weights + live deficits."""
        rep = self.budgets.report()
        rep["weights"] = dict(self.cfg.weights)
        rep["default_weight"] = self.cfg.default_weight
        rep["deficits"] = self.drr.deficits()
        return rep
