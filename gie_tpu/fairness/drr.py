"""Band-scoped weighted deficit-round-robin flow ordering
(docs/FAIRNESS.md "DRR algebra").

The flow queue's fairness contract (proposal 1199) is scoped WITHIN a
criticality band: CRITICAL drains before STANDARD before SHEDDABLE, and
inside each band tenants share capacity. The seed's round-robin shared
request COUNTS; this orderer shares request COST — each drained item
charges ``item.cost`` (prompt + decode-estimate in the scheduler's own
``request_cost`` units, cached on the item at enqueue) against the
tenant's deficit counter, and a tenant is only drained while its
deficit covers the head-of-queue cost. Per-round credit is
``quantum * weight(tenant)``, so ``--fairness-weights a=2`` gives
tenant ``a`` twice the cost share of a weight-1 neighbor; uniform
weights (the default) converge to equal cost shares regardless of
request size mix. Gavel (PAPERS.md) frames the same knob as a max-min
policy over an arbitrary weighted metric — the weight map is the seam
a learned policy later replaces.

Ordering invariants (pinned by tests/test_fairness.py):

  * per-tenant FIFO is preserved (tenant queues only pop from the head);
  * bands drain strictly CRITICAL -> STANDARD -> SHEDDABLE;
  * long-run drained-cost shares converge to the weight ratios while
    tenants stay backlogged;
  * empty and single-tenant inputs degenerate to plain FIFO.

Statefulness: deficits persist ACROSS waves for tenants that remain
backlogged at the take boundary (the classic DRR carry), and reset to
zero when a tenant's queue fully drains (no credit hoarding). Only the
first ``take`` outputs charge the persistent state — those are the
items the collector's next wave actually drains; the remainder is
re-ordered next wave and must not be double-charged. Collector-thread
only: no lock.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Mapping


@dataclasses.dataclass(frozen=True)
class FairnessConfig:
    """Tenant-isolation knobs (the runner wires ``--fairness-*``)."""

    # tenant -> weight; absent tenants get default_weight (uniform).
    weights: Mapping[str, float] = dataclasses.field(default_factory=dict)
    default_weight: float = 1.0
    # DRR per-round credit in cost units; 0 = auto (the wave's max item
    # cost, the classic choice that guarantees every round drains >= 1
    # item from the visited tenant).
    quantum: float = 0.0
    # Over-fair-share verdict: a tenant whose windowed OFFERED-cost
    # share exceeds ``factor x`` its weighted fair share is eligible for
    # preemptive SHEDDABLE sheds under saturation. The formula
    # self-guards the degenerate pool: a lone tenant's share is 1.0 and
    # its fair share is 1.0, so factor > 1 never flags it.
    over_share_factor: float = 2.0
    # Sliding window for every per-tenant rate/cost ledger.
    window_s: float = 10.0
    # Cached over-share set recompute interval (wave cadence reads it).
    eval_interval_s: float = 0.25
    # Bounded-cardinality label policy: top_k tenants by traffic keep
    # their own gie_tenant_* label value, the rest fold into "other";
    # at most label_cap distinct tenants are ever promoted process-wide.
    top_k: int = 8
    # Bounded state: per-tenant accounts and deficit entries beyond this
    # are evicted (least-traffic first).
    max_tracked: int = 512

    def __post_init__(self):
        if self.default_weight <= 0:
            raise ValueError("default_weight must be > 0")
        for t, w in self.weights.items():
            if w <= 0:
                raise ValueError(f"fairness weight for {t!r} must be > 0")
        if self.quantum < 0:
            raise ValueError("quantum must be >= 0 (0 = auto)")
        if self.over_share_factor <= 1.0:
            raise ValueError("over_share_factor must be > 1")
        if self.window_s <= 0 or self.eval_interval_s <= 0:
            raise ValueError("windows must be positive")
        if self.top_k < 1 or self.max_tracked < 1:
            raise ValueError("top_k and max_tracked must be >= 1")

    @property
    def label_cap(self) -> int:
        return 4 * self.top_k

    def weight(self, tenant: str) -> float:
        return self.weights.get(tenant, self.default_weight)


class DeficitRoundRobin:
    """The orderer. Items need three attributes: ``band`` (int, lower =
    more critical), ``tenant`` (str), ``cost`` (float > 0)."""

    def __init__(self, cfg: FairnessConfig | None = None):
        self.cfg = cfg if cfg is not None else FairnessConfig()
        # (band, tenant) -> carried deficit, bounded by _prune.
        self._deficit: dict[tuple[int, str], float] = {}

    def deficits(self) -> dict:
        """Live carried deficits for /debugz/tenants ("band:tenant")."""
        return {
            f"{band}:{tenant or 'default'}": round(d, 4)
            for (band, tenant), d in self._deficit.items()
        }

    def _prune(self, items) -> None:
        if len(self._deficit) <= self.cfg.max_tracked:
            return
        live = {(it.band, it.tenant) for it in items}
        for key in [k for k in self._deficit if k not in live]:
            del self._deficit[key]

    def order(self, items, take: int = 0) -> list:
        """Full ordering of ``items`` (bands strict, DRR within a band).
        Deficit charges persist only for the first ``take`` outputs
        (0 = all)."""
        n = len(items)
        if n <= 1:
            return list(items)
        self._prune(items)
        bands: dict[int, dict[str, deque]] = {}
        tenant_order: dict[int, list[str]] = {}
        for it in items:
            per = bands.setdefault(it.band, {})
            q = per.get(it.tenant)
            if q is None:
                per[it.tenant] = q = deque()
                tenant_order.setdefault(it.band, []).append(it.tenant)
            q.append(it)
        out: list = []
        limit = take if take and take > 0 else n
        persisted = False
        for band in sorted(bands):
            per = bands[band]
            tenants = tenant_order[band]
            if len(tenants) == 1:
                # Degenerate single-tenant band: plain FIFO; a fully-
                # drained tenant carries no deficit forward.
                out.extend(per[tenants[0]])
                per[tenants[0]].clear()
                if not persisted:
                    self._deficit.pop((band, tenants[0]), None)
                    persisted = len(out) >= limit
                continue
            quantum = self.cfg.quantum or max(
                it.cost for q in per.values() for it in q)
            quantum = max(quantum, 1e-9)
            weights = {t: self.cfg.weight(t) for t in tenants}
            local = {t: self._deficit.get((band, t), 0.0) for t in tenants}
            active = deque(tenants)
            while active:
                t = active.popleft()
                q = per[t]
                local[t] += quantum * weights[t]
                while q and local[t] >= q[0].cost:
                    head = q.popleft()
                    local[t] -= head.cost
                    out.append(head)
                    if not persisted and len(out) >= limit:
                        # The take boundary: the next wave drains exactly
                        # this prefix, so THIS is the deficit state the
                        # drain leaves behind. Later pops reorder the
                        # remainder best-effort without touching it.
                        self._persist_band(band, local, per,
                                           quantum, weights)
                        persisted = True
                if q:
                    active.append(t)
                else:
                    # Classic DRR: an emptied queue forfeits its credit —
                    # an idle tenant must not bank a burst allowance.
                    local[t] = 0.0
            if not persisted:
                self._persist_band(band, local, per, quantum, weights)
        return out

    def _persist_band(self, band: int, local: dict, per: dict,
                      quantum: float, weights: dict) -> None:
        """Snapshot one band's boundary-time deficits into the carried
        state: backlogged tenants keep their (capped) deficit, fully
        drained tenants reset to zero."""
        for t, d in local.items():
            if not per[t]:
                self._deficit.pop((band, t), None)
            else:
                cap = 2.0 * quantum * weights[t]
                self._deficit[(band, t)] = min(max(d, 0.0), cap)
