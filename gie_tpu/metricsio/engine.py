"""ScrapeEngine: multiplexed keep-alive metrics ingestion.

The reference's data layer mandates a fast poll (~50 ms) per endpoint
(proposal 1023 README:59-60). The seed implementation spent one Python
thread and one fresh TCP connection per endpoint per tick — at the
ROADMAP's hundreds-of-replicas scale that is hundreds of runnable threads
churning the GIL, thousands of connection setups per second, and one
MetricsStore lock acquisition per row, all stolen from the pick path.

This engine keeps the 50 ms cadence with a SMALL FIXED pool of worker
shards (default ``min(8, cpu)``), each driving many endpoints:

  deadline min-heap   each shard schedules its endpoints by earliest-due
                      deadline (jittered so a pool attached in one sweep
                      does not thundering-herd every tick thereafter).
  keep-alive fetch    one persistent ``http.client`` connection per
                      endpoint, reused across scrapes; a failed reuse
                      retries once on a fresh connection (servers may
                      close idle keep-alives at any time).
  O(1) attach/detach  lifecycle events post a command to the owning
                      shard's inbox and return immediately — detach never
                      joins a thread, so a fetch hung on a dead pod can
                      no longer stall slot reclaim for its 2 s timeout.
  adaptive backoff    an unreachable endpoint's effective interval
                      doubles per consecutive failure up to
                      ``max_backoff_s`` (1 s) and snaps back to the base
                      interval on the first success, so dead pods stop
                      taxing the shard budget live pods need.
  batched writes      a shard's completed sweep lands in the store via
                      ONE ``MetricsStore.update_rows`` lock acquisition,
                      not one per endpoint.

Observability (runtime/metrics.py): ``gie_scrape_staleness_seconds``
(achieved row refresh interval), ``gie_scrape_fetch_seconds``,
``gie_scrape_connection_reuse_ratio``, ``gie_scrape_consecutive_failures_max``
and ``gie_scrape_endpoints``. The autoscale SignalCollector reads
``staleness_seconds()`` as a second staleness source next to the store's
row ages (docs/METRICSIO.md).

The legacy thread-per-endpoint API survives as a thin adapter
(``metricsio.scrape.Scraper``) so existing call sites and tests keep
working during the transition.
"""

from __future__ import annotations

import heapq
import http.client
import itertools
import random
import threading
import urllib.parse
from typing import Optional

from gie_tpu.metricsio.mappings import ServerMapping
from gie_tpu.metricsio.store import MetricsStore
from gie_tpu.resilience import faults
from gie_tpu.resilience.policy import JITTER_SYMMETRIC, Backoff, BackoffPolicy
from gie_tpu.runtime.clock import MONOTONIC, Clock
from gie_tpu.utils.lora import LoraRegistry


def _default_workers() -> int:
    import os

    return max(1, min(8, os.cpu_count() or 1))


class _Endpoint:
    """One attached endpoint's scrape state. Owned by exactly one shard
    after attach; the engine lock guards only the fields the control
    plane touches (``dead``)."""

    __slots__ = (
        "slot", "url", "mapping", "host", "port", "path", "conn",
        "due", "backoff", "last_success", "attached_at", "dead",
    )

    def __init__(self, slot: int, url: str, mapping: ServerMapping,
                 backoff: Backoff, attached_at: float):
        self.slot = slot
        self.url = url
        self.mapping = mapping
        parts = urllib.parse.urlsplit(url)
        self.host = parts.hostname or ""
        self.port = parts.port or 80
        self.path = (parts.path or "/") + (
            f"?{parts.query}" if parts.query else "")
        self.conn: Optional[http.client.HTTPConnection] = None
        self.due = 0.0             # clock deadline for the next scrape
        # Shared resilience policy (gie_tpu/resilience/policy.py): the
        # per-endpoint failure-streak state machine that used to be a bare
        # counter plus inline 2**min(streak, 20) arithmetic here.
        self.backoff = backoff
        self.last_success = 0.0    # clock time; 0 = never scraped
        self.attached_at = attached_at
        self.dead = False          # set under the engine lock on detach

    @property
    def fail_streak(self) -> int:
        return self.backoff.failures

    def close_conn(self) -> None:
        conn, self.conn = self.conn, None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass


# Flush the pending batched writes once this many rows accumulate even if
# more endpoints are due (bounds the staleness a write can sit unflushed).
_FLUSH_MAX = 32


class ScrapeEngine:
    """Multiplexed fast-poll scraper: ``workers`` shard threads drive any
    number of endpoints over persistent connections.

    Drop-in lifecycle API: ``attach(slot, url, mapping)`` /
    ``detach(slot)`` / ``close()`` — both non-blocking (detach marks the
    endpoint dead and clears its row; the owning shard drops the heap
    entry lazily). ``fetcher`` overrides the keep-alive HTTP path with a
    plain callable (tests, benchmarks, simulators).
    """

    def __init__(
        self,
        store: MetricsStore,
        lora: Optional[LoraRegistry] = None,
        interval_s: float = 0.05,
        fetcher=None,
        workers: Optional[int] = None,
        max_backoff_s: float = 1.0,
        timeout_s: Optional[float] = None,
        jitter: float = 0.1,
        breaker_board=None,
        clock: Clock = MONOTONIC,
        rng=None,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        # Clock seam (gie_tpu/runtime/clock.py): shard deadline heaps,
        # backoff pacing, and the staleness clocks all read this — a
        # virtual-time storm drives the whole scrape plane off the
        # simulated timeline. ``rng`` (default: the module-level random
        # the engine always used) seeds the attach phase-stagger AND the
        # per-endpoint backoff jitter, so a seeded engine schedules
        # deterministically.
        self._clock = clock
        self._rng = rng if rng is not None else random
        self.store = store
        self.lora = lora or LoraRegistry()
        self.interval_s = interval_s
        self.fetcher = fetcher
        self.workers = workers if workers else _default_workers()
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        # Backoff never caps below the base interval (an operator running
        # a slow 2 s poll must not see failures SPEED polling up).
        self.max_backoff_s = max(max_backoff_s, interval_s)
        # Shared jittered-backoff policy (resilience/policy.py) replacing
        # the hand-rolled streak-exponent arithmetic: same shape — double
        # per consecutive failure, exponent capped at 20, symmetric
        # jitter, snap back to the base cadence on success (parity pinned
        # by tests/test_resilience.py).
        self._backoff_policy = BackoffPolicy(
            base_s=interval_s,
            max_s=self.max_backoff_s,
            jitter=jitter,
            jitter_mode=JITTER_SYMMETRIC,
            max_exponent=20,
        )
        # Optional resilience.BreakerBoard: fetch outcomes feed the
        # per-endpoint circuit breakers the pick path's candidate filter
        # reads (docs/RESILIENCE.md).
        self.breaker_board = breaker_board
        # Connect/read timeout: a SYN-black-holed pod (typical k8s death —
        # no RST) blocks its shard for the FULL timeout per attempt, so
        # the default scales with the poll cadence instead of inheriting
        # the legacy flat 2 s: at 50 ms that is a 250 ms worst-case shard
        # stall, and with the 1 s backoff between attempts the dead pod
        # costs its shard <25% duty instead of ~70%. Overridable for slow
        # backends.
        self.timeout_s = (timeout_s if timeout_s is not None
                          else min(2.0, max(5.0 * interval_s, 0.25)))
        self._lock = threading.Lock()
        self._live: dict[int, _Endpoint] = {}
        self._fetches = 0        # keep-alive path attempts (engine lock)
        self._reused = 0         # ... that reused a live connection
        self._closed = False
        # Early-scrape window: an endpoint due within this many seconds is
        # scraped NOW instead of paying a timed sleep for the gap. Timed
        # waits on small timeouts cost ~1 ms of timer slack on stock
        # kernels — sleeping per sub-millisecond heap gap convoys the
        # shard into permanent backlog. Scraping early is harmless: the
        # next deadline keys off the fetch start, so cadence is preserved
        # (a constant phase shift, not drift).
        self._early_s = min(0.005, interval_s / 4.0)
        self._shards = [_Shard(self, i) for i in range(self.workers)]
        for s in self._shards:
            s.thread.start()

    # -- lifecycle (control plane; O(1), never blocks on I/O) -------------

    def _shard_for(self, slot: int) -> "_Shard":
        return self._shards[slot % self.workers]

    def attach(self, slot: int, url: str, mapping: ServerMapping) -> None:
        with self._lock:
            if self._closed:
                return
            prev = self._live.get(slot)
            if prev is not None and prev.url == url:
                return
            if prev is not None:
                # Endpoint re-bound (port renumber / pod IP change): the
                # old state is dropped by its shard; the row survives
                # (same pod identity, new address).
                prev.dead = True
            now = self._clock.now()
            ep = _Endpoint(slot, url, mapping,
                           Backoff(self._backoff_policy, rng=self._rng),
                           attached_at=now)
            # Phase-stagger the first scrape so a pool attached in one
            # reconcile sweep spreads over the interval instead of
            # thundering every tick in lockstep.
            ep.due = now + self._rng.uniform(0, self.interval_s)
            self._live[slot] = ep
        shard = self._shard_for(slot)
        shard.inbox.append(ep)
        self._clock.set_event(shard.wake)

    def detach(self, slot: int) -> None:
        """Stop scraping ``slot`` and clear its row. Returns immediately:
        the kill is a flag flip under the engine lock — a fetch currently
        hung on this endpoint finishes (or times out) on its shard and
        its result is discarded by the dead check inside the same lock
        that ordered this removal, so the cleared row cannot be
        resurrected by a late write."""
        with self._lock:
            ep = self._live.pop(slot, None)
            if ep is not None:
                ep.dead = True
            self.store.remove(slot)
        if self.breaker_board is not None:
            # Breaker history must not outlive the endpoint: a reused
            # slot starts CLOSED.
            self.breaker_board.drop(slot)
        if ep is not None:
            self._clock.set_event(self._shard_for(slot).wake)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            slots = list(self._live)
            for ep in self._live.values():
                ep.dead = True
            self._live.clear()
            for slot in slots:
                self.store.remove(slot)
        for s in self._shards:
            self._clock.set_event(s.wake)
        for s in self._shards:
            # Bounded: a shard hung inside a fetch is a daemon thread and
            # holds no locks anyone waits on — close must not inherit the
            # stall the non-blocking detach was built to avoid.
            s.thread.join(timeout=1)

    # -- introspection (autoscale staleness input, tests, bench) ----------

    def staleness_seconds(self, now: Optional[float] = None) -> float:
        """Oldest time-since-last-successful-scrape across attached
        endpoints (attach age for never-scraped ones); 0.0 when nothing
        is attached. The autoscale SignalCollector reads this next to
        the store's row ages: it covers the ingestion-side outage modes
        the row ages cannot (every endpoint unreachable and backing off,
        or a wedged shard), straight from the engine's own clocks."""
        now = self._clock.now() if now is None else now
        with self._lock:
            if not self._live:
                return 0.0
            return max(
                now - (ep.last_success or ep.attached_at)
                for ep in self._live.values()
            )

    def consecutive_failures_max(self) -> int:
        with self._lock:
            return max(
                (ep.fail_streak for ep in self._live.values()), default=0)

    def connection_reuse_ratio(self) -> float:
        with self._lock:
            return self._reused / self._fetches if self._fetches else 0.0

    def endpoint_count(self) -> int:
        with self._lock:
            return len(self._live)

    # -- data plane (shard threads) ---------------------------------------

    def _fetch(self, ep: _Endpoint) -> bytes:
        """Keep-alive GET with a single fresh-connection retry (an idle
        keep-alive may be closed server-side between scrapes; only the
        retry's failure is a real endpoint failure)."""
        if faults.ENABLED:
            # gie-chaos fault points (resilience/faults.py): per-endpoint
            # added latency / hang, then the fetch failure itself. Keyed
            # by URL so a scenario can target a subset of the pool and a
            # seed reproduces the same per-endpoint schedule.
            faults.check("endpoint.slow", key=ep.url)
            faults.check("endpoint.hang", key=ep.url)
            faults.check("scrape.fetch", key=ep.url)
        if self.fetcher is not None:
            return self.fetcher(ep.url)
        fresh = ep.conn is None
        for attempt in (0, 1):
            if ep.conn is None:
                ep.conn = http.client.HTTPConnection(
                    ep.host, ep.port, timeout=self.timeout_s)
                fresh = True
            try:
                ep.conn.request("GET", ep.path)
                resp = ep.conn.getresponse()
                body = resp.read()
                if resp.status != 200:
                    raise OSError(f"HTTP {resp.status} from {ep.url}")
                if resp.will_close:
                    ep.close_conn()
                with self._lock:
                    self._fetches += 1
                    if not fresh:
                        self._reused += 1
                return body
            except Exception:
                ep.close_conn()
                if fresh or attempt == 1:
                    raise
                # else: stale keep-alive; retry once on a new connection.
        raise AssertionError("unreachable")

    def _scrape(self, ep: _Endpoint):
        """Fetch + parse one endpoint; reschedules ``ep.due``. Returns the
        store row tuple or None (failure / empty exposition)."""
        from gie_tpu.metricsio.scrape import parse_scrape
        from gie_tpu.runtime import metrics as own_metrics

        t0 = self._clock.now()
        try:
            payload = self._fetch(ep)
            metrics, active, waiting = parse_scrape(
                payload, ep.mapping, self.lora)
        except Exception:
            # Unreachable endpoint: leave the last row (staleness shows up
            # via METRICS_AGE_S; the reference keeps stale metrics rather
            # than evicting) and back the poll off so a dead pod stops
            # taxing the shard budget its live peers need. The delay
            # shape (exponent capped at 20, symmetric jitter, max_s
            # ceiling) lives in the shared policy module now.
            ep.due = self._clock.now() + ep.backoff.fail()
            if self.breaker_board is not None:
                self.breaker_board.record(ep.slot, False)
            return None
        done = self._clock.now()
        own_metrics.SCRAPE_FETCH.observe(done - t0)
        own_metrics.SCRAPE_STALENESS.observe(
            done - (ep.last_success or ep.attached_at))
        ep.last_success = done
        if self.breaker_board is not None:
            self.breaker_board.record(ep.slot, True)
        # Snap back to the base cadence; next deadline keyed off the fetch
        # START, matching the legacy interval - elapsed pacing; never
        # sooner than 1 ms out.
        ep.due = max(t0 + ep.backoff.ok(), done + 0.001)
        if not metrics:
            return None
        return (ep, metrics, active, waiting)

    def _flush(self, pending: list) -> None:
        """Apply a shard's completed sweep: one engine-lock section, one
        store-lock acquisition (update_rows). The dead check inside this
        lock is what makes detach's row clear final."""
        from gie_tpu.runtime import metrics as own_metrics

        with self._lock:
            rows = [
                (ep.slot, metrics, active, waiting)
                for ep, metrics, active, waiting in pending
                if not ep.dead and self._live.get(ep.slot) is ep
            ]
            if rows:
                self.store.update_rows(rows)
            n_live = len(self._live)
            streak = max(
                (e.fail_streak for e in self._live.values()), default=0)
            reuse = self._reused / self._fetches if self._fetches else 0.0
        pending.clear()
        # Gauges update even on an EMPTY sweep: during a full outage no
        # rows complete, and a failure gauge frozen at its pre-outage
        # value is worthless exactly when it matters.
        own_metrics.SCRAPE_ENDPOINTS.set(n_live)
        own_metrics.SCRAPE_FAILS_MAX.set(streak)
        own_metrics.SCRAPE_REUSE.set(reuse)
        if self.breaker_board is not None:
            own_metrics.BREAKER_OPEN.set(self.breaker_board.open_count())


class _Shard:
    """One worker: a deadline min-heap over its endpoints, an inbox for
    O(1) attach handoff, and a wake event for early deadlines/shutdown."""

    def __init__(self, engine: ScrapeEngine, index: int):
        self.engine = engine
        self.inbox: list[_Endpoint] = []  # append/pop both GIL-atomic
        self.wake = threading.Event()
        self.thread = threading.Thread(
            target=self._run, name=f"scrape-shard-{index}", daemon=True)

    def _run(self) -> None:
        eng = self.engine
        # Virtual-time actor registration (runtime/clock.py): the shard
        # is one of the simulation's parked/active participants; on the
        # real clock this is a no-op.
        tok = eng._clock.actor_begin(self.thread.name)
        try:
            self._run_inner()
        finally:
            eng._clock.actor_end(tok)

    def _run_inner(self) -> None:
        eng = self.engine
        heap: list[tuple[float, int, _Endpoint]] = []
        seq = itertools.count()  # heap tiebreak: _Endpoint is unordered
        pending: list = []
        while True:
            while self.inbox:
                ep = self.inbox.pop()
                heapq.heappush(heap, (ep.due, next(seq), ep))
            if eng._closed:
                eng._flush(pending)
                return
            if not heap:
                eng._flush(pending)
                eng._clock.wait_event(self.wake, 0.2)
                self.wake.clear()
                continue
            due, _, ep = heap[0]
            if ep.dead:
                heapq.heappop(heap)
                ep.close_conn()
                continue
            now = eng._clock.now()
            if due > now + eng._early_s:
                # Idle until the earliest deadline: the sweep is complete,
                # so write it out, then sleep interruptibly (attach of an
                # earlier-due endpoint or close sets the wake event).
                # Deadlines inside the early window are taken immediately
                # instead — see ScrapeEngine._early_s.
                eng._flush(pending)
                eng._clock.wait_event(self.wake, min(due - now, 0.2))
                self.wake.clear()
                continue
            heapq.heappop(heap)
            row = eng._scrape(ep)
            if row is not None:
                pending.append(row)
                if len(pending) >= _FLUSH_MAX:
                    eng._flush(pending)
            heapq.heappush(heap, (ep.due, next(seq), ep))
