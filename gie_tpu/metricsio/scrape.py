"""Prometheus scrape parsing + the fast-poll scraper front ends.

Data-layer ingestion per reference docs/proposals/1023-data-layer-
architecture/README.md:59-60 (goroutine-per-endpoint fast poll) and the
metric semantics of proposal 003. The production path is the multiplexed
keep-alive ``ScrapeEngine`` (metricsio/engine.py, docs/METRICSIO.md);
``Scraper`` here is a thin adapter over it preserving the legacy
attach/detach/close surface, and ``ThreadPerEndpointScraper`` keeps the
original one-thread-one-connection implementation alive as the parity
and benchmark baseline (bench_scrape.py, tests/test_scrape_engine.py).
"""

from __future__ import annotations

import re
import threading
import urllib.request
from typing import Callable, Optional, Union

from prometheus_client.parser import text_string_to_metric_families

from gie_tpu.metricsio.mappings import LabeledGauge, ServerMapping
from gie_tpu.metricsio.store import MetricsStore
from gie_tpu.runtime.clock import MONOTONIC
from gie_tpu.sched.constants import Metric
from gie_tpu.utils.lora import LoraRegistry


def _match(sample, gauge: LabeledGauge) -> bool:
    return all(sample.labels.get(k) == v for k, v in gauge.labels.items())


# Fallback registry for callers that don't inject one: module-level so ids
# stay stable within the process (a per-call registry would reassign ids on
# every scrape and silently break affinity matching).
_DEFAULT_REGISTRY = LoraRegistry()


def wanted_columns(
    mapping: ServerMapping,
) -> list[tuple[int, LabeledGauge]]:
    """The (Metric column, gauge) table one server mapping scrapes —
    shared by the pure-Python loop and the native scanner's query spec so
    the two paths cannot desynchronize."""
    wanted: list[tuple[int, LabeledGauge]] = [
        (Metric.QUEUE_DEPTH, mapping.queued),
        (Metric.RUNNING_REQUESTS, mapping.running),
        (Metric.KV_CACHE_UTIL, mapping.kv_util),
    ]
    if mapping.block_size is not None:
        wanted.append((Metric.BLOCK_SIZE, mapping.block_size))
    if mapping.num_blocks is not None:
        wanted.append((Metric.NUM_BLOCKS, mapping.num_blocks))
    return wanted


def parse_scrape(
    text: Union[str, bytes],
    mapping: ServerMapping,
    lora: Optional[LoraRegistry] = None,
    use_native: bool = True,
) -> tuple[dict[int, float], list[int], list[int]]:
    """Prometheus exposition text -> (metric columns, active/waiting LoRA ids).

    LoRA residency follows the vllm:lora_requests_info contract (proposal
    003:43-57): gauge VALUE is a last-updated timestamp — when several series
    exist, the freshest wins — and the adapter lists ride in the
    running_lora_adapters / waiting_lora_adapters labels.

    When native/libgiepromparse.so is built, a one-pass C++ scanner pulls
    the mapped gauges and the LoRA-info sample lines (this loop is the
    metrics-in hot path: one scrape per endpoint per 50 ms, tens of KB of
    irrelevant families each); only those few lines go through the Python
    parser. Semantics are identical for well-formed expositions — parity is
    pinned in tests/test_promparse_native.py; pass use_native=False to
    force the pure-Python path. Accepts bytes (the fetcher's raw payload)
    or str.
    """
    if use_native:
        from gie_tpu.metricsio import native

        extracted = native.extract(text, mapping)
        if extracted is not None:
            out, lora_lines = extracted
            lora_active, lora_waiting = _apply_lora_lines(
                "\n".join(lora_lines), lora, out)
            return out, lora_active, lora_waiting

    if isinstance(text, bytes):
        text = text.decode("utf-8", "replace")
    out: dict[int, float] = {}
    wanted = wanted_columns(mapping)
    lora_samples = []
    lora_names = (
        {mapping.lora_info, mapping.lora_info.replace(":", "_")}
        if mapping.lora_info else set()
    )
    for family in text_string_to_metric_families(text):
        for sample in family.samples:
            for col, gauge in wanted:
                if sample.name != gauge.name or not _match(sample, gauge):
                    continue
                if gauge.value_label is not None:
                    raw = sample.labels.get(gauge.value_label)
                    if raw is not None:
                        try:
                            out[col] = float(raw)
                        except ValueError:
                            pass
                else:
                    out[col] = float(sample.value)
            if sample.name in lora_names:
                lora_samples.append(sample)
    lora_active, lora_waiting = _apply_lora_samples(lora_samples, lora, out)
    return out, lora_active, lora_waiting


def _apply_lora_samples(
    samples, lora: Optional[LoraRegistry], out: dict[int, float]
) -> tuple[list[int], list[int]]:
    """Freshest-series LoRA rule (003:43-57) — ONE implementation shared by
    both parse paths."""
    lora_active: list[int] = []
    lora_waiting: list[int] = []
    best_lora_ts = float("-inf")
    for sample in samples:
        if sample.value < best_lora_ts:
            continue
        best_lora_ts = sample.value
        out[Metric.MAX_LORA] = float(sample.labels.get("max_lora", "0") or 0)
        reg = lora if lora is not None else _DEFAULT_REGISTRY
        lora_active = reg.ids_for(
            sample.labels.get("running_lora_adapters", "").split(","))
        lora_waiting = reg.ids_for(
            sample.labels.get("waiting_lora_adapters", "").split(","))
        out[Metric.WAITING_LORA] = float(len(lora_waiting))
    return lora_active, lora_waiting


class _Sample:
    """Duck-typed stand-in for prometheus_client's Sample (the shared
    freshest-series rule only reads .value and .labels)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: dict, value: float):
        self.name = name
        self.labels = labels
        self.value = value


_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"((?:[^"\\]|\\.)*)"')
_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape_label(raw: str) -> str:
    # Prometheus exposition label-value escapes: \\ -> \, \" -> ", \n.
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), raw)


def _fast_parse_sample_lines(text: str) -> list[_Sample]:
    """Minimal exposition-line parser for the handful of sample lines the
    native scanner hands back (`name{labels} value [ts]`). The general
    prometheus_client parser costs ~170 us per call — at 256 endpoints on
    a 50 ms cadence that alone is most of a core — while these lines need
    only label extraction and a float. Semantics parity with the full
    parser is pinned in tests/test_promparse_native.py."""
    samples: list[_Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        if brace >= 0:
            end = line.rfind("}")
            if end < brace:
                continue
            name = line[:brace].strip()
            labels = {
                m.group(1): _unescape_label(m.group(2))
                for m in _LABEL_RE.finditer(line[brace + 1:end])
            }
            rest = line[end + 1:].split()
        else:
            parts = line.split()
            name, labels, rest = parts[0], {}, parts[1:]
        if not rest:
            continue
        try:
            value = float(rest[0])
        except ValueError:
            continue
        samples.append(_Sample(name, labels, value))
    return samples


def _apply_lora_lines(
    lora_text: str,
    lora: Optional[LoraRegistry],
    out: dict[int, float],
) -> tuple[list[int], list[int]]:
    """Native fast path: parse just the lora-info sample lines, then run
    the same shared rule."""
    if not lora_text.strip():
        return [], []
    return _apply_lora_samples(
        _fast_parse_sample_lines(lora_text), lora, out)


# Fetchers may return bytes (preferred: the native scanner consumes the
# raw payload without a decode/encode round-trip) or str.
Fetcher = Callable[[str], Union[str, bytes]]


def _http_fetch(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=2.0) as resp:  # noqa: S310
        return resp.read()


class Scraper:
    """Legacy-API adapter over the multiplexed ScrapeEngine.

    ``attach(slot, url, mapping)`` / ``detach(slot)`` / ``close()`` keep
    their historical meaning (detach clears the slot's row), but no call
    ever spawns a per-endpoint thread or joins one: the engine's fixed
    worker-shard pool does all polling. Call sites that want the engine's
    knobs (worker count, backoff ceiling) should construct ScrapeEngine
    directly, as the runner does."""

    def __init__(
        self,
        store: MetricsStore,
        lora: Optional[LoraRegistry] = None,
        interval_s: float = 0.05,
        fetcher: Optional[Fetcher] = None,
        workers: Optional[int] = None,
    ):
        from gie_tpu.metricsio.engine import ScrapeEngine

        self.store = store
        self.interval_s = interval_s
        self._engine = ScrapeEngine(
            store, lora=lora, interval_s=interval_s, fetcher=fetcher,
            workers=workers)
        self.lora = self._engine.lora

    def attach(self, slot: int, url: str, mapping: ServerMapping) -> None:
        self._engine.attach(slot, url, mapping)

    def detach(self, slot: int) -> None:
        self._engine.detach(slot)

    def close(self) -> None:
        self._engine.close()


class ThreadPerEndpointScraper:
    """The seed's per-endpoint fast-poll loop: one poller thread and one
    fresh ``urllib`` connection per endpoint per tick.

    Kept (unchanged in behavior) as the comparison baseline for
    bench_scrape.py and the engine parity tests; production call sites
    use the ScrapeEngine. The reference runs one goroutine per endpoint
    with a configurable interval (1023 README:59-60); 50 ms default
    matches its fast-poll guidance.
    """

    def __init__(
        self,
        store: MetricsStore,
        lora: Optional[LoraRegistry] = None,
        interval_s: float = 0.05,
        fetcher: Fetcher = _http_fetch,
    ):
        self.store = store
        self.lora = lora or LoraRegistry()
        self.interval_s = interval_s
        self.fetcher = fetcher
        self._stops: dict[int, threading.Event] = {}
        self._threads: dict[int, threading.Thread] = {}
        self._urls: dict[int, str] = {}
        self._lock = threading.Lock()

    def attach(self, slot: int, url: str, mapping: ServerMapping) -> None:
        with self._lock:
            if self._urls.get(slot) == url:
                return
            already = slot in self._threads
        if already:
            # Endpoint re-bound (port renumber / pod IP change): restart the
            # poller at the new URL instead of polling the dead one forever.
            self.detach(slot)
        with self._lock:
            if self._urls.get(slot) == url:
                return
            stop = threading.Event()
            t = threading.Thread(
                target=self._poll, args=(slot, url, mapping, stop), daemon=True
            )
            self._stops[slot] = stop
            self._threads[slot] = t
            self._urls[slot] = url
            t.start()

    def detach(self, slot: int) -> None:
        with self._lock:
            stop = self._stops.pop(slot, None)
            thread = self._threads.pop(slot, None)
            self._urls.pop(slot, None)
        if stop is not None:
            stop.set()
        if thread is not None:
            thread.join(timeout=2)
        self.store.remove(slot)

    def close(self) -> None:
        for slot in list(self._threads):
            self.detach(slot)

    def _poll(
        self, slot: int, url: str, mapping: ServerMapping, stop: threading.Event
    ) -> None:
        while not stop.is_set():
            started = MONOTONIC.now()
            try:
                text = self.fetcher(url)
                metrics, active, waiting = parse_scrape(text, mapping, self.lora)
                if metrics:
                    self.store.update(
                        slot, metrics, lora_active=active, lora_waiting=waiting
                    )
            except Exception:
                # Unreachable endpoint: leave the last row; staleness shows
                # up via METRICS_AGE_S and the endpoint stays routable
                # (reference keeps stale metrics rather than evicting).
                pass
            elapsed = MONOTONIC.now() - started
            stop.wait(max(self.interval_s - elapsed, 0.001))
