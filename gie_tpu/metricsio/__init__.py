"""Model-server metrics layer: scrape -> flat [M, K] metrics tensor.

Implements the model-server metrics protocol of reference
docs/proposals/003-model-server-protocol/README.md and the data-layer
architecture of docs/proposals/1023-data-layer-architecture/README.md, with
the TPU twist that the sink is a dense tensor view, not per-endpoint structs.
"""

from gie_tpu.metricsio.store import MetricsStore

__all__ = ["MetricsStore", "ScrapeEngine"]


def __getattr__(name):
    # Lazy: engine pulls in runtime.metrics (prometheus) — keep the bare
    # store import light for the simulator/test paths that only need it.
    if name == "ScrapeEngine":
        from gie_tpu.metricsio.engine import ScrapeEngine

        return ScrapeEngine
    raise AttributeError(name)
