"""MetricsStore: the dense endpoint-metrics sink.

The reference's data layer stores scraped PodMetrics per endpoint object
(reference docs/proposals/1023-data-layer-architecture/README.md:104-164
Endpoint.Store/GetAttributes). Here the store IS the tensor: per-slot rows of
a float32 [M_MAX, NUM_METRICS] matrix plus LoRA residency slots, snapshotted
into an EndpointBatch for the scheduler in O(1) copies.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from gie_tpu.api.types import ROLE_LABEL
from gie_tpu.datastore.objects import Endpoint
from gie_tpu.runtime.clock import REALTIME
from gie_tpu.sched import constants as C
from gie_tpu.sched.types import EndpointBatch

# Pod-label value -> Role column value (unknown/absent -> BOTH).
_ROLE_BY_LABEL = {
    "prefill": int(C.Role.PREFILL),
    "decode": int(C.Role.DECODE),
    "both": int(C.Role.BOTH),
}


class MetricsStore:
    def __init__(self, clock: Callable[[], float] = REALTIME) -> None:
        # Clock seam (gie_tpu/runtime/clock.py): row freshness stamps.
        # Default is wall time (the store's historical convention); a
        # virtual-time storm passes its own clock so row ages and the
        # staleness verdicts derived from them live on the simulated
        # timeline.
        self._clock = clock
        self._lock = threading.Lock()
        self._metrics = np.zeros((C.M_MAX, C.NUM_METRICS), np.float32)
        self._lora_active = np.full((C.M_MAX, C.LORA_SLOTS), -1, np.int32)
        self._lora_waiting = np.full((C.M_MAX, C.LORA_SLOTS), -1, np.int32)
        self._scraped_at = np.zeros((C.M_MAX,), np.float64)
        self._has_data = np.zeros((C.M_MAX,), bool)
        # Scale-from-zero wake signal (ROADMAP): arrivals that found an
        # EMPTY pool (the ext-proc layer 503s them before any endpoint
        # state exists to scrape). The autoscale SignalCollector drains
        # this counter into PoolSignals.wake_arrivals each window.
        self._wake_arrivals = 0

    def update(
        self,
        slot: int,
        metrics: dict[int, float],
        lora_active: Sequence[int] = (),
        lora_waiting: Sequence[int] = (),
        now: Optional[float] = None,
    ) -> None:
        """Record one endpoint's scrape result (metric-column -> value)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._apply_locked(slot, metrics, lora_active, lora_waiting, now)

    def update_rows(
        self,
        rows: Sequence[tuple],
        now: Optional[float] = None,
    ) -> None:
        """Apply one scrape-engine shard's completed sweep under a SINGLE
        lock acquisition: ``rows`` is a sequence of
        ``(slot, metrics, lora_active, lora_waiting)`` tuples, each with
        the exact semantics of ``update()``. At hundreds of endpoints per
        50 ms tick the per-row lock traffic of the thread-per-endpoint
        path measurably contended the scheduler's snapshot reads; the
        batched form costs the readers one acquisition per sweep."""
        now = self._clock() if now is None else now
        with self._lock:
            for slot, metrics, lora_active, lora_waiting in rows:
                self._apply_locked(slot, metrics, lora_active, lora_waiting,
                                   now)

    def _apply_locked(
        self,
        slot: int,
        metrics: dict[int, float],
        lora_active: Sequence[int],
        lora_waiting: Sequence[int],
        now: float,
    ) -> None:
        for col, val in metrics.items():
            self._metrics[slot, col] = val
        self._lora_active[slot] = -1
        self._lora_active[slot, : len(lora_active)] = list(lora_active)[
            : C.LORA_SLOTS
        ]
        self._lora_waiting[slot] = -1
        self._lora_waiting[slot, : len(lora_waiting)] = list(lora_waiting)[
            : C.LORA_SLOTS
        ]
        self._scraped_at[slot] = now
        self._has_data[slot] = True

    def host_queue_depths(self) -> np.ndarray:
        """Host-side copy of the queue-depth column (flow-control hold
        checks run before any device work)."""
        with self._lock:
            return self._metrics[:, C.Metric.QUEUE_DEPTH].copy()

    def pool_rows(
        self, slots: Sequence[int], now: Optional[float] = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Copies of the metric rows + scrape ages for the given slots
        (autoscale signal derivation). Ages are +inf for slots that have
        never been scraped: a fresh pod's row is zeros — optimistic for
        ROUTING (cold-start admission), but a capacity decision must not
        read 'no data yet' as 'idle'."""
        idx = list(slots)
        now = self._clock() if now is None else now
        with self._lock:
            rows = self._metrics[idx].copy()
            ages = np.where(
                self._has_data[idx],
                now - self._scraped_at[idx],
                np.inf,
            )
        return rows, ages

    def pool_aggregates(
        self,
        slots: Sequence[int],
        *,
        queue_limit: float,
        kv_limit: float,
        now: Optional[float] = None,
    ) -> dict:
        """Pool-saturation aggregates over the given slots — the ONE
        derivation shared by the HPA pool gauges (runner._pool_snapshot)
        and the autoscale SignalCollector, so the exported metrics and
        the replica controller can never desynchronize on what
        'saturated' means."""
        rows, ages = self.pool_rows(slots, now=now)
        if len(rows) == 0:
            return {"queue_depth_total": 0.0, "kv_cache_util_mean": 0.0,
                    "saturated_fraction": 0.0, "metrics_age_max_s": 0.0}
        queue = rows[:, C.Metric.QUEUE_DEPTH]
        kv = rows[:, C.Metric.KV_CACHE_UTIL]
        saturated = (queue >= queue_limit) | (kv >= kv_limit)
        return {
            "queue_depth_total": float(queue.sum()),
            "kv_cache_util_mean": float(kv.mean()),
            "saturated_fraction": float(saturated.mean()),
            "metrics_age_max_s": float(ages.max()),
        }

    def note_empty_pool_arrival(self) -> None:
        """Record one request that 503'd against an empty pool — the only
        traffic signal a scaled-to-zero pool can emit (there is no endpoint
        to scrape and no pick to count). Feeds the recommender's
        wake-from-zero trigger."""
        with self._lock:
            self._wake_arrivals += 1

    def take_wake_arrivals(self) -> int:
        """Drain-and-reset the empty-pool arrival count (one consumer: the
        autoscale SignalCollector's window sampling)."""
        with self._lock:
            n, self._wake_arrivals = self._wake_arrivals, 0
            return n

    def remove(self, slot: int) -> None:
        """Forget a reclaimed slot (wired to Datastore.on_slot_reclaimed)."""
        with self._lock:
            self._metrics[slot] = 0.0
            self._lora_active[slot] = -1
            self._lora_waiting[slot] = -1
            self._scraped_at[slot] = 0.0
            self._has_data[slot] = False

    def endpoint_batch(
        self,
        endpoints: Iterable[Endpoint],
        now: Optional[float] = None,
        m_slots: int = C.M_MAX,
    ) -> EndpointBatch:
        """Dense snapshot for one scheduling cycle. Endpoints without any
        scrape yet are still valid (zero metrics = optimistic cold start,
        matching the reference's fresh-endpoint admission).

        `m_slots` is the endpoint-axis width of the snapshot (an M bucket —
        the batching layer sizes it to the live high-water slot so the
        compiled cycle scores only the lanes that can exist); every
        endpoint's slot must be < m_slots."""
        now = self._clock() if now is None else now
        with self._lock:
            metrics = self._metrics[:m_slots].copy()
            active = self._lora_active[:m_slots].copy()
            waiting = self._lora_waiting[:m_slots].copy()
            age = np.where(
                self._has_data[:m_slots],
                now - self._scraped_at[:m_slots], 0.0
            ).astype(np.float32)
        metrics[:, C.Metric.METRICS_AGE_S] = age
        valid = np.zeros((m_slots,), bool)
        role = np.zeros((m_slots,), np.int32)
        for ep in endpoints:
            valid[ep.slot] = True
            labels = getattr(ep, "labels", None) or {}
            role[ep.slot] = _ROLE_BY_LABEL.get(
                labels.get(ROLE_LABEL, ""), C.Role.BOTH)
        return EndpointBatch(
            metrics=jnp.asarray(metrics),
            valid=jnp.asarray(valid),
            lora_active=jnp.asarray(active),
            lora_waiting=jnp.asarray(waiting),
            role=jnp.asarray(role),
        )
