"""Per-model-server metric-name mappings.

The model-server protocol (reference docs/proposals/003-model-server-protocol/
README.md:28-42) fixes the required gauge SEMANTICS and lists each server's
concrete metric names; this module encodes that table.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class LabeledGauge:
    """A gauge identified by name + required label values. For info-style
    metrics (vllm:cache_config_info) `value_label` names the label whose
    VALUE carries the number."""

    name: str
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    value_label: Optional[str] = None

    def __hash__(self):  # labels dict excluded from default hash
        return hash((self.name, tuple(sorted(self.labels.items())), self.value_label))


@dataclasses.dataclass(frozen=True)
class ServerMapping:
    queued: LabeledGauge
    running: LabeledGauge
    kv_util: LabeledGauge
    block_size: Optional[LabeledGauge] = None
    num_blocks: Optional[LabeledGauge] = None
    lora_info: Optional[str] = None  # vllm:lora_requests_info-style gauge


VLLM = ServerMapping(
    queued=LabeledGauge("vllm:num_requests_waiting"),
    running=LabeledGauge("vllm:num_requests_running"),
    kv_util=LabeledGauge("vllm:kv_cache_usage_perc"),
    block_size=LabeledGauge("vllm:cache_config_info", value_label="block_size"),
    num_blocks=LabeledGauge("vllm:cache_config_info", value_label="num_gpu_blocks"),
    lora_info="vllm:lora_requests_info",
)

TRITON_TRTLLM = ServerMapping(
    queued=LabeledGauge(
        "nv_trt_llm_request_metrics", {"request_type": "waiting"}
    ),
    running=LabeledGauge(
        "nv_trt_llm_request_metrics", {"request_type": "scheduled"}
    ),
    kv_util=LabeledGauge(
        "nv_trt_llm_kv_cache_block_metrics", {"kv_cache_block_type": "fraction"}
    ),
    block_size=LabeledGauge(
        "nv_trt_llm_kv_cache_block_metrics", {"kv_cache_block_type": "tokens_per"}
    ),
    num_blocks=LabeledGauge(
        "nv_trt_llm_kv_cache_block_metrics", {"kv_cache_block_type": "max"}
    ),
)

TRTLLM_SERVE = ServerMapping(
    queued=LabeledGauge("trtllm_num_requests_waiting"),
    running=LabeledGauge("trtllm_num_requests_running"),
    kv_util=LabeledGauge("trtllm_kv_cache_utilization"),
    block_size=LabeledGauge("trtllm_kv_cache_tokens_per_block"),
    num_blocks=LabeledGauge("trtllm_kv_cache_max_blocks"),
)

SGLANG = ServerMapping(
    queued=LabeledGauge("sglang:num_queue_reqs"),
    running=LabeledGauge("sglang:num_running_reqs"),
    kv_util=LabeledGauge("sglang:token_usage"),
    block_size=LabeledGauge("sglang:cache_config_info", value_label="page_size"),
    num_blocks=LabeledGauge("sglang:cache_config_info", value_label="num_pages"),
    lora_info="sglang:lora_requests_info",
)

BY_NAME = {
    "vllm": VLLM,
    "triton-tensorrt-llm": TRITON_TRTLLM,
    "trtllm-serve": TRTLLM_SERVE,
    "sglang": SGLANG,
}
