"""ctypes bridge to the C++ exposition parser (native/promparse.cc).

The scrape loop is the metrics-in hot path: one /metrics poll per endpoint
every 50 ms (reference 1023 README:59-60), and a real model-server
exposition is tens of KB of families the EPP does not care about. The C++
scanner pulls only the mapped gauges in one pass and returns the byte
spans of the LoRA-info family's sample lines (BOTH the ':' and '_'
spellings, so the freshest-series rule of proposal 003:43-57 resolves
across them exactly like the pure-Python path); the Python caller parses
just those few lines. Loading follows native/chunker's pattern: built on
demand (`make -C native`), pure-Python fallback when absent, and parity
is pinned by tests/test_promparse_native.py.
"""

from __future__ import annotations

import ctypes
import functools
import threading
from typing import Optional, Union

import numpy as np

from gie_tpu.metricsio.mappings import LabeledGauge, ServerMapping


def _load_native():
    from gie_tpu.utils.nativelib import native_lib_path

    path = native_lib_path("giepromparse")
    try:
        lib = ctypes.CDLL(path)
        fn = lib.gie_prom_extract
    except (OSError, AttributeError):
        return None
    fn.argtypes = [
        ctypes.c_char_p, ctypes.c_long,           # text, n
        ctypes.c_char_p,                          # query spec
        ctypes.c_void_p,                          # out values (f64*)
        ctypes.c_void_p,                          # out found flags (u8*)
        ctypes.c_long,                            # n queries
        ctypes.c_char_p,                          # extra families (or None)
        ctypes.c_void_p,                          # out line offsets (i64*)
        ctypes.c_void_p,                          # out line lengths (i64*)
        ctypes.c_long,                            # cap
    ]
    fn.restype = ctypes.c_long
    return fn


_NATIVE = _load_native()

# More LoRA-info series than this in one exposition would be pathological
# (vLLM emits one, occasionally two during adapter churn).
_LORA_LINES_CAP = 64


def _query_line(gauge: LabeledGauge) -> str:
    labels = ";".join(f"{k}={v}" for k, v in sorted(gauge.labels.items()))
    return f"{gauge.name}|{labels}|{gauge.value_label or ''}"


@functools.lru_cache(maxsize=32)
def _compiled_spec(mapping: ServerMapping):
    """(encoded query spec, column order, encoded extra families) — built
    once per mapping, reused on every 50 ms scrape."""
    from gie_tpu.metricsio.scrape import wanted_columns

    wanted = wanted_columns(mapping)
    spec = "\n".join(_query_line(g) for _, g in wanted).encode()
    extras = None
    if mapping.lora_info:
        fams = {mapping.lora_info, mapping.lora_info.replace(":", "_")}
        extras = "\n".join(sorted(fams)).encode()
    return spec, [col for col, _ in wanted], extras


def available() -> bool:
    return _NATIVE is not None


# Per-thread reusable output buffers: the scrape engine calls extract()
# thousands of times per second across its shards, and fresh np arrays
# plus per-call ndpointer argtype validation cost tens of microseconds —
# a measurable slice of the ~100 us scrape budget. The C side writes
# values[i]/found[i] for every query on every call (promparse.cc:156-157
# initializes them first), so reuse is safe; thread-local because shards
# parse concurrently. The raw data pointers are cached WITH the arrays
# (stable for a numpy array's lifetime) so a call passes plain ints.
_BUFFERS = threading.local()


def _thread_buffers(n_columns: int):
    buf = getattr(_BUFFERS, "buf", None)
    if buf is None or buf[0][0].shape[0] < n_columns:
        arrays = (
            np.full((max(n_columns, 8),), np.nan, np.float64),
            np.zeros((max(n_columns, 8),), np.uint8),
            np.zeros((_LORA_LINES_CAP,), np.int64),
            np.zeros((_LORA_LINES_CAP,), np.int64),
        )
        buf = (arrays, tuple(a.ctypes.data for a in arrays))
        _BUFFERS.buf = buf
    return buf


def extract(
    text: Union[str, bytes], mapping: ServerMapping
) -> Optional[tuple[dict[int, float], list[str]]]:
    """One native pass: (metric columns, LoRA-info sample LINES) — or None
    when the library is not built (caller falls back to pure Python).
    Accepts bytes directly so the fetch loop never round-trips the payload
    through a str."""
    if _NATIVE is None:
        return None
    spec, columns, extras = _compiled_spec(mapping)
    raw = text if isinstance(text, bytes) else text.encode("utf-8", "replace")
    (values, found, offs, lens), ptrs = _thread_buffers(len(columns))
    n_lines = _NATIVE(raw, len(raw), spec, ptrs[0], ptrs[1], len(columns),
                      extras, ptrs[2], ptrs[3], _LORA_LINES_CAP)
    if n_lines < 0:
        return None  # malformed query spec — should be impossible
    out: dict[int, float] = {
        col: float(v)
        for col, v, f in zip(columns, values, found)
        if f  # found flag, NOT isnan: a genuine NaN sample is reported
    }
    n_lines = min(int(n_lines), _LORA_LINES_CAP)
    lora_lines = [
        raw[offs[i]: offs[i] + lens[i]].decode("utf-8", "replace")
        for i in range(n_lines)
    ]
    return out, lora_lines
