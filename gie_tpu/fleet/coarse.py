"""Coarse stage: score request batches against cells, emit candidates.

The cell blend reuses the dense chain's normalization formulas
(scorers.py) over the per-cell aggregates and the dense Weights fields —
a cell row scores exactly like a virtual endpoint carrying its members'
mean metrics. The session column has no cell-level analogue (the
consistent-hash home is a single slot, priced by the compressed dense
stage once its cell survives) and is left out of the coarse blend; see
docs/FLEET.md for the tuning consequence.

Selection is two-phase:
  - per request: top-K cells by coarse score (recorded as flight-record
    provenance and pinned by the recall property test), then
  - per batch: the K highest cells of the request-max score (any cell
    that is SOME request's best candidate ranks at its strongest
    advocate's value), gathered in ascending cell-id order.

The ascending sort is the parity keystone: with k >= cells the
selection is every cell id regardless of what the scores were, the
gather in compress.py degenerates to the identity permutation, and the
compressed dense stage sees byte-identical inputs to the dense cycle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gie_tpu.fleet.cells import CellRows
from gie_tpu.sched.types import EndpointBatch, RequestBatch, Weights


def coarse_total(
    rows: CellRows,
    prefix_col: jax.Array,   # f32[N, cells] cell-granular match fraction
    reqs: RequestBatch,
    weights: Weights,
    *,
    queue_norm: float,
    load_norm: float,
) -> jax.Array:
    """Blended coarse score -> f32[N, cells] (higher = better cell)."""
    queue = jnp.clip(1.0 - rows.queue / queue_norm, 0.0, 1.0)
    kv = jnp.clip(1.0 - rows.kv, 0.0, 1.0)
    load = jnp.clip(1.0 - rows.load / load_norm, 0.0, 1.0)
    # Residency bloom probe: base-model requests are indifferent (1.0);
    # adapter requests prefer cells already holding bit (id % 32).
    bit = jnp.uint32(1) << (
        jnp.maximum(reqs.lora_id, 0) % 32).astype(jnp.uint32)
    resident = ((rows.lora[None, :] & bit[:, None]) != 0).astype(jnp.float32)
    lora = jnp.where(reqs.lora_id[:, None] >= 0, resident, 1.0)

    cellwise = (
        weights.queue * queue
        + weights.kv_cache * kv
        + weights.assumed_load * load
    )[None, :]
    requestwise = weights.prefix * prefix_col + weights.lora * lora
    wsum = (
        weights.queue + weights.kv_cache + weights.assumed_load
        + weights.prefix + weights.lora
    )
    return (cellwise + requestwise) / jnp.maximum(wsum, jnp.float32(1e-6))


def select_cells(
    coarse: jax.Array,       # f32[N, cells]
    rows: CellRows,
    reqs: RequestBatch,
    eps: EndpointBatch,
    *,
    cell_cap: int,
    k: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """-> (sel i32[k] ascending, cand_cells i32[N, k], cand_scores
    f32[N, k]).

    Eligibility folds the request's candidate-subset hint and slot
    liveness to cell granularity (a cell none of whose slots are in the
    subset can never serve the request, so its coarse score must not
    crowd a servable cell out of the batch budget). Ineligible and dead
    cells score NEG; with k >= cells they are still selected — harmless,
    their slots stay masked in the dense stage — which is exactly what
    keeps the covering-case selection score-independent."""
    n = int(coarse.shape[0])
    cells = int(coarse.shape[1])
    elig = jnp.any(
        reqs.subset_mask.reshape(n, cells, cell_cap)
        & eps.valid.reshape(cells, cell_cap)[None, :, :],
        axis=2,
    )
    neg = jnp.float32(-1e9)
    scored = jnp.where(elig & rows.valid[None, :], coarse, neg)

    # Per-request candidates: provenance + the recall property's subject.
    cand_scores, cand_cells = jax.lax.top_k(scored, k)

    # Batch selection: request-max advocacy (padded/invalid rows advocate
    # for nothing), ties broken toward lower cell ids by top_k's stable
    # first-occurrence order.
    advocacy = jnp.max(
        jnp.where(reqs.valid[:, None], scored, neg), axis=0)
    _, sel = jax.lax.top_k(advocacy, k)
    # Canonical ascending gather order — the bitwise-parity keystone:
    # k == cells makes this arange(cells) no matter what was scored.
    sel = jnp.sort(sel)
    return sel.astype(jnp.int32), cand_cells.astype(jnp.int32), cand_scores
