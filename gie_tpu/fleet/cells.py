"""Bounded per-cell rows: the coarse stage's view of the fleet.

A CELL is a fixed, contiguous run of ``cell_cap`` endpoint slots — a
cluster of the federation capacity matrix, a peer's imported slot range,
or simply a pool shard of the local Datastore (slot layout is owned by
the datastore/federation layers; the fleet index only requires that a
cell's slots are contiguous, which is how imported peers are laid out
already). Cell c owns global slots [c*cell_cap, (c+1)*cell_cap).

Per-cell rows fold the dense endpoint tensors into O(cells) aggregates
(Gavel-style pool rows — PAPERS.md "Heterogeneity-Aware Cluster
Scheduling Policies" prices (job, pool) against throughput-matrix rows,
not individual accelerators): mean queue depth, mean KV utilization,
mean assumed load, live-slot count, a LoRA-residency bloom, and — via
:func:`cell_match_from_table` / the sketch table of
gie_tpu/fleet/compress.py — a hot-prefix sketch column. All reductions
follow sinkhorn.py's grouped-partial discipline (fixed group partials +
ordered left-to-right fold), so a tp-sharded cell axis reduces each cell
bit-identically to the replicated layout.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from gie_tpu.sched import constants as C
from gie_tpu.sched.sinkhorn import _group_count
from gie_tpu.sched.types import EndpointBatch, PrefixTable, RequestBatch


@flax.struct.dataclass
class CellRows:
    """One bounded row per cell — everything the coarse stage scores.

    Raw aggregates, not normalized scores: normalization happens in
    coarse.py with the SAME formulas (and the same ProfileConfig norms)
    the dense scorer chain uses, so a cell row reads like a virtual
    endpoint whose metrics are its members' means.
    """

    queue: jax.Array    # f32[cells] mean queue depth over valid slots
    kv: jax.Array       # f32[cells] mean KV-cache utilization
    load: jax.Array     # f32[cells] mean assumed load
    n_valid: jax.Array  # f32[cells] live slot count (exact integer-valued)
    lora: jax.Array     # u32[cells] residency bloom: bit (id % 32) per adapter
    valid: jax.Array    # bool[cells] cell has at least one live slot


def _cell_fold(x: jax.Array, cell_cap: int) -> jax.Array:
    """Grouped-partial per-cell sum: f32[cells*cap] -> f32[cells].

    Fixed contiguous group partials over the cap axis + an ordered
    left-to-right fold (sinkhorn._fold_first's discipline): each cell is
    always whole on one shard (the fleet shards the CELL axis, never
    within a cell), and the unrolled fold pins the add order so the row
    values never depend on layout."""
    cells = int(x.shape[0]) // cell_cap
    g = _group_count(cell_cap)
    parts = jnp.sum(x.reshape(cells, g, cell_cap // g), axis=2)
    acc = parts[:, 0]
    for i in range(1, g):
        acc = acc + parts[:, i]
    return acc


def _or_fold(x: jax.Array, cell_cap: int) -> jax.Array:
    """Bitwise-OR per-cell fold: u32[cells*cap] -> u32[cells]. OR is
    exactly associative, so a plain reduce needs no grouping."""
    cells = int(x.shape[0]) // cell_cap
    return jax.lax.reduce(
        x.reshape(cells, cell_cap), jnp.uint32(0),
        jax.lax.bitwise_or, dimensions=(1,))


def lora_residency_bits(eps: EndpointBatch) -> jax.Array:
    """Per-slot adapter bloom -> u32[m]: bit (adapter_id % 32) for every
    resident adapter on a valid slot. 32 bits is a bloom, not a map —
    false positives send a request to a cell that must then page the
    adapter in, the same soft cost the dense LoRA affinity column
    already prices; false negatives cannot happen."""
    ids = eps.lora_active
    bits = jnp.where(
        ids >= 0,
        jnp.uint32(1) << (ids % 32).astype(jnp.uint32),
        jnp.uint32(0),
    )
    slot_bits = jax.lax.reduce(
        bits, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(1,))
    return jnp.where(eps.valid, slot_bits, jnp.uint32(0))


def build_cell_rows(
    eps: EndpointBatch,
    assumed_load: jax.Array,
    *,
    cell_cap: int,
) -> CellRows:
    """Fold the dense endpoint tensors into per-cell rows -> CellRows.

    Means are over VALID slots only (a half-empty cell of idle pods must
    not look twice as loaded as a full one); cells with no live slots
    are marked invalid and score -inf in the coarse stage."""
    valid_f = eps.valid.astype(jnp.float32)
    n_valid = _cell_fold(valid_f, cell_cap)
    denom = jnp.maximum(n_valid, 1.0)

    def mean(col: jax.Array) -> jax.Array:
        return _cell_fold(jnp.where(eps.valid, col, 0.0), cell_cap) / denom

    return CellRows(
        queue=mean(eps.metrics[:, C.Metric.QUEUE_DEPTH]),
        kv=mean(eps.metrics[:, C.Metric.KV_CACHE_UTIL]),
        load=mean(assumed_load),
        n_valid=n_valid,
        lora=_or_fold(lora_residency_bits(eps), cell_cap),
        valid=n_valid > 0,
    )


def cell_match_from_table(
    table: PrefixTable,
    reqs: RequestBatch,
    tick: jax.Array,
    *,
    cell_cap: int,
    max_age: int,
) -> jax.Array:
    """Cell-granular longest-prefix match fraction -> f32[N, cells], from
    a PER-ENDPOINT packed table (exact mode: fleet_m <= the largest M
    bucket, so the full-resolution table exists).

    Same gather + cumulative-AND sweep as prefix.match_scores, but the
    presence words collapse to one bit per cell ("some endpoint in this
    cell plausibly holds the chunk") before the depth count — the coarse
    stage only needs to know WHICH cells hold the prefix, the compressed
    dense stage re-scores the surviving cells at full resolution."""
    wpc = cell_cap // 32
    nslots = int(table.keys.shape[0])
    slots = (reqs.chunk_hashes & jnp.uint32(nslots - 1)).astype(jnp.int32)
    keys = table.keys[slots]                                   # u32[N, C]
    cmax = reqs.chunk_hashes.shape[1]
    chunk_valid = (
        jnp.arange(cmax, dtype=jnp.int32)[None, :] < reqs.n_chunks[:, None]
    )
    fresh = (tick - table.ages[slots]) <= jnp.uint32(max_age)
    hit = (
        (keys == reqs.chunk_hashes) & (reqs.chunk_hashes != 0)
        & chunk_valid & fresh
    )
    words = table.present[slots] * hit[..., None].astype(jnp.uint32)
    n, _, w = words.shape
    cells = w // wpc
    # One [N, cells] slice per chunk lane — never the unpacked bit tensor.
    cell_words = words.reshape(n, cmax, cells, wpc)
    acc = jnp.ones((n, cells), bool)
    depth = jnp.zeros((n, cells), jnp.float32)
    for ci in range(cmax):
        lane = jax.lax.reduce(
            cell_words[:, ci], jnp.uint32(0),
            jax.lax.bitwise_or, dimensions=(2,))
        acc = acc & (lane != 0)
        depth = depth + acc.astype(jnp.float32)
    denom = jnp.maximum(reqs.n_chunks.astype(jnp.float32), 1.0)
    return depth / denom[:, None]
