"""Candidate compression: gather selected cells into a dense block.

Every helper gathers in the caller-provided ascending cell order and
pads the block up to an M bucket with dead slots (valid=False, subset
bit off, presence words zero), so one compiled compressed cycle serves
every selection and the pad can never be picked. Padded positions index
with an out-of-bounds sentinel on the scatter-back side (JAX drop
semantics), so they alias nothing in the fleet-width state.

The parity mechanics live here: when the selection is arange(cells) —
top-K covered every cell — `global_slots` is the identity, every gather
below returns its input unchanged (no pad: the fleet width is itself
the bucket), and the compressed cycle's inputs are byte-equal to the
dense cycle's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gie_tpu.sched.types import EndpointBatch


def global_slots(sel: jax.Array, *, cell_cap: int, m_c: int) -> jax.Array:
    """Compressed slot j -> global endpoint slot, i32[m_c]. Padded tail
    positions (j >= k*cell_cap) return -1."""
    k = int(sel.shape[0])
    lanes = (
        sel[:, None] * cell_cap
        + jnp.arange(cell_cap, dtype=jnp.int32)[None, :]
    ).reshape(-1)
    pad = m_c - k * cell_cap
    if pad:
        lanes = jnp.concatenate(
            [lanes, jnp.full((pad,), -1, jnp.int32)])
    return lanes.astype(jnp.int32)


def _pad_rows(x: jax.Array, pad: int, fill) -> jax.Array:
    if not pad:
        return x
    widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, widths, constant_values=fill)


def gather_endpoints(
    eps: EndpointBatch, sel: jax.Array, *, cell_cap: int, m_c: int
) -> EndpointBatch:
    """Selected cells' endpoint rows -> EndpointBatch at width m_c."""
    k = int(sel.shape[0])
    pad = m_c - k * cell_cap

    def rows(x: jax.Array, fill) -> jax.Array:
        cells = int(x.shape[0]) // cell_cap
        per_cell = x.reshape((cells, cell_cap) + x.shape[1:])
        return _pad_rows(
            per_cell[sel].reshape((k * cell_cap,) + x.shape[1:]), pad, fill)

    return EndpointBatch(
        metrics=rows(eps.metrics, 0.0),
        valid=rows(eps.valid, False),
        lora_active=rows(eps.lora_active, -1),
        lora_waiting=rows(eps.lora_waiting, -1),
        role=rows(eps.role, 0),
    )


def gather_request_cols(x: jax.Array, gslots: jax.Array) -> jax.Array:
    """[N, fleet_m] request-by-endpoint matrix -> [N, m_c] compressed
    columns; padded positions (gslots == -1) become the dtype zero
    (False for the subset mask), never a clamped neighbor's value."""
    vals = jnp.take(x, jnp.maximum(gslots, 0), axis=1)
    return jnp.where(gslots[None, :] >= 0, vals, jnp.zeros_like(vals))


def gather_vec(x: jax.Array, gslots: jax.Array, fill: float) -> jax.Array:
    """Fleet-width per-endpoint vector -> compressed vector; padded
    positions take `fill` (0 load, 1.0 cold sinkhorn duals)."""
    vals = x[jnp.maximum(gslots, 0)]
    return jnp.where(gslots >= 0, vals, jnp.full_like(vals, fill))


def scatter_vec(
    full: jax.Array, gslots: jax.Array, compressed: jax.Array
) -> jax.Array:
    """Write compressed per-endpoint values back to fleet width; padded
    positions scatter to the drop sentinel and touch nothing."""
    m = int(full.shape[0])
    safe = jnp.where(gslots >= 0, gslots, m)
    return full.at[safe].set(compressed, mode="drop")


def gather_words(
    present: jax.Array, sel: jax.Array, *, cell_cap: int, m_c: int
) -> jax.Array:
    """Exact mode: per-endpoint packed presence u32[S, fleet_m/32] ->
    compressed u32[S, m_c/32] (word-aligned: cell_cap is a multiple of
    32, so a cell's presence is whole words and the gather is exact)."""
    wpc = cell_cap // 32
    k = int(sel.shape[0])
    cells = int(present.shape[1]) // wpc
    per_cell = present.reshape(present.shape[0], cells, wpc)
    out = per_cell[:, sel].reshape(present.shape[0], k * wpc)
    pad = m_c // 32 - k * wpc
    if pad:
        out = jnp.concatenate(
            [out, jnp.zeros((out.shape[0], pad), jnp.uint32)], axis=1)
    return out


def scatter_words(
    present: jax.Array,
    sel: jax.Array,
    compressed: jax.Array,
    new_keys_differ: jax.Array,  # bool[S] rows the compressed insert recycled
    *,
    cell_cap: int,
) -> jax.Array:
    """Inverse of gather_words. Rows whose KEY the compressed insert
    recycled are cleared across ALL fleet words first: the insert's
    row-clear only reached the gathered columns, and a recycled slot
    must not keep the evicted key's presence bits for cells that were
    not selected this wave (they would read as false positives under
    the new key)."""
    wpc = cell_cap // 32
    k = int(sel.shape[0])
    cleared = jnp.where(new_keys_differ[:, None], jnp.uint32(0), present)
    cells = int(present.shape[1]) // wpc
    per_cell = cleared.reshape(present.shape[0], cells, wpc)
    new_cols = compressed[:, : k * wpc].reshape(present.shape[0], k, wpc)
    return per_cell.at[:, sel].set(new_cols).reshape(present.shape)


def compact_presence(
    present: jax.Array, *, cell_cap: int, out_cells: int | None = None
) -> jax.Array:
    """Seed the fleet-level sketch from the packed per-endpoint table:
    u32[S, m/32] -> u32[S, out_cells/32], bit c = "some endpoint of cell
    c holds this chunk". The exact->sketch migration path (and the storm
    twin's way of carrying prefix affinity across a fleet grow):
    `out_cells` >= the source cell count pads the sketch out to the grown
    fleet's cell axis (the source cells of a 1024-slot dense table are
    fewer than a packing word, so the pad is also what word-aligns)."""
    wpc = cell_cap // 32
    s = int(present.shape[0])
    cells = int(present.shape[1]) // wpc
    if out_cells is None:
        out_cells = cells
    if out_cells < cells or out_cells % 32:
        raise ValueError(
            f"out_cells {out_cells} must be a multiple of 32 covering the "
            f"{cells} source cells")
    merged = jax.lax.reduce(
        present.reshape(s, cells, wpc), jnp.uint32(0),
        jax.lax.bitwise_or, dimensions=(2,))
    bits = (merged != 0).astype(jnp.uint32)                  # [S, cells]
    if out_cells > cells:
        bits = jnp.pad(bits, ((0, 0), (0, out_cells - cells)))
    shifted = bits.reshape(s, out_cells // 32, 32) << jnp.arange(
        32, dtype=jnp.uint32)
    return jax.lax.reduce(
        shifted, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(2,))


def broadcast_presence(
    cell_present: jax.Array,  # u32[S, cells/32] cell-bit sketch table
    sel: jax.Array,
    *,
    cell_cap: int,
    m_c: int,
) -> jax.Array:
    """Sketch mode: expand selected cells' sketch bits to compressed
    per-endpoint words u32[S, m_c/32] — every slot of a sketch-hit cell
    reads as present (cluster-granularity affinity, exactly the grain
    the federation's fed.prefix import already works at)."""
    wpc = cell_cap // 32
    k = int(sel.shape[0])
    word = (sel // 32).astype(jnp.int32)
    bit = jnp.uint32(1) << (sel % 32).astype(jnp.uint32)
    hit = (cell_present[:, word] & bit[None, :]) != 0        # bool[S, k]
    words = jnp.where(
        hit[:, :, None], jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    out = jnp.broadcast_to(
        words, (cell_present.shape[0], k, wpc)
    ).reshape(cell_present.shape[0], k * wpc)
    pad = m_c // 32 - k * wpc
    if pad:
        out = jnp.concatenate(
            [out, jnp.zeros((out.shape[0], pad), jnp.uint32)], axis=1)
    return out
