"""gie-fleet: hierarchical two-level pick cycle for 100k+ endpoint fleets.

The dense cycle scores every request against every endpoint slot — even
tp-sharded, O(N*M/(dp*tp)) tops out around M=8192 (ROADMAP item 3). The
fleet subsystem splits the pick into two device-side stages
(docs/FLEET.md):

  1. a COARSE stage over bounded per-cell rows (CellRows: queue / kv /
     assumed-load aggregates, LoRA residency bitsets, hot-prefix
     sketches) that emits top-K candidate cells per request, and
  2. a candidate-COMPRESSED dense stage that gathers the selected
     cells' endpoints into an [N, K*cell_cap] block and runs the
     UNCHANGED scorer chain / picker / sinkhorn over it.

The parity contract (tests/test_fleet.py): selected cells are gathered
in ascending cell-id order, so whenever top-K covers every cell the
gather is the identity permutation, the compressed inputs are byte-equal
to the dense inputs, and the picks are BITWISE-identical to the dense
cycle — independent of what the coarse scores said. Default off
(`--fleet-topk 0`) leaves the dense path byte-identical.
"""

from gie_tpu.fleet.cells import CellRows, build_cell_rows, cell_match_from_table
from gie_tpu.fleet.coarse import coarse_total, select_cells
from gie_tpu.fleet.compress import (
    broadcast_presence,
    compact_presence,
    gather_endpoints,
    global_slots,
)
from gie_tpu.fleet.picker import FleetAux, FleetPicker, fleet_cycle

__all__ = [
    "CellRows",
    "FleetAux",
    "FleetPicker",
    "broadcast_presence",
    "build_cell_rows",
    "cell_match_from_table",
    "coarse_total",
    "compact_presence",
    "fleet_cycle",
    "gather_endpoints",
    "global_slots",
    "select_cells",
]
