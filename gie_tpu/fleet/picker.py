"""FleetPicker: the hierarchical two-level pick cycle, as a Scheduler.

`fleet_cycle` has the SAME signature as profile.scheduling_cycle —
(state, reqs, eps, weights, key, predictor_params) -> (result, state) —
so FleetPicker subclasses the Scheduler facade and swaps the compiled
program: locking, bucket warmup, async dispatch, completion feedback,
checkpointing and the replication digest surface are all inherited
unchanged, and the batching collector cannot tell the difference (the
default-off path never constructs this class at all).

Two resolution modes, chosen by the carried state's presence width:

  exact  — fleet_m is a dense M bucket: the state IS a dense SchedState,
           the coarse stage derives cell rows/sketches from it on the
           fly, and the compressed stage gathers true per-endpoint
           presence words. This is the parity mode: top-K covering every
           cell makes every gather the identity and the picks
           bitwise-identical to the dense cycle.
  sketch — fleet_m exceeds the largest M bucket (the 100k+ regime): the
           prefix index lives at CELL granularity (PrefixTable whose
           packed axis is cells, seeded from the dense table by
           compress.compact_presence on the grow migration), per-
           endpoint affinity inside a selected cell is the cell's
           sketch bit, and inserts happen at cell grain.

The compressed block is deliberately solved UNSHARDED (it is at most one
M bucket wide — that is the whole point of compression), which is what
lets the pallas sinkhorn kernel run under a meshed deployment again: the
inner cycle is invoked mesh-free, so profile.py's `use_pallas and mesh
is None` gate passes (PR 15 residual; docs/FLEET.md).
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from gie_tpu.fleet.cells import build_cell_rows, cell_match_from_table
from gie_tpu.fleet.coarse import coarse_total, select_cells
from gie_tpu.fleet.compress import (
    broadcast_presence,
    compact_presence,
    gather_endpoints,
    gather_request_cols,
    gather_vec,
    gather_words,
    global_slots,
    scatter_vec,
    scatter_words,
)
from gie_tpu.sched import constants as C
from gie_tpu.sched import prefix
from gie_tpu.sched.profile import ProfileConfig, Scheduler, scheduling_cycle
from gie_tpu.sched.types import (
    PickResult,
    PrefixTable,
    SchedState,
    Weights,
    m_bucket_for,
    resize_state,
)


@flax.struct.dataclass
class FleetAux:
    """Per-request coarse-stage provenance, carried on PickResult.fleet
    (flight-record fields: candidate cells + their coarse scores; the
    compression ratio is static per width and stamped host-side)."""

    cells: jax.Array   # i32[N, K] candidate cells, best first
    scores: jax.Array  # f32[N, K] their coarse scores


def _is_sketch(state: SchedState) -> bool:
    """Presence width tells the mode: a dense table packs fleet_m bits
    per row; the sketch table packs one bit per CELL."""
    return int(state.prefix.present.shape[1]) * 32 != int(
        state.assumed_load.shape[0])


def fleet_cycle(
    state: SchedState,
    reqs,
    eps,
    weights: Weights,
    key: jax.Array,
    predictor_params,
    *,
    cfg: ProfileConfig,
    predictor_fn,
    cell_cap: int,
    topk: int,
) -> tuple[PickResult, SchedState]:
    """One hierarchical pick cycle. Pure; jit-compiled per (N-bucket,
    fleet width, cfg) exactly like the dense cycle it wraps."""
    fleet_m = int(eps.valid.shape[0])
    cells = fleet_m // cell_cap
    k_sel = min(topk, cells)
    m_c = m_bucket_for(k_sel * cell_cap)
    sketch = _is_sketch(state)

    # ---- Coarse stage: bounded cell rows -> top-K candidates ----------
    rows = build_cell_rows(eps, state.assumed_load, cell_cap=cell_cap)
    if not cfg.enable_prefix:
        pref_cells = jnp.zeros((reqs.valid.shape[0], cells), jnp.float32)
    elif sketch:
        # The sketch table's packed axis IS the cell axis — the dense
        # matcher runs on it unchanged.
        pref_cells = prefix.match_scores(
            state.prefix, reqs, state.tick, max_age=cfg.prefix_max_age)
    else:
        pref_cells = cell_match_from_table(
            state.prefix, reqs, state.tick,
            cell_cap=cell_cap, max_age=cfg.prefix_max_age)
    coarse = coarse_total(
        rows, pref_cells, reqs, weights,
        queue_norm=cfg.queue_norm, load_norm=cfg.load_norm)
    sel, cand_cells, cand_scores = select_cells(
        coarse, rows, reqs, eps, cell_cap=cell_cap, k=k_sel)

    # ---- Compression: ascending-cell gather into one M bucket ---------
    gslots = global_slots(sel, cell_cap=cell_cap, m_c=m_c)
    eps_c = gather_endpoints(eps, sel, cell_cap=cell_cap, m_c=m_c)
    reqs_c = reqs.replace(
        subset_mask=gather_request_cols(reqs.subset_mask, gslots))
    present_c = (
        broadcast_presence(
            state.prefix.present, sel, cell_cap=cell_cap, m_c=m_c)
        if sketch
        else gather_words(
            state.prefix.present, sel, cell_cap=cell_cap, m_c=m_c)
    )
    state_c = SchedState(
        prefix=state.prefix.replace(present=present_c),
        assumed_load=gather_vec(state.assumed_load, gslots, 0.0),
        rr=state.rr,
        tick=state.tick,
        ot_v=gather_vec(state.ot_v, gslots, 1.0),
    )

    # ---- Dense stage: the UNCHANGED scorer chain over the block -------
    # mesh=None on purpose: the block is one M bucket, replicating it is
    # the design (and what re-opens the pallas sinkhorn gate under mesh).
    res_c, new_c = scheduling_cycle(
        state_c, reqs_c, eps_c, weights, key, predictor_params,
        cfg=cfg, predictor_fn=predictor_fn, mesh=None)

    # ---- Scatter back + remap to global slots -------------------------
    def remap(idx):
        return jnp.where(
            idx >= 0, jnp.take(gslots, jnp.maximum(idx, 0)), idx)

    indices_g = remap(res_c.indices)
    new_load = scatter_vec(
        state.assumed_load * cfg.load_decay, gslots, new_c.assumed_load)
    new_ot = scatter_vec(state.ot_v, gslots, new_c.ot_v)
    if not cfg.enable_prefix:
        new_prefix = state.prefix
    elif sketch:
        # Cell-grain insert into the sketch table; the compressed
        # table's own insert was a broadcast throwaway.
        primary_cell = jnp.where(
            indices_g[:, 0] >= 0, indices_g[:, 0] // cell_cap, -1)
        new_prefix = prefix.insert(
            state.prefix, reqs, primary_cell, state.tick)
    else:
        new_prefix = PrefixTable(
            keys=new_c.prefix.keys,
            present=scatter_words(
                state.prefix.present, sel, new_c.prefix.present,
                new_c.prefix.keys != state.prefix.keys,
                cell_cap=cell_cap),
            ages=new_c.prefix.ages,
        )
    new_state = SchedState(
        prefix=new_prefix,
        assumed_load=new_load,
        rr=new_c.rr,
        tick=new_c.tick,
        ot_v=new_ot,
    )
    result = PickResult(
        indices=indices_g,
        status=res_c.status,
        scores=res_c.scores,
        prefill=(remap(res_c.prefill)
                 if res_c.prefill is not None else None),
        affinity=res_c.affinity,
        fleet=FleetAux(cells=cand_cells, scores=cand_scores),
    )
    return result, new_state


def fleet_resize_state(
    state: SchedState, *, m: int, cell_cap: int
) -> SchedState:
    """resize_state generalized across the exact<->sketch boundary.

    Within a mode it is the dense migration (or its cell-table twin);
    crossing UP seeds the sketch from the packed dense table
    (compact_presence — surviving endpoints keep cluster-grain
    affinity); crossing DOWN broadcasts cell bits to endpoint words
    (every member of a warm cell starts warm, the safe direction for an
    approximate index)."""
    m_old = int(state.assumed_load.shape[0])
    if m == m_old:
        return state
    sketch_old = _is_sketch(state)
    sketch_new = m > C.M_BUCKETS[-1]
    if not sketch_old and not sketch_new:
        return resize_state(state, m)

    if m > m_old:
        load = jnp.pad(state.assumed_load, (0, m - m_old))
        ot_v = jnp.pad(state.ot_v, (0, m - m_old), constant_values=1.0)
    else:
        load = state.assumed_load[:m]
        ot_v = state.ot_v[:m]

    cells_new = m // cell_cap
    if sketch_new:
        if sketch_old:
            w_old, w_new = (
                int(state.prefix.present.shape[1]), cells_new // 32)
            present = (
                jnp.pad(state.prefix.present,
                        ((0, 0), (0, w_new - w_old)))
                if w_new >= w_old
                else state.prefix.present[:, :w_new]
            )
        else:
            present = compact_presence(
                state.prefix.present, cell_cap=cell_cap,
                out_cells=cells_new)
    else:
        present = broadcast_presence(
            state.prefix.present,
            jnp.arange(cells_new, dtype=jnp.int32),
            cell_cap=cell_cap, m_c=m)
    return state.replace(
        assumed_load=load, ot_v=ot_v,
        prefix=state.prefix.replace(present=present))


class FleetPicker(Scheduler):
    """Host facade: the Scheduler, compiled to the hierarchical cycle.

    `mesh` is accepted for constructor parity with Scheduler but the
    fleet program itself runs unsharded — the compressed block is one M
    bucket and the coarse rows are O(cells); sharding either would cost
    more in collectives than it saves (the dense tp-sharded path remains
    the fleet-off configuration). The deployment mesh is kept on
    `deploy_mesh` for operators reading /debugz/fleet.
    """

    def __init__(
        self,
        cfg: ProfileConfig = ProfileConfig(),
        weights: Optional[Weights] = None,
        predictor_fn=None,
        predictor_params=None,
        seed: int = 0,
        mesh=None,
        *,
        topk: int = 4,
        cell_cap: int = 64,
    ):
        if cell_cap < 32 or cell_cap % 32:
            raise ValueError(
                f"fleet cell_cap must be a positive multiple of 32 "
                f"(packed presence words are 32 endpoints wide); got "
                f"{cell_cap}")
        if topk < 1:
            raise ValueError(f"fleet topk must be >= 1; got {topk}")
        if topk * cell_cap > C.M_BUCKETS[-1]:
            raise ValueError(
                f"fleet topk*cell_cap = {topk * cell_cap} exceeds the "
                f"largest compressed bucket {C.M_BUCKETS[-1]} — the "
                f"whole candidate block must fit one dense cycle")
        self.fleet_topk = int(topk)
        self.fleet_cell_cap = int(cell_cap)
        super().__init__(
            cfg, weights, predictor_fn, predictor_params, seed, mesh=None)
        self.deploy_mesh = mesh
        self._jit = jax.jit(
            functools.partial(
                fleet_cycle, cfg=cfg, predictor_fn=predictor_fn,
                cell_cap=self.fleet_cell_cap, topk=self.fleet_topk,
            ),
            donate_argnums=0,
        )
        self._resize = jax.jit(
            functools.partial(
                fleet_resize_state, cell_cap=self.fleet_cell_cap),
            static_argnames=("m",),
        )
        # Sketch-mode eviction twin: load + duals only — one endpoint
        # dying must not clear its whole CELL's sketch bit (survivors
        # still hold the chunks).
        self._evict_sketch = jax.jit(
            lambda st, slot: st.replace(
                assumed_load=st.assumed_load.at[slot].set(0.0),
                ot_v=st.ot_v.at[slot].set(1.0),
            ),
            donate_argnums=0,
        )
        # /debugz/fleet counters, fed by the batching completer (host
        # arrays, never under the pick lock): rank histogram of where
        # the final pick landed in its request's candidate list, and
        # per-cell pick tallies (reported bounded).
        self._fleet_lock = threading.Lock()
        self._rank_hits: collections.Counter = collections.Counter()
        self._cell_picks: collections.Counter = collections.Counter()
        self._fleet_waves = 0

    # -- width policy ------------------------------------------------------

    def _m_ok(self, m: int) -> bool:
        if m % self.fleet_cell_cap:
            return False
        if m in C.M_BUCKETS:
            return True
        return m > C.M_BUCKETS[-1] and (m // self.fleet_cell_cap) % 32 == 0

    def _init_state(self, m: int) -> SchedState:
        if m <= C.M_BUCKETS[-1]:
            return SchedState.init(m=m)
        return SchedState(
            prefix=PrefixTable.empty(
                C.PREFIX_SLOTS, m // self.fleet_cell_cap),
            assumed_load=jnp.zeros((m,), jnp.float32),
            rr=jnp.zeros((), jnp.uint32),
            tick=jnp.zeros((), jnp.uint32),
            ot_v=jnp.ones((m,), jnp.float32),
        )

    def _fleet_width_for(self, n: int) -> int:
        """Smallest valid width covering slot n-1: a dense M bucket while
        those fit, else the next multiple of cell_cap*32 (cells stay a
        multiple of the 32-bit sketch packing word)."""
        if n <= C.M_BUCKETS[-1]:
            return m_bucket_for(n)
        step = self.fleet_cell_cap * 32
        return -(-n // step) * step

    def compression_ratio(self, m: int) -> float:
        """Fraction of the fleet the dense stage actually scores at
        width m (the per-wave flight-record/bench figure)."""
        cells = max(m // self.fleet_cell_cap, 1)
        k_sel = min(self.fleet_topk, cells)
        return m_bucket_for(k_sel * self.fleet_cell_cap) / float(m)

    # -- event-path overrides for the sketch regime ------------------------

    def evict_endpoint(self, slot: int) -> None:
        with self._lock:
            if any(e[1] == slot for e in self._kv_journal):
                self._kv_journal = collections.deque(
                    (e for e in self._kv_journal if e[1] != slot),
                    maxlen=self._KV_JOURNAL_MAX)
            if slot >= self.state.m:
                return
            if _is_sketch(self.state):
                self.state = self._evict_sketch(self.state, jnp.int32(slot))
            else:
                self.state = self._evict(self.state, jnp.int32(slot))

    def clear_prefix_endpoint(self, slot: int) -> None:
        with self._lock:
            if slot >= self.state.m or _is_sketch(self.state):
                # Sketch grain cannot express one endpoint's cache reset
                # without erasing its cell-mates' affinity; the index is
                # approximate and the stale bit ages out (prefix_max_age).
                return
            self.state = self._clear_prefix(self.state, jnp.int32(slot))

    def _fold_prefix_events_locked(self, state, slot, stored, removed):
        if slot >= state.m:
            # Grow here, not in super(): the base grow path only knows
            # dense M buckets and would reject a fleet-regime slot.
            state = self._resize(state, m=self._fleet_width_for(slot + 1))
        if not _is_sketch(state):
            return super()._fold_prefix_events_locked(
                state, slot, stored, removed)
        # Cell-grain ingest: stored chunks set the CELL's sketch bit;
        # removals are dropped (one endpoint evicting a chunk says
        # nothing about its cell-mates — same one-sided rule as
        # clear_prefix_endpoint above).
        cell = slot // self.fleet_cell_cap
        for start in range(0, len(stored), self._EVENT_BUCKETS[-1]):
            part = stored[start:start + self._EVENT_BUCKETS[-1]]
            bucket = next(
                b for b in self._EVENT_BUCKETS if len(part) <= b)
            padded = np.zeros((bucket,), np.uint32)
            padded[: len(part)] = part
            state = state.replace(prefix=self._ingest(
                state.prefix, jnp.asarray(padded), jnp.int32(cell),
                state.tick, remove=False))
        return state

    # -- observability -----------------------------------------------------

    def note_fleet_wave(
        self, cand_cells: np.ndarray, primary_slots: np.ndarray
    ) -> None:
        """Completer-side tally (host arrays, no device pull): where in
        its candidate list did each request's final pick land."""
        chosen_cells = primary_slots // self.fleet_cell_cap
        picked = primary_slots >= 0
        ranks = np.argmax(
            cand_cells == chosen_cells[:, None], axis=1)
        listed = (cand_cells == chosen_cells[:, None]).any(axis=1)
        with self._fleet_lock:
            self._fleet_waves += 1
            for rank, ok, p in zip(ranks, listed, picked):
                if not p:
                    continue
                self._rank_hits[int(rank) if ok else -1] += 1
            for cell, p in zip(chosen_cells, picked):
                if p:
                    self._cell_picks[int(cell)] += 1

    def fleet_report(self, max_cells: int = 32) -> dict:
        """/debugz/fleet payload: static config + bounded tallies (the
        cell table is truncated to the hottest `max_cells` rows plus an
        aggregate, so the page's cardinality is bounded regardless of
        fleet size — same rule obs-check enforces on metric labels)."""
        m = self.state.m
        with self._fleet_lock:
            ranks = dict(sorted(self._rank_hits.items()))
            hot = self._cell_picks.most_common(max_cells)
            other = sum(self._cell_picks.values()) - sum(
                c for _, c in hot)
            waves = self._fleet_waves
        return {
            "topk": self.fleet_topk,
            "cell_cap": self.fleet_cell_cap,
            "cells": m // self.fleet_cell_cap,
            "fleet_m": m,
            "mode": "sketch" if _is_sketch(self.state) else "exact",
            "compression_ratio": round(self.compression_ratio(m), 6),
            "waves": waves,
            # rank -> picks landing on the request's rank-th candidate
            # cell; -1 = the tail filter walked outside the list.
            "topk_hit_histogram": {str(k): v for k, v in ranks.items()},
            "hot_cells": [
                {"cell": c, "picks": n} for c, n in hot],
            "other_cell_picks": max(other, 0),
        }

    def debug_report(self) -> dict:
        report = super().debug_report()
        report["fleet"] = {
            "topk": self.fleet_topk,
            "cell_cap": self.fleet_cell_cap,
            "compression_ratio": round(
                self.compression_ratio(self.state.m), 6),
        }
        return report
