"""gie-learn: offline-trained multiplicative scheduling policies.

The pieces, in data-flow order:

- `dataset.py`  — flight-recorder dumps -> feature matrices + targets,
  train/eval split keyed by schedule fingerprint (no eval leakage).
- `train.py`    — seeded closed-form trainer (CPU-fine JAX/numpy); the
  same dump + seed always produces byte-identical artifact bytes.
- `policy.py`   — the runtime form: exp(sum_s w_s * log(col_s)), one
  fused elementwise op over the existing scorer columns, slotted into
  `sched.profile.build_stages` behind ProfileConfig.scorer="learned".
- `artifact.py` — the versioned, checksummed policy artifact the runner
  loads via --policy-artifact and validates against the live feature
  schema at startup.
- `judge.py`    — head-to-head promotion through the virtual-clock twin:
  learned vs heuristic on identical storm seeds and replayed traces,
  verdict gated on goodput/SLO/p99 no-regression.

The heuristic weighted-sum blend remains the untouched default; nothing
in this package runs unless the operator opts in.
"""
