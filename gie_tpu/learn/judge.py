"""Head-to-head promotion: learned vs heuristic through the twin.

A trained policy is promoted the way a human would promote it — by
racing it against the incumbent on the SAME traffic. Each judged
scenario runs the virtual-clock storm engine twice at one seed: once
with the tuned heuristic blend (the production default, bit-for-bit the
pre-learn path) and once with ProfileConfig.scorer="learned" + the
artifact's exponents. The two runs share the schedule fingerprint by
construction (the Program is compiled from the same drive + seed; the
scorer cannot touch arrivals), and the judgment REFUSES to score a pair
whose fingerprints diverge — a comparison across different traffic is
not a comparison.

Verdict gates (per scenario, all must hold; "no-regression" semantics):

- goodput_tokens_per_s: learned >= heuristic (goodput already counts
  only SLO-met tokens, so this is the headline gate),
- slo_attainment:       learned >= heuristic,
- ttft_p99_s:           learned <= heuristic * p99_tolerance
                        (None = no completions = worst).

Scenario kinds: named storm scenarios (chaos rules armed identically on
both sides — the injector is seeded) and recorded flight-recorder dumps
replayed as literal arrival schedules via shapes.TraceReplay, so a
policy is judged on BOTH synthetic storms and the production traffic it
was trained from.

CLI: ``python -m gie_tpu.learn.judge --policy ART --scenario NAME
--trace-dump DUMP --out JUDGE.json`` (see --help); ``make learn-ci``
pins one seeded verdict end to end.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from gie_tpu.learn import artifact as artifact_mod

SCHEMA = "gie-learn-judge/1"

REQUIRED_SCENARIO_FIELDS = (
    "name", "kind", "seed", "schedule_fingerprint", "heuristic",
    "learned", "gates", "passed",
)

# TraceReplay harness defaults: the replay stretches the duration itself
# and replaces the Poisson draw, so traffic here is just the envelope.
_TRACE_TRAFFIC = {"base_qps": 1.0, "duration_s": 1.0, "n_sessions": 8}
_TRACE_POOL = {"n_pods": 3}
# Replay TTFT SLO: sits between the replayed traffic's median TTFT and
# the heuristic's tail, so goodput on a replayed trace measures tail
# scheduling quality — the thing a latency-trained policy is FOR — not
# raw cache-hit throughput (--trace-slo-s overrides).
_TRACE_SLO_S = 4.0


def policy_weights_spec(art: dict) -> tuple:
    """Artifact -> the hashable ((name, float32-hex), ...) tuple
    EngineConfig.policy_weights carries (feature-schema order)."""
    return tuple(
        (name, str(art["weights"][name]["hex"]))
        for name in art["feature_schema"])


def _summarize(card: dict) -> dict:
    return {
        "goodput_tokens_per_s": round(
            float(card.get("goodput_tokens_per_s") or 0.0), 2),
        "slo_attainment": round(float(card.get("slo_attainment") or 0.0), 4),
        "ttft_p50_s": card.get("ttft_p50_s"),
        "ttft_p99_s": card.get("ttft_p99_s"),
        "serve_latency_p99_ms": card.get("serve_latency_p99_ms"),
        "completed": card.get("completed"),
        "shed": card.get("shed"),
        "client_5xx": card.get("client_5xx"),
        "schedule_fingerprint": card.get("schedule_fingerprint"),
        "decision_fingerprint": card.get("decision_fingerprint"),
    }


def _run_card(storm: dict, scn, *, seed: int, cfg, name: str) -> dict:
    """One engine run -> scorecard (the search._run_one shape: compile,
    warm, arm chaos AFTER warmup, run, always close)."""
    from gie_tpu.resilience import faults
    from gie_tpu.storm.engine import engine_from_drive

    engine = engine_from_drive(storm, seed=seed, cfg=cfg, name=name)
    try:
        schedule = engine.program.compile()
        engine.warmup(schedule)
        inj = scn.arm() if (scn is not None and scn.rules) else None
        try:
            result = engine.run(schedule=schedule, warmup=False)
        finally:
            if inj is not None:
                faults.uninstall()
        return result.scorecard
    finally:
        engine.close()


def _gate(heur: dict, learned: dict, p99_tolerance: float) -> dict:
    h_p99 = heur.get("ttft_p99_s")
    l_p99 = learned.get("ttft_p99_s")
    h_p99 = float(h_p99) if h_p99 is not None else float("inf")
    l_p99 = float(l_p99) if l_p99 is not None else float("inf")
    gates = {
        "goodput": learned["goodput_tokens_per_s"]
        >= heur["goodput_tokens_per_s"],
        "slo": learned["slo_attainment"] >= heur["slo_attainment"],
        "p99": l_p99 <= h_p99 * p99_tolerance or (
            l_p99 == float("inf") and h_p99 == float("inf")),
    }
    return gates


def _judge_one(storm: dict, scn, *, name: str, kind: str, seed: int,
               base_cfg, weights_spec: tuple,
               p99_tolerance: float) -> dict:
    from gie_tpu.storm.engine import EngineConfig

    cfg = base_cfg if base_cfg is not None else EngineConfig()
    storm = dict(storm)
    storm["virtual_time"] = True  # the twin is the judge, always
    heur_card = _run_card(
        storm, scn, seed=seed,
        cfg=dataclasses.replace(cfg, scorer="blend", policy_weights=()),
        name=f"{name}-heuristic")
    learned_card = _run_card(
        storm, scn, seed=seed,
        cfg=dataclasses.replace(
            cfg, scorer="learned", policy_weights=weights_spec),
        name=f"{name}-learned")
    h_fp = heur_card.get("schedule_fingerprint")
    l_fp = learned_card.get("schedule_fingerprint")
    if not h_fp or h_fp != l_fp:
        raise ValueError(
            f"judge {name!r}: schedule fingerprints diverged "
            f"({h_fp!r} vs {l_fp!r}) — the two runs did not see the "
            "same traffic, so the comparison is void")
    heur, learned = _summarize(heur_card), _summarize(learned_card)
    gates = _gate(heur, learned, p99_tolerance)
    return {
        "name": name,
        "kind": kind,
        "seed": int(seed),
        "schedule_fingerprint": h_fp,
        "heuristic": heur,
        "learned": learned,
        "gates": gates,
        "passed": all(gates.values()),
    }


def judge(policy_art: dict, *, scenarios: tuple = (),
          trace_dumps: tuple = (), seed: Optional[int] = None,
          duration_s: Optional[float] = None,
          trace_slo_s: float = _TRACE_SLO_S,
          p99_tolerance: float = 1.10, base_cfg=None) -> dict:
    """Race the artifact against the heuristic on every given scenario
    and replayed dump; return the judgment (schema gie-learn-judge/1).
    ``promote`` is True only when EVERY scenario's gates all pass."""
    from gie_tpu.resilience import scenarios as scenarios_mod

    artifact_mod.validate_artifact(policy_art)
    if not scenarios and not trace_dumps:
        raise ValueError("judge needs at least one scenario or trace dump")
    weights_spec = policy_weights_spec(policy_art)
    results = []
    for scenario in scenarios:
        scn = (scenario if hasattr(scenario, "drive")
               else scenarios_mod.load(scenario))
        storm = (scn.drive or {}).get("storm")
        if not isinstance(storm, dict):
            raise ValueError(
                f"scenario {scn.name!r} has no drive.storm section")
        storm = dict(storm)
        if duration_s is not None:
            storm["duration_s"] = float(duration_s)
        results.append(_judge_one(
            storm, scn, name=scn.name, kind="storm",
            seed=scn.seed if seed is None else seed, base_cfg=base_cfg,
            weights_spec=weights_spec, p99_tolerance=p99_tolerance))
    for path in trace_dumps:
        storm = {
            "traffic": dict(_TRACE_TRAFFIC),
            "shapes": [{"kind": "trace_replay", "path": str(path)}],
            "pool": dict(_TRACE_POOL),
            "ttft_slo_s": float(trace_slo_s),
        }
        results.append(_judge_one(
            storm, None, name=f"trace:{path}", kind="trace_replay",
            seed=0 if seed is None else seed, base_cfg=base_cfg,
            weights_spec=weights_spec, p99_tolerance=p99_tolerance))
    judgment = {
        "schema": SCHEMA,
        "policy_checksum": policy_art["checksum"],
        "policy_weights": {
            name: hexed for name, hexed in weights_spec},
        "p99_tolerance": float(p99_tolerance),
        "scenarios": results,
        "promote": all(r["passed"] for r in results),
    }
    validate(judgment)
    return judgment


def validate(judgment: dict) -> None:
    """Schema check for a judgment (tests + the learn-ci gate)."""
    if judgment.get("schema") != SCHEMA:
        raise ValueError(
            f"unknown judge schema {judgment.get('schema')!r} "
            f"(want {SCHEMA})")
    rows = judgment.get("scenarios")
    if not isinstance(rows, list) or not rows:
        raise ValueError("judgment has no scenarios")
    for row in rows:
        missing = [f for f in REQUIRED_SCENARIO_FIELDS if f not in row]
        if missing:
            raise ValueError(f"judgment scenario missing: {missing}")
        if (row["heuristic"].get("schedule_fingerprint")
                != row["learned"].get("schedule_fingerprint")):
            raise ValueError(
                f"judgment scenario {row['name']!r} compares different "
                "schedules")
    if judgment.get("promote") != all(r["passed"] for r in rows):
        raise ValueError("promote flag disagrees with per-scenario gates")


def main(argv: Optional[list] = None) -> int:
    import argparse
    import os
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m gie_tpu.learn.judge",
        description="Race a trained policy artifact against the "
                    "heuristic blend through the virtual-clock twin.")
    parser.add_argument("--policy", required=True,
                        help="policy artifact path (gie-learn-policy/1)")
    parser.add_argument("--scenario", action="append", default=[],
                        help="storm scenario name/path (repeatable)")
    parser.add_argument("--trace-dump", action="append", default=[],
                        help="flight-recorder dump to replay (repeatable)")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--duration-s", type=float, default=None)
    parser.add_argument("--trace-slo-s", type=float, default=_TRACE_SLO_S)
    parser.add_argument("--p99-tolerance", type=float, default=1.10)
    parser.add_argument("--out", default=None,
                        help="judgment JSON path")
    parser.add_argument("--attach", default=None, metavar="PATH",
                        help="rewrite the artifact here with the "
                             "judgment attached (checksum re-stamped)")
    args = parser.parse_args(argv)

    import jax

    jax.config.update(
        "jax_platforms", os.environ.get("GIE_STORM_PLATFORM", "cpu"))

    art = artifact_mod.load_artifact(args.policy)
    judgment = judge(
        art, scenarios=tuple(args.scenario),
        trace_dumps=tuple(args.trace_dump), seed=args.seed,
        duration_s=args.duration_s, trace_slo_s=args.trace_slo_s,
        p99_tolerance=args.p99_tolerance)
    for row in judgment["scenarios"]:
        gates = ",".join(
            f"{k}={'ok' if v else 'FAIL'}"
            for k, v in row["gates"].items())
        print(f"[judge] {row['name']}: learned "
              f"goodput={row['learned']['goodput_tokens_per_s']} vs "
              f"heuristic {row['heuristic']['goodput_tokens_per_s']} "
              f"({gates})", file=sys.stderr)
    print(f"[judge] verdict: "
          f"{'PROMOTE' if judgment['promote'] else 'HOLD'}",
          file=sys.stderr)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(judgment, fh, indent=1)
    if args.attach:
        stamped = artifact_mod.attach_judgment(art, judgment)
        with open(args.attach, "w", encoding="utf-8") as fh:
            fh.write(artifact_mod.dumps_artifact(stamped))
    print(json.dumps(judgment))
    return 0 if judgment["promote"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
