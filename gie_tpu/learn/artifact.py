"""The versioned, checksummed gie-learn policy artifact.

Wire shape (canonical JSON: sorted keys, compact separators, NaN
banned — byte-stable so "same dump + seed => identical artifact bytes"
is testable with ==):

    {
      "schema": "gie-learn-policy/1",
      "feature_schema": ["queue", "kv_cache", ...],   # ordered columns
      "weights": {"queue": {"hex": "0000803f", "value": 1.0}, ...},
      "provenance": {seed, fingerprints, trained_at, n_train, ...},
      "judgment": {...}  # optional: the twin judge's verdict + cards
      "checksum": "sha256:..."
    }

Weights travel as little-endian float32 hex (policy.float32_hex) — the
bit pattern IS the weight; the decimal ``value`` beside it is advisory
for humans and cross-checked at load so a hand-edit that changes one
but not the other is rejected rather than silently ignored. The
checksum is sha256 over the canonical JSON with the checksum field
removed, so any mutation (including judgment attachment) re-stamps.

Versioning follows the recorder's rule: the major bumps only when a
field CHANGES MEANING; loaders tolerate unknown additive fields, and a
newer major is rejected loudly (the runner must not route on weights
whose semantics it predates).
"""

from __future__ import annotations

import hashlib
import json
import numpy as np

from gie_tpu.learn import policy

SCHEMA_FAMILY = "gie-learn-policy"
SCHEMA_MAJOR = 1
SCHEMA = f"{SCHEMA_FAMILY}/{SCHEMA_MAJOR}"

_REQUIRED = ("schema", "feature_schema", "weights", "provenance",
             "checksum")


def canonical_json(obj) -> str:
    """The one serialization every byte-stability claim rests on."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      allow_nan=False)


def compute_checksum(art: dict) -> str:
    body = {k: v for k, v in art.items() if k != "checksum"}
    digest = hashlib.sha256(canonical_json(body).encode()).hexdigest()
    return f"sha256:{digest}"


def build_artifact(
    weights: dict[str, float],
    feature_schema: tuple[str, ...],
    provenance: dict,
    judgment: dict | None = None,
) -> dict:
    """Assemble + checksum an artifact from trained weights. The weight
    table must cover exactly the feature schema's columns."""
    if set(weights) != set(feature_schema):
        raise ValueError(
            f"weights {sorted(weights)} do not match feature schema "
            f"{list(feature_schema)}")
    table = {}
    for name in feature_schema:
        w = np.float32(weights[name])
        table[name] = {"hex": policy.float32_hex(w), "value": float(w)}
    art = {
        "schema": SCHEMA,
        "feature_schema": list(feature_schema),
        "weights": table,
        "provenance": dict(provenance),
    }
    if judgment is not None:
        art["judgment"] = judgment
    art["checksum"] = compute_checksum(art)
    return art


def attach_judgment(art: dict, judgment: dict) -> dict:
    """Return a copy with the twin judge's verdict attached and the
    checksum re-stamped."""
    out = {k: v for k, v in art.items() if k != "checksum"}
    out["judgment"] = judgment
    out["checksum"] = compute_checksum(out)
    return out


def dumps_artifact(art: dict) -> str:
    return canonical_json(art)


def validate_artifact(art: dict) -> dict:
    """Structural + integrity validation. Returns the artifact. Raises
    ValueError with a load-bearing message on any defect."""
    if not isinstance(art, dict):
        raise ValueError("policy artifact must be a JSON object")
    missing = [k for k in _REQUIRED if k not in art]
    if missing:
        raise ValueError(f"policy artifact missing fields: {missing}")
    schema = str(art["schema"])
    family, _, major_text = schema.partition("/")
    if family != SCHEMA_FAMILY or not major_text.isdigit():
        raise ValueError(
            f"not a policy artifact (schema {schema!r}, "
            f"expected {SCHEMA_FAMILY}/<major>)")
    if int(major_text) > SCHEMA_MAJOR:
        raise ValueError(
            f"policy artifact schema {schema!r} is newer than this "
            f"build understands ({SCHEMA}); refusing to route on "
            "weights whose semantics may have changed")
    expected = compute_checksum(art)
    if art.get("checksum") != expected:
        raise ValueError(
            f"policy artifact checksum mismatch: stamped "
            f"{art.get('checksum')!r}, computed {expected!r}")
    feats = art["feature_schema"]
    if (not isinstance(feats, list) or not feats
            or not all(isinstance(f, str) for f in feats)):
        raise ValueError("feature_schema must be a non-empty name list")
    table = art["weights"]
    if not isinstance(table, dict) or set(table) != set(feats):
        raise ValueError(
            f"weight table columns {sorted(table) if isinstance(table, dict) else table!r} "
            f"do not match feature_schema {feats}")
    for name, entry in table.items():
        if not isinstance(entry, dict) or "hex" not in entry:
            raise ValueError(f"weight {name!r} missing bitwise hex form")
        bits = policy.float32_from_hex(str(entry["hex"]))
        value = entry.get("value")
        if not isinstance(value, (int, float)) or not np.isfinite(bits):
            raise ValueError(f"weight {name!r} is not a finite float32")
        if abs(float(bits) - float(value)) > 1e-5 * max(
                1.0, abs(float(bits))):
            raise ValueError(
                f"weight {name!r} decimal value {value} disagrees with "
                f"its hex bits {float(bits)} — refusing a half-edited "
                "artifact")
    return art


def loads_artifact(text: str) -> dict:
    return validate_artifact(json.loads(text))


def load_artifact(path: str) -> dict:
    with open(path) as f:
        return loads_artifact(f.read())


def validate_feature_schema(art: dict, live_schema: tuple[str, ...]) -> None:
    """Startup gate: every column the artifact was trained on must exist
    in the live profile's column set (profile.feature_schema). Weights
    apply BY NAME, so order differences are fine; a trained column the
    live profile does not build is not — the policy would silently lose
    a signal it was trained to rely on."""
    missing = [f for f in art["feature_schema"] if f not in live_schema]
    if missing:
        raise ValueError(
            f"policy artifact was trained on columns {missing} that the "
            f"live profile does not produce (live schema: "
            f"{list(live_schema)}); refusing to route with a blinded "
            "policy")


def artifact_weight_values(art: dict) -> dict[str, np.float32]:
    """The bit-exact weight mapping (decoded from hex)."""
    return {
        name: policy.float32_from_hex(str(entry["hex"]))
        for name, entry in art["weights"].items()
    }


def to_sched_weights(art: dict):
    """Artifact -> sched Weights struct (absent columns weight 0 — the
    multiplicative no-op)."""
    return policy.weights_from_mapping(
        {k: float(v) for k, v in artifact_weight_values(art).items()})
