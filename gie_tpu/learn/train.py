"""Offline trainer: recorder dumps -> a gie-learn policy artifact.

The model is exactly the runtime form (policy.multiplicative_total):

    score = prod_s col_s ** w_s,   latency ~ prod_s col_s ** (-w_s)

so in log space the fit is LINEAR: regress  -log(latency_ms)  on
log(max(col, EPS)) with an intercept and an L2 ridge, solved in closed
form (float64 normal equations — CPU-fine, no iterations, nothing to
diverge), then projected to non-negative float32 exponents. Non-negative
because every column is normalized "higher is better" by construction;
a negative exponent would invert a heuristic's meaning, and the ridge
prefers 0 for columns the data cannot identify (e.g. a column the dump
never varied) — col**0 == 1, a clean no-op.

Determinism contract (pinned by tests/test_learn.py): the same dumps +
seed produce BYTE-IDENTICAL artifact text. Everything random routes
through the seed (today: only the fingerprint-keyed split salt), the
solve is order-stable float64, and the artifact's ``trained_at``
provenance derives from the DATA (max record timestamp), never the wall
clock.

CLI:  python -m gie_tpu.learn.train --dump DIR_OR_FILE [...] --out PATH
"""

from __future__ import annotations

import argparse
import sys
from typing import Iterable

import numpy as np

from gie_tpu.learn import artifact as artifact_mod
from gie_tpu.learn import dataset as dataset_mod
from gie_tpu.learn import policy

# Floor for the latency target's log (serve_latency_ms is rounded to
# 0.1 ms by the recorder, so anything below this is already clamped).
_MIN_LATENCY_MS = 1e-3


def _data_through_ts(dumps) -> float:
    """Deterministic trained-at provenance: the newest record timestamp
    in the corpus (0.0 for timestamp-free synthetic dumps)."""
    newest = 0.0
    for _, records in dumps:
        for rec in records:
            if not isinstance(rec, dict):
                continue
            ts = rec.get("ts")
            if isinstance(ts, (int, float)) and ts > newest:
                newest = float(ts)
    return round(newest, 3)


def _rmse(x: np.ndarray, w: np.ndarray, intercept: float,
          y: np.ndarray) -> float:
    if x.shape[0] == 0:
        return 0.0
    pred = x @ w + intercept
    return float(np.sqrt(np.mean((pred - y) ** 2)))


def train(
    dumps: Iterable[tuple[str, list[dict]]],
    *,
    seed: int = 0,
    eval_fraction: float = 0.25,
    l2: float = 1e-3,
    schema: tuple[str, ...] = dataset_mod.DEFAULT_FEATURES,
) -> dict:
    """Build the dataset, fit the multiplicative exponents, return a
    finalized (checksummed) policy artifact dict."""
    dumps = list(dumps)
    ds = dataset_mod.build_dataset(dumps, schema=schema)
    if len(ds) == 0:
        raise ValueError(
            f"no trainable rows in {len(dumps)} dump(s) "
            f"(skipped: {ds.skipped or '{}'})")
    train_rows, eval_rows = dataset_mod.split_by_fingerprint(
        ds, eval_fraction=eval_fraction, seed=seed)
    if train_rows.size == 0:
        raise ValueError(
            "fingerprint split left zero training rows — lower "
            "eval_fraction or add dumps")

    logx = np.log(np.maximum(
        ds.features.astype(np.float64), float(policy.EPS)))
    y = -np.log(np.maximum(
        ds.latency_ms.astype(np.float64), _MIN_LATENCY_MS))
    xt, yt = logx[train_rows], y[train_rows]
    n_feat = xt.shape[1]
    a = np.concatenate([xt, np.ones((xt.shape[0], 1))], axis=1)
    # Ridge on the exponents only — the intercept is unpenalized (it
    # cancels in ranking; it exists so the exponents fit slope, not
    # offset).
    reg = float(l2) * np.diag(
        np.concatenate([np.ones(n_feat), np.zeros(1)]))
    beta = np.linalg.solve(a.T @ a + reg, a.T @ y[train_rows])
    raw_w, intercept = beta[:n_feat], float(beta[n_feat])
    w32 = np.maximum(raw_w, 0.0).astype(np.float32)

    weights = {name: float(w32[i]) for i, name in enumerate(ds.schema)}
    eval_groups = sorted(
        {ds.fingerprints[int(g)] for g in ds.group[eval_rows]})
    provenance = {
        "trainer": "gie_tpu.learn.train/closed-form-ridge",
        "seed": int(seed),
        "eval_fraction": float(eval_fraction),
        "l2": float(l2),
        "trained_at": _data_through_ts(dumps),
        "fingerprints": list(ds.fingerprints),
        "eval_fingerprints": eval_groups,
        "n_rows": int(len(ds)),
        "n_train": int(train_rows.size),
        "n_eval": int(eval_rows.size),
        "skipped": dict(sorted(ds.skipped.items())),
        "intercept": round(intercept, 9),
        "rmse_train": round(
            _rmse(xt, raw_w, intercept, yt), 9),
        "rmse_eval": round(
            _rmse(logx[eval_rows], raw_w, intercept, y[eval_rows]), 9),
    }
    return artifact_mod.build_artifact(weights, ds.schema, provenance)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m gie_tpu.learn.train",
        description="Train a multiplicative scheduling policy from "
                    "flight-recorder dumps.")
    parser.add_argument("--dump", action="append", required=True,
                        metavar="PATH",
                        help="dump file or directory of *.json dumps "
                             "(repeatable)")
    parser.add_argument("--out", required=True, metavar="PATH",
                        help="artifact output path")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--eval-fraction", type=float, default=0.25)
    parser.add_argument("--l2", type=float, default=1e-3)
    args = parser.parse_args(argv)

    dumps = dataset_mod.load_dumps(args.dump)
    art = train(dumps, seed=args.seed,
                eval_fraction=args.eval_fraction, l2=args.l2)
    with open(args.out, "w") as f:
        f.write(artifact_mod.dumps_artifact(art))
    prov = art["provenance"]
    print(f"wrote {args.out}: {art['checksum']} "
          f"(rows train={prov['n_train']} eval={prov['n_eval']}, "
          f"rmse train={prov['rmse_train']} eval={prov['rmse_eval']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
