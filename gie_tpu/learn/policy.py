"""The multiplicative learned scorer — runtime form and serialization.

Following "Simple is Better: Multiplication May Be All You Need for LLM
Request Scheduling" (PAPERS.md): the learned score is a product of the
existing normalized scorer columns raised to trained exponents,

    total = prod_s col_s ** w_s  =  exp(sum_s w_s * log(max(col_s, EPS)))

computed in log space so it lowers to one fused elementwise multiply-add
chain over the already-stacked [S, N, M] columns — a drop-in for the
weighted-sum blend at the same seam in build_stages, with the SAME
dynamic `Weights` scalars (retuning or hot-swapping a trained artifact
never recompiles).

Bitwise discipline (the PR 15 rule, applied here): the log-space sum
uses the SAME ``einsum("s,snm->nm", ...)`` idiom as the heuristic blend,
so the single-device and mesh-sharded jitted programs compile one
formula — shards split N/M, never S, and the mesh parity matrix pins
the learned cycle bit-identical across mesh sizes. Across COMPILATION
boundaries (eager per-op vs one fused jit, XLA vs numpy libm) bitwise
equality is not a real property of ANY fused float formula — XLA
rewrites exp(a)*exp(b) into exp(a+b) and contracts multiply-adds into
FMAs inside fusions — so the numpy reference below pins the algebra
with a measured ULP bound instead (tests/test_learn.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# Floor applied inside the log: scorer columns are normalized to [0, 1]
# and 0.0 is a legitimate "worst" value; log(EPS) ~= -13.8 keeps the
# exponentiated product at a representable, strictly-positive minimum so
# masked-out comparisons downstream behave exactly like the blend's.
EPS = np.float32(1e-6)


def multiplicative_total(stacked: jax.Array, wvec: jax.Array) -> jax.Array:
    """exp(sum_s w_s * log(max(col_s, EPS))) over stacked [S, N, M].

    Pure and jittable; S must be static (it always is — the column set
    is baked into the trace by ProfileConfig). The contraction mirrors
    the heuristic blend's einsum exactly, so the sharded cycle treats
    both scorers identically (the mesh splits N/M; the S reduction is
    shard-local either way).
    """
    logs = jnp.log(jnp.maximum(stacked, jnp.float32(EPS)))
    return jnp.exp(jnp.einsum("s,snm->nm", wvec, logs))


def multiplicative_total_reference(
    stacked: np.ndarray, wvec: np.ndarray
) -> np.ndarray:
    """Plain-numpy reference of multiplicative_total for tests and the
    trainer: same algebra, float32 intermediates, left-to-right fold.

    numpy libm and a fused XLA program differ in the last ULPs of
    transcendental chains (see the module docstring), so this reference
    is compared with an ULP bound, not ==; the bitwise claims live where
    they are real — same-formula jit vs jit across mesh shardings.
    """
    stacked = np.asarray(stacked, dtype=np.float32)
    wvec = np.asarray(wvec, dtype=np.float32)
    acc = (wvec[0] * np.log(np.maximum(stacked[0], EPS))).astype(np.float32)
    for s in range(1, stacked.shape[0]):
        term = wvec[s] * np.log(np.maximum(stacked[s], EPS))
        acc = (acc + term).astype(np.float32)
    return np.exp(acc).astype(np.float32)


def float32_hex(value: float) -> str:
    """Little-endian IEEE-754 float32 bytes as hex — the bitwise-stable
    wire form of a trained weight (json floats round-trip through decimal
    repr; this never does)."""
    return np.array(value, dtype="<f4").tobytes().hex()


def float32_from_hex(hexed: str) -> np.float32:
    """Inverse of float32_hex."""
    raw = bytes.fromhex(hexed)
    if len(raw) != 4:
        raise ValueError(f"float32 hex must be 8 hex chars (got {hexed!r})")
    return np.frombuffer(raw, dtype="<f4")[0]


def weights_from_mapping(mapping: dict[str, float]):
    """Build a sched Weights struct from a {column_name: exponent} dict
    (the artifact's weight table). Columns absent from the mapping get
    0.0 — in the multiplicative form col**0 == 1, a clean no-op."""
    import dataclasses

    from gie_tpu.sched.types import Weights

    fields = {f.name for f in dataclasses.fields(Weights)}
    unknown = set(mapping) - fields
    if unknown:
        raise ValueError(
            f"unknown scorer columns in policy weights: {sorted(unknown)}")
    kwargs = {name: np.float32(mapping.get(name, 0.0)) for name in fields}
    return Weights(**kwargs)
